"""Per-program-digest circuit breaker for device launches.

Reference analog: tikv/client-go's region/store blacklisting inside the
copIterator retry loop — a store that keeps failing stops receiving
dispatches for a cooldown instead of burning every statement's retry
budget against it.  Here the failure domain is a compiled PROGRAM (the
scheduler's dag-digest key): a plan whose build/launch keeps crashing
the device is quarantined so repeat offenders fail fast with a
structured error — which the CopClient can turn into a host-oracle
fallback — instead of re-crashing the device under every waiter.

State machine (per digest):

    CLOSED --(N failures within window_s)--> OPEN
    OPEN   --(cooldown_s elapsed; next admit)--> HALF_OPEN (one probe)
    HALF_OPEN --probe success--> CLOSED
    HALF_OPEN --probe failure--> OPEN (cooldown restarts)

`admit` runs in the SUBMITTING thread (before anything queues or
traces), so a quarantined digest costs one dict lookup, not a device
crash.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

CLOSED = "CLOSED"
OPEN = "OPEN"
HALF_OPEN = "HALF_OPEN"

DEFAULT_THRESHOLD = 3        # failures within the window that trip OPEN
DEFAULT_WINDOW_S = 30.0      # failure-counting window
DEFAULT_COOLDOWN_S = 2.0     # OPEN dwell before the HALF_OPEN probe
# a HALF_OPEN probe that never reports back (submitter died between
# admit and launch) stops blocking new probes after this long
PROBE_TTL_S = 60.0


def digest_hex(digest: int) -> str:
    """Display form shared with the scheduler's device-time map."""
    return f"{digest & 0xffffffffffffffff:016x}"


class LaunchQuarantinedError(RuntimeError):
    """Structured fail-fast for a quarantined program digest: the
    breaker is OPEN (or a HALF_OPEN probe is already in flight), so
    this launch would re-crash the device.  Carries what a client needs
    to degrade gracefully or surface a useful error."""

    def __init__(self, digest: int, failures: int, retry_after_s: float):
        super().__init__(
            f"program {digest_hex(digest)} is quarantined after "
            f"{failures} launch failures (circuit breaker OPEN; "
            f"probe in {max(retry_after_s, 0.0):.2f}s)")
        self.digest = digest
        self.failures = failures
        self.retry_after_s = max(retry_after_s, 0.0)


class _Entry:
    __slots__ = ("state", "fail_times", "failures", "opened_at",
                 "probe_since", "trips")

    def __init__(self):
        self.state = CLOSED
        self.fail_times: list = []    # recent failure stamps (window)
        self.failures = 0             # lifetime launch failures
        self.opened_at = 0.0
        self.probe_since = 0.0        # nonzero = probe in flight
        self.trips = 0                # CLOSED->OPEN transitions


class CircuitBreaker:
    """Thread-safe per-digest breaker map (bounded).  `clock` is the
    test seam (defaults to time.monotonic)."""

    def __init__(self, threshold: int = DEFAULT_THRESHOLD,
                 window_s: float = DEFAULT_WINDOW_S,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 cap: int = 256, clock=time.monotonic):
        self.threshold = threshold
        self.window_s = window_s
        self.cooldown_s = cooldown_s
        self.cap = cap
        self.clock = clock
        self._mu = threading.Lock()
        self._entries: dict[int, _Entry] = {}

    # ---- admission (submitting thread) ------------------------------- #

    def admit(self, digest: int) -> None:
        """Pass, or raise LaunchQuarantinedError.  An OPEN entry past
        its cooldown transitions to HALF_OPEN and admits THIS caller as
        the single probe; concurrent submits keep failing fast until
        the probe reports back (or its TTL lapses)."""
        now = self.clock()
        with self._mu:
            e = self._entries.get(digest)
            if e is None or e.state == CLOSED:
                return
            if e.state == OPEN:
                wait = self.cooldown_s - (now - e.opened_at)
                if wait > 0:
                    raise LaunchQuarantinedError(digest, e.failures, wait)
                e.state = HALF_OPEN
                e.probe_since = now
                return                      # this caller is the probe
            # HALF_OPEN: one probe at a time
            if e.probe_since and now - e.probe_since < PROBE_TTL_S:
                raise LaunchQuarantinedError(
                    digest, e.failures,
                    PROBE_TTL_S - (now - e.probe_since))
            e.probe_since = now             # stale probe: take over

    def abort_probe(self, digest: int) -> None:
        """The admitted probe never reached a launch (queue overflow
        etc.): release the slot so the next submit may probe."""
        with self._mu:
            e = self._entries.get(digest)
            if e is not None and e.state == HALF_OPEN:
                e.probe_since = 0.0

    # ---- outcomes (drain thread) ------------------------------------- #

    def record_failure(self, digest: int) -> None:
        now = self.clock()
        with self._mu:
            e = self._entries.get(digest)
            if e is None:
                if len(self._entries) >= self.cap:
                    self._evict_closed()
                e = self._entries[digest] = _Entry()
            e.failures += 1
            if e.state == HALF_OPEN:
                # probe failed: quarantine again, cooldown restarts
                e.state = OPEN
                e.opened_at = now
                e.probe_since = 0.0
                return
            e.fail_times = [t for t in e.fail_times
                            if now - t <= self.window_s]
            e.fail_times.append(now)
            if e.state == CLOSED and \
                    len(e.fail_times) >= self.threshold:
                e.state = OPEN
                e.opened_at = now
                e.trips += 1

    def record_success(self, digest: int) -> None:
        with self._mu:
            e = self._entries.get(digest)
            if e is None:
                return
            if e.state == HALF_OPEN:
                e.state = CLOSED            # probe healed the circuit
                e.probe_since = 0.0
            if e.state == CLOSED:
                e.fail_times = []           # healthy launch resets count

    def _evict_closed(self) -> None:
        """Capped map: drop CLOSED entries first (with _mu held)."""
        for d in [d for d, e in self._entries.items()
                  if e.state == CLOSED][:max(len(self._entries) // 4, 1)]:
            del self._entries[d]
        while len(self._entries) >= self.cap:
            self._entries.pop(next(iter(self._entries)))

    # ---- introspection ----------------------------------------------- #

    def state(self, digest: int) -> str:
        with self._mu:
            e = self._entries.get(digest)
            return e.state if e is not None else CLOSED

    def snapshot(self, max_entries: int = 16) -> dict:
        """Non-trivial entries for /sched: digests with a tripped or
        failing breaker, hex-keyed like digest_device_ms."""
        now = self.clock()
        with self._mu:
            ents = [(d, e) for d, e in self._entries.items()
                    if e.state != CLOSED or e.failures]
            ents.sort(key=lambda de: (de[1].state == CLOSED,
                                      -de[1].failures))
            out = {}
            for d, e in ents[:max_entries]:
                ent = {"state": e.state, "failures": e.failures,
                       "trips": e.trips}
                if e.state == OPEN:
                    ent["probe_in_s"] = round(max(
                        self.cooldown_s - (now - e.opened_at), 0.0), 3)
                out[digest_hex(d)] = ent
            return out

    def reset(self, digest: Optional[int] = None) -> None:
        with self._mu:
            if digest is None:
                self._entries.clear()
            else:
                self._entries.pop(digest, None)


__all__ = ["CircuitBreaker", "LaunchQuarantinedError", "digest_hex",
           "CLOSED", "OPEN", "HALF_OPEN"]
