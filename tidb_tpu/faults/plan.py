"""FaultPlan: seeded, deterministic fault injection at named seams.

Reference analog: tikv/tidb failpoints (failpoint.Inject on rpc/region
errors, the seam pkg/store/copr exercises its backoff loop through) —
but deterministic: every decision is a pure function of (seed, seam,
program digest, attempt counter), so a chaos run replays bit-identically
and a test can poison exactly one member of a fused launch.  Off by
default; armed via the TIDB_TPU_FAULTS env var, the `tidb_tpu_faults`
sysvar, or programmatic `install()` (tests).

Seams (every recovery path in the engine is exercisable on a CPU mesh
through these, no real TPU required):

- ``build``     program build/trace (scheduler resolving a compiled
                program for a cop task)
- ``launch``    device launch (compiled program invocation; fused
                launches consult the seam once PER MEMBER digest, so a
                poisoned member forces the blast-radius demux)
- ``transfer``  device->host transfer / host merge (CopClient result
                decode)
- ``dispatch``  store dispatch (CopClient._retry, next to the legacy
                RegionError failpoint queue)
- ``drain``     drain wakeup (scheduler loop, before a batch serves)

Fault kinds:

- ``transient`` retryable: decided per (seed, seam, key, attempt), so a
                retry rolls fresh dice — the supervised drain recovers
                it through the Backoffer DEVICE_FAILED budget.
- ``poison``    deterministic per (seed, seam, key): every retry of the
                same program fails again — retrying never helps, the
                per-digest circuit breaker is the only way out.
- ``oom``       memory-exhaustion class (XLA RESOURCE_EXHAUSTED /
                device OOM): decided per (seed, seam, key, attempt)
                like ``transient`` — a re-sized or solo retry may fit —
                but classified apart by the supervised drain: an OOM
                bumps the digest's memory correction
                (analysis/calibrate), demuxes fused launches to reduce
                width, and NEVER charges the poison circuit breaker
                (a healthy program that outgrew the budget is not a
                broken kernel).  ``is_oom_error`` also classifies REAL
                backend OOMs (RESOURCE_EXHAUSTED text) the same way,
                so the recovery path is CPU-testable via this seam.
"""

from __future__ import annotations

import os
import threading
import zlib
from dataclasses import dataclass
from typing import Optional

SEAMS = ("build", "launch", "transfer", "dispatch", "drain")

_MASK = (1 << 64) - 1


class InjectedFault(RuntimeError):
    """Base of every fault the plan raises; `transient` drives the
    supervised drain's retry-vs-isolate classification."""

    transient = False

    def __init__(self, seam: str, key=None):
        at = f" digest={key & _MASK:016x}" if isinstance(key, int) else ""
        super().__init__(f"injected {self.kind()} fault at seam "
                         f"'{seam}'{at} (faultline)")
        self.seam = seam
        self.key = key

    @classmethod
    def kind(cls) -> str:
        return "transient" if cls.transient else "poison"


class TransientFault(InjectedFault):
    """Retryable injected failure (store-unreachable / preempted-launch
    class): a fresh attempt may succeed."""
    transient = True


class PoisonFault(InjectedFault):
    """Deterministic injected failure (broken kernel / poisoned plan
    class): the same program fails on every retry."""
    transient = False


class MemoryFault(InjectedFault):
    """Injected device memory exhaustion (XLA RESOURCE_EXHAUSTED
    class): the launch as sized did not fit.  Not retry-as-is worthy
    (the identical launch would OOM again) but also NOT poison — the
    supervised drain recovers it by shrinking the launch (fused-width
    demux, streamed batching, host fallback) and bumping the digest's
    memory correction, never by opening the circuit breaker."""
    transient = False

    @classmethod
    def kind(cls) -> str:
        return "oom"


_KIND_EXC = {"transient": TransientFault, "poison": PoisonFault,
             "oom": MemoryFault}

# substrings that mark a REAL backend launch failure as memory
# exhaustion (jaxlib XlaRuntimeError carries the XLA status name)
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "Resource exhausted", "OOM when allocating")


def is_oom_error(e: BaseException) -> bool:
    """Classify a launch failure as device memory exhaustion: the
    injected MemoryFault, or a real backend error whose text carries an
    XLA RESOURCE_EXHAUSTED / OOM marker.  String-matching is the only
    portable seam — jaxlib's XlaRuntimeError carries the status in its
    message, and importing backend exception types here would bind
    faultline to jax (this module stays jax-free)."""
    if isinstance(e, MemoryFault):
        return True
    if isinstance(e, InjectedFault):
        return False
    text = f"{type(e).__name__}: {e}"
    return any(m in text for m in _OOM_MARKERS)


@dataclass(frozen=True)
class FaultRule:
    """One armed rule.  ``match`` filters by hex program digest
    substring ('' = any key, including unkeyed seams); ``times`` caps
    total injections (0 = unlimited) — the n-shot failpoint idiom."""
    seam: str            # one of SEAMS, or '*'
    kind: str            # 'transient' | 'poison'
    rate: float = 1.0    # injection probability (deterministic hash)
    match: str = ""      # hex-digest substring; keyed checks only
    times: int = 0       # fire at most N times; 0 = unlimited


def _mix(*vals: int) -> int:
    """splitmix64-style avalanche over the inputs: the deterministic
    dice (same idiom as copr/segment's key hash)."""
    x = 0x9E3779B97F4A7C15
    for v in vals:
        x ^= v & _MASK
        x = (x * 0xBF58476D1CE4E5B9) & _MASK
        x ^= x >> 27
        x = (x * 0x94D049BB133111EB) & _MASK
        x ^= x >> 31
    return x


def _seam_id(seam: str) -> int:
    return zlib.crc32(seam.encode())


class FaultPlan:
    """A set of armed FaultRules plus the seed and injection counters.
    Thread-safe; decisions are deterministic given (seed, call order
    per seam, keys)."""

    def __init__(self, rules, seed: int = 0):
        self.rules = tuple(rules)
        self.seed = int(seed)
        self._mu = threading.Lock()
        self._calls: dict = {}          # seam -> checks
        self._injected: dict = {}       # (seam, kind) -> fires
        self._times_left = {i: r.times for i, r in enumerate(self.rules)
                            if r.times > 0}

    # ---- spec parsing ------------------------------------------------ #

    @classmethod
    def parse(cls, spec: str) -> Optional["FaultPlan"]:
        """``seed=42,launch:transient:0.2,build:poison:1:match=ab12``
        -> FaultPlan; empty/blank spec -> None (unarmed)."""
        seed = 0
        rules = []
        for token in (spec or "").split(","):
            token = token.strip()
            if not token:
                continue
            if token.startswith("seed="):
                seed = int(token[5:])
                continue
            parts = token.split(":")
            if len(parts) < 2:
                raise ValueError(f"bad fault rule {token!r}: want "
                                 "seam:kind[:rate][:match=..][:times=..]")
            seam, kind = parts[0], parts[1]
            if seam not in SEAMS and seam != "*":
                raise ValueError(f"unknown fault seam {seam!r} "
                                 f"(one of {SEAMS} or '*')")
            if kind not in _KIND_EXC:
                raise ValueError(f"unknown fault kind {kind!r} "
                                 f"(one of {tuple(sorted(_KIND_EXC))})")
            rate, match, times = 1.0, "", 0
            for extra in parts[2:]:
                if extra.startswith("match="):
                    match = extra[6:]
                elif extra.startswith("times="):
                    times = int(extra[6:])
                else:
                    rate = float(extra)
            rules.append(FaultRule(seam, kind, rate, match, times))
        if not rules:
            return None
        return cls(rules, seed=seed)

    # ---- the seam check ---------------------------------------------- #

    def check(self, seam: str, key: Optional[int] = None) -> None:
        """Raise the armed fault for this (seam, key) attempt, or pass.
        `key` is the program digest where one exists (build/launch and
        keyed transfer checks); unkeyed seams only match rules without
        a `match` filter."""
        fault = None
        with self._mu:
            n = self._calls[seam] = self._calls.get(seam, 0) + 1
            for i, r in enumerate(self.rules):
                if r.seam != seam and r.seam != "*":
                    continue
                if r.match:
                    if key is None or \
                            r.match not in f"{key & _MASK:016x}":
                        continue
                left = self._times_left.get(i)
                if left is not None and left <= 0:
                    continue
                if r.rate < 1.0:
                    kv = (key or 0) & _MASK
                    if r.kind == "poison":
                        # keyed-only dice: the SAME key fails forever
                        u = _mix(self.seed, _seam_id(seam), kv)
                    else:
                        # attempt-counted dice (transient AND oom): a
                        # retry — or a re-sized/demuxed re-launch —
                        # rolls fresh
                        u = _mix(self.seed, _seam_id(seam), kv, n)
                    if u / 2.0 ** 64 >= r.rate:
                        continue
                if left is not None:
                    self._times_left[i] = left - 1
                k = (seam, r.kind)
                self._injected[k] = self._injected.get(k, 0) + 1
                fault = _KIND_EXC[r.kind](seam, key)
                break
        if fault is not None:
            from ..utils.metrics import global_registry
            global_registry().counter(
                "tidb_tpu_faults_injected_total",
                "faults injected by the armed FaultPlan",
                labels=("seam", "kind")).inc(seam=fault.seam,
                                             kind=fault.kind())
            # copscope: statement-thread seams (dispatch/transfer) mark
            # the injection on the active trace; drain-thread seams
            # have no context here — their injections surface through
            # the scheduler's retry/fail span error labels instead
            from ..obs.trace import current as _obs_current
            ctx = _obs_current()
            if ctx is not None:
                import time as _time
                now = _time.perf_counter_ns()
                ctx.add("fault.inject", now, now, seam=fault.seam,
                        kind=fault.kind())
            raise fault

    def backoff_rng(self):
        """Seeded jitter source for Backoffer under this plan: retry
        histories replay bit-identically (store/backoff rng seam)."""
        import random
        return random.Random(self.seed)

    def stats(self) -> dict:
        with self._mu:
            return {
                "seed": self.seed,
                "rules": [f"{r.seam}:{r.kind}:{r.rate}"
                          + (f":match={r.match}" if r.match else "")
                          + (f":times={r.times}" if r.times else "")
                          for r in self.rules],
                "checks": dict(sorted(self._calls.items())),
                "injected": {f"{s}:{k}": v for (s, k), v in
                             sorted(self._injected.items())},
                "total_injected": sum(self._injected.values()),
            }


# --------------------------------------------------------------------- #
# process-wide active plan (the scheduler/client seams consult this)
# --------------------------------------------------------------------- #

_ACTIVE: Optional[FaultPlan] = None
_ENV_CHECKED = False
_SPEC = ""                # last sysvar-installed spec (install_spec)
_MU = threading.Lock()


def install(plan: Optional[FaultPlan]) -> None:
    """Arm `plan` process-wide (tests / embedders); None disarms."""
    global _ACTIVE, _ENV_CHECKED, _SPEC
    with _MU:
        _ACTIVE = plan
        _ENV_CHECKED = True     # explicit install outranks the env
        _SPEC = ""


def clear() -> None:
    install(None)


def install_spec(spec: str) -> None:
    """Sysvar seam (tidb_tpu_faults): (re)arm from a spec string.  An
    empty spec only DISARMS a plan this same seam installed — it never
    clobbers a programmatic install() (tests arm directly while
    statements keep flowing)."""
    global _ACTIVE, _ENV_CHECKED, _SPEC
    spec = (spec or "").strip()
    with _MU:
        if spec == _SPEC:
            return
        if not spec:
            if _SPEC:               # only undo our own install
                _ACTIVE = None
                _SPEC = ""
            return
        _ACTIVE = FaultPlan.parse(spec)
        _ENV_CHECKED = True
        _SPEC = spec


def active() -> Optional[FaultPlan]:
    """The armed plan, if any; first call consults TIDB_TPU_FAULTS."""
    global _ACTIVE, _ENV_CHECKED
    if _ACTIVE is None and not _ENV_CHECKED:
        with _MU:
            if not _ENV_CHECKED:
                _ENV_CHECKED = True
                spec = os.environ.get("TIDB_TPU_FAULTS", "")
                if spec:
                    _ACTIVE = FaultPlan.parse(spec)
    return _ACTIVE


def check(seam: str, key: Optional[int] = None) -> None:
    """Seam hook: no-op when unarmed (the common case — one None read)."""
    p = active()
    if p is not None:
        p.check(seam, key)


def stats() -> Optional[dict]:
    p = active()
    return p.stats() if p is not None else None


__all__ = ["FaultPlan", "FaultRule", "InjectedFault", "TransientFault",
           "PoisonFault", "MemoryFault", "is_oom_error", "SEAMS",
           "install", "install_spec", "clear", "active", "check",
           "stats"]
