"""System-variable registry: scopes, types, defaults, validation.

Reference analog: pkg/sessionctx/variable (sysvar.go + vardef/tidb_vars.go,
~700 vars).  This registry carries the variables this engine actually
honors plus the widely-set compatibility surface; SET validates and
coerces through it, unknown variables are rejected like MySQL's ERROR
1193 (unless prefixed `@@local.`-style passthrough is added later).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

SCOPE_GLOBAL = "global"
SCOPE_SESSION = "session"
SCOPE_BOTH = "both"
SCOPE_NONE = "noop"       # accepted for compatibility, no effect


@dataclass(frozen=True)
class SysVar:
    name: str
    default: Any
    scope: str = SCOPE_BOTH
    kind: str = "int"         # int | bool | float | str | enum
    min: Optional[int] = None
    max: Optional[int] = None
    options: tuple = ()       # enum values
    validator: Optional[Callable] = None


def _v(*args, **kw) -> SysVar:
    return SysVar(*args, **kw)


_VARS = [
    # engine-honored knobs
    # TPU-engine knobs (this framework's own surface — the reference
    # exposes every perf knob as a sysvar, vardef/tidb_vars.go)
    # -1 = unset: the engine default (module constant / ctor value)
    # stays authoritative until a user explicitly SETs the variable
    _v("tidb_tpu_device_mem_cap", -1, kind="int", min=-1,
       scope=SCOPE_GLOBAL),            # bytes; 0 = resident (no streaming)
    _v("tidb_tpu_broadcast_build_max_rows", -1, kind="int", min=-1,
       scope=SCOPE_GLOBAL),            # broadcast- vs shuffle-join cut
    _v("tidb_tpu_shard_count", 8, kind="int", min=1, max=4096),
    _v("tidb_tpu_dense_broadcast_max_groups", -1, kind="int", min=-1,
       max=1 << 20),
    _v("tidb_tpu_result_cache_entries", -1, kind="int", min=-1,
       max=4096, scope=SCOPE_GLOBAL),
    # device admission scheduler (sched/): bounded queue depth (0 =
    # bypass admission, dispatch direct) and the max tasks one launch
    # may coalesce
    _v("tidb_tpu_sched_queue_depth", -1, kind="int", min=-1,
       max=1 << 16, scope=SCOPE_GLOBAL),
    _v("tidb_tpu_sched_max_coalesce", -1, kind="int", min=-1, max=64,
       scope=SCOPE_GLOBAL),
    # cross-query kernel fusion (one scan, many payloads) and the
    # adaptive micro-batch window: -1 = EWMA-tuned wait-for-stragglers,
    # 0 = never hold a launch, >0 = fixed window in microseconds
    _v("tidb_tpu_sched_fusion", 1, kind="bool", scope=SCOPE_GLOBAL),
    _v("tidb_tpu_sched_window_us", -1, kind="int", min=-1, max=100_000,
       scope=SCOPE_GLOBAL),
    # per-mesh HBM admission budget for the static cost gate
    # (analysis/copcost): -1 = auto from device memory stats (CPU
    # fallback constant), 0 = unlimited, >0 = bytes.  Launches whose
    # LaunchCost.peak_hbm_bytes exceed it are rejected pre-trace.
    _v("tidb_tpu_sched_hbm_budget", -1, kind="int", min=-1,
       scope=SCOPE_GLOBAL),
    # resource control plane (rc/): RU-bucket enforcement at the drain.
    # rc_enable=0 reverts to the legacy post-paid statement charge;
    # overdraft is the bounded RU debt the drain tolerates per group
    # (-1 = engine default, DEFAULT_OVERDRAFT_RU)
    _v("tidb_tpu_rc_enable", 1, kind="bool", scope=SCOPE_GLOBAL),
    _v("tidb_tpu_rc_overdraft_ru", -1, kind="int", min=-1,
       max=1 << 20, scope=SCOPE_GLOBAL),
    # launch supervision (faultline): host-oracle fallback for
    # breaker-quarantined program digests (default on — a broken device
    # kernel degrades to slow-but-correct instead of unavailable), and
    # the fault-injection plane spec (seam:kind[:rate][:match=..]
    # [:times=..] rules, comma-separated, optional seed=N; empty = off)
    _v("tidb_tpu_sched_host_fallback", 1, kind="bool",
       scope=SCOPE_GLOBAL),
    _v("tidb_tpu_faults", "", kind="str", scope=SCOPE_GLOBAL),
    # copforge AOT compile cache (compilecache/): cacheable device
    # programs resolve through a warm executable pool; with a cache dir
    # set, compiled executables persist across restarts (digest + mesh
    # fingerprint + donation-plan keyed) and the boot warm pool replays
    # the hot-program manifest at LOW priority.  warm_pool caps the
    # pool/manifest in BYTES (-1 = engine default, 0 = unbounded).
    _v("tidb_tpu_compile_cache", 1, kind="bool", scope=SCOPE_GLOBAL),
    _v("tidb_tpu_compile_cache_dir", "", kind="str", scope=SCOPE_GLOBAL),
    _v("tidb_tpu_compile_warm_pool", -1, kind="int", min=-1,
       scope=SCOPE_GLOBAL),
    # coplace PD-style coordination plane (pd/): N server processes
    # share one RU budget per resource group (debt-weighted refill
    # shares), one compile-artifact registry (compile-once claims +
    # peer warm-pool adoption + cross-process quarantine), and merged
    # cost calibration.  Default OFF — a single process needs no
    # coordination and stays byte-identical to the pre-pd behavior.
    # pd_dir empty = in-process shared store (N Domains in one
    # interpreter); set = file-backed store shared by real processes
    # (advisory locks + atomic rename, one host).
    _v("tidb_tpu_pd", 0, kind="bool", scope=SCOPE_GLOBAL),
    _v("tidb_tpu_pd_dir", "", kind="str", scope=SCOPE_GLOBAL),
    # copmeter closed-loop cost calibration (analysis/calibrate):
    # measured per-digest launch times correct the static LaunchCost
    # terms feeding RU pricing, HBM-budget admission, fusion caps, the
    # micro-batch window, and deadline-aware early shedding.  Off = the
    # static model untouched, no feedback recorded.
    _v("tidb_tpu_cost_calibration", 1, kind="bool", scope=SCOPE_GLOBAL),
    # shardflow typed-link topology view (parallel/topology): the host
    # factorization analysis assumes when classifying collective bytes
    # as same-host ICI vs cross-host DCI.  -1 = derive from the mesh's
    # device process indices (single-host on one machine); >0 declares
    # a (host=N, device=D/N) view — how tier-1 exercises the DCI tier
    # on the 8-vdev CPU mesh
    _v("tidb_tpu_topology_hosts", -1, kind="int", min=-1, max=4096,
       scope=SCOPE_GLOBAL),
    # SCATTER radix-partition Pallas gate (copr/radix + copr/pallas):
    # auto = hand-written Pallas kernels on TPU, XLA lowering elsewhere;
    # on = Pallas everywhere (interpret mode off-TPU, the tier-1 kernel
    # seam); off = XLA lowering everywhere
    _v("tidb_tpu_radix_pallas", "auto", kind="str", scope=SCOPE_GLOBAL),
    # copscope (obs/): per-statement span trees with cross-thread trace
    # propagation + the flight-recorder ring.  tidb_tpu_trace off =
    # no tree is built, no span is recorded anywhere (the overhead
    # guard's baseline); tidb_tpu_trace_sample = keep 1-in-N ordinary
    # traces (failed/degraded/quarantined/retried/slow always kept)
    _v("tidb_tpu_trace", 1, kind="bool"),
    _v("tidb_tpu_trace_sample", 16, kind="int", min=1, max=65536,
       scope=SCOPE_GLOBAL),
    # copgauge (obs/hbm + obs/roofline): the live HBM ledger, measured
    # launch watermarks feeding continuous mem_factor calibration, and
    # per-digest roofline attribution.  Off = no ledger accounting, no
    # measured watermarks, no roofline feed — the static cost model
    # behaves byte-identically to the pre-copgauge engine (mem_factor
    # moves only on OOM).
    _v("tidb_tpu_hbm_ledger", 1, kind="bool", scope=SCOPE_GLOBAL),
    # on-demand jax.profiler capture gate (/profile?ms=N): off by
    # default — a trace capture writes xplane dirs to disk and costs
    # real overhead, so an operator must opt in
    _v("tidb_tpu_profile", 0, kind="bool", scope=SCOPE_GLOBAL),
    # copsan runtime lock sanitizer (utils/locksan): instrumented lock
    # wrappers verify every observed acquisition edge against the
    # static concurrency model (analysis/concurrency).  Off by default
    # — arming only affects locks allocated AFTER it, so flip it
    # before building the domain (the stress smoke and bench do).
    _v("tidb_tpu_lock_sanitizer", 0, kind="bool", scope=SCOPE_GLOBAL),
    # slow-query log threshold (ms), session -> Domain plumb — replaces
    # the constructor-only threshold in utils/stmtsummary; slow entries
    # carry schedWait/compile/ru/retried/trace-id fields
    _v("tidb_tpu_slow_threshold_ms", 300, kind="int", min=0,
       max=86_400_000),
    _v("tidb_distsql_scan_concurrency", 15, kind="int", min=1, max=256),
    _v("tidb_max_chunk_size", 1024, kind="int", min=32, max=65536),
    _v("tidb_enable_vectorized_expression", 1, kind="bool"),
    _v("tidb_ddl_reorg_worker_cnt", 4, kind="int", min=1, max=128),
    _v("tidb_mdl_wait_timeout", 10.0, kind="float", min=0.0, max=3600.0),
    # MySQL client/ORM handshake compat (accepted, enforced where the
    # engine has the corresponding behavior)
    _v("profiling", 0, kind="bool"),
    _v("innodb_strict_mode", 1, kind="bool"),
    _v("optimizer_switch", "", kind="str"),
    _v("big_tables", 0, kind="bool"),
    _v("sql_buffer_result", 0, kind="bool"),
    _v("lc_time_names", "en_US", kind="str"),
    _v("div_precision_increment", 4, kind="int", min=0, max=30),
    _v("tidb_mem_quota_query", -1, kind="int"),
    _v("tidb_enable_tmp_storage_on_oom", 1, kind="bool"),
    _v("tidb_enable_plan_cache", 1, kind="bool"),
    _v("tidb_enable_cascades_planner", 0, kind="bool"),
    _v("tidb_opt_skew_distinct_agg", 0, kind="bool"),
    _v("tidb_gc_life_time_sec", 600, kind="int", min=1),
    _v("tidb_gc_run_interval_sec", 60, kind="int", min=1),
    _v("tidb_ttl_job_interval_sec", 60, kind="int", min=1),
    _v("tidb_auto_analyze_ratio", 0.5, kind="float"),
    _v("tidb_enable_auto_analyze", 1, kind="bool"),
    _v("tidb_txn_mode", "optimistic", kind="enum",
       options=("optimistic", "pessimistic")),
    _v("tidb_slow_log_threshold", 300, kind="int", min=0),
    _v("tidb_resource_group", "default", kind="str"),
    _v("tidb_enable_telemetry", 0, kind="bool", scope=SCOPE_GLOBAL),
    # MySQL compatibility surface (honored where the engine has the
    # concept; stored + reflected otherwise)
    _v("autocommit", 1, kind="bool"),
    _v("sql_mode", "ONLY_FULL_GROUP_BY,STRICT_TRANS_TABLES", kind="str"),
    _v("time_zone", "SYSTEM", kind="str"),
    _v("max_execution_time", 0, kind="int", min=0),
    _v("max_allowed_packet", 67108864, kind="int", min=1024),
    _v("character_set_client", "utf8mb4", kind="str"),
    _v("character_set_connection", "utf8mb4", kind="str"),
    _v("character_set_results", "utf8mb4", kind="str"),
    _v("collation_connection", "utf8mb4_bin", kind="str"),
    _v("default_collation_for_utf8mb4", "utf8mb4_bin", kind="str"),
    _v("transaction_isolation", "REPEATABLE-READ", kind="enum",
       options=("REPEATABLE-READ", "READ-COMMITTED")),
    # pre-8.0 connector/ORM aliases and connect-time compat vars —
    # clients SET these during handshake; they must not error
    _v("tx_isolation", "REPEATABLE-READ", kind="enum",
       options=("REPEATABLE-READ", "READ-COMMITTED")),
    _v("tx_read_only", 0, kind="bool", scope=SCOPE_NONE),
    _v("transaction_read_only", 0, kind="bool", scope=SCOPE_NONE),
    _v("sql_auto_is_null", 0, kind="bool", scope=SCOPE_NONE),
    _v("sql_safe_updates", 0, kind="bool", scope=SCOPE_NONE),
    _v("sql_notes", 1, kind="bool", scope=SCOPE_NONE),
    _v("sql_warnings", 0, kind="bool", scope=SCOPE_NONE),
    _v("sql_log_bin", 1, kind="bool", scope=SCOPE_NONE),
    _v("sql_quote_show_create", 1, kind="bool", scope=SCOPE_NONE),
    _v("character_set_server", "utf8mb4", kind="str"),
    _v("collation_server", "utf8mb4_bin", kind="str"),
    _v("character_set_database", "utf8mb4", kind="str"),
    _v("collation_database", "utf8mb4_bin", kind="str"),
    _v("default_storage_engine", "tpu-columnar", kind="str",
       scope=SCOPE_NONE),
    _v("net_buffer_length", 16384, kind="int", scope=SCOPE_NONE),
    _v("query_cache_size", 0, kind="int", scope=SCOPE_NONE),
    _v("query_cache_type", 0, kind="int", scope=SCOPE_NONE),
    _v("system_time_zone", "UTC", kind="str", scope=SCOPE_GLOBAL),
    _v("sql_require_primary_key", 0, kind="bool", scope=SCOPE_NONE),
    _v("init_connect", "", kind="str", scope=SCOPE_GLOBAL),
    _v("wait_timeout", 28800, kind="int", min=1),
    _v("interactive_timeout", 28800, kind="int", min=1),
    _v("net_write_timeout", 60, kind="int", min=1),
    _v("net_read_timeout", 30, kind="int", min=1),
    _v("lower_case_table_names", 2, kind="int", scope=SCOPE_GLOBAL),
    _v("version_comment", "tidb-tpu", kind="str", scope=SCOPE_GLOBAL),
    _v("port", 4000, kind="int", scope=SCOPE_GLOBAL),
    _v("socket", "", kind="str", scope=SCOPE_GLOBAL),
    _v("datadir", "", kind="str", scope=SCOPE_GLOBAL),
    _v("last_insert_id", 0, kind="int", scope=SCOPE_SESSION),
    _v("auto_increment_increment", 1, kind="int", min=1, max=65535),
    _v("auto_increment_offset", 1, kind="int", min=1, max=65535),
    _v("group_concat_max_len", 1024, kind="int", min=4),
    _v("sql_select_limit", 2 ** 64 - 1, kind="int", min=0),
    _v("foreign_key_checks", 0, kind="bool"),
    _v("unique_checks", 1, kind="bool"),
    _v("innodb_lock_wait_timeout", 50, kind="int", min=1),
    # TiDB-compat knobs accepted as no-ops (reference defines ~700; the
    # ones users commonly SET must not error)
    _v("tidb_enable_async_commit", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_enable_1pc", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_enable_clustered_index", "ON", kind="str", scope=SCOPE_NONE),
    _v("tidb_analyze_version", 2, kind="int", scope=SCOPE_NONE),
    _v("tidb_cost_model_version", 2, kind="int", scope=SCOPE_NONE),
    _v("tidb_partition_prune_mode", "dynamic", kind="str",
       scope=SCOPE_NONE),
    _v("tidb_enable_paging", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_executor_concurrency", 5, kind="int", min=1, max=256),
    _v("tidb_hash_join_concurrency", 5, kind="int", scope=SCOPE_NONE),
    _v("tidb_index_lookup_concurrency", 4, kind="int", scope=SCOPE_NONE),
    _v("tidb_build_stats_concurrency", 4, kind="int", scope=SCOPE_NONE),
    _v("tidb_enable_rate_limit_action", 0, kind="bool", scope=SCOPE_NONE),
    _v("tidb_replica_read", "leader", kind="str", scope=SCOPE_NONE),
    _v("tidb_isolation_read_engines", "tpu", kind="str",
       scope=SCOPE_NONE),
    _v("tidb_enable_stmt_summary", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_stmt_summary_max_stmt_count", 3000, kind="int",
       scope=SCOPE_NONE),
    _v("tidb_enable_collect_execution_info", 1, kind="bool",
       scope=SCOPE_NONE),
    _v("tidb_opt_agg_push_down", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_opt_join_reorder_threshold", 12, kind="int",
       scope=SCOPE_NONE),
    _v("tidb_index_join_batch_size", 25000, kind="int", scope=SCOPE_NONE),
    _v("tidb_init_chunk_size", 32, kind="int", scope=SCOPE_NONE),
    _v("tidb_retry_limit", 10, kind="int", scope=SCOPE_NONE),
    _v("tidb_disable_txn_auto_retry", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_constraint_check_in_place", 0, kind="bool",
       scope=SCOPE_NONE),
    _v("tidb_skip_utf8_check", 0, kind="bool", scope=SCOPE_NONE),
    _v("tidb_enable_window_function", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_enable_table_partition", "ON", kind="str", scope=SCOPE_NONE),
    _v("tidb_scatter_region", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_wait_split_region_finish", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_store_batch_size", 4, kind="int", scope=SCOPE_NONE),
    _v("tidb_enable_index_merge", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_enable_noop_functions", 0, kind="bool", scope=SCOPE_NONE),
    _v("tidb_row_format_version", 2, kind="int", scope=SCOPE_NONE),
    # widely-set TiDB compatibility surface (noop scope): ORMs and
    # operator tooling SET these freely; they must not error
    _v("tidb_allow_batch_cop", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_allow_fallback_to_tikv", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_allow_mpp", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_auto_analyze_end_time", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_auto_analyze_start_time", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_backoff_lock_fast", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_backoff_weight", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_batch_commit", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_batch_delete", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_batch_insert", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_broadcast_join_threshold_count", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_broadcast_join_threshold_size", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_capture_plan_baselines", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_check_mb4_value_in_utf8", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_checksum_table_concurrency", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_committer_concurrency", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_current_ts", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_ddl_error_count_limit", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_ddl_flashback_concurrency", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_ddl_reorg_batch_size", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_ddl_reorg_priority", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_dml_batch_size", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_amend_pessimistic_txn", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_auto_increment_in_generated", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_cascades_planner", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_chunk_rpc", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_column_tracking", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_ddl", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_enhanced_security", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_exchange_partition", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_extended_stats", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_fast_analyze", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_foreign_key", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_gc_aware_memory_track", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_global_index", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_index_merge_join", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_list_partition", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_local_txn", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_metadata_lock", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_mutation_checker", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_new_cost_interface", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_new_only_full_group_by_check", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_noop_variables", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_null_aware_anti_join", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_ordered_result_mode", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_outer_join_reorder", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_parallel_apply", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_pipelined_window_function", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_prepared_plan_cache", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_pseudo_for_outdated_stats", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_resource_control", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_reuse_chunk", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_slow_log", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_strict_double_type_check", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_tiflash_read_for_write_stmt", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_top_sql", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_enable_tso_follower_proxy", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_evolve_plan_baselines", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_expensive_query_time_threshold", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_force_priority", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_gc_concurrency", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_gc_enable", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_gc_max_wait_time", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_gc_scan_lock_mode", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_general_log", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_generate_binary_plan", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_guarantee_linearizability", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_hash_exchange_with_new_collation", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_hashagg_final_concurrency", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_hashagg_partial_concurrency", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_ignore_prepared_cache_close_stmt", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_index_lookup_join_concurrency", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_index_lookup_size", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_index_merge_intersection_concurrency", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_index_serial_scan_concurrency", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_last_ddl_info", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_last_query_info", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_last_txn_info", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_log_file_max_days", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_low_resolution_tso", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_max_auto_analyze_time", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_max_delta_schema_count", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_max_paging_size", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_max_tiflash_threads", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_mem_oom_action", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_mem_quota_analyze", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_mem_quota_apply_cache", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_mem_quota_binding_cache", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_memory_usage_alarm_ratio", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_merge_join_concurrency", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_metric_query_range_duration", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_metric_query_step", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_min_paging_size", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_multi_statement_mode", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_nontransactional_ignore_error", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_broadcast_cartesian_join", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_concurrency_factor", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_copcpu_factor", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_correlation_exp_factor", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_correlation_threshold", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_cpu_factor", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_desc_factor", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_disk_factor", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_distinct_agg_push_down", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_enable_correlation_adjustment", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_force_inline_cte", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_insubq_to_join_and_agg", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_limit_push_down_threshold", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_memory_factor", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_mpp_outer_join_fixed_build_side", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_network_factor", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_prefer_range_scan", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_projection_push_down", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_range_max_size", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_scan_factor", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_seek_factor", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_skew_distinct_agg", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_opt_write_row_id", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_placement_mode", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_pprof_sql_cpu", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_prepared_plan_cache_memory_guard_ratio", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_prepared_plan_cache_size", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_projection_concurrency", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_query_log_max_len", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_rc_read_check_ts", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_read_consistency", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_read_staleness", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_record_plan_in_slow_log", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_redact_log", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_regard_null_as_point", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_remove_orderby_in_subquery", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_restricted_read_only", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_server_memory_limit", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_shard_allocate_step", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_simplified_metrics", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_skip_ascii_check", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_skip_isolation_level_check", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_slow_query_file", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_snapshot", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_source_id", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_stats_cache_mem_quota", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_stats_load_sync_wait", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_stmt_summary_history_size", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_stmt_summary_internal_query", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_stmt_summary_max_sql_length", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_stmt_summary_refresh_interval", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_store_limit", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_streamagg_concurrency", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_super_read_only", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_sysdate_is_now", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_table_cache_lease", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_tmp_table_max_size", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_top_sql_max_meta_count", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_top_sql_max_time_series_count", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_track_aggregate_memory_usage", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_tso_client_batch_max_wait_time", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_txn_assertion_level", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_txn_commit_batch_size", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_wait_split_region_timeout", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_window_concurrency", "", kind="str", scope=SCOPE_NONE),
]

REGISTRY: dict[str, SysVar] = {v.name: v for v in _VARS}


class SysVarError(ValueError):
    pass


def validate_set(name: str, value: Any,
                 scope: Optional[str] = None) -> Any:
    """Coerce + validate a SET value; raises SysVarError on unknown
    variable, wrong scope, or out-of-range value.  Returns the canonical
    value.  `scope` is the statement's scope ('global'/'session')."""
    sv = REGISTRY.get(name)
    if sv is None:
        raise SysVarError(f"Unknown system variable {name!r}")
    if scope == "global" and sv.scope == SCOPE_SESSION:
        raise SysVarError(f"{name} is a SESSION variable")
    if scope == "session" and sv.scope == SCOPE_GLOBAL:
        raise SysVarError(
            f"{name} is a GLOBAL variable; use SET GLOBAL")
    if value is None:
        return sv.default          # SET x = DEFAULT
    if sv.kind == "bool":
        if isinstance(value, str):
            up = value.upper()
            if up in ("ON", "TRUE", "1"):
                return 1
            if up in ("OFF", "FALSE", "0"):
                return 0
            raise SysVarError(f"{name}: bad boolean {value!r}")
        return 1 if value else 0
    if sv.kind == "int":
        try:
            iv = int(value)
        except (TypeError, ValueError):
            raise SysVarError(f"{name}: expected integer, got {value!r}")
        if sv.min is not None and iv < sv.min:
            iv = sv.min           # MySQL clamps with a warning
        if sv.max is not None and iv > sv.max:
            iv = sv.max
        return iv
    if sv.kind == "float":
        try:
            return float(value)
        except (TypeError, ValueError):
            raise SysVarError(f"{name}: expected float, got {value!r}")
    if sv.kind == "enum":
        s = str(value).upper().replace("_", "-")
        for opt in sv.options:
            if s == opt.upper() or str(value).lower() == opt.lower():
                return opt
        raise SysVarError(
            f"{name}: must be one of {', '.join(sv.options)}")
    return str(value)


def defaults() -> dict[str, Any]:
    return {v.name: v.default for v in _VARS}


__all__ = ["SysVar", "REGISTRY", "SysVarError", "validate_set", "defaults"]
