"""System-variable registry: scopes, types, defaults, validation.

Reference analog: pkg/sessionctx/variable (sysvar.go + vardef/tidb_vars.go,
~700 vars).  This registry carries the variables this engine actually
honors plus the widely-set compatibility surface; SET validates and
coerces through it, unknown variables are rejected like MySQL's ERROR
1193 (unless prefixed `@@local.`-style passthrough is added later).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

SCOPE_GLOBAL = "global"
SCOPE_SESSION = "session"
SCOPE_BOTH = "both"
SCOPE_NONE = "noop"       # accepted for compatibility, no effect


@dataclass(frozen=True)
class SysVar:
    name: str
    default: Any
    scope: str = SCOPE_BOTH
    kind: str = "int"         # int | bool | float | str | enum
    min: Optional[int] = None
    max: Optional[int] = None
    options: tuple = ()       # enum values
    validator: Optional[Callable] = None


def _v(*args, **kw) -> SysVar:
    return SysVar(*args, **kw)


_VARS = [
    # engine-honored knobs
    _v("tidb_distsql_scan_concurrency", 15, kind="int", min=1, max=256),
    _v("tidb_max_chunk_size", 1024, kind="int", min=32, max=65536),
    _v("tidb_enable_vectorized_expression", 1, kind="bool"),
    _v("tidb_ddl_reorg_worker_cnt", 4, kind="int", min=1, max=128),
    _v("tidb_mem_quota_query", -1, kind="int"),
    _v("tidb_enable_tmp_storage_on_oom", 1, kind="bool"),
    _v("tidb_enable_plan_cache", 1, kind="bool"),
    _v("tidb_gc_life_time_sec", 600, kind="int", min=1),
    _v("tidb_gc_run_interval_sec", 60, kind="int", min=1),
    _v("tidb_ttl_job_interval_sec", 60, kind="int", min=1),
    _v("tidb_auto_analyze_ratio", 0.5, kind="float"),
    _v("tidb_enable_auto_analyze", 1, kind="bool"),
    _v("tidb_txn_mode", "optimistic", kind="enum",
       options=("optimistic", "pessimistic")),
    _v("tidb_slow_log_threshold", 300, kind="int", min=0),
    _v("tidb_resource_group", "default", kind="str"),
    _v("tidb_enable_telemetry", 0, kind="bool", scope=SCOPE_GLOBAL),
    # MySQL compatibility surface (honored where the engine has the
    # concept; stored + reflected otherwise)
    _v("autocommit", 1, kind="bool"),
    _v("sql_mode", "ONLY_FULL_GROUP_BY,STRICT_TRANS_TABLES", kind="str"),
    _v("time_zone", "SYSTEM", kind="str"),
    _v("max_execution_time", 0, kind="int", min=0),
    _v("max_allowed_packet", 67108864, kind="int", min=1024),
    _v("character_set_client", "utf8mb4", kind="str"),
    _v("character_set_connection", "utf8mb4", kind="str"),
    _v("character_set_results", "utf8mb4", kind="str"),
    _v("collation_connection", "utf8mb4_bin", kind="str"),
    _v("default_collation_for_utf8mb4", "utf8mb4_bin", kind="str"),
    _v("transaction_isolation", "REPEATABLE-READ", kind="enum",
       options=("REPEATABLE-READ", "READ-COMMITTED")),
    # pre-8.0 connector/ORM aliases and connect-time compat vars —
    # clients SET these during handshake; they must not error
    _v("tx_isolation", "REPEATABLE-READ", kind="enum",
       options=("REPEATABLE-READ", "READ-COMMITTED")),
    _v("tx_read_only", 0, kind="bool", scope=SCOPE_NONE),
    _v("transaction_read_only", 0, kind="bool", scope=SCOPE_NONE),
    _v("sql_auto_is_null", 0, kind="bool", scope=SCOPE_NONE),
    _v("sql_safe_updates", 0, kind="bool", scope=SCOPE_NONE),
    _v("sql_notes", 1, kind="bool", scope=SCOPE_NONE),
    _v("sql_warnings", 0, kind="bool", scope=SCOPE_NONE),
    _v("sql_log_bin", 1, kind="bool", scope=SCOPE_NONE),
    _v("sql_quote_show_create", 1, kind="bool", scope=SCOPE_NONE),
    _v("character_set_server", "utf8mb4", kind="str"),
    _v("collation_server", "utf8mb4_bin", kind="str"),
    _v("character_set_database", "utf8mb4", kind="str"),
    _v("collation_database", "utf8mb4_bin", kind="str"),
    _v("default_storage_engine", "tpu-columnar", kind="str",
       scope=SCOPE_NONE),
    _v("net_buffer_length", 16384, kind="int", scope=SCOPE_NONE),
    _v("query_cache_size", 0, kind="int", scope=SCOPE_NONE),
    _v("query_cache_type", 0, kind="int", scope=SCOPE_NONE),
    _v("system_time_zone", "UTC", kind="str", scope=SCOPE_GLOBAL),
    _v("sql_require_primary_key", 0, kind="bool", scope=SCOPE_NONE),
    _v("init_connect", "", kind="str", scope=SCOPE_GLOBAL),
    _v("wait_timeout", 28800, kind="int", min=1),
    _v("interactive_timeout", 28800, kind="int", min=1),
    _v("net_write_timeout", 60, kind="int", min=1),
    _v("net_read_timeout", 30, kind="int", min=1),
    _v("lower_case_table_names", 2, kind="int", scope=SCOPE_GLOBAL),
    _v("version_comment", "tidb-tpu", kind="str", scope=SCOPE_GLOBAL),
    _v("port", 4000, kind="int", scope=SCOPE_GLOBAL),
    _v("socket", "", kind="str", scope=SCOPE_GLOBAL),
    _v("datadir", "", kind="str", scope=SCOPE_GLOBAL),
    _v("last_insert_id", 0, kind="int", scope=SCOPE_SESSION),
    _v("auto_increment_increment", 1, kind="int", min=1, max=65535),
    _v("auto_increment_offset", 1, kind="int", min=1, max=65535),
    _v("group_concat_max_len", 1024, kind="int", min=4),
    _v("sql_select_limit", 2 ** 64 - 1, kind="int", min=0),
    _v("foreign_key_checks", 0, kind="bool"),
    _v("unique_checks", 1, kind="bool"),
    _v("innodb_lock_wait_timeout", 50, kind="int", min=1),
    # TiDB-compat knobs accepted as no-ops (reference defines ~700; the
    # ones users commonly SET must not error)
    _v("tidb_enable_async_commit", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_enable_1pc", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_enable_clustered_index", "ON", kind="str", scope=SCOPE_NONE),
    _v("tidb_analyze_version", 2, kind="int", scope=SCOPE_NONE),
    _v("tidb_cost_model_version", 2, kind="int", scope=SCOPE_NONE),
    _v("tidb_partition_prune_mode", "dynamic", kind="str",
       scope=SCOPE_NONE),
    _v("tidb_enable_paging", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_executor_concurrency", 5, kind="int", scope=SCOPE_NONE),
    _v("tidb_hash_join_concurrency", 5, kind="int", scope=SCOPE_NONE),
    _v("tidb_index_lookup_concurrency", 4, kind="int", scope=SCOPE_NONE),
    _v("tidb_build_stats_concurrency", 4, kind="int", scope=SCOPE_NONE),
    _v("tidb_enable_rate_limit_action", 0, kind="bool", scope=SCOPE_NONE),
    _v("tidb_replica_read", "leader", kind="str", scope=SCOPE_NONE),
    _v("tidb_isolation_read_engines", "tpu", kind="str",
       scope=SCOPE_NONE),
    _v("tidb_enable_stmt_summary", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_stmt_summary_max_stmt_count", 3000, kind="int",
       scope=SCOPE_NONE),
    _v("tidb_enable_collect_execution_info", 1, kind="bool",
       scope=SCOPE_NONE),
    _v("tidb_opt_agg_push_down", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_opt_join_reorder_threshold", 12, kind="int",
       scope=SCOPE_NONE),
    _v("tidb_index_join_batch_size", 25000, kind="int", scope=SCOPE_NONE),
    _v("tidb_init_chunk_size", 32, kind="int", scope=SCOPE_NONE),
    _v("tidb_retry_limit", 10, kind="int", scope=SCOPE_NONE),
    _v("tidb_disable_txn_auto_retry", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_constraint_check_in_place", 0, kind="bool",
       scope=SCOPE_NONE),
    _v("tidb_skip_utf8_check", 0, kind="bool", scope=SCOPE_NONE),
    _v("tidb_enable_window_function", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_enable_table_partition", "ON", kind="str", scope=SCOPE_NONE),
    _v("tidb_scatter_region", "", kind="str", scope=SCOPE_NONE),
    _v("tidb_wait_split_region_finish", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_store_batch_size", 4, kind="int", scope=SCOPE_NONE),
    _v("tidb_enable_index_merge", 1, kind="bool", scope=SCOPE_NONE),
    _v("tidb_enable_noop_functions", 0, kind="bool", scope=SCOPE_NONE),
    _v("tidb_row_format_version", 2, kind="int", scope=SCOPE_NONE),
]

REGISTRY: dict[str, SysVar] = {v.name: v for v in _VARS}


class SysVarError(ValueError):
    pass


def validate_set(name: str, value: Any,
                 scope: Optional[str] = None) -> Any:
    """Coerce + validate a SET value; raises SysVarError on unknown
    variable, wrong scope, or out-of-range value.  Returns the canonical
    value.  `scope` is the statement's scope ('global'/'session')."""
    sv = REGISTRY.get(name)
    if sv is None:
        raise SysVarError(f"Unknown system variable {name!r}")
    if scope == "global" and sv.scope == SCOPE_SESSION:
        raise SysVarError(f"{name} is a SESSION variable")
    if scope == "session" and sv.scope == SCOPE_GLOBAL:
        raise SysVarError(
            f"{name} is a GLOBAL variable; use SET GLOBAL")
    if value is None:
        return sv.default          # SET x = DEFAULT
    if sv.kind == "bool":
        if isinstance(value, str):
            up = value.upper()
            if up in ("ON", "TRUE", "1"):
                return 1
            if up in ("OFF", "FALSE", "0"):
                return 0
            raise SysVarError(f"{name}: bad boolean {value!r}")
        return 1 if value else 0
    if sv.kind == "int":
        try:
            iv = int(value)
        except (TypeError, ValueError):
            raise SysVarError(f"{name}: expected integer, got {value!r}")
        if sv.min is not None and iv < sv.min:
            iv = sv.min           # MySQL clamps with a warning
        if sv.max is not None and iv > sv.max:
            iv = sv.max
        return iv
    if sv.kind == "float":
        try:
            return float(value)
        except (TypeError, ValueError):
            raise SysVarError(f"{name}: expected float, got {value!r}")
    if sv.kind == "enum":
        s = str(value).upper().replace("_", "-")
        for opt in sv.options:
            if s == opt.upper() or str(value).lower() == opt.lower():
                return opt
        raise SysVarError(
            f"{name}: must be one of {', '.join(sv.options)}")
    return str(value)


def defaults() -> dict[str, Any]:
    return {v.name: v.default for v in _VARS}


__all__ = ["SysVar", "REGISTRY", "SysVarError", "validate_set", "defaults"]
