"""Row-store IO helpers: encode/decode table rows through the KV engine.

Reference analog: pkg/table/tables AddRecord (encode at write,
tablecodec.go:111) and the cophandler's rowcodec.ChunkDecoder path (decode
straight into columns at read, cop_handler.go:496) — here decode happens
once per columnarization, not per query.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..store.codec import (decode_record_key, decode_row, encode_row,
                           record_key, record_prefix, record_prefix_end)
from ..types import dtypes as dt


def encode_table_row(table_id: int, handle: int, values, types) -> tuple[bytes, bytes]:
    return record_key(table_id, handle), encode_row(values, types)


def scan_table_rows(kv, table_id: int, ts: int,
                    types: Sequence[dt.DataType]) -> tuple[np.ndarray, list]:
    """Full-table snapshot scan -> (handles, python-value rows)."""
    handles = []
    rows = []
    for k, v in kv.scan(record_prefix(table_id), record_prefix_end(table_id), ts):
        _, h = decode_record_key(k)
        handles.append(h)
        rows.append(decode_row(v, types))
    return np.asarray(handles, dtype=np.int64), rows


__all__ = ["encode_table_row", "scan_table_rows"]
