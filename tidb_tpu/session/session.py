"""Session: statement lifecycle.

Reference analog: pkg/session (session.ExecuteStmt, session.go:2112) —
parse -> plan -> execute, returning a RecordSet.  The Domain analog (shared
catalog + mesh + cop client per process) is session.domain.Domain.
"""

from __future__ import annotations

import sys
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from ..executor.physical import ExecContext, ResultChunk
from ..executor.plan import to_physical
from ..parallel.mesh import get_mesh
from ..planner.build import PlanError, build_query
from ..planner.logical import explain_logical
from ..planner.optimize import optimize_plan
from ..sql import ast as A
from ..sql.parser import parse_sql
from ..store.client import CopClient
from ..store.kv import KVError
from ..types import dtypes as dt
from .catalog import (Catalog, CatalogError, TableInfo, plainify,
                      type_from_sql)


@dataclass
class ResultSet:
    """RecordSet analog: column names + decoded python rows."""
    names: list[str] = field(default_factory=list)
    rows: list[tuple] = field(default_factory=list)
    affected: int = 0
    # column dtypes when known (SELECT paths) — the wire protocol layer
    # maps these to MySQL column types; None entries mean "infer"
    dtypes: Optional[list] = None
    last_insert_id: int = 0

    def scalar(self):
        return self.rows[0][0] if self.rows else None


class Domain:
    """Per-process singleton state (pkg/domain analog): catalog + mesh +
    cop client + sysvars."""

    def __init__(self, mesh=None, data_dir: Optional[str] = None,
                 sync: bool = False, keyspace: str = ""):
        from ..stats.handle import StatsHandle
        from ..store.kv import KVStore
        self.keyspace = keyspace     # tenant prefix (pkg/keyspace analog)
        self.catalog = Catalog()
        self.catalog.domain = self          # memtable binding (infoschema)
        # device mesh acquisition is LAZY: resolving jax.devices() under a
        # pending TPU grant blocks for the whole backend-init timeout, so
        # an embedder constructing a Session (or running host-only
        # statements like SELECT 1) must not pay it.  The CopClient
        # resolves the mesh on first device dispatch; Domain.mesh
        # delegates there.  Explicit platform override: set
        # TIDB_TPU_PLATFORM (e.g. "cpu") before importing tidb_tpu, or
        # pass a concrete mesh here.
        self.client = CopClient(mesh if mesh is not None else get_mesh)
        if data_dir is not None:
            # durable mode: WAL-backed native engine + catalog-on-KV, so
            # data, schema, and DDL-job state all survive restart
            import os as _os
            _os.makedirs(data_dir, exist_ok=True)
            self.kv = KVStore(path=_os.path.join(data_dir, "kv"),
                              sync=sync, keyspace=keyspace)
            from .meta import attach
            self.meta = attach(self.catalog, self.kv)
            self.meta.load_catalog(self.catalog)
            # resume table-id allocation above every persisted table so a
            # new table never reuses a live (or dropped) key range
            max_id = 100
            for tables in self.catalog.databases.values():
                for t in tables.values():
                    max_id = max(max_id, t.table_id)
            max_id = max(max_id, self.meta.load_max_dropped_id())
            self._next_table_id = max_id
        else:
            self.kv = KVStore(keyspace=keyspace)  # native C++ MVCC store
            self.meta = None
        self.stats = StatsHandle()   # pkg/statistics/handle analog
        from ..privilege import PrivilegeManager
        self.privileges = PrivilegeManager()   # pkg/privilege Handle analog
        # etcd-style watch plane (domain.go GlobalVarsWatcher analog):
        # durable domains persist channel logs in the shared KV so other
        # processes on the same store observe SET GLOBAL / GRANT without
        # polling system tables; in-memory domains deliver in-process
        from ..utils.watch import WatchHub
        self.watch = WatchHub(self.kv if data_dir is not None else None)
        self.watch.subscribe("sysvar", self._on_sysvar_event)
        self.watch.subscribe("privilege", self._on_privilege_event)
        from ..planner.plan_cache import PlanCache
        self.plan_cache = PlanCache()          # instance plan cache
        self.schema_version = 1                # bumped per DDL transition
        from ..ddl.mdl import MDLRegistry
        self.mdl = MDLRegistry()               # pkg/ddl/mdl analog
        from ..copr.coordinator import Coordinator
        self.coordinator = Coordinator()       # mppcoordmanager analog
        self._ddl = None
        import threading
        self._ddl_mu = threading.Lock()
        # leaf lock for the domain's id allocators (table ids, conn
        # ids): CREATE TABLE and new connections arrive on concurrent
        # statement threads, and a bare += there loses allocations
        self._id_mu = threading.Lock()
        self._sessions = None           # WeakValueDictionary, lazy
        self._next_conn_id = 0
        from ..utils.stmtsummary import StmtSummary
        self.stmt_summary = StmtSummary()   # util/stmtsummary analog
        # copscope flight recorder (obs/): bounded ring of completed
        # statement traces — failed/degraded/quarantined/slow always
        # kept, the rest sampled; served at /trace on the StatusServer
        from ..obs import FlightRecorder
        self.flight_recorder = FlightRecorder()
        # coplace coordination plane (pd/): the Domain's PdCoordinator
        # slot — attached by pd.configure_domain when tidb_tpu_pd = 1
        # (this Domain then models ONE server process of the fleet)
        self.pd = None
        from ..planner.bindinfo import BindManager
        self.bindings = BindManager()       # GLOBAL plan bindings
        if not hasattr(self, "_next_table_id"):   # durable mode recovered it
            self._next_table_id = 100
        from .sysvars import defaults as _sysvar_defaults
        self.sysvars: dict[str, Any] = _sysvar_defaults()
        self._load_global_sysvars()      # durable SET GLOBALs survive restart
        self._on_privilege_event({})     # durable users/grants reload
        from ..utils.resourcegroup import ResourceGroupManager
        self.resource_groups = ResourceGroupManager()
        from .autoid import AutoIDService
        self.autoid = AutoIDService(self.kv)  # pkg/autoid_service analog
        for _tables in self.catalog.databases.values():
            for _t in _tables.values():       # durable-load rebind
                _t._autoid = self.autoid
        from ..extension import registry as _ext_registry
        _ext_registry.setup_domain(self)   # pkg/extension bootstrap point
        # workload repository (util/workloadrepo): periodic snapshots of
        # the statement summary, queryable via
        # information_schema.workload_repo_statements
        self.workload_repo: list = []

    # ---------------- watch plane (etcd-channel analogs) ---------------- #

    _GVAR_PREFIX = b"m\x00gvar\x00"
    _PRIV_KEY = b"m\x00privsnap"

    def set_global_sysvar(self, name: str, value) -> None:
        """SET GLOBAL: apply locally, persist (durable mode), and
        broadcast on the sysvar watch channel."""
        self.sysvars[name] = value
        if self.meta is not None:
            import json as _json
            txn = self.kv.begin()
            txn.put(self._GVAR_PREFIX + name.encode(),
                    _json.dumps(value, default=str).encode())
            txn.commit()
        self.watch.notify("sysvar", {"name": name, "value": value})

    def _load_global_sysvars(self) -> None:
        if getattr(self, "meta", None) is None:
            return
        import json as _json
        pre = self._GVAR_PREFIX
        for k, v in self.kv.scan(pre, pre + b"\xff", self.kv.alloc_ts()):
            try:
                self.sysvars[k[len(pre):].decode()] = _json.loads(v)
            except ValueError:
                pass

    def _on_sysvar_event(self, p: dict) -> None:
        name = p.get("name")
        if name:
            self.sysvars[name] = p.get("value")

    def broadcast_privileges(self) -> None:
        """After GRANT/REVOKE/CREATE USER...: persist the privilege
        snapshot and nudge the watch channel (privilege cache
        invalidation, privileges.Handle update channel analog)."""
        if self.meta is not None:
            txn = self.kv.begin()
            txn.put(self._PRIV_KEY, self.privileges.snapshot().encode())
            txn.commit()
        self.watch.notify("privilege", {})

    def _on_privilege_event(self, p: dict) -> None:
        if self.meta is None:
            return
        blob = self.kv.get(self._PRIV_KEY, self.kv.alloc_ts())
        if blob:
            try:
                self.privileges.load_snapshot(blob.decode())
            except ValueError:
                pass

    @property
    def mesh(self):
        """Device mesh, resolved on first access (see __init__: lazy so
        Session construction never blocks on TPU backend init)."""
        return self.client.mesh

    @mesh.setter
    def mesh(self, value):
        self.client.mesh = value

    @property
    def dxf(self):
        """Lazily-created distributed task framework manager
        (pkg/disttask analog)."""
        m = getattr(self, "_dxf", None)
        if m is None:
            from ..dxf.tasks import manager_for
            m = self._dxf = manager_for(self)
        return m

    @property
    def ddl(self):
        """Lazily-started online-DDL owner (pkg/ddl analog)."""
        if self._ddl is None:
            with self._ddl_mu:
                if self._ddl is None:
                    from ..ddl import DDLExecutor
                    self._ddl = DDLExecutor(self)
        return self._ddl

    def start_background(self):
        """Start the domain's background workers (domain.go:146 Init
        analog): GC, TTL, auto-analyze on the timer framework."""
        if getattr(self, "timers", None) is not None:
            return self.timers
        from ..store.gcworker import GCWorker
        from ..timer import TimerFramework
        from ..ttl import run_ttl_sweep
        life = float(self.sysvars.get("tidb_gc_life_time_sec", 600))
        self.gc_worker = GCWorker(self.kv, life)
        self.timers = TimerFramework()
        self.timers.register(
            "gc", float(self.sysvars.get("tidb_gc_run_interval_sec", 60)),
            self.gc_worker.run_once)
        self.timers.register(
            "ttl", float(self.sysvars.get("tidb_ttl_job_interval_sec", 60)),
            lambda: run_ttl_sweep(self))
        self.timers.register("auto-analyze", 30.0, self._auto_analyze_sweep)
        self.timers.register("workload-repo", 60.0,
                             self.snapshot_workload_repo)
        self.timers.start()
        return self.timers

    def snapshot_workload_repo(self):
        """Workload repository sweep (pkg/util/workloadrepo): persist a
        timestamped snapshot of the statement summary so workload history
        survives summary eviction; bounded ring."""
        import time as _time
        now = _time.time()
        for row in self.stmt_summary.summary_rows():
            self.workload_repo.append((now,) + tuple(row[:5]))
        if len(self.workload_repo) > 50_000:
            del self.workload_repo[:25_000]

    def _auto_analyze_sweep(self):
        """Background auto-analyze (handle/autoanalyze.go worker)."""
        for db, tables in list(self.catalog.databases.items()):
            for tbl in list(tables.values()):
                if self.stats.needs_auto_analyze(tbl):
                    self.stats.analyze_table(tbl)

    def close(self):
        if getattr(self, "timers", None) is not None:
            self.timers.close()
        if self._ddl is not None:
            self._ddl.close()

    def alloc_table_id(self) -> int:
        with self._id_mu:
            self._next_table_id += 1
            return self._next_table_id

    def query_metrics(self):
        """Cached (counter, histogram) pair for the statement hot path."""
        m = getattr(self, "_query_metrics", None)
        if m is None:
            from ..utils.metrics import global_registry
            reg = global_registry()
            m = self._query_metrics = (
                reg.counter("tidb_tpu_query_total", "statements executed",
                            labels=("type",)),
                reg.histogram("tidb_tpu_query_duration_seconds",
                              "statement latency"))
        return m

    def register_session(self, sess) -> int:
        """Connection registry for SHOW PROCESSLIST (server's
        SessionManager analog)."""
        import weakref
        with self._id_mu:
            if self._sessions is None:
                self._sessions = weakref.WeakValueDictionary()
            self._next_conn_id += 1
            self._sessions[self._next_conn_id] = sess
            return self._next_conn_id

    def sessions(self):
        with self._id_mu:
            if self._sessions is None:
                return []
            return sorted(self._sessions.items())


class Session:
    def __init__(self, domain: Optional[Domain] = None, db: str = "test",
                 user: str = "root"):
        self.domain = domain or Domain()
        self.conn_id = self.domain.register_session(self)
        self.db = db
        self.user = user
        self.vars: dict[str, Any] = {}
        self.user_vars: dict[str, Any] = {}      # SET @x = ...
        from ..planner.bindinfo import BindManager
        self.bindings = BindManager()            # SESSION plan bindings
        self.prepared: dict[str, tuple[str, int]] = {}  # name -> (sql, n_params)
        self.txn = None              # active explicit transaction
        self._txn_tables: set = set()
        self._cur_sql: Optional[str] = None      # text of the running stmt
        # session-scoped temporary tables: (db, name) -> TableInfo;
        # installed as a catalog overlay per statement (catalog.TEMP_TABLES)
        self.temp_tables: dict = {}
        import threading as _th
        self._kill_event = _th.Event()   # KILL QUERY sets; stmt start clears

    def close(self) -> None:
        """Drop session state that outlives no session: temporary tables
        (their KV rows truncate so the shared store does not leak)."""
        for t in list(self.temp_tables.values()):
            try:
                t.truncate()
            except Exception:
                pass
        self.temp_tables.clear()

    # ------------------------------------------------------------- #

    def execute(self, sql: str) -> ResultSet:
        qcnt, qdur = self.domain.query_metrics()
        out = ResultSet()
        for stmt in parse_sql(sql):
            t0 = time.perf_counter_ns()
            span = getattr(stmt, "text_span", None)
            text = sql[span[0]:span[1]].strip() if span else sql
            self._cur_sql = text
            # plan bindings: a matching digest donates its hints
            # (bindinfo BindHandle match; session shadows global).
            # EXPLAIN shows the bound plan too.
            target, btext = stmt, text
            if isinstance(stmt, (A.Explain, A.TraceStmt)):
                target = stmt.stmt
                import re as _re
                btext = _re.sub(r"(?is)^\s*(explain(\s+analyze)?|trace)\s+",
                                "", text)
            if isinstance(target, A.SelectStmt) and not target.hints:
                b = (self.bindings.match(btext)
                     or self.domain.bindings.match(btext))
                if b is not None:
                    target.hints = list(b.hints)
                    # bound statements bypass the plan cache: a cached
                    # unhinted plan must not shadow the binding (and
                    # vice versa after DROP BINDING)
                    self._cur_sql = None
            from ..plugin import registry as _plugins
            _plugins.fire("on_stmt_begin", self, text)
            cpu0 = time.thread_time_ns()    # Top-SQL CPU attribution
            self._last_plan_text = ""
            # coordinator registration + cancellation scope
            # (mppcoordmanager + KILL): the kill event travels to every
            # dispatch/chunk checkpoint via contextvar
            from ..copr.coordinator import KILL_EVENT, QUERY_HANDLE
            from ..planner.build import SESSION_INFO
            from ..sched.task import SCHED_GROUP
            self._kill_event.clear()
            handle = self.domain.coordinator.begin(self.conn_id, text)
            ktok = KILL_EVENT.set(self._kill_event)
            htok = QUERY_HANDLE.set(handle)
            # tag device cop tasks with the statement's resource group so
            # the admission scheduler orders them weighted-fair AND can
            # enforce the group's RU bucket at the drain (rc/): the live
            # group object rides the contextvar so every CopTask carries
            # its bucket without a registry lookup
            gname = self.vars.get("tidb_resource_group") or \
                self.domain.sysvars.get("tidb_resource_group", "default")
            grp = self.domain.resource_groups.get(gname)
            gtok = SCHED_GROUP.set(
                (gname, grp.sched_weight if grp is not None else 8.0,
                 grp))
            def _getvar(name, scope=""):
                if scope == "global":
                    return self.domain.sysvars.get(name)
                merged = {**self.domain.sysvars, **self.vars}
                from .sysvars import REGISTRY
                if name in merged:
                    return merged[name]
                ent = REGISTRY.get(name)
                return ent.default if ent is not None else None

            # copscope statement trace (obs/): one span tree per
            # statement, rooted here; the TraceCtx contextvar carries it
            # into every dispatch so scheduler threads stitch their
            # spans under it.  Off (tidb_tpu_trace=0) = no tree, no
            # contextvar, zero recording anywhere.
            from ..obs import trace as _obs_trace
            _merged_obs = {**self.domain.sysvars, **self.vars}
            trace_tree = None
            trace_root = None
            obs_tok = None
            if _flag_on(_merged_obs, "tidb_tpu_trace", True):
                trace_tree = _obs_trace.SpanTree(sql=text,
                                                 conn_id=self.conn_id)
                trace_root = trace_tree.begin("session.ExecuteStmt")
                obs_tok = _obs_trace.TRACE_CTX.set(
                    _obs_trace.TraceCtx(trace_tree, trace_root))
            stok = SESSION_INFO.set({
                "db": self.db, "user": self.user,
                "conn_id": self.conn_id,
                "last_insert_id": getattr(self, "last_insert_id", 0),
                "row_count": getattr(self, "_row_count", -1),
                "found_rows": getattr(self, "_found_rows", 0),
                "getvar": _getvar,
                "getuservar":
                    lambda name, _s="": self.user_vars.get(name)})
            from ..planner.build import SEQUENCE_RESOLVER
            from .catalog import TEMP_TABLES
            qtok = SEQUENCE_RESOLVER.set(
                lambda nm: self.domain.catalog.get_sequence(self.db, nm))
            ttok = TEMP_TABLES.set(self.temp_tables)
            try:
                out = self._exec_stmt(stmt)
            except Exception as e:
                qcnt.inc(type="error")
                _plugins.fire("on_stmt_end", self, text, str(e),
                              (time.perf_counter_ns() - t0) / 1e9, 0)
                raise
            finally:
                TEMP_TABLES.reset(ttok)
                SEQUENCE_RESOLVER.reset(qtok)
                SESSION_INFO.reset(stok)
                SCHED_GROUP.reset(gtok)
                QUERY_HANDLE.reset(htok)
                KILL_EVENT.reset(ktok)
                if obs_tok is not None:
                    _obs_trace.TRACE_CTX.reset(obs_tok)
                    trace_tree.end(trace_root)
                    trace_tree.latency_ms = \
                        (time.perf_counter_ns() - t0) / 1e6
                    if handle.degraded:
                        trace_tree.flag("degraded")
                    if handle.sched_retried:
                        trace_tree.flag("retried")
                    if sys.exc_info()[0] is not None:
                        # failed statements ALWAYS reach the recorder —
                        # the success path records after the slow-log
                        # verdict below
                        trace_tree.flag("failed")
                        self.domain.flight_recorder.record(trace_tree)
                self.domain.coordinator.end(self.conn_id)
                self._cur_sql = None
            dt_ns = time.perf_counter_ns() - t0
            qcnt.inc(type=type(stmt).__name__)
            qdur.observe(dt_ns / 1e9)
            # slow-log threshold is live sysvar state (session scope
            # shadows global), plumbed session -> Domain on each record
            try:
                self.domain.stmt_summary.slow_threshold_ms = float(
                    _merged_obs.get("tidb_tpu_slow_threshold_ms", 300)
                    or 0)
                self.domain.flight_recorder.sample_every = max(int(
                    _merged_obs.get("tidb_tpu_trace_sample", 16) or 16),
                    1)
            except (TypeError, ValueError):
                pass
            was_slow = self.domain.stmt_summary.record(
                text, dt_ns, len(out.rows),
                cpu_ns=time.thread_time_ns() - cpu0,
                plan_text=self._last_plan_text,
                sched_wait_ns=handle.sched_wait_ns,
                rus=handle.sched_rus,
                compile_ns=handle.compile_ns,
                sched_tasks=handle.sched_tasks,
                fused=handle.sched_fused,
                retried=handle.sched_retried,
                trace_id=trace_tree.trace_id
                if trace_tree is not None else "")
            if trace_tree is not None:
                if was_slow:
                    trace_tree.flag("slow")
                self.domain.flight_recorder.record(trace_tree)
            try:
                # runaway KILL must fire before the success audit hook:
                # a killed statement is an error to the client
                self._charge_resource_group(stmt, out, dt_ns / 1e9,
                                            handle)
            except Exception as e:
                _plugins.fire("on_stmt_end", self, text, str(e),
                              dt_ns / 1e9, 0)
                raise
            _plugins.fire("on_stmt_end", self, text, None, dt_ns / 1e9,
                          len(out.rows) + out.affected)
            # ROW_COUNT()/FOUND_ROWS() state (executor/adapter.go
            # affectedRows analogs): ROW_COUNT is -1 for result-set
            # statements, FOUND_ROWS is the last result-set size
            if out.names:
                self._found_rows = len(out.rows)
                self._row_count = -1
            else:
                self._row_count = out.affected
        return out

    def _exec_kill(self, stmt) -> ResultSet:
        """KILL [QUERY|CONNECTION] <id>: set the victim's kill event;
        its next cancellation checkpoint (dispatch loop, retry/backoff
        iteration, streamed batch, host chunk boundary) raises
        QueryInterrupted — conn.go killConn + mppcoordmanager cancel."""
        sessions = dict(self.domain.sessions())
        target = sessions.get(stmt.conn_id)
        if target is None:
            raise PlanError(f"Unknown thread id: {stmt.conn_id}")
        from ..privilege import PrivilegeError
        priv = getattr(self.domain, "privileges", None)
        is_super = priv is None or priv.check(self.user, "SUPER")
        if target.user != self.user and not is_super:
            raise PrivilegeError(
                "You are not owner of thread "
                f"{stmt.conn_id} (SUPER required)")
        target._kill_event.set()
        return ResultSet()

    def _charge_resource_group(self, stmt, out: ResultSet,
                               elapsed_sec: float, handle=None) -> None:
        """Statement-boundary resource accounting (rc/controller).
        Device work was priced from its LaunchCost and debited at the
        scheduler drain BEFORE launching (handle.sched_rus reports it);
        host-only statements still charge the row-count RU here.  The
        runaway watch covers queue+execution wall time with actions
        KILL / COOLDOWN / SWITCH_GROUP.  ACTION=KILL only raises for
        statements that did not mutate data: the watch runs
        post-execution, and killing an already-committed DML would
        report failure for persisted writes (the reference aborts
        mid-execution; read-only raise is the safe analog)."""
        gname = self.vars.get("tidb_resource_group") or \
            self.domain.sysvars.get("tidb_resource_group", "default")
        group = self.domain.resource_groups.get(gname)
        if group is None or (group.ru_per_sec <= 0
                             and not group.exec_elapsed_sec):
            return
        from ..rc.controller import charge_statement
        from ..rc.runaway import RunawayError
        rc_on = bool(int(self.domain.sysvars.get(
            "tidb_tpu_rc_enable", 1) or 0))
        device_rus = handle.sched_rus if (
            handle is not None and rc_on) else 0.0
        sched_wait = (handle.sched_wait_ns / 1e9
                      if handle is not None else 0.0)
        try:
            charge_statement(group, len(out.rows) + out.affected,
                             elapsed_sec, sched_wait_sec=sched_wait,
                             device_rus=device_rus,
                             manager=self.domain.resource_groups,
                             sql=handle.sql if handle is not None else "")
        except RunawayError:
            if out.affected:
                return           # counted as runaway, writes stand
            raise

    def must_query(self, sql: str) -> list[tuple]:
        """testkit MustQuery analog."""
        return self.execute(sql).rows

    # ------------------------------------------------------------- #

    # statements that implicitly commit an open transaction first
    _IMPLICIT_COMMIT = ("CreateTable", "DropTable", "CreateIndex",
                        "DropIndex", "AlterTable", "TruncateTable",
                        "CreateDatabase", "DropDatabase", "CreateUser",
                        "AlterUser", "DropUser", "GrantStmt", "RevokeStmt")

    _DDL_STMTS = ("CreateTable", "DropTable", "CreateIndex", "DropIndex",
                  "AlterTable", "TruncateTable", "CreateDatabase",
                  "DropDatabase", "CreateSequence", "DropSequence",
                  "CreateView", "DropView")

    def _exec_stmt(self, stmt: A.Node) -> ResultSet:
        self._check_privileges(stmt)
        if (self.txn is not None
                and type(stmt).__name__ in self._IMPLICIT_COMMIT):
            # MySQL semantics: DDL implicitly commits the open transaction
            self._finish_txn(commit=True)
        if type(stmt).__name__ in self._DDL_STMTS:
            # schema plugin kind (plugin/spi.go SchemaManifest
            # OnSchemaChange): fire only AFTER the DDL succeeded, with
            # the statement's resolved database
            from ..plugin import registry as _plugins
            out = self._dispatch_stmt(stmt)
            if isinstance(stmt, (A.CreateDatabase, A.DropDatabase)):
                ev_dbs = [stmt.name]        # the db IS the target
            elif isinstance(stmt, A.DropTable) and stmt.names:
                # one event per distinct database a multi-table DROP
                # touches, so per-schema plugins observe every change
                ev_dbs = list(dict.fromkeys(
                    db or self.db for db, _nm in stmt.names))
            else:
                ev_dbs = [getattr(stmt, "db", None) or self.db]
            for ev_db in ev_dbs:
                _plugins.fire("on_ddl", type(stmt).__name__, ev_db,
                              self._cur_sql or "")
            return out
        return self._dispatch_stmt(stmt)

    def _dispatch_stmt(self, stmt: A.Node) -> ResultSet:
        if isinstance(stmt, (A.CreateUser, A.AlterUser, A.DropUser,
                             A.GrantStmt, A.RevokeStmt, A.FlushStmt)):
            return self._exec_user_admin(stmt)
        if isinstance(stmt, (A.SelectStmt, A.SetOpStmt)):
            return self._exec_select(stmt)
        if isinstance(stmt, A.CreateBinding):
            return self._exec_create_binding(stmt)
        if isinstance(stmt, A.CreateResourceGroup):
            try:
                if stmt.replace:      # ALTER: merge named options only
                    self.domain.resource_groups.alter(
                        stmt.name, stmt.ru_per_sec, stmt.burstable,
                        stmt.exec_elapsed_sec, stmt.action,
                        priority=stmt.priority,
                        switch_target=stmt.switch_target)
                else:
                    self.domain.resource_groups.create(
                        stmt.name, stmt.ru_per_sec, stmt.burstable,
                        stmt.exec_elapsed_sec, stmt.action,
                        if_not_exists=stmt.if_not_exists,
                        priority=stmt.priority,
                        switch_target=stmt.switch_target)
            except ValueError as e:
                raise PlanError(str(e))
            return ResultSet()
        if isinstance(stmt, A.DropResourceGroup):
            try:
                self.domain.resource_groups.drop(stmt.name, stmt.if_exists)
            except ValueError as e:
                raise PlanError(str(e))
            return ResultSet()
        if isinstance(stmt, A.SplitTable):
            tbl = self.domain.catalog.get_table(getattr(stmt, 'db', None) or self.db, stmt.table)
            tbl.split_regions(stmt.regions)
            return ResultSet(affected=stmt.regions)
        if isinstance(stmt, A.SetResourceGroup):
            if self.domain.resource_groups.get(stmt.name) is None:
                raise PlanError(f"unknown resource group {stmt.name!r}")
            self.vars["tidb_resource_group"] = stmt.name
            return ResultSet()
        if isinstance(stmt, A.DropBinding):
            mgr = (self.domain.bindings if stmt.scope == "global"
                   else self.bindings)
            return ResultSet(affected=int(mgr.drop(stmt.original_sql)))
        if isinstance(stmt, A.Explain):
            return self._exec_explain(stmt)
        if isinstance(stmt, A.TraceStmt):
            return self._exec_trace(stmt)
        if isinstance(stmt, A.CreateTable):
            return self._exec_create_table(stmt)
        if isinstance(stmt, A.CreateSequence):
            from .catalog import SequenceInfo
            seq = SequenceInfo(stmt.name, self.db, start=stmt.start,
                               increment=stmt.increment,
                               min_value=stmt.min_value,
                               max_value=stmt.max_value, cache=stmt.cache,
                               cycle=stmt.cycle, kv=self.domain.kv)
            self.domain.catalog.create_sequence(self.db, seq,
                                                stmt.if_not_exists)
            return ResultSet()
        if isinstance(stmt, A.DropSequence):
            self.domain.catalog.drop_sequence(self.db, stmt.name,
                                              stmt.if_exists)
            return ResultSet()
        if isinstance(stmt, A.DropTable):
            # names are (db|None, name) tuples; session temporary tables
            # shadow permanent ones and drop without touching the shared
            # catalog
            def split(n):
                db, nm = n
                return (db or self.db, nm)

            remaining = []
            for n in stmt.names:
                db, nm = split(n)
                t = self.temp_tables.pop((db, nm), None)
                if t is not None:
                    try:
                        t.truncate()
                    except Exception:
                        pass
                else:
                    remaining.append(n)
            if stmt.temporary:
                # DROP TEMPORARY TABLE must NEVER touch a permanent table
                # (MySQL semantics: unknown temp names are errors unless
                # IF EXISTS)
                if remaining and not stmt.if_exists:
                    miss = ".".join(p for p in remaining[0] if p)
                    raise CatalogError(
                        f"unknown temporary table {miss!r}")
                return ResultSet()
            # qualified (db, name) pairs: a same-named table in another
            # database must not suppress the FK guard
            dropping = {split(n) for n in remaining}
            for n in remaining:
                db, nm = split(n)
                refs = [
                    (t.name, fk.column)
                    for t in self.domain.catalog.databases
                    .get(db, {}).values()
                    for fk in getattr(t, "foreign_keys", [])
                    if fk.ref_table == nm and (db, t.name) not in dropping]
                if refs:
                    raise CatalogError(
                        f"Cannot drop table {nm!r}: referenced by a "
                        f"foreign key constraint ({refs[0][0]}."
                        f"{refs[0][1]})")
                self.domain.catalog.drop_table(db, nm, stmt.if_exists)
            return ResultSet()
        if isinstance(stmt, A.CreateView):
            from .catalog import ViewInfo
            self.domain.catalog.create_view(
                self.db, ViewInfo(stmt.name, list(stmt.columns),
                                  stmt.select_sql), stmt.or_replace)
            return ResultSet()
        if isinstance(stmt, A.DropView):
            for n in stmt.names:
                self.domain.catalog.drop_view(self.db, n, stmt.if_exists)
            return ResultSet()
        if isinstance(stmt, A.CreateDatabase):
            self.domain.catalog.create_database(stmt.name, stmt.if_not_exists)
            return ResultSet()
        if isinstance(stmt, A.DropDatabase):
            self.domain.catalog.drop_database(stmt.name, stmt.if_exists)
            return ResultSet()
        if isinstance(stmt, A.UseDatabase):
            from ..infoschema import is_system_db
            if stmt.name not in self.domain.catalog.databases \
                    and not is_system_db(stmt.name):
                raise CatalogError(f"unknown database {stmt.name!r}")
            self.db = stmt.name
            return ResultSet()
        if isinstance(stmt, A.CreateIndex):
            self.domain.catalog.get_table(getattr(stmt, 'db', None) or self.db, stmt.table)  # exist check
            ddl_db = getattr(stmt, 'db', None) or self.db
            tmp = self.temp_tables.get((ddl_db, stmt.table))
            if tmp is not None:
                # session temp tables never reach the (session-agnostic)
                # DDL owner thread: index synchronously, no online ladder
                tmp.create_index(stmt.name, list(stmt.columns),
                                 stmt.unique, stmt.if_not_exists)
                return ResultSet()
            self.domain.ddl.run_job("add index", ddl_db, stmt.table, {
                "name": stmt.name, "columns": list(stmt.columns),
                "unique": stmt.unique, "if_not_exists": stmt.if_not_exists})
            return ResultSet()
        if isinstance(stmt, A.DropIndex):
            self.domain.catalog.get_table(getattr(stmt, 'db', None) or self.db, stmt.table)
            ddl_db = getattr(stmt, 'db', None) or self.db
            tmp = self.temp_tables.get((ddl_db, stmt.table))
            if tmp is not None:
                ix = tmp.index_by_name(stmt.name)
                if ix is not None:
                    tmp.indexes.remove(ix)
                elif not stmt.if_exists:
                    raise CatalogError(f"unknown index {stmt.name!r}")
                return ResultSet()
            self.domain.ddl.run_job("drop index", ddl_db, stmt.table, {
                "name": stmt.name, "if_exists": stmt.if_exists})
            return ResultSet()
        if isinstance(stmt, A.AlterTable):
            return self._exec_alter(stmt)
        if isinstance(stmt, A.Insert):
            return self._dml_atomic(self._exec_insert, stmt)
        if isinstance(stmt, A.LoadData):
            return self._dml_atomic(self._exec_load_data, stmt)
        if isinstance(stmt, A.Update):
            return self._dml_atomic(self._exec_update, stmt)
        if isinstance(stmt, A.Delete):
            return self._dml_atomic(self._exec_delete, stmt)
        if isinstance(stmt, A.TruncateTable):
            n = self.domain.catalog.get_table(self.db, stmt.name).truncate()
            return ResultSet(affected=n)
        if isinstance(stmt, A.ShowStmt):
            return self._exec_show(stmt)
        if isinstance(stmt, A.SetStmt):
            from .sysvars import SysVarError, validate_set
            for name, val in stmt.assignments:
                # full expression eval: SET x = -1 / DEFAULT / 2*1024 all
                # work (reference: variable assignment evals an expression)
                v = (val.value if isinstance(val, A.Lit)
                     else self._eval_scalar(val))
                try:
                    v = validate_set(name.lower(), v, scope=stmt.scope)
                except SysVarError as e:
                    raise PlanError(str(e))
                if stmt.scope == "global":
                    # persist + broadcast on the watch plane
                    self.domain.set_global_sysvar(name.lower(), v)
                else:
                    self.vars[name.lower()] = v
            for name, val in stmt.user_vars:
                self.user_vars[name.lower()] = self._eval_scalar(val)
            return ResultSet()
        if isinstance(stmt, A.PlanReplayerDump):
            return self._exec_plan_replayer(stmt)
        if isinstance(stmt, A.KillStmt):
            return self._exec_kill(stmt)
        if isinstance(stmt, A.TxnStmt):
            return self._exec_txn(stmt)
        if isinstance(stmt, A.PrepareStmt):
            from ..sql.bind import count_placeholders, strip_placeholders
            parse_sql(strip_placeholders(stmt.sql))  # validate syntax now
            self.prepared[stmt.name] = (stmt.sql,
                                        count_placeholders(stmt.sql))
            return ResultSet()
        if isinstance(stmt, A.ExecutePrepared):
            return self._exec_prepared(stmt)
        if isinstance(stmt, A.DeallocateStmt):
            if stmt.name not in self.prepared:
                raise PlanError(f"unknown prepared statement {stmt.name!r}")
            del self.prepared[stmt.name]
            return ResultSet()
        if isinstance(stmt, A.AnalyzeTable):
            tbl = self.domain.catalog.get_table(self.db, stmt.name)
            self.domain.stats.analyze_table(
                tbl, columns=stmt.columns or None,
                sample_rate=stmt.sample_rate,
                predicate_only=stmt.predicate_columns)
            return ResultSet()
        if isinstance(stmt, A.AdminStmt):
            return self._exec_admin(stmt)
        raise PlanError(f"unsupported statement {type(stmt).__name__}")

    # ---------------- privileges ---------------- #

    # statement class -> required privilege on its target tables
    _STMT_PRIVS = {
        "Insert": "INSERT", "Update": "UPDATE", "Delete": "DELETE",
        "TruncateTable": "DROP", "CreateTable": "CREATE",
        "DropTable": "DROP", "CreateIndex": "INDEX", "DropIndex": "INDEX",
        "AlterTable": "ALTER", "CreateDatabase": "CREATE",
        "DropDatabase": "DROP", "AnalyzeTable": "INSERT",
    }

    def _check_privileges(self, stmt: A.Node) -> None:
        """Statement-level privilege verification (reference:
        planner/core/planbuilder.go visitInfo + privilege.Handle
        RequestVerification)."""
        priv = self.domain.privileges
        if isinstance(stmt, (A.SelectStmt, A.SetOpStmt)):
            for db, tbl in self._referenced_tables(stmt):
                priv.require(self.user, "SELECT", db or self.db, tbl)
            return
        if isinstance(stmt, (A.Explain, A.TraceStmt)):
            return self._check_privileges(stmt.stmt)
        if isinstance(stmt, (A.CreateUser, A.AlterUser, A.DropUser)):
            return priv.require(self.user, "CREATE USER")
        if isinstance(stmt, (A.GrantStmt, A.RevokeStmt)):
            # MySQL requires the granter to hold the privileges granted;
            # unqualified table level ('' db) means the current database
            db = "" if stmt.db == "*" else (stmt.db or self.db)
            table = "" if stmt.table == "*" else stmt.table
            for p in stmt.privs:
                priv.require(self.user, p if p != "ALL" else "SUPER",
                             db, table)
            return
        if isinstance(stmt, A.AdminStmt):
            # reference gates ADMIN behind SUPER (planbuilder.go)
            return priv.require(self.user, "SUPER")
        if isinstance(stmt, A.UseDatabase):
            from ..privilege.manager import PrivilegeError
            if not priv.has_db_access(self.user, stmt.name):
                raise PrivilegeError(
                    f"Access denied for user '{self.user}' to database "
                    f"'{stmt.name}'")
            return
        if isinstance(stmt, A.ShowStmt) and stmt.kind == "grants":
            if stmt.target:
                user = stmt.target.partition("@")[0]
                if user != self.user:
                    return priv.require(self.user, "SUPER")
            return
        kind = type(stmt).__name__
        need = self._STMT_PRIVS.get(kind)
        if need is None:
            return
        if isinstance(stmt, A.Insert) and stmt.select is not None:
            self._check_privileges(stmt.select)
        if isinstance(stmt, (A.Update, A.Delete)):
            # reading columns (WHERE clause, or non-literal SET exprs)
            # additionally requires SELECT (planbuilder visitInfo)
            reads = getattr(stmt, "where", None) is not None or any(
                not isinstance(e, A.Lit)
                for _c, e in getattr(stmt, "assignments", ()))
            if reads:
                priv.require(self.user, "SELECT",
                             getattr(stmt, "db", None) or self.db,
                             getattr(stmt, "table", ""))
        target = getattr(stmt, "table", None) or getattr(stmt, "name", "")
        if isinstance(stmt, A.DropTable):
            for db, nm in stmt.names:
                priv.require(self.user, need, db or self.db, nm)
            return
        if isinstance(stmt, (A.CreateDatabase, A.DropDatabase)):
            return priv.require(self.user, need, stmt.name)
        # db-qualified DDL/DML (CREATE INDEX db.t, ALTER TABLE db.t, ...)
        # must check the QUALIFIED database, not the session one
        db = getattr(stmt, "db", None) or self.db
        priv.require(self.user, need, db, target)

    def _referenced_tables(self, node: A.Node) -> list[tuple]:
        """All (db, table) names a query reads — walks FROM clauses,
        joins, subqueries, CTE bodies (skipping CTE self-references)."""
        out: list[tuple] = []
        cte_names: set = set()

        def walk(n):
            if n is None or not isinstance(n, A.Node):
                return
            if isinstance(n, A.TableName):
                if n.name not in cte_names:
                    out.append((n.db, n.name))
                return
            if isinstance(n, A.CTE):
                cte_names.add(n.name)
            # register CTE names BEFORE visiting FROM clauses that
            # reference them (dataclass field order puts from_ first)
            for cte in getattr(n, "ctes", ()):
                walk(cte)
            for f in getattr(n, "__dataclass_fields__", {}):
                if f == "ctes":
                    continue
                v = getattr(n, f, None)
                if isinstance(v, A.Node):
                    walk(v)
                elif isinstance(v, (list, tuple)):
                    for x in v:
                        if isinstance(x, A.Node):
                            walk(x)
                        elif isinstance(x, tuple):
                            for y in x:
                                if isinstance(y, A.Node):
                                    walk(y)
        walk(node)
        return out

    def _exec_user_admin(self, stmt: A.Node) -> ResultSet:
        priv = self.domain.privileges
        if isinstance(stmt, A.CreateUser):
            for spec, pwd in stmt.users:
                priv.create_user(spec.user, spec.host, pwd,
                                 stmt.if_not_exists)
        elif isinstance(stmt, A.AlterUser):
            for spec, pwd in stmt.users:
                priv.alter_user(spec.user, spec.host, pwd)
        elif isinstance(stmt, A.DropUser):
            for spec in stmt.users:
                priv.drop_user(spec.user, spec.host, stmt.if_exists)
        elif isinstance(stmt, A.GrantStmt):
            db = self.db if stmt.db == "" else stmt.db
            for spec in stmt.users:
                priv.grant(stmt.privs, db, stmt.table, spec.user, spec.host)
        elif isinstance(stmt, A.RevokeStmt):
            db = self.db if stmt.db == "" else stmt.db
            for spec in stmt.users:
                priv.revoke(stmt.privs, db, stmt.table, spec.user, spec.host)
        # FLUSH PRIVILEGES: no-op — the manager is authoritative
        if not isinstance(stmt, A.FlushStmt):
            # persist + broadcast the updated grant tables (watch plane)
            self.domain.broadcast_privileges()
        return ResultSet()

    def _note_predicate_columns(self, plan) -> None:
        """Track filtered columns for ANALYZE ... PREDICATE COLUMNS
        (column_stats_usage.go analog) and schedule an async stats load
        for planned-against tables with no stats yet (handle/syncload)."""
        from ..planner.logical import DataSource, LogicalSelection
        from ..planner.optimize import referenced_columns
        stats = self.domain.stats

        def walk(p):
            if isinstance(p, LogicalSelection) \
                    and isinstance(p.children[0], DataSource):
                ds = p.children[0]
                refs = set()
                for c in p.conditions:
                    refs |= referenced_columns(c)
                names = [ds.schema.cols[i].name for i in refs
                         if i < len(ds.schema.cols)]
                stats.note_predicate_columns(ds.table, names)
            if isinstance(p, DataSource) \
                    and not getattr(p.table, "is_memtable", False):
                stats.request_load(p.table)
            for c in getattr(p, "children", []):
                walk(c)

        try:
            walk(plan)
        except Exception:
            pass     # tracking is advisory, never a planning failure

    def _eval_scalar(self, expr: A.Node):
        """Evaluate a scalar expression (SET @x = ...); subqueries inside
        the expression still pass privilege checks."""
        if isinstance(expr, A.Lit):
            return self._literal_value(expr)
        sel = A.SelectStmt(items=[A.SelectItem(expr)])
        self._check_privileges(sel)
        return self._exec_select(sel).scalar()

    def _exec_prepared(self, stmt: A.ExecutePrepared) -> ResultSet:
        from ..sql.bind import bind_placeholders
        ent = self.prepared.get(stmt.name)
        if ent is None:
            raise PlanError(f"unknown prepared statement {stmt.name!r}")
        sql, n_params = ent
        if len(stmt.using) != n_params:
            raise PlanError(
                f"prepared statement {stmt.name!r} needs {n_params} "
                f"parameters, got {len(stmt.using)}")
        params = []
        for uv in stmt.using:
            if uv.lower() not in self.user_vars:
                raise PlanError(f"user variable @{uv} is not set")
            params.append(self.user_vars[uv.lower()])
        return self.execute(bind_placeholders(sql, params))

    # ------------------------------------------------------------- #

    def _plan_select(self, stmt, cache_sql: Optional[str] = None):
        from ..planner.plan_cache import PlanCacheEntry, table_fingerprint
        from ..planner.ranger import apply_index_paths
        cache = self.domain.plan_cache
        merged = {**self.domain.sysvars, **self.vars}
        # knob application precedes the plan-cache lookup: a cached plan
        # must reflect the current planner knobs
        def _knob(name):
            v = merged.get(name)
            return -1 if v is None or v == "" else int(v)
        bm0 = _knob("tidb_tpu_broadcast_build_max_rows")
        if bm0 >= 0:
            from ..executor import plan as _planmod0
            _planmod0.BROADCAST_BUILD_MAX_ROWS = bm0
        dg0 = _knob("tidb_tpu_dense_broadcast_max_groups")
        if dg0 >= 0:
            from ..copr import exec as _execmod0
            _execmod0.DENSE_BROADCAST_MAX_GROUPS = dg0
        use_cache = (cache_sql is not None
                     and _flag_on(merged, "tidb_enable_plan_cache"))
        if use_cache:
            e = cache.get(cache_sql, self.db, merged, self.domain.catalog)
            if e is not None:
                return e.built, e.phys
        # uncorrelated scalar subqueries evaluate eagerly at plan time
        # (EvalSubqueryFirstRow analog); plans that did so are not cached
        # since the folded constant goes stale with the data
        from ..planner import build as _build_mod
        ran_subquery: list = []
        token = _build_mod.SUBQUERY_EXECUTOR.set(
            lambda ast: self._eval_scalar_subquery(ast, ran_subquery))
        token2 = _build_mod.PLAN_TAINTS.set(ran_subquery)
        try:
            built = build_query(stmt, self.domain.catalog, self.db)
        finally:
            _build_mod.SUBQUERY_EXECUTOR.reset(token)
            _build_mod.PLAN_TAINTS.reset(token2)
        self._maybe_auto_analyze(built.plan)
        plan = optimize_plan(built.plan)
        self._note_predicate_columns(plan)
        if _flag_on(merged, "tidb_opt_skew_distinct_agg", default=False):
            from ..planner.rules import rewrite_skew_distinct
            plan = rewrite_skew_distinct(plan)
        if _flag_on(merged, "tidb_enable_cascades_planner", default=False):
            from ..planner.cascades import cascades_optimize
            plan = cascades_optimize(plan, self.domain.stats)
        else:
            from ..planner.join_reorder import reorder_joins
            plan = reorder_joins(plan, self.domain.stats)
        plan = apply_index_paths(plan, self.domain.stats)
        from ..executor.plan import STATS_HANDLE
        tok = STATS_HANDLE.set(self.domain.stats)
        try:
            phys = to_physical(plan)
        finally:
            STATS_HANDLE.reset(tok)
        try:       # Top-SQL plan digest attribution (util/topsql)
            self._last_plan_text = phys.explain()
        except Exception:
            pass
        # static plan-contract gate (analysis/contracts): reject a plan
        # whose operator contracts disagree BEFORE any trace/compile —
        # the typed-IR verification seam of compiler-first engines.
        # PlanContractError is a PlanError, so it surfaces like any
        # planner rejection.  tidb_tpu_verify_plan=0 opts out.
        if _flag_on(merged, "tidb_tpu_verify_plan", default=True):
            from ..analysis.contracts import verify_plan
            verify_plan(phys)
            # sharding-flow pass (analysis/shardflow): layouts and
            # collectives of every device program flowed against the
            # mesh's typed-link topology (declared host view included)
            # — implicit reshards, unknown axes, coordinator-routed
            # merges, and DCI blow-ups reject HERE, pre-trace, like
            # any other contract violation
            from ..analysis.shardflow import verify_plan_sharding
            verify_plan_sharding(phys, self._topology(merged))
            # value-range pass (analysis/valueflow): every device lane
            # flowed over stats-seeded integer intervals — silent int64
            # wraps, unprovable SUM fences, f32 precision cliffs and
            # div pre-scale escapes reject HERE, pre-trace; each
            # verified digest lands in the proof registry the sched
            # admission seam replays
            from ..analysis.valueflow import verify_plan_values
            verify_plan_values(phys, self.domain.stats)
            phys._contract_ok = True
        use_cache = use_cache and not ran_subquery
        if use_cache and _plan_cacheable(phys):
            keys = {}
            for db, name in self._referenced_tables(stmt):
                tdb = db or self.db
                try:
                    tbl = self.domain.catalog.get_table(tdb, name)
                except Exception:
                    continue
                keys[(tdb, name)] = table_fingerprint(tbl)
            cache.put(cache_sql, self.db, merged,
                      PlanCacheEntry(built, phys, keys))
        return built, phys

    def _eval_scalar_subquery(self, sub_ast, ran: list):
        """Plan + execute an uncorrelated scalar subquery and fold its
        result to a Const (reference: EvalSubqueryFirstRow,
        planner/core/expression_rewriter.go)."""
        from ..expr import builders as B
        from ..expr.ir import Const
        from ..planner.ranger import apply_index_paths
        ran.append(True)
        built = build_query(sub_ast, self.domain.catalog, self.db)
        if len(built.plan.schema) != 1:
            raise PlanError("scalar subquery must return one column")
        from ..executor.plan import STATS_HANDLE
        from ..planner.join_reorder import reorder_joins
        plan = optimize_plan(built.plan)
        plan = reorder_joins(plan, self.domain.stats)
        plan = apply_index_paths(plan, self.domain.stats)
        tok = STATS_HANDLE.set(self.domain.stats)
        try:
            phys = to_physical(plan)
        finally:
            STATS_HANDLE.reset(tok)
        chunk = phys.execute(self._exec_ctx())
        if chunk.num_rows > 1:
            raise PlanError("scalar subquery returned more than one row")
        if chunk.num_rows == 0:
            return B.lit(None)
        col = chunk.columns[0]
        if not col.validity[0]:
            return B.lit(None)
        if col.dtype.is_string:
            # decode to a plain string literal so downstream lowering maps
            # it into the OUTER table's dictionary space
            return Const(col.dtype.with_nullable(False), col.to_python()[0])
        v = col.data[0]
        v = v.item() if hasattr(v, "item") else v
        return Const(col.dtype.with_nullable(False), v)

    def _maybe_auto_analyze(self, plan):
        """Refresh stale stats before planning (handle/autoanalyze.go
        analog, run inline instead of in a background worker)."""
        merged = {**self.domain.sysvars, **self.vars}
        if not _flag_on(merged, "tidb_enable_auto_analyze"):
            return
        from ..planner.logical import DataSource
        stack, seen = [plan], set()
        while stack:
            p = stack.pop()
            stack.extend(p.children)
            if isinstance(p, DataSource) and id(p.table) not in seen:
                seen.add(id(p.table))
                if self.domain.stats.needs_auto_analyze(p.table):
                    self.domain.stats.analyze_table(p.table)

    def _exec_ctx(self) -> ExecContext:
        """Statement-scoped execution context with a fresh memory tracker
        rooted at tidb_mem_quota_query (util/memory Tracker analog)."""
        from ..utils.memory import Tracker
        merged = {**self.domain.sysvars, **self.vars}
        quota = int(merged.get("tidb_mem_quota_query", 1 << 30))
        if quota <= 0:
            quota = -1       # TiDB semantics: 0/negative = unlimited
        client = self.domain.client
        # engine knobs ride sysvars (the reference's every-perf-knob-is-a-
        # sysvar discipline, vardef/tidb_vars.go)
        v0 = merged.get("tidb_tpu_device_mem_cap")
        cap = -1 if v0 is None or v0 == "" else int(v0)
        if cap >= 0:
            client.device_mem_cap = cap
        v1 = merged.get("tidb_tpu_result_cache_entries")
        rc = -1 if v1 is None or v1 == "" else int(v1)
        if rc >= 0:
            client._result_cache_cap = rc
        # device admission scheduler knobs (sched/): 0 queue depth
        # bypasses admission entirely
        v2 = merged.get("tidb_tpu_sched_queue_depth")
        qd = -1 if v2 is None or v2 == "" else int(v2)
        if qd >= 0:
            client.sched_queue_depth = qd
        v3 = merged.get("tidb_tpu_sched_max_coalesce")
        mc = -1 if v3 is None or v3 == "" else int(v3)
        if mc > 0:
            client.sched_max_coalesce = mc
        v4 = merged.get("tidb_tpu_sched_fusion")
        if v4 is not None and v4 != "":
            client.sched_fusion = bool(int(v4))
        v5 = merged.get("tidb_tpu_sched_window_us")
        if v5 is not None and v5 != "" and int(v5) >= -1:
            client.sched_window_us = int(v5)
        v6 = merged.get("tidb_tpu_sched_hbm_budget")
        if v6 is not None and v6 != "" and int(v6) >= -1:
            client.sched_hbm_budget = int(v6)
        # resource control plane (rc/): drain-side RU enforcement on/off
        # and the bounded overdraft (-1 = engine default)
        v7 = merged.get("tidb_tpu_rc_enable")
        if v7 is not None and v7 != "":
            client.rc_enable = bool(int(v7))
        v8 = merged.get("tidb_tpu_rc_overdraft_ru")
        if v8 is not None and v8 != "" and int(v8) >= 0:
            client.rc_overdraft = float(v8)
        # launch supervision (faultline): host-oracle fallback for
        # quarantined digests, and the fault-injection plane spec
        v9 = merged.get("tidb_tpu_sched_host_fallback")
        if v9 is not None and v9 != "":
            client.host_fallback = bool(int(v9))
        v10 = merged.get("tidb_tpu_faults")
        if v10 is not None:
            from ..faults import install_spec
            install_spec(str(v10))
        # copmeter closed-loop calibration (analysis/calibrate): on by
        # default; off leaves the static cost model untouched
        v14 = merged.get("tidb_tpu_cost_calibration")
        if v14 is not None and v14 != "":
            client.calibration = bool(int(v14))
        # copgauge live HBM ledger + measured watermarks + roofline
        # (obs/hbm): off = the static memory model byte-identical to
        # the pre-copgauge engine
        v17 = merged.get("tidb_tpu_hbm_ledger")
        if v17 is not None and v17 != "":
            client.hbm_ledger = bool(int(v17))
        # copsan runtime lock sanitizer (utils/locksan): arming only
        # instruments locks allocated after the flip, so operators set
        # it before the domain's threaded machinery is built
        v20 = merged.get("tidb_tpu_lock_sanitizer")
        if v20 is not None and v20 != "":
            from ..utils import locksan
            if bool(int(v20)):
                locksan.arm()
            else:
                locksan.disarm()
        # shardflow topology view (parallel/topology): declared host
        # factorization for per-link transfer classification; -1/unset
        # derives from device process indices
        v16 = merged.get("tidb_tpu_topology_hosts")
        if v16 is not None and v16 != "":
            from ..parallel.topology import set_host_view
            set_host_view(None if int(v16) <= 0 else int(v16))
        # SCATTER radix-partition Pallas gate (copr/radix): auto = the
        # hand-written Pallas kernels on TPU backends, the XLA lowering
        # elsewhere; on = Pallas everywhere (interpret mode off-TPU —
        # the tier-1 kernel-path seam); off = XLA everywhere
        v15 = merged.get("tidb_tpu_radix_pallas")
        if v15 is not None and v15 != "":
            from ..copr import radix as _radix
            _radix.set_pallas_mode(str(v15))
        # copforge AOT compile cache (compilecache/): enable/dir/pool
        # knobs, then the idempotent boot warm-start hook — the first
        # statement after a cache dir lands kicks the background
        # manifest replay through the admission queue at LOW priority
        v11 = merged.get("tidb_tpu_compile_cache")
        v12 = merged.get("tidb_tpu_compile_cache_dir")
        v13 = merged.get("tidb_tpu_compile_warm_pool")
        from ..compilecache import configure as cc_configure
        from ..compilecache import maybe_warm_start
        cc_configure(
            enable=None if v11 is None or v11 == "" else bool(int(v11)),
            cache_dir=None if v12 is None or v12 == "" else str(v12),
            pool_bytes=None if v13 is None or v13 == "" or int(v13) < 0
            else int(v13))
        maybe_warm_start(client)
        # coplace coordination plane (pd/): attach/detach the Domain's
        # coordinator from the sysvars, arm the scheduler-side hooks,
        # and tick the statement-driven heartbeat (internally
        # throttled; a degraded store costs one failed grant per tick,
        # never a statement)
        v18 = merged.get("tidb_tpu_pd")
        v19 = merged.get("tidb_tpu_pd_dir")
        pd_on = bool(int(v18)) if v18 is not None and v18 != "" \
            else False
        client.pd_enable = pd_on
        from ..pd import configure_domain
        coord = configure_domain(
            self.domain, pd_on,
            "" if v19 is None else str(v19))
        if coord is not None:
            coord.tick()
        return ExecContext(client, merged,
                           mem_tracker=Tracker("query", quota))

    def _exec_select(self, stmt) -> ResultSet:
        cache_sql = self._cur_sql
        self._cur_sql = None  # inner selects (INSERT..SELECT) don't cache
        if getattr(stmt, "for_update", False):
            self._lock_for_update(stmt)
        built, phys = self._plan_select(stmt, cache_sql)
        ctx = self._exec_ctx()
        chunk = phys.execute(ctx)
        n_out = len(built.output_names)
        cols = chunk.columns[:n_out]  # trim hidden ORDER BY columns
        rows = list(zip(*[c.to_python() for c in cols])) if cols else []
        return ResultSet(built.output_names, rows,
                         dtypes=[c.dtype for c in cols])

    def _exec_explain(self, stmt: A.Explain) -> ResultSet:
        if not isinstance(stmt.stmt, (A.SelectStmt, A.SetOpStmt)):
            raise PlanError("EXPLAIN supports SELECT only")
        built, phys = self._plan_select(stmt.stmt)
        if stmt.analyze:
            from ..utils.execdetails import (RuntimeStatsColl,
                                             explain_analyze_text,
                                             instrument_tree)
            coll = RuntimeStatsColl()
            instrument_tree(phys, coll)
            ctx = self._exec_ctx()
            phys.execute(ctx)
            return ResultSet(["operator", "actRows", "time", "loops"],
                             explain_analyze_text(phys, coll))
        text = phys.explain()
        rows = [(line,) for line in text.split("\n")]
        if getattr(phys, "_contract_ok", False):
            # the static gate verified this plan's operator contracts
            # (analysis/contracts.verify_plan) — surfaced like the
            # reference's EXPLAIN diagnostics footer
            rows.append(("contract: ok",))
            footer = self._cost_footer(phys)
            if footer is not None:
                rows.append((footer,))
                transfer = self._transfer_footer(phys)
                if transfer is not None:
                    rows.append((transfer,))
                calib = self._calibration_footer(phys)
                if calib is not None:
                    rows.append((calib,))
            strat = self._agg_strategy_footer(phys)
            if strat is not None:
                rows.append((strat,))
        return ResultSet(["plan"], rows)

    def _cost_footer(self, phys) -> Optional[str]:
        """EXPLAIN cost footer from the static shape/memory model
        (analysis/copcost): estimated peak device bytes, host<->device
        transfer, and the padded/live ratio of the scan inputs.  None
        for host-only plans or shapes the model cannot walk — the
        footer must never break EXPLAIN."""
        try:
            from ..analysis.copcost import format_bytes, plan_cost
            mesh = self.domain.client._mesh     # never force device init
            n_dev = int(mesh.devices.size) if mesh is not None else 8
            cost = plan_cost(phys, n_dev)
            if not cost.transfer_bytes:
                return None
            footer = (f"est. device bytes: "
                      f"{format_bytes(cost.peak_hbm_bytes)} peak / "
                      f"{format_bytes(cost.transfer_bytes)} transfer, "
                      f"padding {cost.padding_waste:.1f}x")
            # buffer-lifetime verdict (analysis/lifetime): how many
            # input buffers / bytes a donation-eligible launch aliases
            # into outputs on the streamed (launch-unique) path
            from ..analysis.lifetime import plan_donation
            bufs, saved = plan_donation(phys, n_dev)
            if bufs:
                footer += (f", donate: {bufs} bufs / "
                           f"{format_bytes(saved)}")
            return footer
        except (AttributeError, TypeError, KeyError, ValueError,
                ImportError):
            return None

    def _topology(self, merged=None):
        """The mesh's typed-link topology under the declared host view
        (tidb_tpu_topology_hosts) — the analysis seam the plan-path
        shardflow verification and the EXPLAIN transfer footer share.
        Never forces device init."""
        from ..parallel.topology import set_host_view, topology_for
        if merged is None:
            merged = {**self.domain.sysvars, **self.vars}
        v = merged.get("tidb_tpu_topology_hosts")
        if v is not None and v != "":
            set_host_view(None if int(v) <= 0 else int(v))
        mesh = self.domain.client._mesh
        n_dev = int(mesh.devices.size) if mesh is not None else 8
        return topology_for(mesh, n_devices=n_dev)

    def _transfer_footer(self, phys) -> Optional[str]:
        """EXPLAIN per-link transfer footer (analysis/shardflow):
        ``transfer: X ici / Y dci`` — the plan's statically-classified
        collective bytes under the declared host view
        (tidb_tpu_topology_hosts).  None for plans without collective
        traffic; must never break EXPLAIN."""
        try:
            from ..analysis.copcost import format_bytes
            from ..analysis.shardflow import plan_transfer
            bd = plan_transfer(phys, self._topology())
            if not bd.collective:
                return None
            return (f"transfer: {format_bytes(bd.ici)} ici / "
                    f"{format_bytes(bd.dci)} dci")
        except (AttributeError, TypeError, KeyError, ValueError,
                ImportError):
            return None

    def _calibration_footer(self, phys) -> Optional[str]:
        """EXPLAIN ``cost:`` verdict (copmeter, analysis/calibrate):
        ``cost: calibrated (err N%)`` when the plan's device program
        has measured corrections, ``cost: static`` otherwise (or when
        tidb_tpu_cost_calibration is off).  None for plans without a
        device dag; must never break EXPLAIN."""
        try:
            from ..copr import dag as Dg
            dag = None
            stack = [phys]
            while stack and dag is None:
                op = stack.pop()
                d = getattr(op, "dag", None)
                if isinstance(d, Dg.CopNode):
                    dag = d
                    break
                for c in getattr(op, "children", []) or []:
                    if c is not None:
                        stack.append(c)
            if dag is None:
                return None
            merged = {**self.domain.sysvars, **self.vars}
            v = merged.get("tidb_tpu_cost_calibration")
            enabled = True if v is None or v == "" else bool(int(v))
            if not enabled:
                return "cost: static"
            from ..analysis.calibrate import correction_store
            from ..analysis.compilekey import stable_digest
            ent = correction_store().get(stable_digest(dag))
            if ent is None or not ent.samples:
                return "cost: static"
            return f"cost: calibrated (err {ent.err * 100:.0f}%)"
        except (AttributeError, TypeError, ValueError, ImportError):
            return None

    def _agg_strategy_footer(self, phys) -> Optional[str]:
        """EXPLAIN ``agg strategy:`` tag: which device group-by strategy
        the pushed aggregation takes, with its capacity knob — dense
        (domain product), sort (regrow capacity), or segment (radix
        bucket space).  None for scalar/host-only plans; must never
        break EXPLAIN."""
        try:
            from ..copr import dag as Dg
            stack = [phys]
            while stack:
                op = stack.pop()
                dag = getattr(op, "dag", None)
                if dag is None:
                    dag = getattr(getattr(op, "spec", None), "top", None)
                if isinstance(dag, Dg.Aggregation) and dag.group_by:
                    if dag.strategy is Dg.GroupStrategy.SCATTER:
                        return (f"agg strategy: scatter "
                                f"({dag.num_buckets} buckets, "
                                f"{Dg.radix_passes(dag.num_buckets)} "
                                "passes)")
                    if dag.strategy is Dg.GroupStrategy.SEGMENT:
                        return (f"agg strategy: segment "
                                f"({dag.num_buckets} buckets)")
                    if dag.strategy is Dg.GroupStrategy.SORT:
                        return (f"agg strategy: sort (capacity "
                                f"{dag.group_capacity or 'auto'})")
                    return (f"agg strategy: dense "
                            f"({dag.num_groups} groups)")
                for c in getattr(op, "children", []) or []:
                    if c is not None:
                        stack.append(c)
        except (AttributeError, TypeError):
            return None
        return None

    def _exec_plan_replayer(self, stmt: A.PlanReplayerDump) -> ResultSet:
        """PLAN REPLAYER DUMP EXPLAIN <sql> (executor/plan_replayer.go):
        writes a zip bundle — sql, plan text, CREATE TABLE statements for
        every referenced table, stats JSON, session/global sysvars,
        engine version — and returns its token filename."""
        import json as _json
        import os
        import tempfile
        import time as _time
        import zipfile

        parsed = parse_sql(stmt.sql)[0]
        if not isinstance(parsed, (A.SelectStmt, A.SetOpStmt)):
            raise PlanError("PLAN REPLAYER DUMP supports SELECT only")
        built, phys = self._plan_select(parsed)
        plan_text = phys.explain()
        tables = []
        for db, name in self._referenced_tables(parsed):
            try:
                tables.append(self.domain.catalog.get_table(
                    db or self.db, name))
            except Exception:
                continue
        stats_blob = {}
        for t in tables:
            st = self.domain.stats.get(t)
            if st is None:
                continue
            stats_blob[t.name] = {
                "count": st.count,
                "modify_count": st.modify_count,
                "columns": {cn: {"ndv": cs.ndv,
                                 "null_count": cs.null_count}
                            for cn, cs in st.cols.items()},
            }
        out_dir = os.path.join(tempfile.gettempdir(), "tidb_tpu_replayer")
        os.makedirs(out_dir, exist_ok=True)
        token = f"replayer_{int(_time.time() * 1000):x}.zip"
        path = os.path.join(out_dir, token)
        with zipfile.ZipFile(path, "w") as z:
            z.writestr("sql/sql.sql", stmt.sql)
            z.writestr("plan.txt", plan_text)
            z.writestr("schema/schema.sql", "\n\n".join(
                _render_create_table(t) for t in tables))
            z.writestr("stats.json", _json.dumps(stats_blob, indent=1))
            z.writestr("variables.json", _json.dumps(
                {**self.domain.sysvars, **self.vars}, default=str,
                indent=1))
            z.writestr("meta.txt", "tidb-tpu 0.2.0")
        return ResultSet(["File_token"], [(token,)])

    def _exec_trace(self, stmt: A.TraceStmt) -> ResultSet:
        """TRACE <stmt>: span tree of the statement's phases
        (executor/trace.go analog)."""
        from ..utils.tracing import Tracer
        tracer = Tracer()
        with tracer.region("session.ExecuteStmt"):
            if isinstance(stmt.stmt, (A.SelectStmt, A.SetOpStmt)):
                with tracer.region("planner.Optimize"):
                    built, phys = self._plan_select(stmt.stmt)
                with tracer.region("executor.Run"):
                    ctx = self._exec_ctx()
                    phys.execute(ctx)
            else:
                with tracer.region("executor.Run"):
                    self._exec_stmt(stmt.stmt)
        return ResultSet(["operation", "startTS_us", "duration_us"],
                         tracer.rows())

    def _exec_txn(self, stmt: A.TxnStmt) -> ResultSet:
        """Explicit transactions over the native MVCC store.

        Round-1 scope: INSERTs inside BEGIN...COMMIT buffer in one
        percolator txn (atomic, conflict-checked 2PC at COMMIT); reads see
        the last committed snapshot (union-scan of own writes comes with
        the distsql-over-KV path); UPDATE/DELETE inside a txn autocommit."""
        if stmt.kind == "begin":
            if self.txn is not None:
                self._finish_txn(commit=True)
            merged = {**self.domain.sysvars, **self.vars}
            mode = stmt.mode or str(merged.get("tidb_txn_mode", "optimistic"))
            self.txn = self.domain.kv.begin(
                pessimistic=(mode == "pessimistic"))
            if self.txn.pessimistic:
                self.txn.lock_wait_ms = int(
                    merged.get("innodb_lock_wait_timeout", 3)) * 1000
            self._txn_tables = set()
            self._txn_table_vers = {}
            self._txn_schema_ver = self.domain.schema_version
        elif stmt.kind == "commit":
            self._finish_txn(commit=True)
        else:  # rollback
            self._finish_txn(commit=False)
        return ResultSet()

    def _finish_txn(self, commit: bool):
        """End the active txn; on commit failure roll back and clear state
        so the session isn't wedged (review finding)."""
        txn, self.txn = self.txn, None
        if txn is None:
            return
        try:
            if not commit:
                txn.rollback()
                self._txn_tables = set()
                return
            # commit-time schema validation, PER WRITTEN TABLE (kv.go:533
            # SchemaVar / domain SchemaValidator): F1 adjacent states are
            # mutually compatible, so ONE version step on a written table
            # is fine (MDL drains before the second step); a >=2 gap
            # means this txn straddled two transitions (MDL timeout path)
            # and could miss index entries -> abort with retry semantics
            stale = [t.name for t, ver in
                     getattr(self, "_txn_table_vers", {}).items()
                     if t.schema_ver > ver + 1]
            if stale:
                txn.rollback()
                self._txn_tables = set()
                raise CatalogError(
                    "Information schema is changed during the execution "
                    f"of the statement (DDL on {', '.join(stale)} ran "
                    "concurrently); transaction rolled back, please retry")
            try:
                txn.commit()
                self._invalidate_txn_tables()
            except Exception:
                txn.rollback()
                self._txn_tables = set()
                raise
        finally:
            self.domain.mdl.release_all(id(txn))
            self._txn_table_vers = {}

    def _invalidate_txn_tables(self):
        for t in self._txn_tables:
            t._invalidate()
        self._txn_tables = set()

    def _txn_note_table(self, tbl) -> None:
        """Record a table the open txn writes: registers the metadata
        lock (pkg/ddl/mdl) at the schema version this txn first saw, so a
        concurrent DDL transition drains this txn before advancing, and
        pins the version for the per-table commit check."""
        self._txn_tables.add(tbl)
        if not hasattr(self, "_txn_table_vers") \
                or self._txn_table_vers is None:
            self._txn_table_vers = {}
        if tbl not in self._txn_table_vers:
            self._txn_table_vers[tbl] = tbl.schema_ver
            self.domain.mdl.acquire(tbl.table_id, id(self.txn),
                                     tbl.schema_ver)

    def _exec_create_table(self, stmt: A.CreateTable) -> ResultSet:
        db = stmt.db or self.db
        names, types = [], []
        auto_inc = None
        for c in stmt.columns:
            names.append(c.name)
            not_null = c.not_null or c.name in stmt.primary_key
            types.append(type_from_sql(c.type_name, c.prec, c.scale, not_null,
                                       c.collation, c.members))
            if c.auto_increment:
                auto_inc = c.name
        tbl = TableInfo(stmt.name, names, types, stmt.primary_key, auto_inc,
                        table_id=self.domain.alloc_table_id(),
                        kv=self.domain.kv,
                        n_shards=int({**self.domain.sysvars, **self.vars}
                                     .get("tidb_tpu_shard_count", 8) or 8))
        tbl._autoid = self.domain.autoid
        if stmt.ttl is not None:
            if stmt.ttl.column not in names:
                raise CatalogError(
                    f"unknown TTL column {stmt.ttl.column!r}")
            t = types[names.index(stmt.ttl.column)]
            if t.kind not in (dt.TypeKind.DATE, dt.TypeKind.DATETIME):
                raise CatalogError("TTL column must be DATE or DATETIME")
            tbl.ttl_col = stmt.ttl.column
            tbl.ttl_interval_sec = stmt.ttl.interval_sec
            tbl.ttl_enable = stmt.ttl.enable
        if stmt.partition is not None:
            pc = stmt.partition.column
            if pc not in names:
                raise CatalogError(f"unknown partition column {pc!r}")
            t = types[names.index(pc)]
            if t.kind not in (dt.TypeKind.INT64, dt.TypeKind.UINT64,
                              dt.TypeKind.DATE, dt.TypeKind.DATETIME):
                raise CatalogError(
                    "partition column must be integer or date typed")
            tbl.partition = stmt.partition
        if stmt.foreign_keys:
            # integer keys only: FK comparison runs over raw int64 column
            # data; date/string values are not canonical at check time
            ok_kinds = (dt.TypeKind.INT64, dt.TypeKind.UINT64)
            for fk in stmt.foreign_keys:
                if fk.column not in names:
                    raise CatalogError(f"unknown FK column {fk.column!r}")
                if types[names.index(fk.column)].kind not in ok_kinds:
                    raise CatalogError(
                        "FOREIGN KEY columns must be integer typed")
                parent = tbl if fk.ref_table == stmt.name else \
                    self.domain.catalog.get_table(db, fk.ref_table)
                if fk.ref_column not in parent.col_names:
                    raise CatalogError(
                        f"unknown referenced column "
                        f"{fk.ref_table}.{fk.ref_column}")
                pk = parent.col_types[
                    parent.col_names.index(fk.ref_column)].kind
                if pk not in ok_kinds:
                    raise CatalogError(
                        "FOREIGN KEY must reference an integer column "
                        f"({fk.ref_table}.{fk.ref_column} is {pk.value})")
            tbl.foreign_keys = list(stmt.foreign_keys)
            cat = self.domain.catalog
            tbl._fk_resolver = (
                lambda nm, _t=tbl, _db=db, _cat=cat:
                _t if nm == _t.name else _cat.get_table(_db, nm))
        gen = [(c.name, c.generated, c.generated_stored)
               for c in stmt.columns if c.generated is not None]
        if gen:
            self._bind_generated_columns(tbl, stmt, gen)
        if stmt.temporary:
            # session-scoped: registered in the session overlay, never in
            # the shared catalog (reference: temptable / local temporary
            # table infoschema overlay)
            key = (db, stmt.name)
            if key in self.temp_tables:
                if stmt.if_not_exists:
                    return ResultSet()
                raise CatalogError(f"table {stmt.name!r} exists")
            self.temp_tables[key] = tbl
            created = tbl
        else:
            self.domain.catalog.create_table(db, tbl,
                                             stmt.if_not_exists)
            created = self.domain.catalog.get_table(db, stmt.name)
        if created is tbl:
            # implicit PRIMARY index gives PK uniqueness + the point-get
            # path (the reference's clustered-handle role, tablecodec)
            if stmt.primary_key:
                tbl.create_index("PRIMARY", list(stmt.primary_key), True)
            for i, (iname, cols, uniq) in enumerate(stmt.indexes):
                tbl.create_index(iname or f"idx_{i+1}_" + "_".join(cols),
                                 cols, uniq)
        return ResultSet()

    def _bind_generated_columns(self, tbl, stmt: A.CreateTable, gen) -> None:
        """Compile generated-column expressions over the table schema and
        attach them for the write paths (reference: table/column.go
        generated column eval; computed at write for STORED and — as a
        simplification — VIRTUAL alike, which is observationally
        equivalent for the deterministic expressions MySQL requires)."""
        from ..planner.build import ExprBuilder
        from ..planner.logical import Schema, SchemaCol
        schema = Schema([SchemaCol(n, t)
                         for n, t in zip(tbl.col_names, tbl.col_types)])
        eb = ExprBuilder(schema)

        def refs(e):
            from ..expr.ir import ColumnRef, Func
            if isinstance(e, ColumnRef):
                yield e
            elif isinstance(e, Func):
                for a in e.args:
                    yield from refs(a)

        compiled = []
        gen_names = {name for name, _a, _s in gen}
        for name, ast_expr, _stored in gen:
            ir = eb.build(ast_expr)
            for r in refs(ir):
                if tbl.col_names[r.index] in gen_names \
                        and tbl.col_names.index(name) <= r.index:
                    raise CatalogError(
                        "generated column may only reference earlier "
                        "generated columns")
                if tbl.auto_inc_col is not None \
                        and tbl.col_names[r.index] == tbl.auto_inc_col:
                    # MySQL ER_GENERATED_COLUMN_REF_AUTO_INC: the value is
                    # allocated after generation would run
                    raise CatalogError(
                        "generated column cannot refer to an "
                        "auto-increment column")
            compiled.append((tbl.col_names.index(name), ir))
        tbl.generated_cols = compiled

    def _exec_alter(self, stmt: A.AlterTable) -> ResultSet:
        tbl = self.domain.catalog.get_table(getattr(stmt, 'db', None) or self.db, stmt.table)
        # session temp tables never reach the DDL owner thread (its
        # catalog lookups cannot see the session overlay)
        ddl_db = getattr(stmt, 'db', None) or self.db
        is_temp = self.temp_tables.get((ddl_db, stmt.table)) is tbl
        for act in stmt.actions:
            if act[0] == "add_index":
                _, iname, cols, uniq = act
                if is_temp:
                    tbl.create_index(iname or "idx_" + "_".join(cols),
                                     list(cols), uniq)
                    continue
                self.domain.ddl.run_job("add index", ddl_db, tbl.name, {
                    "name": iname or "idx_" + "_".join(cols),
                    "columns": list(cols), "unique": uniq})
            elif act[0] == "drop_index":
                if is_temp:
                    ix = tbl.index_by_name(act[1])
                    if ix is None:
                        raise CatalogError(f"unknown index {act[1]!r}")
                    tbl.indexes.remove(ix)
                    continue
                self.domain.ddl.run_job("drop index", ddl_db, tbl.name,
                                        {"name": act[1]})
            elif act[0] == "add_column":
                self._alter_add_column(tbl, act[1])
            elif act[0] == "drop_column":
                self._alter_drop_column(tbl, act[1])
            else:
                raise PlanError(f"unsupported ALTER action {act[0]}")
        tbl._persist_meta()   # catalog-on-KV: column changes survive
        return ResultSet()

    def _alter_add_column(self, tbl, cd) -> None:
        if cd.name in tbl.col_names:
            raise CatalogError(f"column {cd.name!r} already exists")
        t = type_from_sql(cd.type_name, cd.prec, cd.scale, cd.not_null,
                          cd.collation, cd.members)
        default = None
        if cd.default is not None:
            default = self._literal_value(cd.default)
        snap = tbl.snapshot()
        if cd.not_null and default is None and snap.num_rows:
            raise CatalogError(
                f"cannot add NOT NULL column {cd.name!r} without a DEFAULT "
                "to a non-empty table")
        rows = [tuple(plainify(v) for v in r)
                for r in zip(*[c.to_python() for c in snap.columns])] \
            if snap.num_rows else []
        new_rows = [r + (default,) for r in rows]
        self._rewrite_with_schema(tbl, tbl.col_names + [cd.name],
                                  tbl.col_types + [t], new_rows)

    def _alter_drop_column(self, tbl, name: str) -> None:
        if name not in tbl.col_names:
            raise CatalogError(f"unknown column {name!r}")
        for ix in tbl.indexes:
            if name in ix.columns:
                raise CatalogError(
                    f"cannot drop column {name!r}: used by index {ix.name!r}")
        i = tbl.col_names.index(name)
        snap = tbl.snapshot()
        rows = [tuple(plainify(v) for j, v in enumerate(r) if j != i)
                for r in zip(*[c.to_python() for c in snap.columns])] \
            if snap.num_rows else []
        self._rewrite_with_schema(tbl,
                                  [n for n in tbl.col_names if n != name],
                                  [t for j, t in enumerate(tbl.col_types)
                                   if j != i], rows)

    def _rewrite_with_schema(self, tbl, names, types, rows) -> None:
        """Swap in a new column schema + rewritten rows; restore the old
        schema if the rewrite fails so catalog and storage never diverge."""
        old_names, old_types = tbl.col_names, tbl.col_types
        tbl.col_names, tbl.col_types = list(names), list(types)
        try:
            tbl.replace_columns(_rows_to_columns(tbl, rows))
        except Exception:
            tbl.col_names, tbl.col_types = old_names, old_types
            tbl._invalidate()
            raise

    def _exec_insert(self, stmt: A.Insert) -> ResultSet:
        tbl = self.domain.catalog.get_table(getattr(stmt, 'db', None) or self.db, stmt.table)
        if stmt.select is not None:
            res = self._exec_select(stmt.select)
            rows = [tuple(plainify(v) for v in r) for r in res.rows]
        else:
            rows = [tuple(self._literal_value(v) for v in r)
                    for r in stmt.rows]
        gen_names = {tbl.col_names[i]
                     for i, _ in getattr(tbl, "generated_cols", [])}
        if stmt.columns:
            for n in stmt.columns:
                if n in gen_names:
                    raise PlanError(
                        f"The value specified for generated column {n!r} "
                        "in table is not allowed")
            idx = {n: i for i, n in enumerate(stmt.columns)}
            full = []
            for r in rows:
                if len(r) != len(stmt.columns):
                    raise PlanError("column count mismatch")
                full.append(tuple(
                    r[idx[n]] if n in idx else None for n in tbl.col_names))
            rows = full
        elif gen_names:
            # positional inserts must leave generated slots NULL/DEFAULT
            gidx = [i for i, _ in tbl.generated_cols]
            for r in rows:
                for i in gidx:
                    if i < len(r) and r[i] is not None:
                        raise PlanError(
                            "The value specified for generated column "
                            f"{tbl.col_names[i]!r} in table is not allowed")
        if stmt.on_dup:
            write = lambda txn: self._insert_on_dup(tbl, rows,
                                                    stmt.on_dup, txn)
        elif stmt.replace:
            write = lambda txn: tbl.replace_rows(rows, txn=txn)
        elif stmt.ignore:
            write = lambda txn: self._insert_ignore(tbl, rows, txn)
        else:
            write = lambda txn: tbl.insert_rows(rows, txn=txn)
        if self.txn is None:
            n = self._retry_write_conflict(lambda: write(None))
        else:
            n = write(self.txn)
        if self.txn is not None:
            self._txn_note_table(tbl)
        if tbl.auto_inc_col is not None and n:
            # MySQL LAST_INSERT_ID(): first auto-generated id of the last
            # batch; the table counter sits past the batch after insert
            self.last_insert_id = max(int(tbl._auto_inc) - n + 1, 1)
        self.domain.stats.note_modify(tbl, n)
        return ResultSet(affected=n)

    def _exec_create_binding(self, stmt: A.CreateBinding) -> ResultSet:
        """CREATE [GLOBAL|SESSION] BINDING: both statements must parse,
        normalize to the same digest, and the bind side must carry hints."""
        from ..utils.stmtsummary import normalize_sql
        orig = parse_sql(stmt.original_sql)
        bind = parse_sql(stmt.bind_sql)
        if len(orig) != 1 or len(bind) != 1 \
                or not isinstance(bind[0], A.SelectStmt):
            raise PlanError("BINDING takes single SELECT statements")
        if normalize_sql(stmt.original_sql) != normalize_sql(stmt.bind_sql):
            raise PlanError(
                "binding statement digest differs from the original")
        if not bind[0].hints:
            raise PlanError("binding statement carries no optimizer hints")
        mgr = (self.domain.bindings if stmt.scope == "global"
               else self.bindings)
        mgr.create(stmt.original_sql, stmt.bind_sql, bind[0].hints)
        return ResultSet()

    def _dml_atomic(self, handler, stmt) -> ResultSet:
        """MySQL statement atomicity inside an explicit transaction: stage
        the DML against a membuffer savepoint so a mid-statement failure
        (late duplicate key, type error on a later row) unwinds THIS
        statement's writes only, leaving the txn usable (the reference's
        StmtCommit/StmtRollback membuffer staging)."""
        if self.txn is None:
            return handler(stmt)
        sp = self.txn.savepoint()
        try:
            res = handler(stmt)
        except Exception:
            self.txn.rollback_to(sp)
            raise
        self.txn.release_savepoint()
        return res

    def _insert_on_dup(self, tbl, rows, on_dup, txn) -> int:
        """INSERT ... ON DUPLICATE KEY UPDATE (executor/insert.go upsert):
        per row, a conflict on any public unique index turns the insert
        into an update of the EXISTING row; assignment expressions may
        reference existing columns by name and the proposed row via
        VALUES(col).  Affected-rows: 1 per insert, 2 per changing update,
        0 when the update leaves the row identical (MySQL counting)."""
        from .catalog import DuplicateKeyError, canon_write_value
        if tbl.kv is None:
            # conflict probing walks unique-index KV entries; without a
            # KV backing the upsert would silently degrade to a plain
            # insert and surface as a confusing DuplicateKeyError
            raise PlanError("INSERT ... ON DUPLICATE KEY UPDATE requires "
                            "a KV-backed table")
        affected = 0
        ci = {n: i for i, n in enumerate(tbl.col_names)}
        for col, _e in on_dup:
            if col not in ci:
                raise PlanError(f"unknown column {col!r} in ON DUPLICATE "
                                "KEY UPDATE")
        for r in rows:
            proposed = tuple(
                canon_write_value(t, v, n)
                for t, v, n in zip(tbl.col_types, r, tbl.col_names))
            hit = self._find_unique_conflict(tbl, proposed, txn)
            if hit is None:
                affected += tbl.insert_rows([r], txn=txn)
                continue
            handle, existing = hit
            new_row = list(existing)
            for col, expr_ast in on_dup:
                new_row[ci[col]] = self._eval_upsert_expr(
                    expr_ast, tbl, existing, proposed)
            new_row = tuple(plainify(v) for v in new_row)
            if tuple(existing) == new_row:
                continue               # identical: 0 affected
            tbl.update_rows([handle], [tuple(existing)], [new_row],
                            txn=txn)
            affected += 2
        return affected

    def _find_unique_conflict(self, tbl, row, txn):
        """(handle, existing_row) of the first public unique-index
        conflict for a proposed row, or None."""
        from ..store.codec import decode_index_handle, decode_row, record_key
        if tbl.kv is None:
            return None
        reader = txn if txn is not None else tbl.kv
        ts = None if txn is not None else tbl.kv.alloc_ts()
        for ix in tbl.indexes:
            if not ix.unique or ix.state != "public":
                continue
            key, val = tbl._index_entry(ix, row, 0)
            if not val:
                continue               # NULL key parts never conflict
            got = (reader.get(key) if txn is not None
                   else reader.get(key, ts))
            if got is None:
                continue
            h = decode_index_handle(key, got)
            rk = record_key(tbl.table_id, h)
            rv = (reader.get(rk) if txn is not None
                  else reader.get(rk, ts))
            if rv is not None:
                return h, decode_row(rv, tbl.col_types)
        return None

    def _eval_upsert_expr(self, node, tbl, existing, proposed):
        """Evaluate an ON DUPLICATE KEY UPDATE assignment over the
        existing row (idents) and the proposed row (VALUES(col))."""
        ci = {n: i for i, n in enumerate(tbl.col_names)}
        if isinstance(node, A.Lit):
            return self._literal_value(node)
        if isinstance(node, A.Ident):
            name = node.parts[-1].lower()
            if name not in ci:
                raise PlanError(f"unknown column {name!r}")
            return existing[ci[name]]
        if isinstance(node, A.FuncCall) and node.name == "VALUES" \
                and len(node.args) == 1 \
                and isinstance(node.args[0], A.Ident):
            name = node.args[0].parts[-1].lower()
            if name not in ci:
                raise PlanError(f"unknown column {name!r}")
            return proposed[ci[name]]
        if isinstance(node, A.Binary) and node.op in "+-*":
            a = self._eval_upsert_expr(node.left, tbl, existing, proposed)
            b = self._eval_upsert_expr(node.right, tbl, existing, proposed)
            if a is None or b is None:
                return None
            return {"+": a + b, "-": a - b, "*": a * b}[node.op]
        raise PlanError("unsupported ON DUPLICATE KEY UPDATE expression "
                        "(literals, columns, VALUES(col), + - * only)")

    @staticmethod
    def _insert_ignore(tbl, rows, txn) -> int:
        """INSERT IGNORE: duplicate-key rows are skipped, not errors."""
        from .catalog import DuplicateKeyError
        n = 0
        for r in rows:
            try:
                n += tbl.insert_rows([r], txn=txn)
            except DuplicateKeyError:
                pass
        return n

    def _exec_load_data(self, stmt: A.LoadData) -> ResultSet:
        """LOAD DATA INFILE (executor/load_data.go analog): parse the file
        with the FIELDS/LINES options and batch-insert."""
        import csv as _csv
        import io
        tbl = self.domain.catalog.get_table(getattr(stmt, 'db', None) or self.db, stmt.table)
        try:
            with open(stmt.path, "r", newline="") as f:
                text = f.read()
        except OSError as e:
            raise CatalogError(f"cannot read {stmt.path!r}: {e}")
        if stmt.line_sep not in ("\n", "\r\n"):
            text = text.replace(stmt.line_sep, "\n")
        sep = stmt.field_sep or "\t"
        if len(sep) > 1:
            # csv only takes 1-char delimiters: normalize multi-char
            # separators to an unlikely control char first
            text = text.replace(sep, "\x01")
            sep = "\x01"
        reader = _csv.reader(
            io.StringIO(text), delimiter=sep,
            quotechar=(stmt.enclosed or '"')[0])
        names = stmt.columns or tbl.col_names
        idx = {n: i for i, n in enumerate(names)}
        total = 0
        batch: list[tuple] = []
        # one transaction for the WHOLE load: a failure in a late batch
        # must not leave earlier batches committed (statement atomicity;
        # the explicit-txn case is staged by _dml_atomic's savepoint)
        own = self.txn is None
        txn = self.txn or tbl.kv.begin()

        def flush():
            nonlocal total
            if not batch:
                return
            if stmt.replace:
                total += tbl.replace_rows(batch, txn=txn)
            elif stmt.ignore:
                total += self._insert_ignore(tbl, batch, txn)
            else:
                # MySQL: without IGNORE/REPLACE a duplicate key ERRORS
                total += tbl.insert_rows(batch, txn=txn)
            batch.clear()

        try:
            for ln, rec in enumerate(reader):
                if ln < stmt.ignore_lines or not rec:
                    continue
                vals = []
                for cn, ct in zip(tbl.col_names, tbl.col_types):
                    if cn not in idx or idx[cn] >= len(rec):
                        vals.append(None)
                        continue
                    raw = rec[idx[cn]]
                    if raw == "\\N" or (raw == "" and not ct.is_string):
                        vals.append(None)
                    else:
                        vals.append(raw)
                batch.append(tuple(vals))
                if len(batch) >= 4096:
                    flush()
            flush()
            if own:
                txn.commit()
        except Exception:
            if own:
                txn.rollback()
            raise
        finally:
            tbl._invalidate()
        if self.txn is not None:
            self._txn_tables.add(tbl)
        self.domain.stats.note_modify(tbl, total)
        return ResultSet(affected=total)

    def _where_mask(self, tbl: TableInfo, where: Optional[A.Node]) -> np.ndarray:
        """Evaluate WHERE over the table snapshot -> bool mask (NULL=false)."""
        snap = tbl.snapshot()
        return self._where_mask_cols(tbl, snap.columns, snap.dictionaries,
                                     where)

    def _where_mask_cols(self, tbl: TableInfo, columns, dicts,
                         where: Optional[A.Node]) -> np.ndarray:
        """WHERE mask over explicit columns (txn union-scan views pass
        their own overlaid columns here, not the shared snapshot)."""
        n = len(columns[0]) if columns else 0
        if where is None:
            return np.ones(n, bool)
        from ..expr.compile import eval_expr
        from ..expr.lower_strings import lower_strings
        from ..planner.build import ExprBuilder
        from ..planner.logical import Schema, SchemaCol
        sch = Schema([SchemaCol(nm, c.dtype)
                      for nm, c in zip(tbl.col_names, columns)])
        ir = ExprBuilder(sch).build(where)
        ir = lower_strings(ir, dicts)
        pairs = [(c.data, (True if c.validity.all() else c.validity))
                 for c in columns]
        v, m = eval_expr(np, ir, pairs)
        v = np.broadcast_to(np.asarray(v), (n,))
        if v.dtype != bool:
            v = v != 0
        if m is not True:
            v = v & np.broadcast_to(np.asarray(m), (n,))
        return v

    def _retry_write_conflict(self, fn, attempts: int = 18):
        """Re-run an autocommit DML on optimistic write conflict / lock
        (session doCommitWithRetry analog, session.go:798): the statement
        recomputes against a fresh snapshot each attempt.  Capped
        exponential backoff: a DDL backfill batch on a loaded host can
        hold its locks for >100ms, which the old 72ms linear budget
        couldn't ride out."""
        import time as _t
        from ..store.kv import KVError
        for a in range(attempts):
            try:
                return fn()
            except KVError as e:
                if e.code not in (1, 2) or a == attempts - 1:
                    raise
                _t.sleep(min(0.002 * (2 ** a), 0.3))

    def _exec_update(self, stmt: A.Update) -> ResultSet:
        return self._retry_write_conflict(lambda: self._do_update(stmt))

    def _txn_row_overlay(self, tbl: TableInfo) -> dict:
        """handle -> decoded row (None = buffered delete) from the active
        txn's membuffer for this table — the UnionScanExec ingredient."""
        from ..store.codec import decode_record_key, decode_row, record_prefix
        out: dict = {}
        if self.txn is None or tbl.kv is None:
            return out
        pre = record_prefix(tbl.table_id)
        for k, v in self.txn.mutations.items():
            if k.startswith(pre):
                h = decode_record_key(k)[1]
                out[h] = None if v is None else tuple(
                    decode_row(v, tbl.col_types))
        return out

    def _update_view(self, tbl: TableInfo):
        """(rows, handles, columns, dicts) the UPDATE statement sees:
        committed snapshot merged with the txn's own buffered mutations
        (union scan), never mutating the shared snapshot cache."""
        snap = tbl.snapshot()
        rows = [list(r) for r in zip(*[c.to_python() for c in snap.columns])] \
            if snap.num_rows else []
        handles = [int(h) for h in (tbl._snapshot_handles
                                    if tbl._snapshot_handles is not None
                                    else range(len(rows)))]
        overlay = self._txn_row_overlay(tbl)
        if not overlay:
            return rows, handles, snap.columns, snap.dictionaries
        merged, mh, seen = [], [], set()
        for h, r in zip(handles, rows):
            seen.add(h)
            if h in overlay:
                if overlay[h] is None:
                    continue              # buffered delete
                merged.append(list(overlay[h]))
            else:
                merged.append(r)
            mh.append(h)
        for h in sorted(set(overlay) - seen):
            if overlay[h] is not None:    # buffered insert
                merged.append(list(overlay[h]))
                mh.append(h)
        cols = _rows_to_columns(tbl, [tuple(plainify(x) for x in r)
                                      for r in merged])
        dicts = {i: c.dictionary for i, c in enumerate(cols)
                 if c.dictionary is not None}
        return merged, mh, cols, dicts

    def _do_update(self, stmt: A.Update) -> ResultSet:
        tbl = self.domain.catalog.get_table(getattr(stmt, 'db', None) or self.db, stmt.table)
        if self.txn is not None and getattr(self.txn, "pessimistic", False) \
                and tbl.kv is not None:
            # pessimistic statement protocol: lock the affected record
            # keys FIRST (blocking conflicting writers), then recompute
            # from a post-lock view so the update applies on top of
            # whatever committed while we waited (no lost updates)
            from ..store.codec import record_key
            locked: set = set()
            for attempt in range(8):
                tbl._invalidate()
                rows0, handles0, cols0, dicts0 = self._update_view(tbl)
                m = self._where_mask_cols(tbl, cols0, dicts0, stmt.where)
                matched = {handles0[i] for i in np.nonzero(m)[0]}
                fresh = matched - locked
                if not fresh:
                    break
                self.txn.lock_keys([record_key(tbl.table_id, h)
                                    for h in sorted(fresh)])
                locked |= fresh
            else:
                raise KVError(0, "pessimistic lock retry limit exceeded "
                                 "(contended WHERE set keeps growing)")
        rows, handles, cols, dicts = self._update_view(tbl)
        mask = self._where_mask_cols(tbl, cols, dicts, stmt.where)
        mask = self._dml_restrict_mask(tbl, mask, stmt.order_by,
                                       stmt.limit, cols=cols, dicts=dicts)
        n_rows = len(rows)
        n_aff = int(mask.sum())
        if n_aff == 0:
            return ResultSet(affected=0)
        from ..expr.compile import eval_expr
        from ..expr.lower_strings import lower_strings
        from ..planner.build import ExprBuilder
        from ..planner.logical import Schema, SchemaCol
        sch = Schema([SchemaCol(nm, c.dtype)
                      for nm, c in zip(tbl.col_names, cols)])
        pairs = [(c.data, (True if c.validity.all() else c.validity))
                 for c in cols]
        ci = {n: i for i, n in enumerate(tbl.col_names)}
        midx = np.nonzero(mask)[0]
        old_rows = [tuple(rows[i]) for i in midx]
        for col, expr_ast in stmt.assignments:
            if col not in ci:
                raise PlanError(f"unknown column {col!r}")
            if isinstance(expr_ast, A.Lit):
                val = self._literal_value(expr_ast)
                for i in midx:
                    rows[i][ci[col]] = val
                continue
            ir = lower_strings(ExprBuilder(sch).build(expr_ast), dicts)
            if ir.dtype.is_string:
                raise PlanError("computed string UPDATE not supported yet")
            v, m = eval_expr(np, ir, pairs)
            v = np.broadcast_to(np.asarray(v), (n_rows,))
            for i in midx:
                ok = True if m is True else bool(np.broadcast_to(
                    np.asarray(m), (n_rows,))[i])
                rows[i][ci[col]] = _decode_val(v[i], ir.dtype) if ok else None
        self._fk_parent_update_check(tbl, cols, midx, old_rows, rows)
        if tbl.kv is not None:
            # targeted in-place rewrite through the row store: handles stay
            # stable, and inside a pessimistic txn each record key is
            # locked at DML time (blocking conflicting writers)
            upd_handles = [handles[i] for i in midx]
            updated = [tuple(plainify(x) for x in rows[i]) for i in midx]
            if self.txn is not None:
                tbl.update_rows(upd_handles, old_rows, updated,
                                txn=self.txn)
                self._txn_note_table(tbl)
            else:
                tbl.update_rows(upd_handles, old_rows, updated)
        else:
            new_rows = [tuple(plainify(x) for x in r) for r in rows]
            tbl._fk_check_rows([new_rows[i] for i in midx])
            tbl.replace_columns(_rows_to_columns(tbl, new_rows))
        self.domain.stats.note_modify(tbl, n_aff, delta=0)
        return ResultSet(affected=n_aff)

    def _exec_delete(self, stmt: A.Delete) -> ResultSet:
        return self._retry_write_conflict(lambda: self._do_delete(stmt))

    def _do_delete(self, stmt: A.Delete) -> ResultSet:
        tbl = self.domain.catalog.get_table(getattr(stmt, 'db', None) or self.db, stmt.table)
        if self.txn is not None and tbl.kv is not None:
            self._txn_note_table(tbl)
        if stmt.where is None and stmt.limit is None:
            self._fk_on_delete(tbl, np.ones(tbl.num_rows, bool))
            if self.txn is not None and tbl.kv is not None:
                # DELETE without WHERE is still transactional (TRUNCATE
                # is the implicit-commit one): buffer row deletes
                n = tbl.delete_where(np.zeros(tbl.num_rows, bool),
                                     txn=self.txn)
            else:
                n = tbl.truncate()
            self.domain.stats.note_modify(tbl, n, delta=-n)
            return ResultSet(affected=n)
        if stmt.where is None:
            mask = np.ones(tbl.num_rows, bool)
            mask = self._dml_restrict_mask(tbl, mask, stmt.order_by,
                                           stmt.limit)
            self._fk_on_delete(tbl, mask)
            n = tbl.delete_where(~mask, txn=self.txn)
            self.domain.stats.note_modify(tbl, n, delta=-n)
            return ResultSet(affected=n)
        mask = self._where_mask(tbl, stmt.where)
        mask = self._dml_restrict_mask(tbl, mask, stmt.order_by,
                                       stmt.limit)
        if tbl.kv is not None and self._fk_children(tbl):
            # cascades may reshuffle this table's own snapshot (self-
            # referential FKs): pin the doomed rows by stable handle
            tbl.snapshot()
            del_handles = np.asarray(tbl._snapshot_handles)[mask].tolist()
            self._fk_on_delete(tbl, mask)
            n = tbl.delete_handles(del_handles, txn=self.txn)
        else:
            self._fk_on_delete(tbl, mask)
            n = tbl.delete_where(~mask, txn=self.txn)
        self.domain.stats.note_modify(tbl, n, delta=-n)
        return ResultSet(affected=n)

    # -- foreign keys: parent-side enforcement (executor side of
    # -- planner/core/foreign_key.go: FKCheck/FKCascade plans) ---------- #

    def _lock_for_update(self, stmt) -> None:
        """SELECT ... FOR UPDATE: inside an explicit transaction, lock
        the matched rows of a single-table read so conflicting writers
        block until COMMIT (the pessimistic locking-read contract;
        adapter.go handles it via the ForUpdate flag).  Outside a
        transaction the read is a plain snapshot (locks would release
        immediately); multi-table locking reads are not supported."""
        if self.txn is None:
            return
        if not isinstance(stmt.from_, A.TableName):
            return
        try:
            tbl = self.domain.catalog.get_table(
                stmt.from_.db or self.db, stmt.from_.name)
        except Exception:
            return
        if getattr(tbl, "kv", None) is None \
                or getattr(tbl, "is_memtable", False):
            return
        from ..store.codec import record_key
        try:
            mask = self._where_mask(tbl, stmt.where)
        except Exception:
            # predicate not evaluable standalone (subqueries): lock the
            # whole scanned table — conservative, never under-locks
            mask = np.ones(tbl.num_rows, bool)
        tbl.snapshot()
        handles = (np.asarray(tbl._snapshot_handles)[mask]
                   if tbl._snapshot_handles is not None else [])
        if len(handles):
            self.txn.lock_keys(
                [record_key(tbl.table_id, int(h)) for h in handles])

    def _dml_restrict_mask(self, tbl, mask, order_by, limit,
                           cols=None, dicts=None):
        """Apply DML ORDER BY ... LIMIT n: keep only the first n matched
        rows in key order (UpdateExec/DeleteExec with ORDER BY+LIMIT).
        `cols`/`dicts` must be the SAME view the mask was computed over
        (txn membuffer views differ from the snapshot)."""
        if limit is None and not order_by:
            return mask
        idx = np.nonzero(mask)[0]
        if order_by:
            from ..expr.compile import eval_expr
            from ..expr.lower_strings import lower_strings
            from ..planner.build import ExprBuilder
            from ..planner.logical import Schema, SchemaCol
            if cols is None:
                snap = tbl.snapshot()
                cols = snap.columns
                dicts = snap.dictionaries
            sch = Schema([SchemaCol(nm, c.dtype)
                          for nm, c in zip(tbl.col_names, cols)])
            pairs = [(c.data, (True if c.validity.all() else c.validity))
                     for c in cols]
            n_all = len(cols[0]) if cols else 0
            keys = []
            for e_ast, desc in reversed(list(order_by)):
                ir = lower_strings(ExprBuilder(sch).build(e_ast),
                                   dicts or {})
                v, valid = eval_expr(np, ir, pairs)
                v = np.broadcast_to(np.asarray(v), (n_all,))[idx]
                if isinstance(valid, np.ndarray):
                    valid = np.broadcast_to(valid, (n_all,))[idx]
                else:
                    valid = np.broadcast_to(np.asarray(bool(valid)),
                                            (len(idx),))
                # Sort on dense ranks, not raw values: negating raw keys
                # wraps uint64 (0 stays 0 → sorts FIRST in DESC) and maps
                # INT64_MIN to itself. Ranks start at 1 so the NULL rank 0
                # sorts first ASC and (after negation) last DESC — MySQL's
                # NULL ordering.
                _, ranks = np.unique(v, return_inverse=True)
                ranks = ranks.astype(np.int64) + 1
                if desc:
                    ranks = -ranks
                keys.append(np.where(valid, ranks, 0))
            idx = idx[np.lexsort(tuple(keys))]
        if limit is not None:
            idx = idx[:limit]
        out = np.zeros(len(mask), bool)
        out[idx] = True
        return out

    def _fk_children(self, tbl):
        return [(t, fk)
                for t in self.domain.catalog.databases
                .get(self.db, {}).values()
                for fk in getattr(t, "foreign_keys", [])
                if fk.ref_table == tbl.name]

    def _fk_on_delete(self, tbl, del_mask, depth: int = 0):
        """RESTRICT rejects the delete while referencing child rows exist;
        CASCADE deletes them first (recursively — FKCascade exec).
        Cascade deletes go by STABLE handles: a sibling/deeper cascade may
        reshuffle a table's snapshot between mask computation and the
        delete, so positional masks cannot be trusted across levels.
        `del_mask` must align with tbl.snapshot() at call time."""
        if depth > 32:
            raise CatalogError("foreign key cascade depth exceeded")
        children = self._fk_children(tbl)
        if not children or not del_mask.any():
            return
        if depth == 0:
            # PRE-CHECK the whole cascade closure read-only first: a
            # RESTRICT violation behind a sibling CASCADE must reject the
            # statement BEFORE any child rows are deleted (MySQL rolls
            # the whole statement back)
            self._fk_check_delete(tbl, del_mask)
        snap = tbl.snapshot()
        excl = set()
        if tbl.kv is not None and tbl._snapshot_handles is not None:
            excl = set(np.asarray(tbl._snapshot_handles)[del_mask]
                       .tolist())
        for child, fk in children:
            pcol = snap.columns[tbl.col_names.index(fk.ref_column)]
            pvals = pcol.data[del_mask & pcol.validity]
            if not len(pvals):
                continue
            csnap = child.snapshot()
            ccol = csnap.columns[child.col_names.index(fk.column)]
            hit = ccol.validity & np.isin(ccol.data, pvals)
            if child is tbl and excl:
                hit = hit & ~np.isin(
                    np.asarray(child._snapshot_handles, dtype=np.int64),
                    np.asarray(sorted(excl), dtype=np.int64))
            if not hit.any():
                continue
            if fk.on_delete == "restrict":
                raise CatalogError(
                    "Cannot delete or update a parent row: a foreign "
                    f"key constraint fails (`{child.name}`.`{fk.column}` "
                    f"REFERENCES `{tbl.name}`.`{fk.ref_column}`)")
            n = int(hit.sum())
            if child.kv is not None:
                child_handles = np.asarray(child._snapshot_handles)[hit]
                self._fk_on_delete(child, hit, depth + 1)
                # cascades ride the SAME txn as the parent delete: a
                # rollback must restore the whole closure together
                child.delete_handles(child_handles.tolist(),
                                     txn=self.txn)
                if self.txn is not None:
                    self._txn_note_table(child)
            else:
                self._fk_on_delete(child, hit, depth + 1)
                child.delete_where(~hit)
            self.domain.stats.note_modify(child, n, delta=-n)

    def _fk_check_delete(self, tbl, del_mask, depth: int = 0):
        """Read-only pass over the cascade closure: raises on the first
        RESTRICT violation without mutating anything."""
        if depth > 32:
            raise CatalogError("foreign key cascade depth exceeded")
        children = self._fk_children(tbl)
        if not children or not del_mask.any():
            return
        snap = tbl.snapshot()
        excl = set()
        if tbl.kv is not None and tbl._snapshot_handles is not None:
            excl = set(np.asarray(tbl._snapshot_handles)[del_mask]
                       .tolist())
        for child, fk in children:
            pcol = snap.columns[tbl.col_names.index(fk.ref_column)]
            pvals = pcol.data[del_mask & pcol.validity]
            if not len(pvals):
                continue
            ccol = child.snapshot().columns[
                child.col_names.index(fk.column)]
            hit = ccol.validity & np.isin(ccol.data, pvals)
            if child is tbl and excl:
                hit = hit & ~np.isin(
                    np.asarray(child._snapshot_handles, dtype=np.int64),
                    np.asarray(sorted(excl), dtype=np.int64))
            if not hit.any():
                continue
            if fk.on_delete == "restrict":
                raise CatalogError(
                    "Cannot delete or update a parent row: a foreign "
                    f"key constraint fails (`{child.name}`.`{fk.column}` "
                    f"REFERENCES `{tbl.name}`.`{fk.ref_column}`)")
            self._fk_check_delete(child, hit, depth + 1)

    def _fk_parent_update_check(self, tbl, cols, midx, old_rows, rows):
        """Changing a referenced key value while child rows point at it is
        rejected (ON UPDATE RESTRICT — the only supported update action)."""
        children = self._fk_children(tbl)
        if not children:
            return
        ci = {n: i for i, n in enumerate(tbl.col_names)}
        for child, fk in children:
            pci = ci[fk.ref_column]
            changed = [int(i) for k, i in enumerate(midx)
                       if old_rows[k][pci] != rows[i][pci]]
            if not changed:
                continue
            pcol = cols[pci]
            sel = np.array(changed, dtype=np.int64)
            pvals = pcol.data[sel][pcol.validity[sel]]
            if not len(pvals):
                continue
            ccol = child.snapshot().columns[
                child.col_names.index(fk.column)]
            if (ccol.validity & np.isin(ccol.data, pvals)).any():
                raise CatalogError(
                    "Cannot delete or update a parent row: a foreign "
                    f"key constraint fails (`{child.name}`.`{fk.column}` "
                    f"REFERENCES `{tbl.name}`.`{fk.ref_column}`)")

    def _exec_show(self, stmt: A.ShowStmt) -> ResultSet:
        cat = self.domain.catalog
        if stmt.kind == "create table":
            tbl = cat.get_table(self.db, stmt.target)
            return ResultSet(["Table", "Create Table"],
                             [(tbl.name, _render_create_table(tbl))])
        if stmt.kind == "bindings":
            rows = []
            if stmt.target in (None, "session"):
                rows += [r + ("session",) for r in self.bindings.rows()]
            if stmt.target in (None, "global"):
                rows += [r + ("global",)
                         for r in self.domain.bindings.rows()]
            return ResultSet(
                ["Original_sql", "Bind_sql", "Status", "Scope"], rows)
        if stmt.kind == "tables":
            from ..infoschema import is_system_db, system_tables
            if is_system_db(self.db):
                names = system_tables(self.db)
            else:
                names = sorted(set(cat.databases[self.db])
                               | set(cat.views.get(self.db, {})))
            return ResultSet([f"Tables_in_{self.db}"],
                             [(n,) for n in names])
        if stmt.kind == "databases":
            from ..infoschema import system_databases
            return ResultSet(["Database"],
                             [(n,) for n in sorted(list(cat.databases)
                                                   + system_databases())])
        if stmt.kind == "columns":
            t = cat.get_table(self.db, stmt.target)
            return ResultSet(["Field", "Type", "Null"],
                             [(n, str(ty), "YES" if ty.nullable else "NO")
                              for n, ty in zip(t.col_names, t.col_types)])
        if stmt.kind == "index":
            t = cat.get_table(self.db, stmt.target)
            return ResultSet(
                ["Table", "Key_name", "Non_unique", "Column_name"],
                [(t.name, ix.name, int(not ix.unique), ",".join(ix.columns))
                 for ix in t.indexes])
        if stmt.kind in ("stats_meta", "stats_histograms", "stats_topn"):
            return self._exec_show_stats(stmt.kind)
        if stmt.kind == "statements_summary":
            return ResultSet(
                ["Digest_text", "Exec_count", "Avg_latency_ms",
                 "Max_latency_ms", "Sum_rows", "Sample_sql",
                 "Avg_sched_wait_ms", "Avg_compile_ms",
                 "Sum_sched_tasks", "Sum_fused", "Avg_ru"],
                self.domain.stmt_summary.summary_rows())
        if stmt.kind == "slow_queries":
            return ResultSet(["Query", "Latency_ms", "Rows",
                              "Sched_wait_ms", "Compile_ms", "Ru",
                              "Retried", "Trace_id"],
                             self.domain.stmt_summary.slow_rows())
        if stmt.kind == "processlist":
            # without PROCESS, only the caller's own sessions are visible
            # (mysql semantics; reference executor/show.go)
            see_all = self.domain.privileges.check(self.user, "PROCESS")
            return ResultSet(
                ["Id", "db", "Command", "State"],
                [(sid, sess.db, "Sleep" if sess is not self else "Query",
                  "autocommit" if sess.txn is None else "in transaction")
                 for sid, sess in self.domain.sessions()
                 if see_all or sess.user == self.user])
        if stmt.kind == "grants":
            if stmt.target:
                user, _, host = stmt.target.partition("@")
            else:
                user, host = self.user, "%"
            return ResultSet([f"Grants for {user}@{host}"],
                             [(g,) for g in
                              self.domain.privileges.show_grants(user, host)])
        if stmt.kind == "collation":
            from ..utils.collate import collation_rows
            rows = collation_rows()     # shared with infoschema
            if stmt.like:
                from ..expr.lower_strings import like_to_regex
                rx = like_to_regex(stmt.like.lower())
                rows = [r for r in rows if rx.match(r[0].lower())]
            return ResultSet(["Collation", "Charset", "Id", "Default",
                              "Compiled", "Sortlen", "Pad_attribute"],
                             rows)
        if stmt.kind == "charset":
            from ..utils.collate import charset_rows
            rows = [(cs, desc, dflt, ml)
                    for cs, dflt, desc, ml in charset_rows()]
            if stmt.like:
                from ..expr.lower_strings import like_to_regex
                rx = like_to_regex(stmt.like.lower())
                rows = [r for r in rows if rx.match(r[0].lower())]
            return ResultSet(["Charset", "Description",
                              "Default collation", "Maxlen"], rows)
        if stmt.kind == "variables":
            from .sysvars import REGISTRY
            vs = {name: ent.default for name, ent in REGISTRY.items()}
            vs.update(self.domain.sysvars)
            vs.update(self.vars)
            rows = sorted((k, "" if v is None else str(v))
                          for k, v in vs.items())
            if stmt.like:
                from ..expr.lower_strings import like_to_regex
                rx = like_to_regex(stmt.like.lower())
                rows = [r for r in rows if rx.match(r[0].lower())]
            return ResultSet(["Variable_name", "Value"], rows)
        if stmt.kind == "status":
            import time as _t
            qs = sum(1 for _ in self.domain.sessions())
            rows = [("Threads_connected", str(qs)),
                    ("Uptime", str(int(_t.time()
                                       - getattr(self.domain, "_t0",
                                                 _t.time())))),
                    ("Ssl_cipher", ""),
                    ("Queries", str(len(self.domain.stmt_summary.rows())
                                    if hasattr(self.domain.stmt_summary,
                                               "rows") else 0))]
            if stmt.like:
                from ..expr.lower_strings import like_to_regex
                rx = like_to_regex(stmt.like.lower())
                rows = [r for r in rows if rx.match(r[0].lower())]
            return ResultSet(["Variable_name", "Value"], rows)
        raise PlanError(f"unsupported SHOW {stmt.kind}")

    def _exec_show_stats(self, kind: str) -> ResultSet:
        """SHOW STATS_META / STATS_HISTOGRAMS / STATS_TOPN (reference:
        executor/show_stats.go)."""
        cat = self.domain.catalog
        rows = []
        for db, tables in sorted(cat.databases.items()):
            for name in sorted(tables):
                tbl = tables[name]
                ts = self.domain.stats.get(tbl)
                if ts is None:
                    continue
                if kind == "stats_meta":
                    rows.append((db, name, ts.modify_count,
                                 ts.realtime_count))
                elif kind == "stats_histograms":
                    for cn, cs in sorted(ts.cols.items()):
                        rows.append((db, name, cn, cs.ndv, cs.null_count,
                                     len(cs.hist.bounds)))
                else:
                    for cn, cs in sorted(ts.cols.items()):
                        for v, c in sorted(cs.topn.values.items(),
                                           key=lambda kv: -kv[1]):
                            rows.append((db, name, cn, v, c))
        headers = {
            "stats_meta": ["Db_name", "Table_name", "Modify_count",
                           "Row_count"],
            "stats_histograms": ["Db_name", "Table_name", "Column_name",
                                 "Distinct_count", "Null_count",
                                 "Bucket_count"],
            "stats_topn": ["Db_name", "Table_name", "Column_name", "Value",
                           "Count"],
        }[kind]
        return ResultSet(headers, rows)

    def _exec_admin(self, stmt: A.AdminStmt) -> ResultSet:
        if stmt.kind == "show ddl jobs":
            rows = []
            for j in self.domain.ddl.storage.all_jobs():
                rows.append((j.job_id, j.job_type, j.db, j.table,
                             j.schema_state, j.state, j.rows_backfilled,
                             j.error))
            return ResultSet(
                ["Job_id", "Type", "Db", "Table", "Schema_state", "State",
                 "Row_count", "Error"], rows)
        if stmt.kind == "check table":
            return self._admin_check_table(stmt.target)
        if stmt.kind == "recommend index":
            from ..planner.advisor import recommend_indexes
            return ResultSet(
                ["Table", "Columns", "Est_benefit_execs", "Sample_sql"],
                recommend_indexes(self.domain, self.db))
        if stmt.kind == "checksum table":
            # br/pkg/checksum analog: order-independent XOR of per-pair
            # CRCs over the table's record+index ranges at one ts
            import zlib

            from ..store.codec import (index_prefix, index_prefix_end,
                                       record_prefix, record_prefix_end)
            tbl = self.domain.catalog.get_table(self.db, stmt.target)
            ts = self.domain.kv.alloc_ts()
            cksum = kvs = nbytes = 0
            for lo, hi in ((record_prefix(tbl.table_id),
                            record_prefix_end(tbl.table_id)),
                           (index_prefix(tbl.table_id),
                            index_prefix_end(tbl.table_id))):
                for k, v in self.domain.kv.scan(lo, hi, ts):
                    cksum ^= zlib.crc32(v, zlib.crc32(k))
                    kvs += 1
                    nbytes += len(k) + len(v)
            return ResultSet(
                ["Db_name", "Table_name", "Checksum_crc32_xor",
                 "Total_kvs", "Total_bytes"],
                [(self.db, tbl.name, cksum, kvs, nbytes)])
        raise PlanError(f"unsupported ADMIN {stmt.kind}")

    def _admin_check_table(self, name: str) -> ResultSet:
        """Row <-> index consistency check (executor/check_table_index.go
        analog): recompute every index entry from rows and compare with
        the stored index keyspace."""
        tbl = self.domain.catalog.get_table(self.db, name)
        if tbl.kv is None:
            return ResultSet()   # bulk snapshots carry no indexes
        from ..session.codec_io import scan_table_rows
        from ..store.codec import index_prefix, index_prefix_end
        ts = tbl.kv.alloc_ts()
        handles, rows = scan_table_rows(tbl.kv, tbl.table_id, ts,
                                        tbl.col_types)
        for ix in tbl.indexes:
            if ix.state != "public":
                continue
            want = set()
            for h, r in zip(handles, rows):
                key, _ = tbl._index_entry(ix, tuple(r), int(h))
                want.add(key)
            got = {k for k, _ in tbl.kv.scan(
                index_prefix(tbl.table_id, ix.index_id),
                index_prefix_end(tbl.table_id, ix.index_id), ts)}
            if want != got:
                raise CatalogError(
                    f"admin check table {name}: index {ix.name!r} "
                    f"inconsistent (missing {len(want - got)}, "
                    f"orphan {len(got - want)})")
        return ResultSet()

    def _literal_value(self, node: A.Node):
        if isinstance(node, A.Lit):
            if node.kind in ("int", "bool"):
                return int(node.value)
            if node.kind == "float":
                return float(node.value)
            if node.kind == "decimal":
                return str(node.value)
            return node.value
        if isinstance(node, A.Unary) and node.op == "-":
            v = self._literal_value(node.arg)
            return -v if not isinstance(v, str) else "-" + v
        # general scalar expressions in VALUES: NOW(), NEXTVAL(seq),
        # arithmetic... evaluated through the expression engine
        # (the reference's insert value expression eval)
        try:
            return plainify(self._eval_scalar(node))
        except PlanError:
            raise
        except Exception as e:
            raise PlanError(f"unsupported INSERT value expression: {e}")



def _plan_cacheable(phys) -> bool:
    """A cached plan must hold no materialized row state: CTE scans carry
    a shared storage (executor CTEScanExec.storage) that memoizes results
    and races across sessions — exclude them (the reference likewise
    skips caching for non-deterministic/stateful plans)."""
    stack = [phys]
    while stack:
        p = stack.pop()
        if hasattr(p, "storage"):
            return False
        stack.extend(getattr(p, "children", ()))
    return True


def _flag_on(merged: dict, name: str, default: bool = True) -> bool:
    """Boolean sysvar semantics tolerant of ON/OFF/1/0/None values."""
    v = merged.get(name)
    if v is None:
        return default
    try:
        return int(v) != 0
    except (TypeError, ValueError):
        return str(v).strip().lower() in ("on", "true", "1", "yes")


def _rows_to_columns(tbl: TableInfo, rows: list[tuple]):
    from ..chunk.column import Column
    cols = []
    for i, t in enumerate(tbl.col_types):
        cols.append(Column.from_values(t, [r[i] for r in rows]))
    return cols



def _decode_val(v, t: dt.DataType):
    from ..types import decimal as dec, temporal as tmp
    k = t.kind
    if k == dt.TypeKind.DECIMAL:
        return dec.to_string(int(v), t.scale)
    if k == dt.TypeKind.DATE:
        return tmp.date_to_string(int(v))
    if k == dt.TypeKind.DATETIME:
        return tmp.datetime_to_string(int(v))
    if k in (dt.TypeKind.FLOAT64, dt.TypeKind.FLOAT32):
        return float(v)
    return int(v)




__all__ = ["Session", "Domain", "ResultSet"]


def _render_create_table(tbl) -> str:
    """SHOW CREATE TABLE rendering (executor/show.go ConstructResultOfShow
    CreateTable analog)."""
    from ..types import dtypes as dt
    from ..utils.collate import is_binary
    K = dt.TypeKind
    lines = []
    for name, t in zip(tbl.col_names, tbl.col_types):
        if t.kind == K.DECIMAL:
            ty = f"decimal({t.prec},{t.scale})"
        elif t.kind == K.ENUM:
            ty = "enum(" + ",".join(f"'{m}'" for m in t.members) + ")"
        elif t.kind == K.SET:
            ty = "set(" + ",".join(f"'{m}'" for m in t.members) + ")"
        elif t.kind == K.BIT:
            ty = f"bit({t.prec})"
        else:
            ty = t.kind.value
        line = f"  `{name}` {ty}"
        if t.kind == K.STRING and not is_binary(t.collation):
            line += f" COLLATE {t.collation}"
        if not t.nullable:
            line += " NOT NULL"
        if tbl.auto_inc_col == name:
            line += " AUTO_INCREMENT"
        lines.append(line)
    if tbl.primary_key:
        lines.append("  PRIMARY KEY (" +
                     ",".join(f"`{c}`" for c in tbl.primary_key) + ")")
    for ix in getattr(tbl, "indexes", []):
        if ix.state != "public" or ix.name.upper() == "PRIMARY":
            continue      # the PK's backing index renders as PRIMARY KEY
        kind = "UNIQUE KEY" if ix.unique else "KEY"
        lines.append(f"  {kind} `{ix.name}` (" +
                     ",".join(f"`{c}`" for c in ix.columns) + ")")
    return (f"CREATE TABLE `{tbl.name}` (\n" + ",\n".join(lines) +
            "\n) ENGINE=tpu-columnar DEFAULT CHARSET=utf8mb4")
