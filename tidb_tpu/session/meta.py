"""Catalog-on-KV: schema metadata persisted in the MVCC store.

Reference analog: pkg/meta (meta.go:78) — the catalog lives under the `m`
key prefix in the same transactional KV store as the data, so schema and
rows share one durability story and survive restarts together.  Keys:

    m\\0db\\0<db>              -> "1" (database existence)
    m\\0tbl\\0<db>\\0<name>    -> JSON-encoded TableInfo

The in-memory Catalog (infoschema analog) stays the read path; this module
is the write-through + recovery layer.
"""

from __future__ import annotations

import json
from typing import Optional

from ..types import dtypes as dt
from .catalog import Catalog, IndexInfo, TableInfo

M_DB = b"m\x00db\x00"
M_TBL = b"m\x00tbl\x00"
M_SEQ = b"m\x00seq\x00"     # sequence definitions (values live at m_seq_)
M_MAXID = b"m\x00maxid"     # high-water table id incl. dropped tables


def db_key(db: str) -> bytes:
    return M_DB + db.encode()


def table_key(db: str, name: str) -> bytes:
    return M_TBL + db.encode() + b"\x00" + name.encode()


def _enc_type(t: dt.DataType) -> dict:
    out = {"k": t.kind.name, "n": t.nullable, "p": t.prec, "s": t.scale}
    if t.collation != "binary":
        out["c"] = t.collation
    if t.members:
        out["m"] = list(t.members)
    return out


def _dec_type(d: dict) -> dt.DataType:
    return dt.DataType(dt.TypeKind[d["k"]], d["n"], d["p"], d["s"],
                       collation=d.get("c", "binary"),
                       members=tuple(d.get("m", ())))


def encode_table(tbl: TableInfo) -> bytes:
    return json.dumps({
        "name": tbl.name,
        "cols": tbl.col_names,
        "types": [_enc_type(t) for t in tbl.col_types],
        "pk": tbl.primary_key,
        "auto_inc_col": tbl.auto_inc_col,
        "table_id": tbl.table_id,
        "indexes": [{"name": ix.name, "id": ix.index_id,
                     "cols": ix.columns, "unique": ix.unique,
                     "state": ix.state} for ix in tbl.indexes],
        "next_index_id": tbl._next_index_id,
        "n_shards": tbl.n_shards,
        "ttl": [tbl.ttl_col, tbl.ttl_interval_sec, tbl.ttl_enable],
        # generated columns: compiled IR pickled (internal format; the
        # IR is a frozen dataclass tree over stable dtypes)
        "gen": [[i, __import__("base64").b64encode(
                     __import__("pickle").dumps(ir)).decode()]
                for i, ir in getattr(tbl, "generated_cols", [])],
    }).encode()


def decode_table(data: bytes, kv) -> TableInfo:
    d = json.loads(data)
    tbl = TableInfo(d["name"], list(d["cols"]),
                    [_dec_type(t) for t in d["types"]],
                    primary_key=list(d["pk"]),
                    auto_inc_col=d["auto_inc_col"],
                    table_id=d["table_id"], kv=kv)
    tbl.indexes = [IndexInfo(ix["name"], ix["id"], list(ix["cols"]),
                             ix["unique"], ix["state"])
                   for ix in d["indexes"]]
    tbl._next_index_id = d["next_index_id"]
    tbl.n_shards = d["n_shards"]
    tbl.ttl_col, tbl.ttl_interval_sec, tbl.ttl_enable = d["ttl"]
    if d.get("gen"):
        import base64
        import pickle
        tbl.generated_cols = [(i, pickle.loads(base64.b64decode(b)))
                              for i, b in d["gen"]]
    # handle/auto-inc counters recover lazily from the data on first
    # write (MySQL restart semantics: AUTO_INCREMENT resumes at max+1)
    tbl._needs_counter_recovery = True
    return tbl


class MetaStore:
    """Write-through schema persistence attached to a Catalog."""

    def __init__(self, kv):
        self.kv = kv

    def _put(self, key: bytes, value: Optional[bytes]):
        txn = self.kv.begin()
        if value is None:
            txn.delete(key)
        else:
            txn.put(key, value)
        txn.commit()

    def save_db(self, db: str):
        self._put(db_key(db), b"1")

    def drop_db(self, db: str, tables: list):
        txn = self.kv.begin()
        txn.delete(db_key(db))
        for t in tables:
            txn.delete(table_key(db, t.name if isinstance(t, TableInfo)
                                 else t))
        # drop the database's sequence definitions (value keys are purged
        # by Catalog.drop_database via SequenceInfo._purge_value_key)
        pre = M_SEQ + db.encode() + b"\x00"
        for k, _ in self.kv.scan(pre, pre + b"\xff", txn.start_ts):
            txn.delete(k)
        txn.commit()
        for t in tables:
            if isinstance(t, TableInfo):
                self._purge_table_data(t)

    def save_table(self, db: str, tbl: TableInfo):
        self._put(table_key(db, tbl.name), encode_table(tbl))

    def drop_table(self, db: str, name: str,
                   tbl: Optional[TableInfo] = None):
        self._put(table_key(db, name), None)
        if tbl is not None:
            self._purge_table_data(tbl)

    def _purge_table_data(self, tbl: TableInfo):
        """Delete the dropped table's record+index key range (the
        reference's delete-range GC task) and remember its id so the
        allocator never hands the range out again."""
        self.note_table_id(tbl.table_id)
        if tbl.kv is not self.kv or tbl.table_id <= 0:
            return
        from ..store.codec import encode_int_key
        lo = b"t" + encode_int_key(tbl.table_id)
        hi = lo + b"\xff"
        txn = self.kv.begin()
        for k, _ in self.kv.scan(lo, hi, txn.start_ts):
            txn.delete(k)
        txn.commit()

    def note_table_id(self, tid: int):
        cur = self.load_max_dropped_id()
        if tid > cur:
            self._put(M_MAXID, str(tid).encode())

    def load_max_dropped_id(self) -> int:
        v = self.kv.get(M_MAXID, self.kv.alloc_ts())
        return int(v) if v else 0

    def save_sequence(self, db: str, seq) -> None:
        self._put(M_SEQ + db.encode() + b"\x00" + seq.name.encode(),
                  json.dumps({
                      "name": seq.name, "start": seq.start,
                      "increment": seq.increment,
                      "min_value": seq.min_value,
                      "max_value": seq.max_value,
                      "cache": seq.cache, "cycle": seq.cycle}).encode())

    def drop_sequence(self, db: str, name: str) -> None:
        self._put(M_SEQ + db.encode() + b"\x00" + name.encode(), None)

    def load_catalog(self, catalog: Catalog) -> int:
        """Rebuild the in-memory catalog from KV at startup (infoschema
        load at domain init, domain.go:146 analog).  Returns #tables."""
        ts = self.kv.alloc_ts()
        for k, _v in self.kv.scan(M_DB, M_DB + b"\xff", ts):
            db = k[len(M_DB):].decode()
            if db not in catalog.databases:
                catalog.databases[db] = {}
        n = 0
        for k, v in self.kv.scan(M_TBL, M_TBL + b"\xff", ts):
            db, _name = k[len(M_TBL):].decode().split("\x00", 1)
            tbl = decode_table(v, self.kv)
            catalog.databases.setdefault(db, {})[tbl.name] = tbl
            tbl._meta_hook = (lambda t=tbl, d=db: self.save_table(d, t))
            n += 1
        from .catalog import SequenceInfo
        for k, v in self.kv.scan(M_SEQ, M_SEQ + b"\xff", ts):
            db, _name = k[len(M_SEQ):].decode().split("\x00", 1)
            d = json.loads(v)
            seq = SequenceInfo(d["name"], db, start=d["start"],
                               increment=d["increment"],
                               min_value=d["min_value"],
                               max_value=d["max_value"], cache=d["cache"],
                               cycle=d["cycle"], kv=self.kv)
            catalog.sequences[(db, seq.name)] = seq
        return n


def attach(catalog: Catalog, kv) -> MetaStore:
    """Wire write-through persistence into the catalog's mutation paths."""
    meta = MetaStore(kv)
    catalog._meta = meta

    orig_create_db = catalog.create_database
    orig_drop_db = catalog.drop_database
    orig_create = catalog.create_table
    orig_drop = catalog.drop_table

    def create_database(name, if_not_exists=False):
        orig_create_db(name, if_not_exists)
        meta.save_db(name)

    def drop_database(name, if_exists=False):
        tables = list(catalog.databases.get(name, {}).values())
        orig_drop_db(name, if_exists)
        meta.drop_db(name, tables)

    def create_table(db, tbl, if_not_exists=False):
        orig_create(db, tbl, if_not_exists)
        if catalog.databases.get(db, {}).get(tbl.name) is tbl:
            tbl._meta_hook = (lambda t=tbl, d=db: meta.save_table(d, t))
            meta.save_table(db, tbl)

    def drop_table(db, name, if_exists=False):
        tbl = catalog.databases.get(db, {}).get(name)
        orig_drop(db, name, if_exists)
        if tbl is not None:
            meta.drop_table(db, name, tbl)

    orig_create_seq = catalog.create_sequence
    orig_drop_seq = catalog.drop_sequence

    def create_sequence(db, seq, if_not_exists=False):
        orig_create_seq(db, seq, if_not_exists)
        if catalog.sequences.get((db, seq.name)) is seq:
            meta.save_sequence(db, seq)

    def drop_sequence(db, name, if_exists=False):
        existed = (db, name) in catalog.sequences
        orig_drop_seq(db, name, if_exists)
        if existed:
            meta.drop_sequence(db, name)

    catalog.create_database = create_database
    catalog.drop_database = drop_database
    catalog.create_table = create_table
    catalog.drop_table = drop_table
    catalog.create_sequence = create_sequence
    catalog.drop_sequence = drop_sequence
    return meta


__all__ = ["MetaStore", "attach", "encode_table", "decode_table"]
