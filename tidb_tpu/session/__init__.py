from .catalog import Catalog, TableInfo, CatalogError, type_from_sql
from .session import Session, Domain, ResultSet
