"""Catalog + table storage.

Reference analog: pkg/meta (catalog) + pkg/infoschema (cached schema) +
the TiKV-row-store/TiFlash-columnar split: writes land in a host-side row
buffer (the row store / membuffer analog), reads columnarize lazily into a
ColumnarSnapshot whose epoch bumps on every write — the raft-learner
columnarization role of TiFlash (SURVEY.md §7 hard part #6).  When the C++
KV engine lands, the row buffer moves behind the MVCC store and snapshots
carry read timestamps.
"""

from __future__ import annotations

import contextvars
import threading
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from ..chunk.column import Column, StringDict
from ..store.columnar import ColumnarSnapshot, snapshot_from_columns
from ..types import dtypes as dt

# per-session temporary-table overlay: {(db, name): TableInfo}, installed
# by Session.execute for the duration of each statement
TEMP_TABLES: contextvars.ContextVar = contextvars.ContextVar(
    "temp_tables", default=None)

K = dt.TypeKind


class CatalogError(ValueError):
    pass


class DuplicateKeyError(CatalogError):
    """MySQL error 1062 analog."""


@dataclass
class IndexInfo:
    """Secondary (or PRIMARY) index metadata (reference: meta/model
    IndexInfo)."""
    name: str
    index_id: int
    columns: list[str]
    unique: bool = False
    # online-DDL visibility state (F1 states, ddl/index.go:880): round-1
    # indexes are created synchronously straight to 'public'
    state: str = "public"


TYPE_MAP = {
    "BIGINT": dt.bigint, "INT": dt.bigint, "INTEGER": dt.bigint,
    "SMALLINT": dt.bigint, "TINYINT": dt.bigint, "MEDIUMINT": dt.bigint,
    "DOUBLE": dt.double, "REAL": dt.double, "FLOAT": dt.double,
    "DATE": dt.date, "DATETIME": dt.datetime, "TIMESTAMP": dt.datetime,
    "TIME": dt.time,
    "VARCHAR": dt.varchar, "CHAR": dt.varchar, "TEXT": dt.varchar,
    "STRING": dt.varchar,
    # JSON columns store normalized text (dict-encoded like VARCHAR); the
    # JSON_* builtins evaluate per-distinct-value over the dictionary
    "JSON": dt.varchar,
}


def type_from_sql(name: str, prec: int, scale: int, not_null: bool,
                  collation: str = "", members: tuple = ()) -> dt.DataType:
    base = name.split(" ")[0]
    unsigned = "UNSIGNED" in name
    if base in ("DECIMAL", "NUMERIC"):
        p = prec if prec > 0 else 10
        s = scale if scale >= 0 else 0
        return dt.decimal(p, s, nullable=not not_null)
    if base == "ENUM":
        return dt.enum_type(members, nullable=not not_null)
    if base == "SET":
        try:
            return dt.set_type(members, nullable=not not_null)
        except ValueError as e:
            raise CatalogError(str(e))
    if base == "BIT":
        return dt.bit(prec if prec > 0 else 1, nullable=not not_null)
    if base == "VECTOR":
        if prec > 16000:
            raise CatalogError("vector dimension cannot exceed 16000")
        return dt.vector(prec if prec > 0 else -1, nullable=not not_null)
    fn = TYPE_MAP.get(base)
    if fn is None:
        raise CatalogError(f"unsupported column type {name}")
    t = fn(nullable=not not_null)
    if unsigned and t.kind == K.INT64:
        t = dt.ubigint(nullable=not not_null)
    if collation and t.kind == K.STRING:
        from dataclasses import replace
        t = replace(t, collation=collation)
    return t


@dataclass(eq=False)  # identity semantics: tables are stateful singletons
class TableInfo:
    """One table: schema + KV-backed row store + cached columnar snapshot.

    Two storage modes:
    - KV mode (default when a store is attached): rows live in the native
      MVCC engine under record keys t{id}_r{handle} (SURVEY.md §A.2);
      writes go through percolator transactions; snapshots scan at a read
      ts and decode once into columns.
    - bulk mode (register_columns): pre-built columns bypass the row store
      — the TiFlash-style bulk-load path used by benchmarks.
    """
    name: str
    col_names: list[str]
    col_types: list[dt.DataType]
    primary_key: list[str] = field(default_factory=list)
    auto_inc_col: Optional[str] = None
    table_id: int = 0
    kv: Any = None                              # store.kv.KVStore

    indexes: list[IndexInfo] = field(default_factory=list)

    _base_cols: Optional[list[Column]] = None   # bulk-registered columns
    _pending: list = field(default_factory=list)  # bulk-mode write buffer
    _snapshot: Optional[ColumnarSnapshot] = None
    _epoch: int = 0
    # per-table schema version for MDL + commit-time validation
    # (infoschema version as seen by this table's DDL transitions)
    schema_ver: int = 0
    _auto_inc: int = 0
    _next_handle: int = 0
    _next_index_id: int = 0
    n_shards: int = 8
    # row TTL (pkg/ttl): rows with ttl_col older than now-interval expire
    ttl_col: Optional[str] = None
    ttl_interval_sec: int = 0
    ttl_enable: bool = True
    # table partitioning (sql/ast.PartitionSpec | None); partitions are
    # logical row sets over one store — pruning skips whole partitions at
    # scan time (rule_partition_processor.go analog)
    partition: Any = None
    _part_snap_cache: Any = None   # (epoch, ids) -> sub-snapshot
    # foreign keys THIS table declares (child side): list of
    # ast.ForeignKeyDef; parent resolution through _fk_resolver
    # (set by the session at CREATE TABLE — planner/core/foreign_key.go)
    foreign_keys: list = field(default_factory=list)
    _fk_resolver: Any = None       # (table_name) -> TableInfo
    # centralized autoid service (session/autoid.py): when bound, auto-inc
    # values come from batched RANGES the service persists; None keeps the
    # local counter (pre-service tables, tests)
    _autoid: Any = None
    _ai_cache_end: int = 0         # exclusive end of the fetched range
    # schema gate: writers hold read side per statement; online-DDL state
    # transitions take the write side to drain in-flight writers (the F1
    # schema-lease wait analog, utils/rwlock.py)
    schema_gate: Any = None

    _alloc_mu: Any = None
    # generated columns: [(col_index, compiled IR over the table schema)],
    # computed on every write path (table/column.go generated-column eval)
    generated_cols: list = field(default_factory=list)
    # catalog-on-KV write-through (session/meta.py): called after every
    # schema mutation so the persisted TableInfo stays current
    _meta_hook: Any = None
    # set when loaded from persisted metadata: handle/auto-inc counters
    # recover from the data on first write (MySQL max+1 restart semantics)
    _needs_counter_recovery: bool = False

    def __post_init__(self):
        import threading
        if self.schema_gate is None:
            from ..utils.rwlock import RWLock
            self.schema_gate = RWLock()
        if self._alloc_mu is None:
            self._alloc_mu = threading.Lock()

    # ---------------- index helpers ---------------- #

    def index_by_name(self, name: str) -> Optional[IndexInfo]:
        for ix in self.indexes:
            if ix.name.lower() == name.lower():
                return ix
        return None

    def _index_cols(self, ix: IndexInfo) -> list[int]:
        return [self.col_names.index(c) for c in ix.columns]

    def _index_entry(self, ix: IndexInfo, row: tuple, handle: int):
        from ..store.codec import encode_index_entry
        offs = self._index_cols(ix)
        vals = [row[i] for i in offs]
        types = [self.col_types[i] for i in offs]
        return encode_index_entry(self.table_id, ix.index_id, vals, types,
                                  handle, ix.unique)

    def _put_index_entry(self, txn, ix: IndexInfo, row: tuple, handle: int):
        """Write one index entry, enforcing uniqueness (shared by the
        insert path and CREATE INDEX backfill)."""
        key, val = self._index_entry(ix, row, handle)
        if ix.unique and val and txn.get(key) is not None:
            raise DuplicateKeyError(
                f"Duplicate entry for key '{self.name}.{ix.name}'")
        txn.put(key, val)

    def writable_indexes(self):
        """F1 online-DDL contract (ddl/index.go): an index in 'none' or
        'delete only' does not receive new entries from inserts.  Single
        source of truth for every write path (DML, backfill, bulk import)."""
        return [ix for ix in self.indexes
                if ix.state not in ("none", "delete only")]

    def _write_index_entries(self, txn, row: tuple, handle: int):
        for ix in self.writable_indexes():
            self._put_index_entry(txn, ix, row, handle)

    def _delete_index_entries(self, txn, row: tuple, handle: int):
        for ix in self.indexes:
            if ix.state == "none":
                continue
            key, _ = self._index_entry(ix, row, handle)
            txn.delete(key)

    def create_index(self, name: str, columns: list[str], unique: bool,
                     if_not_exists: bool = False) -> IndexInfo:
        """Create + synchronously backfill a secondary index (the round-1
        stand-in for the online-DDL write-reorg backfill)."""
        if self.index_by_name(name) is not None:
            if if_not_exists:
                return self.index_by_name(name)
            raise CatalogError(f"index {name!r} already exists")
        for c in columns:
            if c not in self.col_names:
                raise CatalogError(f"unknown column {c!r} in index {name!r}")
        if self.kv is None:
            raise CatalogError(
                "indexes require a KV-backed table (bulk-loaded snapshots "
                "are scan-only)")
        self._next_index_id += 1
        ix = IndexInfo(name, self._next_index_id, list(columns), unique)
        # backfill existing rows before publishing
        from .codec_io import scan_table_rows
        ts = self.kv.alloc_ts()
        handles, rows = scan_table_rows(self.kv, self.table_id, ts,
                                        self.col_types)
        txn = self.kv.begin()
        try:
            for h, r in zip(handles, rows):
                self._put_index_entry(txn, ix, tuple(r), int(h))
            txn.commit()
        except Exception:
            txn.rollback()
            raise
        self.indexes.append(ix)
        self._persist_meta()
        return ix

    def _persist_meta(self):
        if self._meta_hook is not None:
            self._meta_hook()

    def drop_index(self, name: str, if_exists: bool = False):
        ix = self.index_by_name(name)
        if ix is None:
            if if_exists:
                return
            raise CatalogError(f"unknown index {name!r}")
        from ..store.codec import index_prefix, index_prefix_end
        txn = self.kv.begin()
        for k, _ in self.kv.scan(index_prefix(self.table_id, ix.index_id),
                                 index_prefix_end(self.table_id, ix.index_id),
                                 txn.start_ts):
            txn.delete(k)
        txn.commit()
        self.indexes.remove(ix)
        self._persist_meta()

    # ---------------- write path ---------------- #

    def _prepare_insert(self, rows: list[tuple]) -> tuple[list[tuple], int]:
        """Validate + canonicalize rows and allocate handles/auto-inc."""
        for r in rows:
            if len(r) != len(self.col_names):
                raise CatalogError(
                    f"column count mismatch: got {len(r)}, want {len(self.col_names)}")
        fixed = []
        ai_idx = (self.col_names.index(self.auto_inc_col)
                  if self.auto_inc_col else -1)
        self._recover_counters()
        with self._alloc_mu:
            # handle/auto-inc allocation is a critical section: concurrent
            # inserters hold the schema gate's READ side together, so the
            # counters need their own lock (autoid allocator analog)
            for r in rows:
                r = list(r)
                if ai_idx >= 0 and r[ai_idx] is None:
                    if self._autoid is not None \
                            and self._auto_inc >= self._ai_cache_end:
                        # range exhausted: fetch the next batch from the
                        # centralized service (autoid_service analog)
                        start, end = self._autoid.alloc_range(
                            self.table_id, at_least=self._auto_inc)
                        self._auto_inc, self._ai_cache_end = start, end
                    self._auto_inc += 1
                    r[ai_idx] = self._auto_inc
                elif ai_idx >= 0 and isinstance(r[ai_idx], int):
                    if r[ai_idx] > self._auto_inc:
                        self._auto_inc = r[ai_idx]
                        if self._autoid is not None \
                                and r[ai_idx] >= self._ai_cache_end:
                            self._autoid.bump(self.table_id, r[ai_idx])
                            self._ai_cache_end = max(self._ai_cache_end,
                                                     r[ai_idx])
                for i, t in enumerate(self.col_types):
                    if r[i] is None and not t.nullable:
                        raise CatalogError(
                            f"column {self.col_names[i]!r} cannot be null")
                    r[i] = canon_write_value(t, r[i], self.col_names[i])
                fixed.append(tuple(r))
            first_handle = self._next_handle + 1
            self._next_handle += len(fixed)
        return fixed, first_handle

    def _insert_fixed(self, t, fixed: list[tuple], first_handle: int):
        """Write prepared rows into an open txn. Caller holds the schema
        gate's read side.  Uniqueness is PRE-checked for the WHOLE batch
        (including intra-batch duplicates) before any buffered write, so a
        DuplicateKeyError leaves the txn clean — statement atomicity
        inside an explicit transaction."""
        from .codec_io import encode_table_row
        uix = [ix for ix in self.writable_indexes() if ix.unique]
        seen: set = set()
        for j, r in enumerate(fixed):
            for ix in uix:
                key, val = self._index_entry(ix, r, first_handle + j)
                if not val:
                    continue        # NULL-containing keys never conflict
                if key in seen or t.get(key) is not None:
                    raise DuplicateKeyError(
                        f"Duplicate entry for key '{self.name}.{ix.name}'")
                seen.add(key)
        for j, r in enumerate(fixed):
            h = first_handle + j
            key, val = encode_table_row(self.table_id, h, r, self.col_types)
            t.put(key, val)
            self._write_index_entries(t, r, h)

    def _fk_check_rows(self, fixed: list) -> None:
        """Child-side FK validation: every non-NULL FK value must exist in
        the parent's referenced column (reads the parent's committed
        snapshot — executor/fktest parent-exists check).  NULL FK values
        always pass (MySQL semantics)."""
        if not self.foreign_keys or self._fk_resolver is None or not fixed:
            return
        for fk in self.foreign_keys:
            ci = self.col_names.index(fk.column)
            vals = [r[ci] for r in fixed if r[ci] is not None]
            if not vals:
                continue
            parent = self._fk_resolver(fk.ref_table)
            snap = parent.snapshot()
            pci = parent.col_names.index(fk.ref_column)
            pcol = snap.columns[pci]
            have = pcol.data[pcol.validity]
            if parent is self:
                # self-referential: rows earlier in this batch also count
                kci = self.col_names.index(fk.ref_column)
                batch_keys = np.array(
                    [r[kci] for r in fixed if r[kci] is not None],
                    dtype=np.int64) if any(
                        r[kci] is not None for r in fixed) else \
                    np.empty(0, np.int64)
                have = np.concatenate([have.astype(np.int64), batch_keys])
            missing = ~np.isin(np.array(vals, dtype=np.int64),
                               have.astype(np.int64))
            if missing.any():
                bad = np.array(vals)[missing][0]
                raise CatalogError(
                    "Cannot add or update a child row: a foreign key "
                    f"constraint fails (`{self.name}`.`{fk.column}` -> "
                    f"`{fk.ref_table}`.`{fk.ref_column}`, value {bad})")

    def _apply_generated(self, rows: list) -> list:
        """Compute generated-column values for a write batch, vectorized
        through the expression engine (columns built from the python-level
        row values, results decoded back)."""
        if not self.generated_cols or not rows:
            return rows
        from ..executor.physical import ResultChunk, _eval_to_column
        rows = [list(r) for r in rows]
        cols = [Column.from_values(t, [r[i] for r in rows])
                for i, t in enumerate(self.col_types)]
        chunk = ResultChunk(list(self.col_names), cols)
        for idx, ir in self.generated_cols:
            out = _eval_to_column(ir, chunk)
            vals = out.to_python()
            for j, r in enumerate(rows):
                r[idx] = vals[j]
            # later generated columns may reference this one
            chunk.columns[idx] = Column.from_values(self.col_types[idx],
                                                    vals)
        return [tuple(r) for r in rows]

    def insert_rows(self, rows: list[tuple], txn=None) -> int:
        rows = self._apply_generated(rows)
        fixed, first_handle = self._prepare_insert(rows)
        self._fk_check_rows(fixed)
        if self.partition is not None and self.partition.kind == "range" \
                and self.partition.parts[-1][1] is not None and fixed:
            ci = self.col_names.index(self.partition.column)
            hi = self.partition.parts[-1][1]
            for r in fixed:
                if r[ci] is not None and int(r[ci]) >= hi:
                    raise CatalogError(
                        f"Table has no partition for value {int(r[ci])}")
        if self.kv is not None:
            own = txn is None
            with self.schema_gate.read():
                t = txn or self.kv.begin()
                try:
                    self._insert_fixed(t, fixed, first_handle)
                    if own:
                        t.commit()
                except Exception:
                    if own:
                        t.rollback()
                    raise
        else:
            self._pending.extend(fixed)
        self._invalidate()
        return len(fixed)

    def replace_rows(self, rows: list[tuple], txn=None) -> int:
        """REPLACE INTO semantics (executor/replace.go analog): per row,
        delete every existing row that conflicts on a public unique index,
        then insert.  Returns deleted + inserted (MySQL affected-rows
        counting).  Rows process in order, so later rows replace earlier
        ones within one batch."""
        from ..store.codec import decode_index_handle, decode_row, record_key
        uix = [ix for ix in self.indexes
               if ix.unique and ix.state == "public"]
        if self.kv is None:
            raise CatalogError("REPLACE requires the KV row store")
        affected = 0
        own = txn is None
        with self.schema_gate.read():
            t = txn or self.kv.begin()
            try:
                for r in rows:
                    fixed, fh = self._prepare_insert([r])
                    canon = fixed[0]
                    for ix in uix:
                        offs = self._index_cols(ix)
                        if any(canon[i] is None for i in offs):
                            continue     # NULL unique keys never conflict
                        key, _ = self._index_entry(ix, canon, 0)
                        got = t.get(key)
                        if got is None:
                            continue
                        h = decode_index_handle(key, got)
                        rk = record_key(self.table_id, h)
                        data = t.get(rk)
                        if data is None:
                            continue
                        old = tuple(decode_row(data, self.col_types))
                        self._delete_index_entries(t, old, h)
                        t.delete(rk)
                        affected += 1
                    self._insert_fixed(t, fixed, fh)
                    affected += 1
                if own:
                    t.commit()
            except Exception:
                if own:
                    t.rollback()
                raise
        self._invalidate()
        return affected

    def update_rows(self, handles, old_rows, new_rows, txn=None) -> int:
        """Rewrite specific rows IN PLACE (stable handles) through the row
        store — the UpdateExec analog.  Inside an explicit transaction the
        caller's txn buffers the writes (and, in pessimistic mode, locks
        each record key at DML time via Txn.put)."""
        from .codec_io import encode_table_row
        new_rows = self._apply_generated(new_rows)
        self._fk_check_rows(new_rows)
        new_rows = [tuple(canon_write_value(t_, v, n)
                          for t_, v, n in zip(self.col_types, r,
                                              self.col_names))
                    for r in new_rows]
        own = txn is None
        with self.schema_gate.read():
            t = txn or self.kv.begin()
            try:
                for h, old, new in zip(handles, old_rows, new_rows):
                    self._delete_index_entries(t, old, int(h))
                    key, val = encode_table_row(self.table_id, int(h), new,
                                                self.col_types)
                    t.put(key, val)
                    self._write_index_entries(t, new, int(h))
                if own:
                    t.commit()
            except Exception:
                if own:
                    t.rollback()
                raise
        self._invalidate()
        return len(handles)

    def delete_handles(self, drop_handles, txn=None) -> int:
        """Delete rows by STABLE row-store handle — immune to snapshot
        re-ordering between mask computation and the delete (the FK
        cascade path interleaves deletes across tables).  Inside an
        explicit transaction the caller\'s txn buffers the deletes
        (DeleteExec: statement writes ride the membuffer and roll back
        with the transaction)."""
        if self.kv is None:
            raise CatalogError("handle deletes need the KV row store")
        self.snapshot()                      # (re)bind _snapshot_handles
        drop = np.asarray(sorted(drop_handles), dtype=np.int64)
        keep = ~np.isin(np.asarray(self._snapshot_handles, dtype=np.int64),
                        drop)
        return self.delete_where(keep, txn=txn)

    def delete_where(self, keep_mask: np.ndarray, txn=None) -> int:
        """Delete rows where ~keep_mask (aligned with snapshot row order)."""
        snap = self.snapshot()
        idx = np.nonzero(keep_mask)[0]
        deleted = snap.num_rows - len(idx)
        if self.kv is not None:
            handles = self._snapshot_handles
            with self.schema_gate.read():
                return self._delete_rows_locked(snap, keep_mask, handles,
                                                deleted, txn=txn)
        else:
            self._base_cols = [c.take(idx) for c in snap.columns]
        self._invalidate()
        return deleted

    def _delete_rows_locked(self, snap, keep_mask, handles, deleted,
                            txn=None) -> int:
        own = txn is None
        t = txn or self.kv.begin()
        from ..store.codec import record_key
        drop = np.nonzero(~np.asarray(keep_mask))[0]
        # materialize ONLY the dropped rows for index-entry removal
        drop_rows = None
        if self.indexes and len(drop):
            dropped = [c.take(drop) for c in snap.columns]
            drop_rows = list(zip(*[c.to_python() for c in dropped]))
        try:
            for j, i in enumerate(drop):
                h = int(handles[i])
                t.delete(record_key(self.table_id, h))
                if drop_rows is not None:
                    self._delete_index_entries(
                        t, tuple(plainify(v) for v in drop_rows[j]), h)
            if own:
                t.commit()
        except Exception:
            if own:
                t.rollback()
            raise
        self._invalidate()
        return deleted

    def replace_columns(self, cols: list[Column]) -> None:
        """Full rewrite (UPDATE path, round 1)."""
        if self.kv is not None:
            # rewrite through the row store in ONE txn so a failed rewrite
            # (e.g. a duplicate-key error on re-insert) leaves the table
            # untouched, keeping MVCC history coherent
            t = self.kv.begin()
            from ..store.codec import (index_prefix, index_prefix_end,
                                       record_prefix, record_prefix_end)
            for k, _ in self.kv.scan(record_prefix(self.table_id),
                                     record_prefix_end(self.table_id),
                                     t.start_ts):
                t.delete(k)
            for k, _ in self.kv.scan(index_prefix(self.table_id),
                                     index_prefix_end(self.table_id),
                                     t.start_ts):
                t.delete(k)
            self._base_cols = None
            rows = list(zip(*[c.to_python() for c in cols])) if cols and len(cols[0]) else []
            try:
                self.insert_rows([tuple(plainify(v) for v in r)
                                  for r in rows], txn=t)
                t.commit()
            except Exception:
                t.rollback()
                raise
            finally:
                self._invalidate()
            return
        self._base_cols = cols
        self._invalidate()

    def truncate(self) -> int:
        n = 0
        if self.kv is not None:
            t = self.kv.begin()
            from ..store.codec import (index_prefix, index_prefix_end,
                                       record_prefix, record_prefix_end)
            for k, _ in self.kv.scan(record_prefix(self.table_id),
                                     record_prefix_end(self.table_id),
                                     t.start_ts):
                t.delete(k)
                n += 1
            for k, _ in self.kv.scan(index_prefix(self.table_id),
                                     index_prefix_end(self.table_id),
                                     t.start_ts):
                t.delete(k)
            t.commit()
        elif self._base_cols or self._pending:
            n = (len(self._base_cols[0]) if self._base_cols else 0) + len(self._pending)
        self._base_cols = None
        self._pending = []
        self._invalidate()
        return n

    def _recover_counters(self):
        """After a restart, resume handle/auto-inc allocation above the
        persisted data (AUTO_INCREMENT = max+1, autoid allocator analog)."""
        if not self._needs_counter_recovery:
            return
        with self._alloc_mu:
            if not self._needs_counter_recovery:
                return
            self._needs_counter_recovery = False
            if self.kv is None:
                return
            snap = self.snapshot()
            handles = self._snapshot_handles
            if handles is not None and len(handles):
                self._next_handle = max(self._next_handle,
                                        int(np.max(handles)))
            if self.auto_inc_col is not None and snap.num_rows:
                c = snap.columns[self.col_names.index(self.auto_inc_col)]
                live = c.data[c.validity]
                if len(live):
                    self._auto_inc = max(self._auto_inc, int(np.max(live)))

    def register_columns(self, cols: list[Column]):
        """Bulk load pre-built columns (benchmarks; TiFlash bulk ingest
        analog) — bypasses the row store."""
        self._base_cols = cols
        self._pending = []
        self.kv = None
        self._invalidate()

    def _invalidate(self):
        self._snapshot = None
        self._epoch += 1

    def split_regions(self, n_shards: int) -> None:
        """Re-shard the table's scan fan-out (SPLIT TABLE ... REGIONS n,
        the region-split analog): the next snapshot carries the new shard
        count and a bumped epoch, so device programs re-fan-out — the
        same invalidation path a real region split takes through the
        region cache."""
        if not 1 <= n_shards <= 4096:
            raise CatalogError("REGIONS must be between 1 and 4096")
        self.n_shards = int(n_shards)
        self._invalidate()

    # ---------------- read path (columnarize) ---------------- #

    @property
    def num_rows(self) -> int:
        if self._snapshot is not None:
            return self._snapshot.num_rows
        if self.kv is None:
            base = len(self._base_cols[0]) if self._base_cols else 0
            return base + len(self._pending)
        return self.snapshot().num_rows

    _placement_excluded: Any = None    # store exclusions survive epochs

    def snapshot(self) -> ColumnarSnapshot:
        if self._snapshot is not None:
            return self._snapshot
        cols = self._columnarize()
        from ..store.placement import Placement
        n = len(cols[0]) if cols else 0
        placement = Placement.even(n, self.n_shards)
        if self._placement_excluded:
            # re-place shards away from stores excluded in prior epochs
            # (the region cache remembers dead stores across refreshes)
            for st in sorted(self._placement_excluded):
                placement.exclude_store(st)
        placement.on_change = self._note_placement
        self._snapshot = snapshot_from_columns(
            self.col_names, cols, n_shards=self.n_shards, epoch=self._epoch,
            placement=placement)
        return self._snapshot

    def _note_placement(self, placement) -> None:
        self._placement_excluded = set(placement.excluded)

    def snapshot_at(self, ts: int) -> ColumnarSnapshot:
        """Historical snapshot at an MVCC read ts (stale read,
        sessiontxn/staleread): columnarizes the row store as of `ts`,
        uncached (one-shot reads; GC may reclaim very old versions)."""
        if self.kv is None:
            raise CatalogError("snapshot_at needs the KV row store")
        from .codec_io import scan_table_rows
        _handles, rows = scan_table_rows(self.kv, self.table_id, int(ts),
                                         self.col_types)
        cols = [Column.from_values(t, [r[i] for r in rows])
                for i, t in enumerate(self.col_types)]
        return snapshot_from_columns(self.col_names, cols,
                                     n_shards=self.n_shards,
                                     epoch=-int(ts))

    # ---------------- partitioning (logical row sets) ---------------- #

    def partition_names(self) -> list[str]:
        return [p[0] for p in self.partition.parts] if self.partition else []

    def _partition_index(self, col: Column) -> "np.ndarray":
        """Per-row partition id for the partition column (model:
        rule_partition_processor.go partition locating).  NULL routes to
        partition 0 (MySQL: lowest RANGE partition / hash bucket 0)."""
        v = col.data.astype(np.int64)
        spec = self.partition
        if spec.kind == "hash":
            pid = np.abs(v) % np.int64(spec.num)
        else:
            bounds = np.array([b for _, b in spec.parts if b is not None],
                              np.int64)
            pid = np.searchsorted(bounds, v, side="right")
            # beyond the last finite bound: MAXVALUE partition if present,
            # else clamp (insert-time validation rejects such rows)
            pid = np.minimum(pid, len(spec.parts) - 1)
        return np.where(col.validity, pid, 0)

    def check_partition_rows(self, col: Column) -> None:
        """RANGE without MAXVALUE rejects out-of-range rows
        (ER_NO_PARTITION_FOR_GIVEN_VALUE)."""
        spec = self.partition
        if spec is None or spec.kind != "range" or \
                spec.parts[-1][1] is None:
            return
        hi = spec.parts[-1][1]
        bad = col.data[col.validity & (col.data >= hi)]
        if len(bad):
            raise CatalogError(
                f"Table has no partition for value {int(bad[0])}")

    def partition_snapshot(self, ids) -> ColumnarSnapshot:
        """Snapshot restricted to the given partition ids (pruned scan)."""
        snap = self.snapshot()
        if self.partition is None or ids is None:
            return snap
        ids = tuple(sorted(set(ids)))
        if ids == tuple(range(len(self.partition.parts))):
            return snap
        if self._part_snap_cache and \
                self._part_snap_cache[0] == (snap.epoch, ids):
            return self._part_snap_cache[1]
        col = snap.columns[self.col_names.index(self.partition.column)]
        pid = self._partition_index(col)
        idx = np.nonzero(np.isin(pid, np.array(ids, np.int64)))[0]
        sub = snapshot_from_columns(
            self.col_names, [c.take(idx) for c in snap.columns],
            n_shards=self.n_shards, epoch=snap.epoch)
        self._part_snap_cache = ((snap.epoch, ids), sub)
        return sub

    _snapshot_handles: Any = None

    def _columnarize(self) -> list[Column]:
        if self.kv is not None:
            from .codec_io import scan_table_rows
            ts = self.kv.alloc_ts()
            handles, rows = scan_table_rows(self.kv, self.table_id, ts,
                                            self.col_types)
            self._snapshot_handles = handles
            return [Column.from_values(t, [r[i] for r in rows])
                    for i, t in enumerate(self.col_types)]
        if self._pending:
            self._base_cols = self._columnarize_append(self._pending)
            self._pending = []
        return self._base_cols or [Column.from_values(t, [])
                                   for t in self.col_types]

    def _columnarize_append(self, new_rows: list[tuple]) -> list[Column]:
        base = self._base_cols or [
            Column.from_values(t, []) for t in self.col_types]
        out = []
        for i, t in enumerate(self.col_types):
            vals = [r[i] for r in new_rows]
            if t.kind == K.STRING:
                old = base[i]
                old_vals = old.to_python() if len(old) else []
                d = StringDict.build(list(old_vals) + vals)
                out.append(Column.from_values(t, list(old_vals) + vals, d))
            else:
                newc = Column.from_values(t, vals)
                out.append(Column.concat([base[i], newc]) if len(base[i])
                           else newc)
        return out


def canon_write_value(t: dt.DataType, v, col_name: str = ""):
    """Canonicalize one value at the WRITE boundary (insert/update/import):
    ENUM/SET string literals become ordinal/bitmask ints (pkg/types
    ParseEnum/ParseSet analog)."""
    if v is None or not isinstance(v, str):
        return v
    if t.kind == K.ENUM:
        ix = dt.enum_index(t, v)
        if ix < 0:
            raise CatalogError(f"invalid ENUM value {v!r} for {col_name!r}")
        return ix
    if t.kind == K.SET:
        m = dt.set_mask(t, v)
        if m < 0:
            raise CatalogError(f"invalid SET value {v!r} for {col_name!r}")
        return m
    return v


def plainify(v):
    """Normalize result-surface values (Decimal/date) back to plain
    encodable python values — shared by INSERT-SELECT and UPDATE paths."""
    import decimal as pydec
    import datetime as pydt
    if isinstance(v, pydec.Decimal):
        return str(v)
    if isinstance(v, pydt.date):
        return v.isoformat()
    return v


@dataclass
class ViewInfo:
    """A stored view: column names + the defining SELECT kept as SQL text,
    re-planned at every expansion so base-table schema changes flow
    through (meta/model ViewInfo analog; parser.y CreateViewStmt)."""
    name: str
    columns: list            # [] = inherit the select's output names
    select_sql: str


class SequenceInfo:
    """A sequence object: batched, KV-persisted value allocation.

    Reference analog: pkg/ddl/sequence.go + the meta sequence value key —
    NEXTVAL allocates from an in-memory cache of `cache` values and
    persists only the batch high-water mark, so a restart skips to the
    next batch boundary instead of repeating values (the autoid
    discipline).  LASTVAL is per-session (keyed by connection id)."""

    META_PREFIX = b"m_seq_"

    def __init__(self, name: str, db: str, start: int = 1,
                 increment: int = 1, min_value: Optional[int] = None,
                 max_value: Optional[int] = None, cache: int = 1000,
                 cycle: bool = False, kv=None):
        if increment == 0:
            raise CatalogError("sequence INCREMENT must be nonzero")
        self.name = name
        self.db = db
        self.increment = increment
        self.min_value = min_value if min_value is not None else \
            (1 if increment > 0 else -(2 ** 63) + 1)
        self.max_value = max_value if max_value is not None else \
            (2 ** 63 - 1 if increment > 0 else -1)
        self.start = start
        self.cache = max(cache, 1)
        self.cycle = cycle
        self.kv = kv
        self._mu = threading.Lock()
        self._next = start            # next value to hand out
        self._cache_end = start       # first value NOT covered by the batch
        self._lastval: dict[int, int] = {}    # conn_id -> last value
        self._restore()

    def _meta_key(self) -> bytes:
        return self.META_PREFIX + f"{self.db}.{self.name}".encode()

    def _purge_value_key(self):
        """Delete the persisted batch high-water mark: a dropped-and-
        recreated sequence must restart, not resume (sequence.go drop).
        Failures propagate — a silent miss would re-enable stale
        resumption with no diagnostic."""
        if self.kv is None:
            return
        txn = self.kv.begin()
        txn.delete(self._meta_key())
        txn.commit()

    def _restore(self):
        if self.kv is None:
            return
        ts = self.kv.alloc_ts()
        end = self._meta_key() + b"\x00"
        for k, v in self.kv.scan(self._meta_key(), end, ts):
            self._next = self._cache_end = int(v.decode())

    def _persist(self, value: int):
        if self.kv is None:
            return
        txn = self.kv.begin()
        txn.put(self._meta_key(), str(value).encode())
        txn.commit()

    def next_value(self, conn_id: int = 0) -> int:
        with self._mu:
            if self.increment > 0 and self._next > self.max_value or \
                    self.increment < 0 and self._next < self.min_value:
                if not self.cycle:
                    raise CatalogError(
                        f"sequence {self.name!r} has run out")
                self._next = (self.min_value if self.increment > 0
                              else self.max_value)
                self._cache_end = self._next
            if (self._next - self._cache_end) * (1 if self.increment > 0
                                                 else -1) >= 0:
                # batch exhausted (or first use): reserve the next batch
                new_end = self._next + self.increment * self.cache
                self._persist(new_end)
                self._cache_end = new_end
            v = self._next
            self._next += self.increment
            self._lastval[conn_id] = v
            return v

    def last_value(self, conn_id: int = 0) -> Optional[int]:
        with self._mu:
            return self._lastval.get(conn_id)

    def set_value(self, value: int, conn_id: int = 0) -> Optional[int]:
        """SETVAL: only moves the sequence FORWARD; a value at or below
        the current position is ignored and returns None/NULL (TiDB/
        MariaDB semantics — issued values must stay unique)."""
        with self._mu:
            if (value - self._next) * (1 if self.increment > 0
                                       else -1) < 0:
                return None
            self._next = value + self.increment
            self._persist(self._next + self.increment * self.cache)
            self._cache_end = self._next + self.increment * self.cache
            return value


class Catalog:
    """In-memory catalog of databases/tables (infoschema analog).

    information_schema / performance_schema resolve to virtual memtables
    (infoschema/__init__.py) bound to the owning Domain."""

    def __init__(self):
        self.databases: dict[str, dict[str, TableInfo]] = {"test": {},
                                                           "mysql": {}}
        # views per db: name -> ViewInfo (planner expands at reference
        # time, logical_plan_builder BuildDataSourceFromView analog)
        self.views: dict[str, dict[str, "ViewInfo"]] = {}
        # sequences: (db, name) -> SequenceInfo (ddl/sequence.go analog)
        self.sequences: dict[tuple, "SequenceInfo"] = {}
        self.domain = None       # set by Domain.__init__ (memtable binding)

    def create_database(self, name: str, if_not_exists=False):
        from ..infoschema import is_system_db
        if is_system_db(name):
            raise CatalogError(f"database {name!r} is a system database")
        if name in self.databases:
            if if_not_exists:
                return
            raise CatalogError(f"database {name!r} exists")
        self.databases[name] = {}

    def drop_database(self, name: str, if_exists=False):
        if name not in self.databases:
            if if_exists:
                return
            raise CatalogError(f"unknown database {name!r}")
        del self.databases[name]
        for key in [k for k in self.sequences if k[0] == name]:
            self.sequences[key]._purge_value_key()
            del self.sequences[key]

    def create_table(self, db: str, tbl: TableInfo, if_not_exists=False):
        d = self._db(db)
        if tbl.name in d:
            if if_not_exists:
                return
            raise CatalogError(f"table {tbl.name!r} exists")
        d[tbl.name] = tbl

    def drop_table(self, db: str, name: str, if_exists=False):
        d = self._db(db)
        if name not in d:
            if if_exists:
                return
            raise CatalogError(f"unknown table {name!r}")
        del d[name]

    def get_table(self, db: str, name: str) -> TableInfo:
        from ..infoschema import get_memtable, is_system_db
        if is_system_db(db):
            mt = get_memtable(db, name)
            mt.domain = self.domain
            return mt
        # session temporary tables shadow permanent ones (reference:
        # infoschema local temporary table overlay, temptable pkg)
        tmp = TEMP_TABLES.get()
        if tmp is not None:
            t = tmp.get((db, name))
            if t is not None:
                return t
        d = self._db(db)
        if name not in d:
            raise CatalogError(f"table {db}.{name} doesn't exist")
        return d[name]

    def _db(self, db: str) -> dict:
        from ..infoschema import is_system_db
        if is_system_db(db):
            raise CatalogError(f"database {db!r} is a system database")
        if db not in self.databases:
            raise CatalogError(f"unknown database {db!r}")
        return self.databases[db]

    # ---------------- sequences ---------------- #

    def create_sequence(self, db: str, seq: "SequenceInfo",
                        if_not_exists=False):
        self._db(db)      # existence check
        key = (db, seq.name)
        if key in self.sequences:
            if if_not_exists:
                return
            raise CatalogError(f"sequence {seq.name!r} exists")
        self.sequences[key] = seq

    def drop_sequence(self, db: str, name: str, if_exists=False):
        if (db, name) not in self.sequences:
            if if_exists:
                return
            raise CatalogError(f"unknown sequence {name!r}")
        self.sequences[(db, name)]._purge_value_key()
        del self.sequences[(db, name)]

    def get_sequence(self, db: str, name: str) -> "SequenceInfo":
        seq = self.sequences.get((db, name))
        if seq is None:
            raise CatalogError(f"table {db}.{name} doesn't exist")
        return seq

    # ---------------- views ---------------- #

    def create_view(self, db: str, view: "ViewInfo",
                    or_replace: bool = False):
        d = self._db(db)            # existence/system-db validation
        if view.name in d:
            raise CatalogError(f"table {view.name!r} exists")
        vs = self.views.setdefault(db, {})
        if view.name in vs and not or_replace:
            raise CatalogError(f"view {view.name!r} exists")
        vs[view.name] = view

    def drop_view(self, db: str, name: str, if_exists=False):
        vs = self.views.get(db, {})
        if name not in vs:
            if if_exists:
                return
            raise CatalogError(f"unknown view {db}.{name}")
        del vs[name]

    def get_view(self, db: str, name: str) -> Optional["ViewInfo"]:
        return self.views.get(db, {}).get(name)


__all__ = ["Catalog", "TableInfo", "IndexInfo", "CatalogError",
           "DuplicateKeyError", "type_from_sql"]
