"""Centralized AUTO_INCREMENT allocation service.

Reference analog: pkg/autoid_service/autoid.go — the AUTO_ID_CACHE=1
centralized allocator: one leader-elected service owns the counter per
table, persisted through the meta KV; clients fetch id RANGES and
consume them locally, so per-row allocation never crosses the service
(and a restart resumes past the last persisted range end — MySQL's
id-jump semantics, never a reuse).

Single-process deployment: the service runs in the Domain (the "owner"
node, consistent with the lease-based owner election the DDL uses); the
KV persistence makes ranges durable under data_dir domains.
"""

from __future__ import annotations

import struct
import threading
from typing import Optional, Tuple

DEFAULT_BATCH = 4000          # ids per client range (AUTO_ID_CACHE)

_KEY_PREFIX = b"m_autoid_"


def _key(table_id: int) -> bytes:
    return _KEY_PREFIX + str(int(table_id)).encode()


class AutoIDService:
    """Per-cluster allocator: alloc_range / bump over persisted counters."""

    def __init__(self, kv):
        self.kv = kv
        self._mu = threading.Lock()
        self._cache: dict[int, int] = {}       # table_id -> persisted max

    def _load(self, table_id: int) -> int:
        if table_id in self._cache:
            return self._cache[table_id]
        cur = 0
        if self.kv is not None:
            raw = self.kv.get(_key(table_id), self.kv.alloc_ts())
            if raw:
                cur = struct.unpack("<q", raw)[0]
        self._cache[table_id] = cur
        return cur

    def _store(self, table_id: int, val: int) -> None:
        self._cache[table_id] = val
        if self.kv is not None:
            t = self.kv.begin()
            t.put(_key(table_id), struct.pack("<q", val))
            t.commit()

    def alloc_range(self, table_id: int, n: int = DEFAULT_BATCH,
                    at_least: int = 0) -> Tuple[int, int]:
        """Reserve (start, end]: ids start+1 .. end inclusive.  at_least
        skips past explicitly-inserted values the client observed."""
        with self._mu:
            base = max(self._load(table_id), int(at_least))
            end = base + max(int(n), 1)
            self._store(table_id, end)
            return base, end

    def bump(self, table_id: int, val: int) -> None:
        """Raise the persisted counter past an explicit value (INSERT with
        a literal id beyond the current range)."""
        with self._mu:
            if int(val) > self._load(table_id):
                self._store(table_id, int(val))

    def current(self, table_id: int) -> int:
        with self._mu:
            return self._load(table_id)


__all__ = ["AutoIDService", "DEFAULT_BATCH"]
