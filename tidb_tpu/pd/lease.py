"""coplace: member leases with TTL + explicit failover semantics.

Reference analog: PD client leases.  Every tidb-tpu process holds ONE
lease on the coordination store; the lease epoch fences all its
writes (pd/store).  The failover contract this module owns:

- store unreachable (``PdUnavailable``) or lease expired
  (``PdLeaseExpired``) => the member flips to DEGRADED: local quota
  slice, local-only caches, no shared writes.  **Never an error a
  statement sees** — degradation is silent, counted
  (``tidb_tpu_pd_degraded_total``), and flagged on the active trace.
- the next successful renewal RE-JOINS: a fresh epoch is granted (the
  old one may have been fenced), and the coordinator runs a full
  resync (quota shares, calibration, registry) on the rejoin tick.

Renewal is statement-driven (the coordinator ticks from the session
hot path) and internally throttled to ~1/3 of the TTL, so a busy
process renews a handful of times per TTL and an idle one simply
lapses — exactly the semantics a crashed process would show.
"""

from __future__ import annotations

import itertools
import os
import socket
import time

from .store import PD_LEASE_TTL_S, PdError, PdLeaseExpired, PdStore

# distinguishes N Domains inside one process (tier-1 runs two members
# over one MemoryBackend in a single interpreter)
_MEMBER_SEQ = itertools.count(1)


def default_member_id() -> str:
    return (f"{socket.gethostname()}:{os.getpid()}"
            f":{next(_MEMBER_SEQ)}")


class PdMember:
    """One process's (strictly: one Domain's) lease on the plane."""

    def __init__(self, store: PdStore, member_id: str = "",
                 ttl_s: float = PD_LEASE_TTL_S):
        self.store = store
        self.member_id = member_id or default_member_id()
        self.ttl_s = ttl_s
        self.epoch = 0               # 0 = never joined
        self.degraded = False
        self._deadline = 0.0         # local view of our lease deadline
        self._rejoined = False       # set on recovery, consumed by
                                     # the coordinator's resync tick
        # lifetime counters (surfaced via coordinator.stats)
        self.renews = 0
        self.grants = 0
        self.rejoins = 0
        self.degraded_total = 0

    def joined(self) -> bool:
        return self.epoch > 0 and not self.degraded

    def consume_rejoin(self) -> bool:
        """True exactly once after a degraded->live transition — the
        coordinator forces a full quota/calibration/registry resync."""
        out = self._rejoined
        self._rejoined = False
        return out

    def ensure(self, now: float = 0.0) -> bool:
        """Grant or renew when due.  True = lease live (writes with
        ``self.epoch`` will validate); False = degraded.  Raises
        nothing — this IS the failover seam."""
        now = now or time.time()
        if self.joined() and now < self._deadline - self.ttl_s * (2.0 / 3.0):
            return True          # renewed recently; not due yet
        try:
            if self.epoch > 0 and not self.degraded:
                try:
                    self.store.renew(self.member_id, self.epoch,
                                     self.ttl_s)
                    self.renews += 1
                except PdLeaseExpired:
                    # fenced out (TTL lapsed between ticks): re-grant
                    # under a NEW epoch — old-epoch writes stay fenced
                    self.epoch = self.store.grant(self.member_id,
                                                  self.ttl_s)
                    self.grants += 1
                    self._rejoined = True
                    self.rejoins += 1
            else:
                was_degraded = self.degraded
                self.epoch = self.store.grant(self.member_id, self.ttl_s)
                self.grants += 1
                if was_degraded:
                    self._rejoined = True
                    self.rejoins += 1
            self.degraded = False
            self._deadline = now + self.ttl_s
            return True
        except PdError:
            self.degrade()
            return False

    def degrade(self) -> None:
        """Flip to degraded-local (idempotent).  The caller bumps the
        degraded counter/trace flag on the False edge it observes."""
        if not self.degraded:
            self.degraded = True
            self.degraded_total += 1

    def leave(self) -> None:
        """Graceful departure (pd disabled / Domain close): release
        the lease so peers reclaim our quota slice immediately."""
        if self.epoch > 0:
            try:
                self.store.release(self.member_id, self.epoch)
            except PdError:
                pass             # leaving a dead store is still leaving
        self.epoch = 0
        self.degraded = False
        self._deadline = 0.0

    def stats(self) -> dict:
        return {"member_id": self.member_id,
                "epoch": self.epoch,
                "ttl_s": self.ttl_s,
                "degraded": self.degraded,
                "renews": self.renews,
                "grants": self.grants,
                "rejoins": self.rejoins,
                "degraded_total": self.degraded_total}


__all__ = ["PdMember", "default_member_id"]
