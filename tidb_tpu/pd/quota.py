"""coplace: the global per-resource-group RU token pool.

Reference analog: the reference's resource-control token server —
RU_PER_SEC is a CLUSTER budget that PD leases out in refill shares to
each server's local token bucket (pkg/mcs/resourcemanager).  Before
this module, two tidb-tpu processes each refilled a group's
``TokenBucket`` at the full declared rate: N processes N-times
over-admit the group.

Mechanics, one renewal round per group:

- every member reports its bucket DEBT into ``quota/<group>``
  (txn_update under its lease epoch), prunes members whose reports
  are older than ``PD_QUOTA_TTL_S`` (crashed peers yield their slice),
  and reads the merged membership back.
- debt-weighted shares: ``w_i = 1 + debt_i``, ``share_i =
  RU_PER_SEC * w_i / sum(w)`` — a member whose sessions queued deeper
  refills faster next period, so the global budget chases demand
  instead of splitting evenly forever.  Sum of shares == the declared
  budget, always: ONE RU_PER_SEC holds across N processes.
- the share applies through ``TokenBucket.set_limit`` (balance and
  debt carry over — the rc drain's admission logic is untouched).

Failover (pd/lease contract): degraded members fall back to a LOCAL
SLICE — the declared rate divided by the last known member count — so
an isolated process can not spend the whole cluster budget, and a
fully partitioned fleet converges to the same split a live store
would give.  Disabling pd restores the full declared rate.
"""

from __future__ import annotations

import time

from .lease import PdMember
from .store import PD_QUOTA_TTL_S

QUOTA_PREFIX = "quota/"


class QuotaPool:
    """One member's view of the shared RU pools (one per limited
    resource group in its Domain's ResourceGroupManager)."""

    def __init__(self, member: PdMember, manager):
        self.member = member
        self.manager = manager            # rc ResourceGroupManager
        self.shares: dict[str, float] = {}       # group -> leased ru/s
        self._member_counts: dict[str, int] = {}  # last seen per group
        self.rebalances = 0
        self.local_slices = 0

    def _limited_groups(self) -> list:
        return [g for g in self.manager.groups_snapshot() if g.limited]

    # ---- the renewal round ------------------------------------------ #

    def sync(self, now: float = 0.0) -> None:
        """Report debt + rebalance every limited group.  Raises
        PdUnavailable/PdLeaseExpired — the coordinator catches and
        degrades (this module never decides failover policy)."""
        store = self.member.store
        epoch = self.member.epoch
        mid = self.member.member_id
        now = now or time.time()
        for group in self._limited_groups():
            debt = max(group.bucket.debt, 0.0)

            def merge(cur, _group=group, _debt=debt):
                doc = cur if isinstance(cur, dict) else {}
                doc["ru_per_sec"] = _group.ru_per_sec
                doc["burstable"] = _group.burstable
                members = doc.setdefault("members", {})
                members[mid] = {"debt": round(_debt, 3), "ts": now}
                for m in sorted(members):
                    if now - members[m].get("ts", 0.0) > PD_QUOTA_TTL_S:
                        del members[m]    # crashed peer: reclaim slice
                return doc

            doc = store.txn_update(QUOTA_PREFIX + group.name, merge,
                                   epoch=epoch)
            self._member_counts[group.name] = len(doc.get("members", {}))
            self._apply(group, self._share_of(doc, mid))
        self.rebalances += 1

    def _share_of(self, doc: dict, mid: str) -> float:
        """Debt-weighted refill share; shares over all members sum to
        the declared budget exactly (modulo float rounding)."""
        limit = max(doc.get("ru_per_sec", 0), 0)
        members = doc.get("members", {})
        if limit <= 0 or not members:
            return limit * 1.0
        weights = {m: 1.0 + max(info.get("debt", 0.0), 0.0)
                   for m, info in sorted(members.items())}
        total = sum(weights.values())
        return limit * weights.get(mid, 1.0) / max(total, 1e-9)

    def _apply(self, group, share: float) -> None:
        group.bucket.set_limit(share, group.burstable)
        self.shares[group.name] = round(share, 3)

    # ---- failover ---------------------------------------------------- #

    def degrade_to_local_slice(self) -> None:
        """Store lost / lease fenced: every limited group refills at
        ``declared / last_known_member_count`` — the conservative split
        that keeps the COMBINED spend of a fully partitioned fleet at
        the declared budget.  A never-synced member (count unknown)
        keeps the full rate: pd never makes a single process worse."""
        for group in self._limited_groups():
            n = max(self._member_counts.get(group.name, 1), 1)
            self._apply(group, group.ru_per_sec / n)
        self.local_slices += 1

    def restore_full(self) -> None:
        """pd disabled / member left: declared single-process rates."""
        for group in self._limited_groups():
            group.bucket.set_limit(group.ru_per_sec, group.burstable)
        self.shares.clear()
        self._member_counts.clear()

    def stats(self) -> dict:
        return {"shares": dict(sorted(self.shares.items())),
                "member_counts": dict(sorted(
                    self._member_counts.items())),
                "rebalances": self.rebalances,
                "local_slices": self.local_slices}


__all__ = ["QuotaPool", "QUOTA_PREFIX"]
