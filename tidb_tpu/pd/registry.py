"""coplace: the shared copforge program-digest registry.

Reference analog: the reference's placement rules + the plan-cache
interaction with the stats/schema version — shared metadata that every
server consults before doing expensive local work.  Here the expensive
local work is an AOT compile (BENCH_r05: 153 s of warmup on SF100 Q6),
and the registry guarantees three cross-process properties:

- **compile-once**: before compiling, a process claims
  ``claim/<entry>`` (TTL'd — a crashed compiler unblocks its peers in
  ``PD_CLAIM_TTL_S``).  A denied claimant polls the shared cache dir
  briefly for the winner's persisted entry instead of re-compiling
  (compilecache.cache hooks ``try_compile_claim`` on its miss path).
- **warm-pool gossip**: each member publishes the entry anatomy of
  its persisted executables under ``program/<digest>``; peers adopt a
  bounded number per sync tick via ``CompileCache.load_warm`` (the
  shared ``tidb_tpu_compile_cache_dir`` holds the bytes; the registry
  carries the *names*, so B's pool warms from A's compiles without B
  ever tracing).
- **quarantine propagation**: a breaker-opened digest broadcasts a
  ``quarantine/<digest>`` tombstone; every peer purges it from its
  warm pool, manifest, and correction store on the next sync — a
  poisoned program cannot launder back through a peer any more than
  through a restart (PR 9's invariant, now cross-process).
"""

from __future__ import annotations

import time

from .lease import PdMember
from .store import PD_CLAIM_TTL_S, PD_PROGRAM_TTL_S

PROGRAM_PREFIX = "program/"
CLAIM_PREFIX = "claim/"
QUARANTINE_PREFIX = "quarantine/"

# per-sync-tick bound on peer warm-pool adoptions: deserializing is
# cheap but not free; the long tail trickles in over later ticks
ADOPT_PER_SYNC = 4
# per-sync-tick bound on published entries (MRU-first)
PUBLISH_PER_SYNC = 32


class ProgramRegistry:
    """One member's view of the shared digest registry."""

    def __init__(self, member: PdMember):
        self.member = member
        self._published: set = set()     # entry hexes we pushed
        self._adopt_tried: set = set()   # entry hexes we probed
        self._quarantine_seen: set = set()
        # lifetime counters (coordinator.stats + tidb_tpu_pd_* metrics)
        self.claims = 0                  # claims we won
        self.claim_denials = 0           # claims a live peer held
        self.peer_warm = 0               # entries adopted from peers
        self.quarantine_purged = 0       # tombstones applied locally
        self.published = 0

    # ---- in-flight compile claims ------------------------------------ #

    def try_claim(self, entry_hex: str) -> bool:
        """True = this member holds the claim (go compile); False = a
        live peer holds it (poll the cache dir instead).  Raises
        PdUnavailable/PdLeaseExpired for the coordinator/caller to map
        to degraded-local (= just compile)."""
        store = self.member.store
        key = CLAIM_PREFIX + entry_hex
        now = time.time()
        cur, ver = store.get(key)
        if (isinstance(cur, dict)
                and cur.get("member") != self.member.member_id
                and cur.get("deadline", 0.0) > now):
            self.claim_denials += 1
            return False
        won = store.cas(key, ver,
                        {"member": self.member.member_id,
                         "deadline": now + PD_CLAIM_TTL_S},
                        epoch=self.member.epoch)
        if won:
            self.claims += 1
        else:
            self.claim_denials += 1    # lost the CAS race to a peer
        return won

    def release_claim(self, entry_hex: str) -> None:
        """Drop our claim (compile finished or failed) so peers stop
        polling early instead of waiting out the TTL."""
        store = self.member.store
        key = CLAIM_PREFIX + entry_hex
        cur, _ver = store.get(key)
        if isinstance(cur, dict) and \
                cur.get("member") == self.member.member_id:
            store.delete(key, epoch=self.member.epoch)

    # ---- warm-pool gossip -------------------------------------------- #

    def publish_manifest(self, manifest, now: float = 0.0) -> int:
        """Push our persisted entries' anatomy (MRU-first, bounded) so
        peers can warm-load them by name from the shared cache dir."""
        store = self.member.store
        mid = self.member.member_id
        now = now or time.time()
        pushed = 0
        for entry_hex, meta in manifest.entries_mru()[:PUBLISH_PER_SYNC]:
            if entry_hex in self._published:
                continue
            digest = meta.get("digest", "")
            if not digest:
                continue

            def add(cur, _hex=entry_hex, _meta=meta):
                doc = cur if isinstance(cur, dict) else {}
                entries = doc.setdefault("entries", {})
                entries[_hex] = {"by": mid, "ts": now,
                                 "bytes": _meta.get("bytes", 0),
                                 "family": _meta.get("family", ""),
                                 "capacity": _meta.get("capacity", 0)}
                for hx in sorted(entries):
                    if now - entries[hx].get("ts", 0.0) > \
                            PD_PROGRAM_TTL_S:
                        del entries[hx]
                return doc

            store.txn_update(PROGRAM_PREFIX + digest, add,
                             epoch=self.member.epoch)
            self._published.add(entry_hex)
            self.published += 1
            pushed += 1
        return pushed

    def adopt_from_peers(self, cache, limit: int = ADOPT_PER_SYNC) -> int:
        """Warm-load entries peers published that we never resolved:
        the shared cache dir holds the serialized executable, so this
        is a deserialize, never a compile.  Bounded per tick."""
        store = self.member.store
        mid = self.member.member_id
        adopted = 0
        docs = store.read_prefix(PROGRAM_PREFIX)
        for key in sorted(docs):
            doc, _ver = docs[key]
            entries = doc.get("entries", {}) if isinstance(doc, dict) \
                else {}
            for entry_hex in sorted(entries):
                info = entries[entry_hex]
                if info.get("by") == mid or \
                        entry_hex in self._adopt_tried:
                    continue
                self._adopt_tried.add(entry_hex)
                if cache.load_warm(entry_hex):
                    self.peer_warm += 1
                    adopted += 1
                if adopted >= limit:
                    return adopted
        return adopted

    # ---- quarantine propagation -------------------------------------- #

    def broadcast_quarantine(self, digest: str) -> None:
        """Our breaker opened on ``digest``: tombstone it for every
        peer (and drop its registry entries — nothing to adopt)."""
        store = self.member.store
        mid = self.member.member_id

        def put(_cur, _d=digest):
            return {"ts": time.time(), "by": mid}

        store.txn_update(QUARANTINE_PREFIX + digest, put,
                         epoch=self.member.epoch)
        store.delete(PROGRAM_PREFIX + digest, epoch=self.member.epoch)
        self._quarantine_seen.add(digest)

    def sync_quarantine(self, cache) -> int:
        """Apply unseen peer tombstones locally: quarantine the digest
        in the compile cache (purges warm pool records, manifest
        entries, and — via the cache — its cost corrections)."""
        store = self.member.store
        applied = 0
        docs = store.read_prefix(QUARANTINE_PREFIX)
        for key in sorted(docs):
            digest = key[len(QUARANTINE_PREFIX):]
            if digest in self._quarantine_seen:
                continue
            self._quarantine_seen.add(digest)
            cache.quarantine(digest)
            self.quarantine_purged += 1
            applied += 1
        return applied

    def stats(self) -> dict:
        return {"claims": self.claims,
                "claim_denials": self.claim_denials,
                "peer_warm": self.peer_warm,
                "published": self.published,
                "quarantine_purged": self.quarantine_purged}


__all__ = ["ProgramRegistry", "PROGRAM_PREFIX", "CLAIM_PREFIX",
           "QUARANTINE_PREFIX", "ADOPT_PER_SYNC", "PUBLISH_PER_SYNC"]
