"""coplace: a PD-style coordination plane for N tidb-tpu processes.

Reference analog: PD, the placement driver in the reference
architecture's layer map — the component that turns N servers into
one cluster.  Everything the repo built so far (RU admission, the
copforge AOT cache, closed-loop calibration) was per-process; this
package coordinates them through one tiny epoch/CAS store:

- ``pd/store.py``  — the store (in-process + file backends, lease-
  epoch write fencing).
- ``pd/lease.py``  — member leases; failover = graceful degradation
  to local quota slices + local-only caches, never errors.
- ``pd/quota.py``  — ONE ``RU_PER_SEC`` across processes via
  debt-weighted refill shares into each process's TokenBucket.
- ``pd/registry.py`` — compile-once claims, peer warm-pool adoption,
  cross-process quarantine tombstones.
- ``pd/coordinator.py`` — the per-Domain statement-driven tick.

This module owns the process-wide surfaces: the sysvar plumbing seam
(``configure_domain``), the default in-process shared backend (two
Domains in one interpreter = two simulated servers), the compile-
claim hooks the cache calls on its miss path, and ``pd_status()`` for
``/pd`` + the scheduler's ``/sched`` section.

Enable with ``SET GLOBAL tidb_tpu_pd = 1`` (and point
``tidb_tpu_pd_dir`` at a shared directory for real multi-process
coordination; empty = the in-process backend).
"""

from __future__ import annotations

import threading
from typing import Optional

from .coordinator import PD_SYNC_S, PdCoordinator
from .lease import PdMember
from .quota import QuotaPool
from .registry import ProgramRegistry
from .store import (KEY_FAMILIES, FileBackend, KeyFamily, MemoryBackend,
                    PdError, PdLeaseExpired, PdStore, PdUnavailable,
                    pd_report, verify_key_families)

_MU = threading.Lock()
_COORDS: list = []                      # every attached coordinator
_SHARED_BACKEND: Optional[MemoryBackend] = None


def shared_memory_backend() -> MemoryBackend:
    """The process-default backend for ``tidb_tpu_pd_dir = ''``: every
    Domain in this interpreter joins the same in-process store."""
    global _SHARED_BACKEND
    with _MU:
        if _SHARED_BACKEND is None:
            _SHARED_BACKEND = MemoryBackend()
        return _SHARED_BACKEND


def configure_domain(domain, enable: bool, pd_dir: str = ""):
    """The sysvar seam (session/_exec_ctx): attach, retarget, or
    detach a Domain's coordinator.  Idempotent and cheap when nothing
    changed; returns the live coordinator (None when disabled)."""
    coord = getattr(domain, "pd", None)
    if not enable:
        if coord is not None:
            coord.leave()
            _detach(coord)
            domain.pd = None
        return None
    if coord is not None and coord.matches(pd_dir):
        return coord
    if coord is not None:
        coord.leave()
        _detach(coord)
    backend = FileBackend(pd_dir) if pd_dir else shared_memory_backend()
    coord = PdCoordinator(PdStore(backend), domain.resource_groups,
                          pd_dir=pd_dir)
    domain.pd = coord
    with _MU:
        _COORDS.append(coord)
    return coord


def _detach(coord) -> None:
    with _MU:
        if coord in _COORDS:
            _COORDS.remove(coord)


def coordinators() -> list:
    with _MU:
        return list(_COORDS)


def reset_pd() -> None:
    """Test seam: detach every coordinator and drop the shared
    in-process backend (fresh plane for the next test)."""
    global _SHARED_BACKEND
    with _MU:
        coords = list(_COORDS)
        _COORDS.clear()
        _SHARED_BACKEND = None
    for c in coords:
        c.leave()


# ---- compile-claim hooks (compilecache.cache miss path) ----------- #

def _live_coordinator():
    for c in coordinators():
        if c.member.joined():
            return c
    return None


def try_compile_claim(entry_hex: str) -> Optional[bool]:
    """None = pd off/degraded (compile as usual); True = claim won
    (compile, then release); False = a live peer is compiling this
    entry (poll the shared cache dir for its persisted result)."""
    coord = _live_coordinator()
    if coord is None:
        return None
    try:
        return coord.registry.try_claim(entry_hex)
    except PdError:
        return None          # store loss mid-claim: degraded-local

def release_compile_claim(entry_hex: str) -> None:
    coord = _live_coordinator()
    if coord is None:
        return
    try:
        coord.registry.release_claim(entry_hex)
    except PdError:
        pass


def broadcast_quarantine(digest: str) -> None:
    """Scheduler breaker hook: tombstone a quarantined digest for
    every peer.  No-op when pd is off or degraded."""
    coord = _live_coordinator()
    if coord is None:
        return
    try:
        coord.registry.broadcast_quarantine(digest)
    except PdError:
        pass


# ---- status surfaces ---------------------------------------------- #

def pd_status() -> dict:
    """The ``pd`` section of ``/sched`` + the backbone of ``/pd``."""
    coords = coordinators()
    if not coords:
        return {"enabled": False, "coordinators": 0}
    out = {"enabled": True,
           "coordinators": len(coords),
           "members": [c.stats() for c in coords]}
    try:
        out["store"] = coords[0].store.dump()
    except PdError:
        out["store"] = {"unavailable": True}
    return out


__all__ = ["PdStore", "MemoryBackend", "FileBackend", "PdError",
           "PdUnavailable", "PdLeaseExpired", "PdMember", "QuotaPool",
           "ProgramRegistry", "PdCoordinator", "KeyFamily",
           "KEY_FAMILIES", "verify_key_families", "pd_report",
           "PD_SYNC_S",
           "configure_domain", "coordinators", "reset_pd",
           "shared_memory_backend", "try_compile_claim",
           "release_compile_claim", "broadcast_quarantine",
           "pd_status"]
