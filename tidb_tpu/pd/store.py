"""coplace: the PD coordination store — a tiny epoch/CAS KV service.

Reference analog: PD (placement driver) in the reference architecture
keeps cluster-wide state in etcd — leases, quota budgets, and shared
metadata — and every writer fences its writes with a lease so a
process that lost its lease (partitioned, paused, restarted) cannot
clobber state the survivors moved on from.  This module is that store
scaled to the repo's deployment unit: N tidb-tpu server processes on
one host over one TPU pod.

Two backends behind one transactional facade:

- ``MemoryBackend`` — in-process dict under a leaf lock; tier-1 tests
  and the ``podshare`` bench rung share one instance between Domains.
  A ``down`` test seam simulates store loss without monkeypatching.
- ``FileBackend`` — one JSON document per pd directory, every
  transaction under an advisory file lock (utils/filelock) with
  atomic temp-file + rename for the write, so real processes sharing
  ``tidb_tpu_pd_dir`` get the same CAS semantics.  Any OSError maps to
  ``PdUnavailable`` — store loss is a *degradation signal*, never an
  exception a statement sees (pd/lease owns that contract).

Write fencing: every mutation carries the writer's lease epoch and is
refused (``PdLeaseExpired``) unless that epoch belongs to a live,
unexpired lease in the same document.  Concurrency between two LIVE
members is resolved by per-key version CAS, not by epoch ordering —
epochs fence the dead, versions serialize the living.

Like copcost and calibrate, this module never imports jax: the
coordination plane is pure host-side bookkeeping.
"""

from __future__ import annotations

import json
import os
import threading
import time
from copy import deepcopy
from dataclasses import dataclass
from typing import Callable, Optional

# lease TTL: a member that misses renewal for this long is fenced out
# (its epoch stops validating) and its quota share redistributes
PD_LEASE_TTL_S = 3.0
# an in-flight cross-process compile claim expires after this long —
# a crashed compiler must not block peers forever (pd/registry)
PD_CLAIM_TTL_S = 30.0
# shared program-registry entries and quarantine tombstones age out
# after this horizon (refreshed on every publish)
PD_PROGRAM_TTL_S = 7 * 24 * 3600.0
# merged calibration payloads older than this are dropped on merge
PD_CALIB_TTL_S = 3600.0
# quota member reports older than 2 lease TTLs are pruned from the
# share computation (the member is gone; its slice redistributes)
PD_QUOTA_TTL_S = 2.0 * PD_LEASE_TTL_S

# bounded CAS retries inside txn_update before reporting contention as
# unavailability (each backend transaction is globally serialized, so
# real contention resolves in one or two rounds)
_TXN_ATTEMPTS = 16

STORE_FILE = "pd.json"
LOCK_FILE = "pd.lock"


class PdError(RuntimeError):
    """Base class for coordination-plane failures.  NEVER escapes to a
    statement: pd/lease converts both subclasses into degraded-local
    operation."""


class PdUnavailable(PdError):
    """The store cannot be reached (file backend I/O failure, memory
    backend ``down`` seam, unresolvable CAS contention)."""


class PdLeaseExpired(PdError):
    """The writer's lease epoch no longer validates — the member was
    fenced out and must re-grant (new epoch) before writing again."""


@dataclass(frozen=True)
class KeyFamily:
    """One row of the shared-store schema (``--pd-report`` renders the
    table and the gate verifies every family names an owner + TTL)."""

    prefix: str     # key prefix ("calib" is a single fixed key)
    owner: str      # pd module that owns every write to the family
    ttl_s: float    # staleness horizon for entries of the family
    epoch_rule: str  # how the lease epoch fences writes
    desc: str


KEY_FAMILIES = (
    KeyFamily("lease/", "pd/lease.py", PD_LEASE_TTL_S,
              "grant assigns the epoch; renew validates it",
              "member leases: epoch + deadline per member id"),
    KeyFamily("quota/", "pd/quota.py", PD_QUOTA_TTL_S,
              "live-lease epoch fencing + version CAS",
              "per-resource-group RU pool: declared budget + per-member "
              "debt reports feeding debt-weighted refill shares"),
    KeyFamily("program/", "pd/registry.py", PD_PROGRAM_TTL_S,
              "live-lease epoch fencing + version CAS",
              "copforge digest registry: persisted entry anatomy peers "
              "adopt into their warm pools"),
    KeyFamily("claim/", "pd/registry.py", PD_CLAIM_TTL_S,
              "live-lease epoch fencing + version CAS",
              "TTL'd in-flight compile claims: first claimant compiles, "
              "peers poll the shared cache dir instead"),
    KeyFamily("quarantine/", "pd/registry.py", PD_PROGRAM_TTL_S,
              "live-lease epoch fencing + version CAS",
              "breaker tombstones: a quarantined digest purges from "
              "every peer's warm pool and correction store"),
    KeyFamily("calib", "pd/coordinator.py", PD_CALIB_TTL_S,
              "live-lease epoch fencing + version CAS",
              "merged CorrectionStore payloads (observation-count-"
              "weighted EWMA merge, clamp [1/8, 8] preserved)"),
)


def _fresh_state() -> dict:
    return {"seq": 0, "leases": {}, "keys": {}}


class MemoryBackend:
    """In-process backend: one dict, one leaf lock, shared by every
    Domain handed the same instance (tier-1 and the podshare bench
    model N processes this way).  ``down = True`` simulates killing
    the store mid-run."""

    def __init__(self):
        self._mu = threading.Lock()
        self._state = _fresh_state()
        self.down = False
        self.transactions = 0

    def transact(self, fn: Callable[[dict], object]):
        with self._mu:
            if self.down:
                raise PdUnavailable("memory backend down (test seam)")
            self.transactions += 1
            return fn(self._state)

    # reads share the write path: the state dict must not be observed
    # mid-mutation from another thread
    transact_read = transact


class FileBackend:
    """File backend: the whole store is one JSON document under the pd
    directory, every transaction serialized by an advisory lock and
    committed by atomic rename.  Deleting the directory mid-run is the
    cross-process equivalent of ``MemoryBackend.down``."""

    def __init__(self, pd_dir: str):
        self.pd_dir = pd_dir
        self._path = os.path.join(pd_dir, STORE_FILE)
        self._lock_path = os.path.join(pd_dir, LOCK_FILE)
        self.transactions = 0
        try:
            os.makedirs(pd_dir, exist_ok=True)
        except OSError:
            pass          # unusable dir surfaces as PdUnavailable on
                          # the first transaction (degraded, not fatal)

    def _read_locked(self) -> dict:
        try:
            with open(self._path, encoding="utf-8") as f:
                doc = json.load(f)
            if isinstance(doc, dict) and "keys" in doc:
                return doc
        except FileNotFoundError:
            pass
        except ValueError:
            # a corrupt document cannot happen via the atomic-rename
            # write path; treat external damage as a fresh store rather
            # than wedging every member permanently
            pass
        return _fresh_state()

    def _transact(self, fn: Callable[[dict], object], write: bool):
        from ..utils.filelock import locked_file
        try:
            with locked_file(self._lock_path):
                state = self._read_locked()
                out = fn(state)
                if write:
                    tmp = self._path + f".tmp{os.getpid()}"
                    with open(tmp, "w", encoding="utf-8") as f:
                        json.dump(state, f)
                    os.replace(tmp, self._path)
                # flock excludes same-process threads too (each call
                # opens its own fd), so this += never runs concurrently
                self.transactions += 1  # planlint: ok - flock-serialized
                return out
        except OSError as e:
            raise PdUnavailable(f"pd store I/O: {e}") from e

    def transact(self, fn: Callable[[dict], object]):
        return self._transact(fn, write=True)

    def transact_read(self, fn: Callable[[dict], object]):
        return self._transact(fn, write=False)


class PdStore:
    """The transactional facade every pd module writes through.

    API shape (all raise only PdUnavailable / PdLeaseExpired):

    - ``grant(member_id, ttl_s) -> epoch`` — new lease, new fencing
      epoch (monotonic per store via the ``seq`` counter).
    - ``renew(member_id, epoch, ttl_s)`` — extend a live lease;
      PdLeaseExpired when the lease lapsed or the epoch is stale.
    - ``cas(key, expect_ver, value, *, epoch) -> bool`` — versioned
      compare-and-swap, fenced by the writer's live lease epoch.
    - ``txn_update(key, fn, *, epoch) -> value`` — read-modify-write
      via a bounded CAS loop (fn gets a deep copy; absent key = None).
    - ``delete(key, *, epoch)`` / ``get`` / ``read_prefix`` /
      ``members`` / ``dump``.
    """

    def __init__(self, backend):
        self._b = backend

    @property
    def backend(self):
        return self._b

    # ---- leases ------------------------------------------------------ #

    def grant(self, member_id: str, ttl_s: float = PD_LEASE_TTL_S) -> int:
        def txn(state: dict) -> int:
            state["seq"] = state.get("seq", 0) + 1
            epoch = state["seq"]
            state.setdefault("leases", {})[member_id] = {
                "epoch": epoch, "deadline": time.time() + ttl_s}
            return epoch
        return self._b.transact(txn)

    def renew(self, member_id: str, epoch: int,
              ttl_s: float = PD_LEASE_TTL_S) -> None:
        def txn(state: dict) -> None:
            lease = state.get("leases", {}).get(member_id)
            now = time.time()
            if (lease is None or lease.get("epoch") != epoch
                    or lease.get("deadline", 0.0) < now):
                raise PdLeaseExpired(
                    f"lease {member_id!r} epoch {epoch} lapsed")
            lease["deadline"] = now + ttl_s
        self._b.transact(txn)

    def release(self, member_id: str, epoch: int) -> None:
        """Graceful leave: drop the lease iff it is still ours."""
        def txn(state: dict) -> None:
            lease = state.get("leases", {}).get(member_id)
            if lease is not None and lease.get("epoch") == epoch:
                del state["leases"][member_id]
        self._b.transact(txn)

    def members(self) -> dict:
        """Live (unexpired) leases: member id -> {epoch, deadline}."""
        def txn(state: dict) -> dict:
            now = time.time()
            return {m: dict(lease)
                    for m, lease in sorted(
                        state.get("leases", {}).items())
                    if lease.get("deadline", 0.0) >= now}
        return self._b.transact_read(txn)

    def _check_epoch_locked(self, state: dict, epoch: int) -> None:
        """Fencing: the writer's epoch must belong to a live lease.
        (Between two live members, per-key version CAS serializes —
        see module doc.)"""
        now = time.time()
        for _m, lease in sorted(state.get("leases", {}).items()):
            if (lease.get("epoch") == epoch
                    and lease.get("deadline", 0.0) >= now):
                return
        raise PdLeaseExpired(f"write epoch {epoch} has no live lease")

    # ---- keys -------------------------------------------------------- #

    def get(self, key: str) -> tuple:
        """(value, version); (None, 0) when absent.  Values are deep
        copies — callers never hold a live reference into the store."""
        def txn(state: dict) -> tuple:
            ent = state.get("keys", {}).get(key)
            if ent is None:
                return None, 0
            return deepcopy(ent.get("val")), ent.get("ver", 0)
        return self._b.transact_read(txn)

    def read_prefix(self, prefix: str) -> dict:
        """key -> (value, version) for every key under ``prefix``."""
        def txn(state: dict) -> dict:
            out = {}
            for key in sorted(state.get("keys", {})):
                if key.startswith(prefix):
                    ent = state["keys"][key]
                    out[key] = (deepcopy(ent.get("val")),
                                ent.get("ver", 0))
            return out
        return self._b.transact_read(txn)

    def cas(self, key: str, expect_ver: int, value,
            *, epoch: int) -> bool:
        def txn(state: dict) -> bool:
            self._check_epoch_locked(state, epoch)
            ent = state.get("keys", {}).get(key)
            ver = ent.get("ver", 0) if ent is not None else 0
            if ver != expect_ver:
                return False
            state.setdefault("keys", {})[key] = {
                "val": deepcopy(value), "ver": ver + 1, "epoch": epoch}
            return True
        return self._b.transact(txn)

    def txn_update(self, key: str, fn: Callable[[Optional[object]], object],
                   *, epoch: int):
        """Read-modify-write under the lease-epoch CAS check: ``fn``
        receives the current value (None when absent) and returns the
        replacement.  Bounded retries; sustained contention reports as
        PdUnavailable (degrade, don't spin)."""
        for _attempt in range(_TXN_ATTEMPTS):
            cur, ver = self.get(key)
            new = fn(cur)
            if self.cas(key, ver, new, epoch=epoch):
                return new
        raise PdUnavailable(f"txn contention on {key!r}")

    def delete(self, key: str, *, epoch: int) -> None:
        def txn(state: dict) -> None:
            self._check_epoch_locked(state, epoch)
            state.get("keys", {}).pop(key, None)
        self._b.transact(txn)

    # ---- introspection (the /pd route) ------------------------------- #

    def dump(self, max_keys: int = 64) -> dict:
        """Bounded snapshot for the status surface: live leases + key
        census per family + the first ``max_keys`` keys."""
        def txn(state: dict) -> dict:
            now = time.time()
            keys = state.get("keys", {})
            families = {}
            for fam in KEY_FAMILIES:
                if fam.prefix.endswith("/"):
                    n = sum(1 for k in keys if k.startswith(fam.prefix))
                else:
                    n = 1 if fam.prefix in keys else 0
                families[fam.prefix] = n
            return {
                "seq": state.get("seq", 0),
                "leases": {m: {"epoch": lease.get("epoch"),
                               "ttl_left_s": round(
                                   lease.get("deadline", 0.0) - now, 3)}
                           for m, lease in sorted(
                               state.get("leases", {}).items())},
                "families": families,
                "keys": {k: {"ver": keys[k].get("ver", 0),
                             "epoch": keys[k].get("epoch", 0)}
                         for k in sorted(keys)[:max_keys]},
                "n_keys": len(keys),
            }
        return self._b.transact_read(txn)


def verify_key_families() -> list:
    """``--pd-report`` gate check: every key family must declare an
    owner module, a positive TTL, and an epoch rule.  Returns the list
    of violations (empty = pass)."""
    bad = []
    seen = set()
    for fam in KEY_FAMILIES:
        if fam.prefix in seen:
            bad.append(f"duplicate family {fam.prefix!r}")
        seen.add(fam.prefix)
        if not fam.owner.startswith("pd/"):
            bad.append(f"{fam.prefix!r}: owner {fam.owner!r} not a pd "
                       "module")
        if fam.ttl_s <= 0:
            bad.append(f"{fam.prefix!r}: non-positive TTL")
        if "epoch" not in fam.epoch_rule and \
                "grant" not in fam.epoch_rule:
            bad.append(f"{fam.prefix!r}: no epoch rule")
        if not fam.desc:
            bad.append(f"{fam.prefix!r}: undocumented")
    return bad


def pd_report() -> str:
    """Human-readable shared-store schema (``--pd-report``): every key
    family with its owner module, TTL, and epoch-fencing rule, plus a
    live micro-simulation of the fence on a fresh in-memory store."""
    lines = ["coplace shared-store schema",
             "=" * 68, ""]
    for fam in KEY_FAMILIES:
        ttl = (f"{fam.ttl_s:g}s" if fam.ttl_s < 86400.0
               else f"{fam.ttl_s / 86400.0:g}d")
        lines.append(f"{fam.prefix:<12} owner {fam.owner:<20} ttl {ttl}")
        lines.append(f"{'':>12} fence: {fam.epoch_rule}")
        lines.append(f"{'':>12} {fam.desc}")
        lines.append("")
    bad = verify_key_families()
    # live fence check: a granted epoch writes, a released one is
    # refused, version CAS rejects stale writers
    store = PdStore(MemoryBackend())
    e1 = store.grant("report-a")
    e2 = store.grant("report-b")
    if not store.cas("quota/report", 0, {"v": 1}, epoch=e1):
        bad.append("live store refused a fresh epoch-carrying CAS")
    if store.cas("quota/report", 0, {"v": 2}, epoch=e2):
        bad.append("live store accepted a stale-version CAS")
    store.release("report-b", e2)
    try:
        store.cas("quota/report", 1, {"v": 3}, epoch=e2)
        bad.append("live store accepted a write from a dead epoch")
    except PdLeaseExpired:
        pass
    for v in bad:
        lines.append(f"VIOLATION {v}")
    lines.append(f"pd: {len(KEY_FAMILIES)} key families verified "
                 f"(owner+ttl+epoch), dead-epoch writes fenced, "
                 f"{len(bad)} violations")
    return "\n".join(lines)


__all__ = ["PdStore", "MemoryBackend", "FileBackend", "PdError",
           "PdUnavailable", "PdLeaseExpired", "KeyFamily",
           "KEY_FAMILIES", "verify_key_families", "pd_report",
           "PD_LEASE_TTL_S", "PD_CLAIM_TTL_S", "PD_PROGRAM_TTL_S",
           "PD_CALIB_TTL_S", "PD_QUOTA_TTL_S", "STORE_FILE",
           "LOCK_FILE"]
