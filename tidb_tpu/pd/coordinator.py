"""coplace: the per-Domain coordination loop.

Reference analog: the PD client embedded in every tidb-server —
owns the lease heartbeat and fans state both ways on a tick.  The
tick is STATEMENT-DRIVEN (session/_exec_ctx calls ``tick()`` on the
hot path), internally throttled, and deterministic: no background
thread, nothing to leak on Domain close, and tests force a tick
instead of sleeping.

One tick, when due:

1. ``pd.renew`` span — grant/renew the lease (pd/lease).  A failure
   here flips DEGRADED: quota falls to local slices
   (pd/quota.degrade_to_local_slice), caches go local-only, the
   ``tidb_tpu_pd_degraded_total`` counter bumps, the active trace is
   flagged ``pd_degraded`` — and the statement proceeds normally.
2. ``pd.sync`` span — quota rebalance (debt-weighted shares),
   calibration merge (observation-count-weighted EWMA through the
   ``calib`` key), registry gossip (publish persisted entries, adopt
   a bounded number of peer entries, apply quarantine tombstones).
   A rejoin after degradation forces this full resync immediately.
"""

from __future__ import annotations

import threading
import time

from .lease import PdMember
from .quota import QuotaPool
from .registry import ProgramRegistry
from .store import (PD_CALIB_TTL_S, PD_LEASE_TTL_S, PdError, PdStore)

# min seconds between sync rounds (renewal is additionally throttled
# to ~TTL/3 inside pd/lease); tests pass force=True instead of waiting
PD_SYNC_S = 0.5

CALIB_KEY = "calib"

# hard bound on the shared calibration document (the store holds the
# hot corpus, not an unbounded history); lowest-sample digests drop
CALIB_SHARED_CAP = 512


def _pd_metrics() -> dict:
    from ..utils.metrics import global_registry
    reg = global_registry()
    return {
        "renew": reg.counter("tidb_tpu_pd_renew_total",
                             "pd lease grants + renewals"),
        "sync": reg.counter("tidb_tpu_pd_sync_total",
                            "pd sync rounds completed"),
        "degraded": reg.counter("tidb_tpu_pd_degraded_total",
                                "transitions into degraded-local "
                                "operation (store loss / lease fence)"),
        "members": reg.gauge("tidb_tpu_pd_members",
                             "live members on the coordination store"),
        "share": reg.gauge("tidb_tpu_pd_quota_share_ru",
                           "leased RU/s refill share per resource "
                           "group", labels=("group",)),
        "peer_warm": reg.counter("tidb_tpu_pd_peer_warm_total",
                                 "compile-cache entries adopted from "
                                 "peers' registry publications"),
        "calib": reg.counter("tidb_tpu_pd_calib_merged_total",
                             "correction payloads merged from the "
                             "shared store"),
        "quarantine": reg.counter(
            "tidb_tpu_pd_quarantine_purged_total",
            "peer quarantine tombstones applied locally"),
    }


class PdCoordinator:
    """One Domain's membership: lease + quota + registry + calibration
    sync over one PdStore."""

    def __init__(self, store: PdStore, manager, member_id: str = "",
                 ttl_s: float = PD_LEASE_TTL_S, pd_dir: str = "",
                 calib=None, cache=None):
        self.store = store
        self.pd_dir = pd_dir
        self.member = PdMember(store, member_id, ttl_s)
        self.quota = QuotaPool(self.member, manager)
        self.registry = ProgramRegistry(self.member)
        self._calib = calib          # None = process correction_store()
        self._cache = cache          # None = process compile_cache()
        self._tick_mu = threading.Lock()   # leaf: throttle state only
        self._last_sync = 0.0
        self.sync_total = 0
        self.calib_merged = 0
        self._m = _pd_metrics()

    # test seams default to the process singletons
    def _calibration(self):
        if self._calib is not None:
            return self._calib
        from ..analysis.calibrate import correction_store
        return correction_store()

    def _compile_cache(self):
        if self._cache is not None:
            return self._cache
        from ..compilecache import compile_cache
        return compile_cache()

    def matches(self, pd_dir: str) -> bool:
        return self.pd_dir == pd_dir

    # ---- the tick ---------------------------------------------------- #

    def tick(self, now: float = 0.0, force: bool = False) -> None:
        """Statement-driven heartbeat.  Never raises, never blocks on
        a peer's tick (contended ticks are simply skipped — the next
        statement retries)."""
        if not self._tick_mu.acquire(blocking=False):
            return
        try:
            now = now or time.time()
            due = force or now - self._last_sync >= PD_SYNC_S
            if not due:
                return
            self._last_sync = now
            self._run_round(now)
        finally:
            self._tick_mu.release()

    def _run_round(self, now: float) -> None:
        from ..obs import trace
        was_degraded = self.member.degraded
        with trace.span("pd.renew", member=self.member.member_id):
            live = self.member.ensure(now)
        if live:
            self._m["renew"].inc()
        if not live:
            if not was_degraded:
                # the degradation EDGE: local quota slices, counter,
                # trace flag — statements keep flowing
                self.quota.degrade_to_local_slice()
                self._m["degraded"].inc()
                trace.flag("pd_degraded")
            return
        rejoined = self.member.consume_rejoin()
        with trace.span("pd.sync", rejoin=rejoined):
            try:
                self._sync_round(rejoined)
            except PdError:
                # the store died mid-sync: same edge as a failed renew
                self.member.degrade()
                self.quota.degrade_to_local_slice()
                self._m["degraded"].inc()
                trace.flag("pd_degraded")

    def _sync_round(self, rejoined: bool) -> None:
        self.quota.sync()
        merged = self._sync_calibration()
        cache = self._compile_cache()
        manifest = cache.manifest
        if manifest is not None:
            manifest.refresh()     # fold peers' persisted entries in
            self.registry.publish_manifest(manifest)
            adopted = self.registry.adopt_from_peers(cache)
            if adopted:
                self._m["peer_warm"].inc(adopted)
        purged = self.registry.sync_quarantine(cache)
        if purged:
            self._m["quarantine"].inc(purged)
        if merged:
            self._m["calib"].inc(merged)
        self.sync_total += 1
        self._m["sync"].inc()
        self._m["members"].set(len(self.store.members()))
        for group, share in sorted(self.quota.shares.items()):
            self._m["share"].set(share, group=group)

    # ---- calibration sync -------------------------------------------- #

    def _sync_calibration(self) -> int:
        """Two-way merge through the ``calib`` key: publish local
        payloads into the shared doc (observation-count-weighted EWMA
        merge, clamp preserved — analysis/calibrate owns the math),
        then fold the merged doc back into the local store.  Returns
        how many local entries moved."""
        from ..analysis.calibrate import merge_correction_payloads
        calib = self._calibration()
        local = calib.entries_payload()
        now = time.time()
        publish = {d: p for d, p in sorted(local.items())
                   if p.get("samples", 0) > 0
                   or p.get("mem_samples", 0) > 0
                   or p.get("oom_bumps", 0) > 0}

        def merge(cur):
            doc = cur if isinstance(cur, dict) else {}
            for d in sorted(publish):
                prev = doc.get(d)
                fresh = dict(publish[d])
                merged = merge_correction_payloads(
                    prev if isinstance(prev, dict) else None, fresh)
                merged["ts"] = now
                doc[d] = merged
            for d in sorted(doc):
                if now - doc[d].get("ts", 0.0) > PD_CALIB_TTL_S:
                    del doc[d]
            if len(doc) > CALIB_SHARED_CAP:
                keep = sorted(doc,
                              key=lambda k: (-doc[k].get("samples", 0),
                                             k))[:CALIB_SHARED_CAP]
                return {d: doc[d] for d in keep}
            return doc

        doc = self.store.txn_update(CALIB_KEY, merge,
                                    epoch=self.member.epoch)
        merged_n = 0
        for d in sorted(doc):
            if calib.merge_payload(d, doc[d]):
                merged_n += 1
        self.calib_merged += merged_n
        return merged_n

    # ---- lifecycle / introspection ----------------------------------- #

    def leave(self) -> None:
        """Graceful detach (pd disabled): release the lease, restore
        full single-process refill rates."""
        self.member.leave()
        self.quota.restore_full()

    def stats(self) -> dict:
        return {"enabled": True,
                "pd_dir": self.pd_dir or "(in-process)",
                "member": self.member.stats(),
                "quota": self.quota.stats(),
                "registry": self.registry.stats(),
                "sync_total": self.sync_total,
                "calib_merged": self.calib_merged}


__all__ = ["PdCoordinator", "PD_SYNC_S", "CALIB_KEY",
           "CALIB_SHARED_CAP"]
