"""Dumpling analog: parallel logical export with a consistent snapshot.

Reference: dumpling/ (12.8k LoC) — exports schema + data as SQL or CSV,
one file set per table, all tables read at ONE snapshot ts so the dump
is transactionally consistent; N worker threads export tables in
parallel (dumpling's per-table goroutines + chunked files).
"""

from __future__ import annotations

import csv
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from ..session.codec_io import scan_table_rows
from ..sql.bind import sql_literal


def _create_table_sql(tbl) -> str:
    cols = []
    for n, t in zip(tbl.col_names, tbl.col_types):
        line = f"  `{n}` {_sql_type(t)}"
        if not t.nullable:
            line += " NOT NULL"
        if tbl.auto_inc_col == n:
            line += " AUTO_INCREMENT"
        cols.append(line)
    if tbl.primary_key:
        cols.append("  PRIMARY KEY (" +
                    ", ".join(f"`{c}`" for c in tbl.primary_key) + ")")
    for ix in tbl.indexes:
        if ix.name == "PRIMARY" or ix.state != "public":
            continue
        kind = "UNIQUE KEY" if ix.unique else "KEY"
        cols.append(f"  {kind} `{ix.name}` (" +
                    ", ".join(f"`{c}`" for c in ix.columns) + ")")
    return (f"CREATE TABLE `{tbl.name}` (\n" + ",\n".join(cols) + "\n);")


def _sql_type(t) -> str:
    from ..types import dtypes as dt
    K = dt.TypeKind
    return {
        K.INT64: "bigint", K.UINT64: "bigint unsigned", K.FLOAT64: "double",
        K.FLOAT32: "float", K.STRING: "varchar(255)", K.DATE: "date",
        K.DATETIME: "datetime", K.TIME: "time",
    }.get(t.kind, f"decimal({max(t.prec, 1)},{max(t.scale, 0)})"
          if t.kind == K.DECIMAL else "varchar(255)")


def dump_database(domain, db: str, out_dir: str, fmt: str = "sql",
                  threads: int = 4, rows_per_stmt: int = 200) -> dict:
    """Export all tables of `db`; returns {table: row_count}.

    Layout mirrors dumpling: {db}-schema-create.sql, {db}.{t}-schema.sql,
    {db}.{t}.{000000000}.sql|csv.
    """
    os.makedirs(out_dir, exist_ok=True)
    tables = domain.catalog.databases.get(db)
    if tables is None:
        raise ValueError(f"unknown database {db!r}")
    with open(os.path.join(out_dir, f"{db}-schema-create.sql"), "w") as f:
        f.write(f"CREATE DATABASE IF NOT EXISTS `{db}`;\n")
    # ONE snapshot ts for every table = consistent dump
    ts = domain.kv.alloc_ts()
    counts: dict[str, int] = {}

    def dump_table(name: str) -> tuple[str, int]:
        tbl = tables[name]
        with open(os.path.join(out_dir, f"{db}.{name}-schema.sql"), "w") as f:
            f.write(_create_table_sql(tbl) + "\n")
        if tbl.kv is not None:
            # decode_row already yields dump-ready values (decimals and
            # temporals as strings)
            _, rows = scan_table_rows(tbl.kv, tbl.table_id, ts,
                                      tbl.col_types)
        else:
            snap = tbl.snapshot()
            rows = list(zip(*[c.to_python() for c in snap.columns])) \
                if snap.num_rows else []
        path = os.path.join(out_dir, f"{db}.{name}.000000000.{fmt}")
        if fmt == "csv":
            with open(path, "w", newline="") as f:
                w = csv.writer(f)
                w.writerow(tbl.col_names)
                for r in rows:
                    w.writerow(["\\N" if v is None else v for v in r])
        else:
            with open(path, "w") as f:
                for off in range(0, len(rows), rows_per_stmt):
                    chunk = rows[off:off + rows_per_stmt]
                    vals = ",\n".join(
                        "(" + ",".join(sql_literal(v) for v in r) + ")"
                        for r in chunk)
                    f.write(f"INSERT INTO `{name}` VALUES\n{vals};\n")
        return name, len(rows)

    with ThreadPoolExecutor(max_workers=max(threads, 1),
                            thread_name_prefix="dump") as pool:
        for name, n in pool.map(dump_table, sorted(tables)):
            counts[name] = n
    return counts


__all__ = ["dump_database"]
