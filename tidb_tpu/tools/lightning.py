"""Lightning analog: bulk CSV import via pre-sorted KV batch ingest.

Reference: lightning/ + pkg/lightning (87k LoC) — reads source files,
encodes rows to KV pairs, sorts, and ingests SSTs directly into the
store (local backend), bypassing the SQL write path; checkpoints let an
interrupted import resume; duplicate detection reports conflicting keys.

Here: parse CSV with a worker pool (chunked by byte ranges like
mydump's region split), encode rows + index entries with the production
codecs, sort each engine batch by key, ingest through large KV txns,
checkpoint per chunk, and run a post-import duplicate check on unique
keyspaces (errors mirror lightning's conflict detection).
"""

from __future__ import annotations

import csv
import json
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from ..session.codec_io import encode_table_row

CHUNK_ROWS = 4096        # one checkpointed ingest unit (region/SST analog)


def import_csv(domain, db: str, table: str, path: str,
               threads: int = 4, has_header: bool = True,
               checkpoint_path: Optional[str] = None) -> int:
    """Bulk-load a CSV file into an existing (empty or non-empty) table.
    Returns rows imported.  Resumes from `checkpoint_path` if given."""
    tbl = domain.catalog.get_table(db, table)
    if tbl.kv is None:
        raise ValueError("bulk import needs a KV-backed table")
    with open(path, newline="") as f:
        reader = csv.reader(f)
        rows = list(reader)
    if has_header:
        if rows and [c.strip().lower() for c in rows[0]] == \
                [c.lower() for c in tbl.col_names]:
            rows = rows[1:]
        elif rows:
            rows = rows[1:]
    # checkpoint: chunks already ingested (lightning/checkpoints analog)
    done: set[int] = set()
    if checkpoint_path and os.path.exists(checkpoint_path):
        done = set(json.load(open(checkpoint_path)))

    chunks = [(ci, rows[off:off + CHUNK_ROWS])
              for ci, off in enumerate(range(0, len(rows), CHUNK_ROWS))]
    # pre-assign handle ranges per chunk so parallel encode is determinate
    # (allocation under the table's autoid lock)
    with tbl._alloc_mu:
        starts = {}
        h = tbl._next_handle
        for ci, chunk in chunks:
            starts[ci] = h
            h += len(chunk)
        tbl._next_handle = h

    def to_value(raw: str, t):
        if raw == "\\N" or raw == "":
            return None
        if t.is_integer:
            return int(raw)
        if t.is_float:
            return float(raw)
        return raw

    def ingest_chunk(arg) -> int:
        ci, chunk = arg
        if ci in done:
            return 0
        # hold the schema gate across the chunk: a concurrent online DDL
        # transition (or its rollback wipe) must not interleave with this
        # ingest, and index entries are written only for indexes whose F1
        # state accepts writes ('none'/'delete only' must NOT receive
        # inserts — mirrors catalog._write_index_entries)
        with tbl.schema_gate.read():
            pairs = []
            handle = starts[ci]
            for raw in chunk:
                if len(raw) != len(tbl.col_names):
                    raise ValueError(
                        f"row width {len(raw)} != {len(tbl.col_names)} "
                        f"columns: {raw!r}")
                vals = tuple(to_value(c, t)
                             for c, t in zip(raw, tbl.col_types))
                for i, t in enumerate(tbl.col_types):
                    if vals[i] is None and not t.nullable:
                        raise ValueError(
                            f"NULL in NOT NULL column {tbl.col_names[i]!r}")
                handle += 1
                pairs.append(encode_table_row(tbl.table_id, handle, vals,
                                              tbl.col_types))
                for ix in tbl.writable_indexes():
                    pairs.append(tbl._index_entry(ix, vals, handle))
            pairs.sort(key=lambda kv: kv[0])   # sorted ingest (SST build)
            txn = tbl.kv.begin()
            for k, v in pairs:
                txn.put(k, v)
            txn.commit()
        return len(chunk)

    total = 0
    with ThreadPoolExecutor(max_workers=max(threads, 1),
                            thread_name_prefix="lightning") as pool:
        for (ci, _), n in zip(chunks, pool.map(ingest_chunk, chunks)):
            total += n
            done.add(ci)
            if checkpoint_path:
                with open(checkpoint_path + ".tmp", "w") as f:
                    json.dump(sorted(done), f)
                os.replace(checkpoint_path + ".tmp", checkpoint_path)
    tbl._invalidate()
    _duplicate_check(tbl)
    return total


def _duplicate_check(tbl):
    """Post-import conflict detection on unique indexes (lightning's
    duplicate resolution surface, backend/local duplicate detector)."""
    from ..session.catalog import DuplicateKeyError
    from ..store.codec import index_prefix, index_prefix_end
    ts = tbl.kv.alloc_ts()
    for ix in tbl.indexes:
        if not ix.unique:
            continue
        # unique index: one key per distinct column tuple — a second row
        # with the same tuple overwrote the first entry, so compare counts
        n_entries = sum(1 for _ in tbl.kv.scan(
            index_prefix(tbl.table_id, ix.index_id),
            index_prefix_end(tbl.table_id, ix.index_id), ts))
        n_rows = tbl.snapshot().num_rows
        if n_entries != n_rows:
            raise DuplicateKeyError(
                f"import produced {n_rows - n_entries} duplicate(s) on "
                f"unique index {ix.name!r} of {tbl.name!r}")


__all__ = ["import_csv"]


def global_sort_import(domain, db: str, table: str, path: str,
                       run_dir: str, mem_budget_bytes: int = 64 << 20,
                       has_header: bool = True,
                       ingest_batch: int = 8192) -> int:
    """Bulk import through GLOBAL SORT on external storage (the
    lightning external backend, pkg/lightning/backend/external): stream
    the source, encode record + index KV pairs, spill sorted runs to
    `run_dir` under a memory budget, then k-way-merge the runs and
    ingest one fully KEY-ORDERED stream — the path that scales past RAM
    where import_csv materializes the file.

    `run_dir` is the external-storage seam: re-running with the same
    directory resumes from completed runs (only the unfinished tail of
    the source re-encodes)."""
    import csv as _csv

    from .external_sort import ExternalSorter

    tbl = domain.catalog.get_table(db, table)
    if tbl.kv is None:
        raise ValueError("bulk import needs a KV-backed table")

    def to_value(raw: str, t):
        if raw == "\\N" or raw == "":
            return None
        if t.is_integer:
            return int(raw)
        if t.is_float:
            return float(raw)
        return raw

    sorter = ExternalSorter(run_dir, mem_budget_bytes)
    n_rows = 0
    with tbl.schema_gate.read():
        if not sorter.runs:          # fresh import: encode + spill runs
            with open(path, newline="") as f:
                reader = _csv.reader(f)
                first = True
                with tbl._alloc_mu:
                    handle = tbl._next_handle
                for raw in reader:
                    if first:
                        first = False
                        if has_header:
                            continue
                    if not raw:
                        continue
                    vals = tuple(to_value(c, t)
                                 for c, t in zip(raw, tbl.col_types))
                    for i, t in enumerate(tbl.col_types):
                        if vals[i] is None and not t.nullable:
                            raise ValueError(
                                "NULL in NOT NULL column "
                                f"{tbl.col_names[i]!r}")
                    handle += 1
                    n_rows += 1
                    k, v = encode_table_row(tbl.table_id, handle, vals,
                                            tbl.col_types)
                    sorter.add(k, v)
                    for ix in tbl.writable_indexes():
                        ik, iv = tbl._index_entry(ix, vals, handle)
                        sorter.add(ik, iv)
                with tbl._alloc_mu:
                    tbl._next_handle = max(tbl._next_handle, handle)
            sorter.flush()
        # merge-read every run in key order, ingest in batches
        txn = tbl.kv.begin()
        in_batch = 0
        from ..store.codec import record_prefix
        rec_prefix = record_prefix(tbl.table_id)
        merged_rows = 0
        for k, v in sorter.merged():
            txn.put(k, v)
            if k.startswith(rec_prefix):
                merged_rows += 1
            in_batch += 1
            if in_batch >= ingest_batch:
                txn.commit()
                txn = tbl.kv.begin()
                in_batch = 0
        txn.commit()
    sorter.cleanup()
    tbl._invalidate()
    _duplicate_check(tbl)
    return n_rows or merged_rows
