"""Lightning analog: bulk CSV import via pre-sorted KV batch ingest.

Reference: lightning/ + pkg/lightning (87k LoC) — reads source files,
encodes rows to KV pairs, sorts, and ingests SSTs directly into the
store (local backend), bypassing the SQL write path; checkpoints let an
interrupted import resume; duplicate detection reports conflicting keys.

Here: parse CSV with a worker pool (chunked by byte ranges like
mydump's region split), encode rows + index entries with the production
codecs, sort each engine batch by key, ingest through large KV txns,
checkpoint per chunk, and run a post-import duplicate check on unique
keyspaces (errors mirror lightning's conflict detection).
"""

from __future__ import annotations

import csv
import json
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from ..session.codec_io import encode_table_row

CHUNK_ROWS = 4096        # one checkpointed ingest unit (region/SST analog)


def _to_value(raw: str, t):
    """CSV field -> python value in the column's type (shared by both
    import paths so NULL/number coercion can never diverge)."""
    if raw == "\\N" or raw == "":
        return None
    if t.is_integer:
        return int(raw)
    if t.is_float:
        return float(raw)
    return raw


def import_csv(domain, db: str, table: str, path: str,
               threads: int = 4, has_header: bool = True,
               checkpoint_path: Optional[str] = None) -> int:
    """Bulk-load a CSV file into an existing (empty or non-empty) table.
    Returns rows imported.  Resumes from `checkpoint_path` if given."""
    tbl = domain.catalog.get_table(db, table)
    if tbl.kv is None:
        raise ValueError("bulk import needs a KV-backed table")
    with open(path, newline="") as f:
        reader = csv.reader(f)
        rows = list(reader)
    if has_header:
        if rows and [c.strip().lower() for c in rows[0]] == \
                [c.lower() for c in tbl.col_names]:
            rows = rows[1:]
        elif rows:
            rows = rows[1:]
    # checkpoint: chunks already ingested (lightning/checkpoints analog)
    done: set[int] = set()
    if checkpoint_path and os.path.exists(checkpoint_path):
        done = set(json.load(open(checkpoint_path)))

    chunks = [(ci, rows[off:off + CHUNK_ROWS])
              for ci, off in enumerate(range(0, len(rows), CHUNK_ROWS))]
    # pre-assign handle ranges per chunk so parallel encode is determinate
    # (allocation under the table's autoid lock)
    with tbl._alloc_mu:
        starts = {}
        h = tbl._next_handle
        for ci, chunk in chunks:
            starts[ci] = h
            h += len(chunk)
        tbl._next_handle = h

    to_value = _to_value

    def ingest_chunk(arg) -> int:
        ci, chunk = arg
        if ci in done:
            return 0
        # hold the schema gate across the chunk: a concurrent online DDL
        # transition (or its rollback wipe) must not interleave with this
        # ingest, and index entries are written only for indexes whose F1
        # state accepts writes ('none'/'delete only' must NOT receive
        # inserts — mirrors catalog._write_index_entries)
        with tbl.schema_gate.read():
            pairs = []
            handle = starts[ci]
            for raw in chunk:
                if len(raw) != len(tbl.col_names):
                    raise ValueError(
                        f"row width {len(raw)} != {len(tbl.col_names)} "
                        f"columns: {raw!r}")
                vals = tuple(to_value(c, t)
                             for c, t in zip(raw, tbl.col_types))
                for i, t in enumerate(tbl.col_types):
                    if vals[i] is None and not t.nullable:
                        raise ValueError(
                            f"NULL in NOT NULL column {tbl.col_names[i]!r}")
                handle += 1
                pairs.append(encode_table_row(tbl.table_id, handle, vals,
                                              tbl.col_types))
                for ix in tbl.writable_indexes():
                    pairs.append(tbl._index_entry(ix, vals, handle))
            pairs.sort(key=lambda kv: kv[0])   # sorted ingest (SST build)
            txn = tbl.kv.begin()
            for k, v in pairs:
                txn.put(k, v)
            txn.commit()
        return len(chunk)

    total = 0
    with ThreadPoolExecutor(max_workers=max(threads, 1),
                            thread_name_prefix="lightning") as pool:
        for (ci, _), n in zip(chunks, pool.map(ingest_chunk, chunks)):
            total += n
            done.add(ci)
            if checkpoint_path:
                with open(checkpoint_path + ".tmp", "w") as f:
                    json.dump(sorted(done), f)
                os.replace(checkpoint_path + ".tmp", checkpoint_path)
    tbl._invalidate()
    _duplicate_check(tbl)
    return total


def _duplicate_check(tbl):
    """Post-import conflict detection on unique indexes (lightning's
    duplicate resolution surface, backend/local duplicate detector)."""
    from ..session.catalog import DuplicateKeyError
    from ..store.codec import index_prefix, index_prefix_end
    ts = tbl.kv.alloc_ts()
    for ix in tbl.indexes:
        if not ix.unique:
            continue
        # unique index: one key per distinct column tuple — a second row
        # with the same tuple overwrote the first entry, so compare counts
        n_entries = sum(1 for _ in tbl.kv.scan(
            index_prefix(tbl.table_id, ix.index_id),
            index_prefix_end(tbl.table_id, ix.index_id), ts))
        n_rows = tbl.snapshot().num_rows
        if n_entries != n_rows:
            raise DuplicateKeyError(
                f"import produced {n_rows - n_entries} duplicate(s) on "
                f"unique index {ix.name!r} of {tbl.name!r}")


def global_sort_import(domain, db: str, table: str, path: str,
                       run_dir: str, mem_budget_bytes: int = 64 << 20,
                       has_header: bool = True,
                       ingest_batch: int = 8192) -> int:
    """Bulk import through GLOBAL SORT on external storage (the
    lightning external backend, pkg/lightning/backend/external): stream
    the source, encode record + index KV pairs, spill sorted runs to
    `run_dir` under a memory budget, then k-way-merge the runs and
    ingest one fully KEY-ORDERED stream — the path that scales past RAM
    where import_csv materializes the file.

    `run_dir` must be empty/fresh: a partial previous attempt's runs are
    an incomplete encode, so resuming from them would silently drop data
    (re-run imports re-encode from the source instead).  Handle ranges
    reserve in blocks under the table's allocation lock, so concurrent
    INSERTs can never collide with imported rows."""
    import csv as _csv

    from .external_sort import ExternalSorter

    tbl = domain.catalog.get_table(db, table)
    if tbl.kv is None:
        raise ValueError("bulk import needs a KV-backed table")
    sorter = ExternalSorter(run_dir, mem_budget_bytes)
    if sorter.runs:
        raise ValueError(
            f"run_dir {run_dir!r} already holds sorted runs from an "
            "earlier attempt; use a fresh directory (a partial encode "
            "must not be mistaken for the whole source)")

    HBLOCK = 65536
    block_next, block_end = 0, 0

    def next_handle() -> int:
        nonlocal block_next, block_end
        if block_next >= block_end:
            with tbl._alloc_mu:
                block_next = tbl._next_handle + 1
                tbl._next_handle += HBLOCK
            block_end = block_next + HBLOCK
        h = block_next
        block_next += 1
        return h

    n_rows = 0
    with tbl.schema_gate.read():
        with open(path, newline="") as f:
            reader = _csv.reader(f)
            first = True
            for raw in reader:
                if first:
                    first = False
                    if has_header:
                        continue
                if not raw:
                    continue
                if len(raw) != len(tbl.col_names):
                    raise ValueError(
                        f"row width {len(raw)} != {len(tbl.col_names)} "
                        f"columns: {raw!r}")
                vals = tuple(_to_value(c, t)
                             for c, t in zip(raw, tbl.col_types))
                for i, t in enumerate(tbl.col_types):
                    if vals[i] is None and not t.nullable:
                        raise ValueError(
                            "NULL in NOT NULL column "
                            f"{tbl.col_names[i]!r}")
                h = next_handle()
                n_rows += 1
                k, v = encode_table_row(tbl.table_id, h, vals,
                                        tbl.col_types)
                sorter.add(k, v)
                for ix in tbl.writable_indexes():
                    ik, iv = tbl._index_entry(ix, vals, h)
                    sorter.add(ik, iv)
        sorter.flush()
        # merge-read every run in key order, ingest in batches
        txn = tbl.kv.begin()
        in_batch = 0
        for k, v in sorter.merged():
            txn.put(k, v)
            in_batch += 1
            if in_batch >= ingest_batch:
                txn.commit()
                txn = tbl.kv.begin()
                in_batch = 0
        txn.commit()
    sorter.cleanup()
    tbl._invalidate()
    _duplicate_check(tbl)
    return n_rows


__all__ = ["import_csv", "global_sort_import"]
