from .br import backup, restore
from .dump import dump_database
from .lightning import import_csv

__all__ = ["backup", "restore", "dump_database", "import_csv"]
