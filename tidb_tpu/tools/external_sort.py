"""Global sort on external storage.

Reference analog: pkg/lightning/backend/external (merge.go, the one-file
writers) — the TB-scale sort that ADD INDEX / IMPORT INTO use when data
exceeds memory: encode to KV pairs, spill SORTED RUNS to external
storage, then k-way merge-read the runs in key order so ingestion sees a
single sorted stream.

"External storage" here is a pluggable directory (the S3/GCS seam of the
reference's storage.ExternalStorage): runs are independent files with a
footer of (count, min_key, max_key) statistics, so a merge plan can
re-shard by key range — the multi-node story of the reference's merge
step (subtask per range) maps onto DXF subtasks.
"""

from __future__ import annotations

import heapq
import os
import struct
from typing import Iterable, Iterator, Optional, Tuple

KV = Tuple[bytes, bytes]

_MAGIC = b"XSRT1\n"


_TAIL = b"XSRTEND1"


class RunWriter:
    """One sorted run file: length-prefixed (key, value) records in key
    order, closed by a STATS FOOTER (count, min_key, max_key) readable in
    O(1) from the file tail — the external/onefile writer's statistics
    that a merge planner splits key ranges from."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "wb")
        self._f.write(_MAGIC)
        self.count = 0
        self.min_key: Optional[bytes] = None
        self.max_key: Optional[bytes] = None

    def write_sorted(self, pairs: Iterable[KV]) -> None:
        last = None
        for k, v in pairs:
            if last is not None and k < last:
                raise ValueError("run records must arrive in key order")
            last = k
            self._f.write(struct.pack("<II", len(k), len(v)))
            self._f.write(k)
            self._f.write(v)
            if self.min_key is None:
                self.min_key = k
            self.max_key = k
            self.count += 1

    def close(self) -> None:
        mn = self.min_key or b""
        mx = self.max_key or b""
        footer = struct.pack("<QII", self.count, len(mn), len(mx)) + mn + mx
        self._f.write(footer)
        self._f.write(struct.pack("<I", len(footer)))
        self._f.write(_TAIL)
        self._f.close()


def run_stats(path: str) -> tuple:
    """(count, min_key, max_key) from the footer — O(1), no data scan."""
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(size - len(_TAIL) - 4)
        flen_raw = f.read(4)
        if f.read(len(_TAIL)) != _TAIL:
            raise ValueError(f"{path}: missing sorted-run footer")
        flen = struct.unpack("<I", flen_raw)[0]
        f.seek(size - len(_TAIL) - 4 - flen)
        footer = f.read(flen)
    count, lmn, lmx = struct.unpack("<QII", footer[:16])
    mn = footer[16:16 + lmn]
    mx = footer[16 + lmn:16 + lmn + lmx]
    return count, (mn if lmn else None), (mx if lmx else None)


def _payload_end(path: str) -> int:
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(size - len(_TAIL) - 4)
        flen = struct.unpack("<I", f.read(4))[0]
    return size - len(_TAIL) - 4 - flen


def read_run(path: str, start: Optional[bytes] = None,
             end: Optional[bytes] = None) -> Iterator[KV]:
    """Stream one run in key order, optionally clipped to [start, end)."""
    stop = _payload_end(path)
    with open(path, "rb") as f:
        if f.read(len(_MAGIC)) != _MAGIC:
            raise ValueError(f"{path}: not a sorted-run file")
        while f.tell() < stop:
            lk, lv = struct.unpack("<II", f.read(8))
            k = f.read(lk)
            v = f.read(lv)
            if end is not None and k >= end:
                return
            if start is None or k >= start:
                yield k, v


class ExternalSorter:
    """Accumulate unsorted KV pairs, spill sorted runs at the memory
    budget, and merge-read everything in key order.

    The run directory is the external-storage seam: runs survive the
    process, so an interrupted import resumes by re-merging existing
    runs (checkpoint discipline of backend/external)."""

    def __init__(self, run_dir: str, mem_budget_bytes: int = 64 << 20):
        os.makedirs(run_dir, exist_ok=True)
        self.run_dir = run_dir
        self.mem_budget = max(int(mem_budget_bytes), 1 << 16)
        self._buf: list[KV] = []
        self._buf_bytes = 0
        self.runs: list[str] = sorted(
            os.path.join(run_dir, f) for f in os.listdir(run_dir)
            if f.endswith(".run"))

    def add(self, key: bytes, value: bytes) -> None:
        self._buf.append((key, value))
        self._buf_bytes += len(key) + len(value) + 16
        if self._buf_bytes >= self.mem_budget:
            self.flush()

    def flush(self) -> None:
        if not self._buf:
            return
        self._buf.sort(key=lambda kv: kv[0])
        path = os.path.join(self.run_dir, f"{len(self.runs):06d}.run")
        w = RunWriter(path + ".tmp")
        w.write_sorted(self._buf)
        w.close()
        os.replace(path + ".tmp", path)
        self.runs.append(path)
        self._buf = []
        self._buf_bytes = 0

    def merged(self, start: Optional[bytes] = None,
               end: Optional[bytes] = None) -> Iterator[KV]:
        """K-way merge over all runs (merge.go MergeOverlappingFiles
        analog), optionally clipped to a key range — the unit a DXF
        subtask would own."""
        self.flush()
        streams = [read_run(p, start, end) for p in self.runs]
        yield from heapq.merge(*streams, key=lambda kv: kv[0])

    def stats(self) -> list[tuple]:
        """(path, count, min_key, max_key) per run, read from each run's
        footer in O(1) — the statistics a merge planner splits key ranges
        from."""
        return [(p,) + run_stats(p) for p in self.runs]

    def cleanup(self) -> None:
        for p in self.runs:
            try:
                os.remove(p)
            except OSError:
                pass
        self.runs = []


__all__ = ["ExternalSorter", "RunWriter", "read_run", "run_stats"]
