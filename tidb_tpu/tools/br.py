"""BR analog: physical snapshot backup & restore with checkpointing.

Reference: br/pkg (113.7k LoC) — snapshot backup exports each table's KV
range as SST files at one backup ts plus a backupmeta manifest; restore
ingests the files and recreates schemas; an interrupted run resumes from
its checkpoint (br/pkg/checkpoint).  Here: one raw KV dump file per
table (sorted key/value pairs at the backup ts — the SST stand-in), a
JSON backupmeta with schemas + ts, and a checkpoint file listing
finished tables so backup/restore resume midway.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Optional

from ..store.codec import (encode_int_key, index_prefix, index_prefix_end,
                           record_prefix, record_prefix_end)

META_FILE = "backupmeta.json"
CKPT_FILE = "checkpoint.json"


def _table_meta(tbl) -> dict:
    return {
        "name": tbl.name, "table_id": tbl.table_id,
        "col_names": list(tbl.col_names),
        "col_types": [_type_meta(t) for t in tbl.col_types],
        "primary_key": list(tbl.primary_key),
        "auto_inc_col": tbl.auto_inc_col,
        "auto_inc": tbl._auto_inc, "next_handle": tbl._next_handle,
        "indexes": [{"name": ix.name, "index_id": ix.index_id,
                     "columns": ix.columns, "unique": ix.unique}
                    for ix in tbl.indexes if ix.state == "public"],
    }


def _type_meta(t) -> dict:
    return {"kind": t.kind.name, "nullable": t.nullable, "prec": t.prec,
            "scale": t.scale}


def _type_from_meta(m):
    from ..types import dtypes as dt
    return dt.DataType(dt.TypeKind[m["kind"]], m["nullable"], m["prec"],
                       m["scale"])


def _write_kvs(path: str, pairs) -> int:
    n = 0
    with open(path + ".tmp", "wb") as f:
        for k, v in pairs:
            f.write(struct.pack("<I", len(k)) + k)
            f.write(struct.pack("<I", len(v)) + v)
            n += 1
    os.replace(path + ".tmp", path)   # atomic publish (SST upload analog)
    return n


def _read_kvs(path: str):
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    while off < len(data):
        (kl,) = struct.unpack_from("<I", data, off); off += 4
        k = data[off:off + kl]; off += kl
        (vl,) = struct.unpack_from("<I", data, off); off += 4
        v = data[off:off + vl]; off += vl
        yield k, v


def _load_ckpt(out_dir: str) -> set:
    p = os.path.join(out_dir, CKPT_FILE)
    if os.path.exists(p):
        return set(json.load(open(p)))
    return set()


def _save_ckpt(out_dir: str, done: set):
    p = os.path.join(out_dir, CKPT_FILE)
    with open(p + ".tmp", "w") as f:
        json.dump(sorted(done), f)
    os.replace(p + ".tmp", p)


def backup(domain, db: str, out_dir: str) -> dict:
    """Snapshot backup of `db` into out_dir; resumable via checkpoint.
    Returns {table: kv_pair_count}."""
    os.makedirs(out_dir, exist_ok=True)
    tables = domain.catalog.databases.get(db)
    if tables is None:
        raise ValueError(f"unknown database {db!r}")
    meta_path = os.path.join(out_dir, META_FILE)
    if os.path.exists(meta_path):
        meta = json.load(open(meta_path))
        backup_ts = meta["backup_ts"]       # resume: keep the original ts
    else:
        backup_ts = domain.kv.alloc_ts()
        meta = {"db": db, "backup_ts": backup_ts,
                "tables": {n: _table_meta(t) for n, t in tables.items()
                           if t.kv is not None}}
        with open(meta_path, "w") as f:
            json.dump(meta, f, indent=1)
    done = _load_ckpt(out_dir)
    counts = {}
    for name in sorted(meta["tables"]):
        if name in done:
            continue
        tbl = tables[name]
        pairs = list(domain.kv.scan(record_prefix(tbl.table_id),
                                    record_prefix_end(tbl.table_id),
                                    backup_ts))
        pairs += list(domain.kv.scan(index_prefix(tbl.table_id),
                                     index_prefix_end(tbl.table_id),
                                     backup_ts))
        counts[name] = _write_kvs(
            os.path.join(out_dir, f"{db}.{name}.kv"), pairs)
        done.add(name)
        _save_ckpt(out_dir, done)
    return counts


def restore(domain, out_dir: str, db: Optional[str] = None,
            batch: int = 512) -> dict:
    """Restore a backup into `domain` (schemas + data).  `db` overrides
    the target database name.  Returns {table: kv_pair_count}."""
    from ..session.catalog import IndexInfo, TableInfo
    meta = json.load(open(os.path.join(out_dir, META_FILE)))
    target_db = db or meta["db"]
    if target_db not in domain.catalog.databases:
        domain.catalog.create_database(target_db)
    counts = {}
    for name, tm in sorted(meta["tables"].items()):
        # fresh table id: restored keys are rewritten to the new id (BR's
        # table-id rewrite rule, br/pkg/restore)
        new_id = domain.alloc_table_id()
        tbl = TableInfo(
            tm["name"], list(tm["col_names"]),
            [_type_from_meta(m) for m in tm["col_types"]],
            list(tm["primary_key"]), tm["auto_inc_col"],
            table_id=new_id, kv=domain.kv)
        tbl._auto_inc = tm["auto_inc"]
        tbl._next_handle = tm["next_handle"]
        for ixm in tm["indexes"]:
            tbl.indexes.append(IndexInfo(ixm["name"], ixm["index_id"],
                                         list(ixm["columns"]),
                                         ixm["unique"]))
            tbl._next_index_id = max(tbl._next_index_id, ixm["index_id"])
        domain.catalog.create_table(target_db, tbl)
        old_prefix = b"t" + encode_int_key(tm["table_id"])
        new_prefix = b"t" + encode_int_key(new_id)
        pairs = list(_read_kvs(os.path.join(out_dir,
                                            f"{meta['db']}.{name}.kv")))
        n = 0
        for off in range(0, len(pairs), batch):
            txn = domain.kv.begin()
            for k, v in pairs[off:off + batch]:
                assert k.startswith(old_prefix)
                txn.put(new_prefix + k[len(old_prefix):], v)
                n += 1
            txn.commit()
        tbl._invalidate()
        counts[name] = n
    return counts


__all__ = ["backup", "restore"]
