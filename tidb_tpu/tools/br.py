"""BR analog: physical snapshot backup & restore with checkpointing.

Reference: br/pkg (113.7k LoC) — snapshot backup exports each table's KV
range as SST files at one backup ts plus a backupmeta manifest; restore
ingests the files and recreates schemas; an interrupted run resumes from
its checkpoint (br/pkg/checkpoint).  Here: one raw KV dump file per
table (sorted key/value pairs at the backup ts — the SST stand-in), a
JSON backupmeta with schemas + ts, and a checkpoint file listing
finished tables so backup/restore resume midway.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Optional

from ..store.codec import (encode_int_key, index_prefix, index_prefix_end,
                           record_prefix, record_prefix_end)

META_FILE = "backupmeta.json"
CKPT_FILE = "checkpoint.json"


def _table_meta(tbl) -> dict:
    return {
        "name": tbl.name, "table_id": tbl.table_id,
        "col_names": list(tbl.col_names),
        "col_types": [_type_meta(t) for t in tbl.col_types],
        "primary_key": list(tbl.primary_key),
        "auto_inc_col": tbl.auto_inc_col,
        "auto_inc": tbl._auto_inc, "next_handle": tbl._next_handle,
        "indexes": [{"name": ix.name, "index_id": ix.index_id,
                     "columns": ix.columns, "unique": ix.unique}
                    for ix in tbl.indexes if ix.state == "public"],
    }


def _type_meta(t) -> dict:
    return {"kind": t.kind.name, "nullable": t.nullable, "prec": t.prec,
            "scale": t.scale}


def _type_from_meta(m):
    from ..types import dtypes as dt
    return dt.DataType(dt.TypeKind[m["kind"]], m["nullable"], m["prec"],
                       m["scale"])


def _write_kvs(path: str, pairs) -> int:
    n = 0
    with open(path + ".tmp", "wb") as f:
        for k, v in pairs:
            f.write(struct.pack("<I", len(k)) + k)
            f.write(struct.pack("<I", len(v)) + v)
            n += 1
    os.replace(path + ".tmp", path)   # atomic publish (SST upload analog)
    return n


def _read_kvs(path: str):
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    while off < len(data):
        (kl,) = struct.unpack_from("<I", data, off); off += 4
        k = data[off:off + kl]; off += kl
        (vl,) = struct.unpack_from("<I", data, off); off += 4
        v = data[off:off + vl]; off += vl
        yield k, v


def _load_ckpt(out_dir: str) -> set:
    p = os.path.join(out_dir, CKPT_FILE)
    if os.path.exists(p):
        return set(json.load(open(p)))
    return set()


def _save_ckpt(out_dir: str, done: set):
    p = os.path.join(out_dir, CKPT_FILE)
    with open(p + ".tmp", "w") as f:
        json.dump(sorted(done), f)
    os.replace(p + ".tmp", p)


def backup(domain, db: str, out_dir: str) -> dict:
    """Snapshot backup of `db` into out_dir; resumable via checkpoint.
    Returns {table: kv_pair_count}."""
    os.makedirs(out_dir, exist_ok=True)
    tables = domain.catalog.databases.get(db)
    if tables is None:
        raise ValueError(f"unknown database {db!r}")
    meta_path = os.path.join(out_dir, META_FILE)
    if os.path.exists(meta_path):
        meta = json.load(open(meta_path))
        backup_ts = meta["backup_ts"]       # resume: keep the original ts
    else:
        backup_ts = domain.kv.alloc_ts()
        meta = {"db": db, "backup_ts": backup_ts,
                "tables": {n: _table_meta(t) for n, t in tables.items()
                           if t.kv is not None}}
        with open(meta_path, "w") as f:
            json.dump(meta, f, indent=1)
    done = _load_ckpt(out_dir)
    counts = {}
    ranges: dict[str, list] = {}
    for name, rng in _table_ranges(meta):
        ranges.setdefault(name, []).append(rng)
    for name in sorted(meta["tables"]):
        if name in done:
            continue
        pairs = []
        for lo, hi in ranges[name]:
            pairs += list(domain.kv.scan(lo, hi, backup_ts))
        counts[name] = _write_kvs(
            os.path.join(out_dir, f"{db}.{name}.kv"), pairs)
        done.add(name)
        _save_ckpt(out_dir, done)
    return counts


def restore(domain, out_dir: str, db: Optional[str] = None,
            batch: int = 512) -> dict:
    """Restore a backup into `domain` (schemas + data).  `db` overrides
    the target database name.  Returns {table: kv_pair_count}."""
    from ..session.catalog import IndexInfo, TableInfo
    meta = json.load(open(os.path.join(out_dir, META_FILE)))
    target_db = db or meta["db"]
    if target_db not in domain.catalog.databases:
        domain.catalog.create_database(target_db)
    counts = {}
    for name, tm in sorted(meta["tables"].items()):
        # fresh table id: restored keys are rewritten to the new id (BR's
        # table-id rewrite rule, br/pkg/restore)
        new_id = domain.alloc_table_id()
        tbl = TableInfo(
            tm["name"], list(tm["col_names"]),
            [_type_from_meta(m) for m in tm["col_types"]],
            list(tm["primary_key"]), tm["auto_inc_col"],
            table_id=new_id, kv=domain.kv)
        tbl._auto_inc = tm["auto_inc"]
        tbl._next_handle = tm["next_handle"]
        for ixm in tm["indexes"]:
            tbl.indexes.append(IndexInfo(ixm["name"], ixm["index_id"],
                                         list(ixm["columns"]),
                                         ixm["unique"]))
            tbl._next_index_id = max(tbl._next_index_id, ixm["index_id"])
        domain.catalog.create_table(target_db, tbl)
        old_prefix = b"t" + encode_int_key(tm["table_id"])
        new_prefix = b"t" + encode_int_key(new_id)
        pairs = list(_read_kvs(os.path.join(out_dir,
                                            f"{meta['db']}.{name}.kv")))
        n = 0
        for off in range(0, len(pairs), batch):
            txn = domain.kv.begin()
            for k, v in pairs[off:off + batch]:
                assert k.startswith(old_prefix)
                txn.put(new_prefix + k[len(old_prefix):], v)
                n += 1
            txn.commit()
        tbl._invalidate()
        counts[name] = n
    return counts


# ------------------------------------------------------------------ #
# log backup + PITR (br/pkg/streamhelper + restore point-in-time analog)
# ------------------------------------------------------------------ #

STREAM_FILE = "stream.json"


def _table_ranges(meta):
    for name, tm in sorted(meta["tables"].items()):
        tid = tm["table_id"]
        yield name, (record_prefix(tid), record_prefix_end(tid))
        yield name, (index_prefix(tid), index_prefix_end(tid))


def _scan_all(kv, meta, ts) -> dict:
    out: dict[bytes, bytes] = {}
    for _name, (lo, hi) in _table_ranges(meta):
        for k, v in kv.scan(lo, hi, ts):
            out[k] = v
    return out


def log_backup_start(domain, db: str, out_dir: str) -> dict:
    """Begin a PITR-capable backup stream: a base snapshot backup plus
    stream bookkeeping.  Subsequent log_backup_tick() calls append
    incremental change chunks (the log-backup task analog: RPO = tick
    interval; each chunk carries the new values and tombstones of every
    key that changed since the previous checkpoint ts)."""
    counts = backup(domain, db, out_dir)
    meta = json.load(open(os.path.join(out_dir, META_FILE)))
    _save_stream(out_dir, {"last_ts": meta["backup_ts"],
                           "increments": []})
    return counts


def _save_stream(out_dir: str, state: dict) -> None:
    spath = os.path.join(out_dir, STREAM_FILE)
    with open(spath + ".tmp", "w") as f:
        json.dump(state, f)
    os.replace(spath + ".tmp", spath)    # atomic: crash can't corrupt


def log_backup_tick(domain, out_dir: str) -> int:
    """Archive one incremental chunk: every key whose value changed (or
    that was deleted) between the stream's checkpoint ts and now.
    Returns the number of changed keys.  Restorable to any tick ts.

    Cost note: the diff is computed from two full snapshot scans, so a
    tick is O(database), not O(churn) — acceptable at this engine's
    scale; the upgrade path is a native-engine version-range scan
    (commit_ts in (last_ts, new_ts]), which the MVCC store already has
    the data for."""
    meta = json.load(open(os.path.join(out_dir, META_FILE)))
    spath = os.path.join(out_dir, STREAM_FILE)
    state = json.load(open(spath))
    new_ts = domain.kv.alloc_ts()
    old = _scan_all(domain.kv, meta, state["last_ts"])
    new = _scan_all(domain.kv, meta, new_ts)
    changes = []
    for k, v in new.items():
        if old.get(k) != v:
            changes.append((b"P" + k, v))           # put/update
    for k in old:
        if k not in new:
            changes.append((b"D" + k, b""))          # tombstone
    if changes:
        _write_kvs(os.path.join(out_dir, f"inc-{new_ts}.kv"), changes)
        state["increments"].append(new_ts)
    state["last_ts"] = new_ts
    _save_stream(out_dir, state)
    return len(changes)


def restore_pitr(domain, out_dir: str, restore_ts: Optional[int] = None,
                 db: Optional[str] = None) -> dict:
    """Point-in-time restore: base snapshot + every incremental chunk
    with ts <= restore_ts (default: all), with the same table-id rewrite
    as snapshot restore."""
    meta = json.load(open(os.path.join(out_dir, META_FILE)))
    state = json.load(open(os.path.join(out_dir, STREAM_FILE)))
    if restore_ts is not None and restore_ts < meta["backup_ts"]:
        raise ValueError(
            f"restore_ts {restore_ts} predates the base backup "
            f"({meta['backup_ts']}); no data exists before it")
    counts = restore(domain, out_dir, db=db)
    target_db = db or meta["db"]
    # old table id -> new prefix mapping from the freshly restored tables
    remap = {}
    for name, tm in meta["tables"].items():
        tbl = domain.catalog.get_table(target_db, name)
        remap[tm["table_id"]] = (b"t" + encode_int_key(tm["table_id"]),
                                 b"t" + encode_int_key(tbl.table_id), tbl)
    applied = 0
    for ts in sorted(state["increments"]):
        if restore_ts is not None and ts > restore_ts:
            break
        txn = domain.kv.begin()
        for tag_k, v in _read_kvs(os.path.join(out_dir, f"inc-{ts}.kv")):
            tag, k = tag_k[:1], tag_k[1:]
            for old_p, new_p, _tbl in remap.values():
                if k.startswith(old_p):
                    nk = new_p + k[len(old_p):]
                    if tag == b"P":
                        txn.put(nk, v)
                    else:
                        txn.delete(nk)
                    applied += 1
                    break
        txn.commit()
    for _old, _new, tbl in remap.values():
        tbl._invalidate()
        tbl._needs_counter_recovery = True   # handles may have grown
        tbl._recover_counters()
    counts["_incremental_keys"] = applied
    return counts


__all__ = ["backup", "restore", "log_backup_start", "log_backup_tick",
           "restore_pitr"]
