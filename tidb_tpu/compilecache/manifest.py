"""Warm-pool manifest: the persisted record of hot compiled programs.

Reference analog: the plan-cache eviction bookkeeping of the reference
(pkg/planner/core/plan_cache_lru.go) applied to persisted executables.
One JSON file per cache directory lists every persisted entry with its
key anatomy and measured compile/load times; a restarted server replays
it MRU-first to pre-warm the corpus shape before the first query lands
(compilecache/warmup.py), and the measured per-digest times are the
feed the ROADMAP's measured-calibration item will consume next.

Two hard rules:

- bounded by BYTES, LRU-evicted (``tidb_tpu_compile_warm_pool`` caps
  it): evicting a manifest entry also deletes its ``.copforge`` file,
  so the disk footprint tracks the cap too.
- a QUARANTINED digest is never recorded and is purged on quarantine:
  a program the circuit breaker opened on must not launder its way back
  through a restart's warm replay (the chaos bench rung asserts this).

coplace (ISSUE 16) made saves safe under CONCURRENT WRITERS: N
processes share one ``tidb_tpu_compile_cache_dir``, so every save is
an advisory-locked read-MERGE-write (utils/filelock) committed by
atomic temp-file + rename — a concurrent save folds the other
process's entries in instead of clobbering them.  Locally dropped
entries and purged digests are remembered so a merge can never
resurrect what eviction or quarantine removed here; cross-process
quarantine is the pd registry's tombstone job, not the manifest's.
``refresh()`` folds peers' writes into the live view without writing
(the pd sync tick calls it so adopted entries carry their measured
times and capacities).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

MANIFEST_NAME = "manifest.json"
MANIFEST_VERSION = 1

# default byte bound when the sysvar leaves -1 in place
DEFAULT_CAP_BYTES = 256 << 20


class WarmManifest:
    """Thread-safe manifest of one cache directory (leaf lock only)."""

    def __init__(self, cache_dir: str, cap_bytes: int = DEFAULT_CAP_BYTES):
        self.cache_dir = cache_dir
        self.cap_bytes = cap_bytes
        self._mu = threading.Lock()
        self._entries: dict[str, dict] = {}       # entry_hex -> meta
        # copmeter (analysis/calibrate): per-digest measured cost
        # corrections ride the same file, so calibration survives
        # restarts exactly as far as the programs it describes
        self._calib: dict[str, dict] = {}         # stable digest -> payload
        # merge fences: what THIS process dropped must not come back
        # via a concurrent writer's copy (see module doc)
        self._dropped: set = set()                # entry hexes evicted here
        self._purged: set = set()                 # digests quarantined here
        self.evictions = 0
        self._load()

    # ---- persistence ------------------------------------------------ #

    def _path(self) -> str:
        return os.path.join(self.cache_dir, MANIFEST_NAME)

    def _load(self) -> None:
        try:
            with open(self._path(), encoding="utf-8") as f:
                doc = json.load(f)
            if doc.get("version") == MANIFEST_VERSION:
                self._entries = dict(doc.get("entries", {}))
                self._calib = dict(doc.get("calibration", {}))
        except (OSError, ValueError):
            self._entries = {}
            self._calib = {}

    def _read_disk(self) -> dict:
        try:
            with open(self._path(), encoding="utf-8") as f:
                doc = json.load(f)
            if isinstance(doc, dict) and \
                    doc.get("version") == MANIFEST_VERSION:
                return doc
        except (OSError, ValueError):
            pass
        return {}

    def _merge_disk_locked(self, doc: dict) -> int:
        """Fold a concurrent writer's document into the live view:
        unknown entries adopt, conflicts keep OURS (our copy carries
        this process's hits/last_used), and nothing this process
        dropped or quarantined may resurrect.  Returns adoptions."""
        n = 0
        for hx, meta in sorted(doc.get("entries", {}).items()):
            if hx in self._entries or hx in self._dropped:
                continue
            if meta.get("digest", "") in self._purged:
                continue
            self._entries[hx] = dict(meta)
            n += 1
        for d, payload in sorted(doc.get("calibration", {}).items()):
            if d in self._calib or d in self._purged:
                continue
            self._calib[d] = dict(payload)
        return n

    def _save_locked(self) -> None:
        """Advisory-locked read-merge-write + atomic rename: safe
        against concurrent writers sharing the cache dir (see module
        doc).  Still never a failure — the manifest is an
        optimization."""
        from ..utils.filelock import locked_file
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            with locked_file(self._path() + ".lock"):
                self._merge_disk_locked(self._read_disk())
                self._evict_locked()
                tmp = self._path() + f".tmp{os.getpid()}"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump({"version": MANIFEST_VERSION,
                               "entries": self._entries,
                               "calibration": self._calib}, f)
                os.replace(tmp, self._path())
        except OSError:
            pass          # manifest is an optimization, never a failure

    def refresh(self) -> int:
        """Fold entries other processes persisted since our last save
        into the live view WITHOUT writing — the pd sync tick's read
        channel (peer adoption then sees measured compile/load times
        and regrow capacities, not just entry names)."""
        with self._mu:
            return self._merge_disk_locked(self._read_disk())

    # ---- recording -------------------------------------------------- #

    def record(self, entry_hex: str, key_parts: dict, nbytes: int,
               compile_ms: float, quarantined: bool = False) -> None:
        """One persisted executable: key anatomy + measured compile
        time.  Quarantined digests are refused — see module doc."""
        if quarantined:
            return
        with self._mu:
            self._entries[entry_hex] = {
                "digest": key_parts.get("digest", ""),
                "family": key_parts.get("family", ""),
                "mesh_fp": key_parts.get("mesh_fp", ""),
                "capacity": key_parts.get("capacity", 0),
                "bytes": int(nbytes),
                "compile_ms": round(float(compile_ms), 3),
                "load_ms": 0.0,
                "hits": 0,
                "last_used": time.time(),
            }
            self._evict_locked()
            self._save_locked()

    def touch(self, entry_hex: str, load_ms: float = 0.0) -> None:
        with self._mu:
            e = self._entries.get(entry_hex)
            if e is not None:
                e["hits"] = e.get("hits", 0) + 1
                e["last_used"] = time.time()
                if load_ms:
                    e["load_ms"] = round(float(load_ms), 3)

    def purge_digest(self, digest: str) -> int:
        """Drop (and unlink) every entry of a quarantined digest — and
        its persisted cost corrections (analysis/calibrate): measured
        feedback from a poisoned program must not launder through a
        restart any more than its executable may."""
        with self._mu:
            self._purged.add(digest)     # merge fence: never readopt
            doomed = [hx for hx, e in sorted(self._entries.items())
                      if e.get("digest") == digest]
            for hx in doomed:
                self._drop_locked(hx)
            self._calib.pop(digest, None)
            self._save_locked()          # persist the purge even when
                                         # only the fence changed
            return len(doomed)

    # ---- calibration persistence (analysis/calibrate) ---------------- #

    def save_calibration(self, entries: dict) -> None:
        """Persist the correction store's per-digest payloads (keyed by
        the restart-stable dag digest — the same digest field the
        entries above carry and purge_digest matches on)."""
        with self._mu:
            self._calib = {str(d): dict(p)
                           for d, p in sorted(entries.items())}
            self._save_locked()

    def load_calibration(self) -> dict:
        with self._mu:
            return {d: dict(p) for d, p in self._calib.items()}

    def _drop_locked(self, entry_hex: str) -> None:
        self._dropped.add(entry_hex)     # merge fence: stay dropped
        self._entries.pop(entry_hex, None)
        try:
            os.unlink(os.path.join(self.cache_dir,
                                   entry_hex + ".copforge"))
        except OSError:
            pass

    def _evict_locked(self) -> None:
        """LRU by bytes: oldest-used entries (and their files) go first
        until the manifest fits the cap.  cap_bytes 0 = unbounded."""
        if self.cap_bytes <= 0:
            return
        total = sum(e.get("bytes", 0) for e in self._entries.values())
        while total > self.cap_bytes and len(self._entries) > 1:
            lru = min(sorted(self._entries.items()),
                      key=lambda kv: kv[1].get("last_used", 0.0))
            total -= lru[1].get("bytes", 0)
            self._drop_locked(lru[0])
            self.evictions += 1

    # ---- introspection ---------------------------------------------- #

    def entries_mru(self) -> list:
        """(entry_hex, meta) pairs, most-recently-used first — the warm
        replay order (hottest programs load before the long tail)."""
        with self._mu:
            return sorted(self._entries.items(),
                          key=lambda kv: -kv[1].get("last_used", 0.0))

    def has_program(self, digest: str) -> bool:
        """Is any entry of this (stable) dag digest warm-replayable?"""
        with self._mu:
            return any(e.get("digest") == digest
                       for e in self._entries.values())

    def capacities_for(self, family: str) -> list:
        """Recorded regrow capacities of one plan family, ascending —
        the client's warm-capacity pick reads this on regrow re-entry."""
        with self._mu:
            caps = {int(e.get("capacity", 0))
                    for e in self._entries.values()
                    if e.get("family") == family and e.get("capacity")}
        return sorted(caps)

    def stats(self) -> dict:
        with self._mu:
            return {"entries": len(self._entries),
                    "bytes": sum(e.get("bytes", 0)
                                 for e in self._entries.values()),
                    "cap_bytes": self.cap_bytes,
                    "evictions": self.evictions,
                    "calibration_entries": len(self._calib)}


__all__ = ["WarmManifest", "MANIFEST_NAME", "MANIFEST_VERSION",
           "DEFAULT_CAP_BYTES"]
