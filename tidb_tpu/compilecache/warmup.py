"""Boot-time warm program pool: replay the manifest before traffic.

A restarted server holding a populated cache directory should serve its
first TPC-H-shaped query WITHOUT compiling: this module deserializes
every manifest entry (MRU-first, so the hottest programs warm first)
into the in-process pool.  The loads run OFF the serving thread and
THROUGH the existing admission queue at LOW priority (weight 1.0, the
resource-group LOW weight), so a fat manifest can never starve live
traffic — a live statement's tasks outweigh warmup 8:1 in the
weighted-fair drain, and warmup tasks never coalesce or fuse with
anything (opaque tasks by construction).
"""

from __future__ import annotations

import threading

from .cache import compile_cache

# the warm replay's resource-group identity: weight 1.0 == PRIORITY LOW
# (rc/controller.PRIORITY_WEIGHTS), distinct name so /sched shows the
# replay as its own group
WARM_GROUP = "copforge-warm"
WARM_WEIGHT = 1.0

# one replay per (process, cache_dir): reconfiguring to a new dir warms
# again, re-running a statement does not
_WARMED: set = set()
_WARM_MU = threading.Lock()


def warm_start(client=None, wait: bool = False) -> int:
    """Replay the manifest into the warm pool.  ``client`` (a CopClient)
    provides the admission queue; None = load inline (tests, tools).
    ``wait=True`` blocks until every entry loaded (bench/tests); the
    serving path uses the default fire-and-forget thread.  Returns the
    number of entries scheduled (or loaded, when waiting)."""
    cache = compile_cache()
    m = cache.manifest
    if not cache.enable or m is None:
        return 0
    entries = [hx for hx, _meta in m.entries_mru()]
    if not entries:
        return 0

    sched = None
    if client is not None:
        try:
            sched = client._scheduler()
        except Exception:   # noqa: BLE001 - warmup must never take down
            sched = None    # boot; a mesh that cannot resolve loads inline

    def load_all() -> int:
        n = 0
        for hx in entries:
            if sched is not None:
                from ..sched import CopTask
                t = CopTask(fn=lambda hx=hx: cache.load_warm(hx),
                            group=WARM_GROUP, weight=WARM_WEIGHT)
                try:
                    sched.submit(t)
                    n += bool(t.wait())
                except Exception:   # noqa: BLE001 - a full queue or a
                    # stale entry skips that entry; warmup is best-effort
                    continue
            else:
                n += bool(cache.load_warm(hx))
        return n

    if wait:
        return load_all()
    threading.Thread(target=load_all, name="copforge-warmup",
                     daemon=True).start()
    return len(entries)


def maybe_warm_start(client) -> None:
    """Idempotent boot hook (called from the session's sysvar plumb):
    first statement after a cache directory is configured kicks the
    background replay exactly once per (process, dir)."""
    cache = compile_cache()
    if not cache.enable or not cache.cache_dir:
        return
    with _WARM_MU:
        if cache.cache_dir in _WARMED:
            return
        _WARMED.add(cache.cache_dir)
    warm_start(client)


def reset_warmed() -> None:
    """Test/bench seam: forget which directories already replayed."""
    with _WARM_MU:
        _WARMED.clear()


def simulate_restart() -> None:
    """Restart-simulation seam (tests + the bench coldwarm rung): model
    a process death without exiting — drop every in-process compiled
    program (the spmd builder memos AND the warm executable pool) while
    the cache directory survives.  A query served after this with zero
    compiles proves the persisted path end to end."""
    from ..parallel import spmd
    spmd._cached.cache_clear()
    spmd._cached_fused.cache_clear()
    spmd._cached_fused_rows.cache_clear()
    spmd._cached_batched.cache_clear()
    spmd._cached_batched_rows.cache_clear()
    compile_cache().clear_pool()
    reset_warmed()


__all__ = ["warm_start", "maybe_warm_start", "reset_warmed",
           "simulate_restart", "WARM_GROUP", "WARM_WEIGHT"]
