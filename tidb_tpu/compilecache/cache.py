"""copforge: AOT compile cache + warm program pool.

Reference analog: compilation is the tail-latency cliff of every
compiled query engine — BENCH_r05 measured 153 s of warmup on SF100 Q6
and 3 s on SF10 Q1, and at production traffic every cold program digest
is a p99 disaster.  Flare's answer (PAPERS.md) is to keep compilation
off the hot path entirely; the compiler-first O(1)-caching inference
stack persists digest-keyed executables across process restarts.  This
module is that pattern for the spmd cop programs:

- every cacheable builder resolves its executable THROUGH this cache
  (``CachedProgram``): warm-pool hit -> call the held ``Compiled``
  object (zero trace, zero compile); disk hit -> ``deserialize_and_load``
  the persisted executable (zero trace); miss -> explicit AOT staging
  ``jit.lower(*args).compile()`` (SNIPPETS.md [1], the pjit ``Lowered``
  seam), then serialize + persist for the next process.
- entries are keyed by the restart-stable variant key
  (analysis/compilekey: dag digest + mesh fingerprint + capacity +
  DonationPlan signature + backend fingerprint) plus the concrete call
  signature; EVERY part is re-verified at load — a stale, corrupt, or
  backend-mismatched entry is skipped with a counter, never silently
  deserialized and never a crash.
- backends whose runtime cannot serialize executables keep the full
  warm-pool semantics in-process (the ``Lowered`` pool): persistence is
  probed once and skipped, nothing else changes — tier-1 exercises the
  whole code path on the CPU mesh either way.
- the warm pool is LRU-bounded by bytes (``tidb_tpu_compile_warm_pool``)
  and its persisted twin (compilecache/manifest.py) is replayed at boot
  through the admission queue at LOW priority (compilecache/warmup.py).
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from collections import OrderedDict
from typing import Optional

from ..analysis.compilekey import (CompileKey, backend_fingerprint,
                                   shape_signature)
from .manifest import DEFAULT_CAP_BYTES, WarmManifest

ENTRY_SUFFIX = ".copforge"
FORMAT_VERSION = 1
MAGIC = "copforge"

# nominal pool accounting for executables the backend cannot serialize
# (no payload to size): small enough that a CPU-mesh pool holds the
# whole corpus, large enough that eviction still means something
NOMINAL_EXE_BYTES = 64 << 10


class _Counters(threading.local):
    """Per-thread mirror of the compile/load totals: the drain thread
    reads ITS OWN deltas around a launch, so concurrent sessions on
    other threads cannot pollute one launch's compile attribution."""

    def __init__(self):
        self.compiled_ns = 0
        self.loaded_ns = 0
        self.misses = 0
        self.hits = 0
        # copgauge: largest per-device (argument+output+temp) bytes of
        # an executable resolved on THIS thread since the drain's mark
        # — the measured-watermark source where the backend reports no
        # live memory_stats (the CPU mesh, so tier-1 exercises it)
        self.mem_peak = 0


class CompileCache:
    """Process-wide program cache (one per process, like the metric
    registry): the pool is keyed by entry hex so every builder object
    over the same program shares one executable."""

    def __init__(self):
        self.enable = os.environ.get(
            "TIDB_TPU_COMPILE_CACHE", "1") != "0"
        self.cache_dir = os.environ.get("TIDB_TPU_COMPILE_CACHE_DIR", "")
        self.pool_cap_bytes = DEFAULT_CAP_BYTES
        self._mu = threading.Lock()
        self._pool: OrderedDict[str, tuple] = OrderedDict()  # hex -> (exe, nbytes)
        self._pool_bytes = 0
        # copgauge: entry hex -> per-device executable memory bytes
        # (argument+output+temp from Compiled.memory_analysis; 0 =
        # backend reports none) — memoized next to the pool
        self._mem_info: dict[str, int] = {}
        self._bad_entries: set = set()     # rejected on disk; don't re-read
        self._caps: dict[str, set] = {}    # family -> warm capacities
        self._quarantined: set = set()     # stable digests the breaker opened
        self._manifest: Optional[WarmManifest] = None
        # persistence support is probed on first serialize attempt:
        # None = unknown, False = backend can't (in-process pool only)
        self._persist_ok: Optional[bool] = None
        self._tl = _Counters()
        # lifetime counters (mirrored to /sched + prometheus)
        self.hits = 0              # warm-pool hits (no trace, no load)
        self.disk_hits = 0         # persisted entries deserialized
        self.misses = 0            # AOT lower+compile runs
        self.uncacheable = 0       # programs the AOT path refused
        self.rejected = 0          # corrupt/stale/mismatched disk entries
        self.persisted = 0         # entries written to the cache dir
        self.evictions = 0         # pool LRU evictions
        self.fallback_calls = 0    # pooled executable refused the args
        self.warm_loaded = 0       # entries loaded by the boot warm pool
        self.compile_ms_total = 0.0
        self.load_ms_total = 0.0
        from ..utils.metrics import global_registry
        reg = global_registry()
        self._m_hits = reg.counter("tidb_tpu_compile_cache_hits",
                                   "compile cache hits (pool + disk)")
        self._m_miss = reg.counter("tidb_tpu_compile_cache_misses",
                                   "compile cache misses (AOT compiles)")
        self._m_load = reg.counter("tidb_tpu_compile_cache_load_ms",
                                   "milliseconds spent deserializing "
                                   "persisted executables")
        self._m_bytes = reg.gauge("tidb_tpu_compile_cache_bytes",
                                  "warm program pool resident bytes")
        # copscope (obs/): resolve latency histogram by outcome — every
        # perf_counter_ns measurement in this module records through
        # the obs histogram API (TPU-SPAN-LEAK contract)
        from ..utils.metrics import Histogram
        self._m_resolve_ms = reg.histogram(
            "tidb_tpu_compile_resolve_ms",
            "program resolve latency by outcome (load/compile/warm)",
            buckets=Histogram.MS_BUCKETS, labels=("outcome",))

    # ---- knobs (sysvars ride through session._exec_ctx) -------------- #

    def configure(self, enable: Optional[bool] = None,
                  cache_dir: Optional[str] = None,
                  pool_bytes: Optional[int] = None) -> None:
        if enable is not None:
            self.enable = bool(enable)
        if cache_dir is not None and cache_dir != self.cache_dir:
            with self._mu:
                self.cache_dir = cache_dir
                self._manifest = None
                self._bad_entries.clear()
        if pool_bytes is not None and pool_bytes >= 0:
            self.pool_cap_bytes = (pool_bytes if pool_bytes > 0
                                   else 0)        # 0 = unbounded
            if self._manifest is not None:
                self._manifest.cap_bytes = self.pool_cap_bytes

    @property
    def manifest(self) -> Optional[WarmManifest]:
        if not self.cache_dir:
            return None
        with self._mu:
            if self._manifest is None:
                self._manifest = WarmManifest(self.cache_dir,
                                              self.pool_cap_bytes)
            return self._manifest

    # ---- attribution seam (sched drain reads per-thread deltas) ------ #

    def thread_snapshot(self) -> tuple:
        t = self._tl
        return (t.compiled_ns + t.loaded_ns, t.misses, t.hits)

    # ---- measured-watermark seam (copgauge, obs/hbm) ----------------- #

    def thread_mem_mark(self) -> None:
        """Reset this thread's per-launch executable-memory high-water;
        the drain marks before a serve and takes after it."""
        self._tl.mem_peak = 0

    def thread_mem_take(self) -> int:
        """Largest per-device (argument + output + temp) bytes among
        the executables resolved on this thread since the mark — the
        compiled ``memory_analysis`` of the ACTUALLY-SERVED program, so
        the measured watermark reflects the executable that ran, not a
        re-lowered twin."""
        return self._tl.mem_peak

    def _entry_mem_bytes(self, entry_hex: str, exe) -> int:
        """Per-device (argument + output + temp) bytes of one pooled
        executable, from ``Compiled.memory_analysis`` — computed once
        per entry and memoized (the analysis walks the whole HLO
        module; doing it per launch would tax the drain)."""
        with self._mu:
            n = self._mem_info.get(entry_hex)
        if n is not None:
            return n
        n = 0
        try:
            ma = exe.memory_analysis()
            if ma is not None:
                n = (int(ma.argument_size_in_bytes)
                     + int(ma.output_size_in_bytes)
                     + int(ma.temp_size_in_bytes))
        except Exception:   # noqa: BLE001 - backend capability probe:
            # deserialized or exotic executables may expose no memory
            # analysis; the ledger then runs on its own accounting
            n = 0
        n = max(n, 0)
        with self._mu:
            self._mem_info[entry_hex] = n
        return n

    def _note_mem(self, entry_hex: str, exe) -> None:
        n = self._entry_mem_bytes(entry_hex, exe)
        if n > self._tl.mem_peak:
            self._tl.mem_peak = n

    # ---- pool ------------------------------------------------------- #

    def _pool_put_locked(self, entry_hex: str, exe, nbytes: int) -> None:
        old = self._pool.pop(entry_hex, None)
        if old is not None:
            self._pool_bytes -= old[1]
        self._pool[entry_hex] = (exe, nbytes)
        self._pool_bytes += nbytes
        while self.pool_cap_bytes > 0 and \
                self._pool_bytes > self.pool_cap_bytes and \
                len(self._pool) > 1:
            _hx, (_exe, nb) = self._pool.popitem(last=False)
            self._pool_bytes -= nb
            self.evictions += 1
        self._m_bytes.set(self._pool_bytes)

    def _note_caps(self, key: CompileKey) -> None:
        if key.capacity:
            with self._mu:
                self._caps.setdefault(key.family, set()).add(key.capacity)

    def warm_capacity(self, family: str, needed: int,
                      limit_factor: int = 4) -> Optional[int]:
        """Smallest warm capacity >= needed for this plan family, from
        the in-process pool and the persisted manifest — the regrow /
        paging loops round UP to a capacity that is already compiled
        instead of re-tracing at the minimal pow2 step.  Bounded: a warm
        buffer more than ``limit_factor``x the need wastes more HBM than
        the compile costs."""
        if not self.enable or needed <= 0:
            return None
        with self._mu:
            caps = set(self._caps.get(family, ()))
        m = self.manifest
        if m is not None:
            caps.update(m.capacities_for(family))
        good = [c for c in sorted(caps)
                if needed <= c <= needed * limit_factor]
        return good[0] if good else None

    # ---- quarantine (breaker -> manifest exclusion) ------------------ #

    def quarantine(self, digest: str) -> None:
        """The circuit breaker opened on this (stable) dag digest: purge
        its manifest entries and refuse new records, so a poisoned
        program cannot launder its quarantine through a restart's warm
        replay.  Its live cost corrections (analysis/calibrate) drop
        too — the manifest purge removes the persisted twin."""
        with self._mu:
            self._quarantined.add(digest)
        m = self.manifest
        if m is not None:
            m.purge_digest(digest)
        from ..analysis.calibrate import correction_store
        correction_store().purge(digest)

    def quarantine_report(self) -> dict:
        """Chaos-rung assertion surface: quarantined digests must have
        ZERO manifest presence (laundered == 0, always)."""
        with self._mu:
            quarantined = sorted(self._quarantined)
        m = self.manifest
        laundered = [d for d in quarantined
                     if m is not None and m.has_program(d)]
        return {"quarantined": len(quarantined),
                "laundered": len(laundered)}

    # ---- disk entries ------------------------------------------------ #

    def _entry_path(self, entry_hex: str) -> str:
        return os.path.join(self.cache_dir, entry_hex + ENTRY_SUFFIX)

    def _persist(self, entry_hex: str, key: CompileKey, exe) -> int:
        """Serialize one executable next to its FULL key anatomy: the
        header carries the digest + mesh-fingerprint + donation triple
        (and the rest of key.parts()) that the loader re-verifies, so a
        renamed or collided file can never deserialize silently."""
        if not self.cache_dir or self._persist_ok is False:
            return 0
        try:
            from jax.experimental import serialize_executable as se
            payload, in_tree, out_tree = se.serialize(exe)
            # the TPU-COMPILE-KEY triple is spelled AT the write seam
            # (not just inside key.parts()) so the gate can see every
            # serialized entry carries digest + mesh_fp + donation_sig
            header = {"magic": MAGIC, "version": FORMAT_VERSION,
                      "key": key.parts(), "entry": entry_hex,
                      "digest": key.digest, "mesh_fp": key.mesh_fp,
                      "donation_sig": key.donation_sig}
            blob = pickle.dumps((header, payload, in_tree, out_tree),
                                protocol=pickle.HIGHEST_PROTOCOL)
            self._persist_ok = True
        except Exception:   # noqa: BLE001 - backend capability probe:
            # runtimes without executable serialization keep the
            # in-process pool (full warm semantics, no persistence)
            self._persist_ok = False
            return 0
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            path = self._entry_path(entry_hex)
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError:
            return 0
        with self._mu:
            self.persisted += 1
        return len(blob)

    def _load_entry(self, entry_hex: str, key_parts: Optional[dict]):
        """Deserialize one persisted executable, re-verifying the header
        against the expected key anatomy.  Returns (exe, nbytes) or
        None; every rejection is counted, none raises."""
        path = self._entry_path(entry_hex)
        try:
            with open(path, "rb") as f:
                blob = f.read()
            header, payload, in_tree, out_tree = pickle.loads(blob)
            if (header.get("magic") != MAGIC
                    or header.get("version") != FORMAT_VERSION
                    or header.get("entry") != entry_hex):
                raise ValueError("header mismatch")
            stored = header.get("key", {})
            if stored.get("backend_fp") != backend_fingerprint():
                raise ValueError("backend fingerprint mismatch")
            if key_parts is not None:
                for field in ("digest", "mesh_fp", "donation_sig"):
                    if stored.get(field) != key_parts.get(field):
                        raise ValueError(f"key {field} mismatch")
            from jax.experimental import serialize_executable as se
            exe = se.deserialize_and_load(payload, in_tree, out_tree)
            return exe, len(blob)
        except FileNotFoundError:
            return None
        except Exception:   # noqa: BLE001 - corrupt/stale entries are
            # skipped with a counter, never a crash (and never re-read)
            with self._mu:
                self.rejected += 1
                self._bad_entries.add(entry_hex)
            return None

    # ---- the resolve seam ------------------------------------------- #

    def resolve(self, key: CompileKey, jit_fn, args, execute_ok=True):
        """The executable for (key, shape-of-args): pool -> disk ->
        AOT compile.  Returns a callable, or None when the program is
        uncacheable (caller falls back to the plain jit path)."""
        entry_hex = key.entry_hex(shape_signature(args))
        with self._mu:
            hit = self._pool.get(entry_hex)
            if hit is not None:
                self._pool.move_to_end(entry_hex)
                self.hits += 1
                self._tl.hits += 1
            bad = entry_hex in self._bad_entries
        if hit is not None:
            self._m_hits.inc()
            self._note_mem(entry_hex, hit[0])
            return hit[0]
        if self.cache_dir and not bad:
            t0 = time.perf_counter_ns()
            loaded = self._load_entry(entry_hex, key.parts())
            if loaded is not None:
                exe, nbytes = loaded
                dt_ns = time.perf_counter_ns() - t0
                with self._mu:
                    self._pool_put_locked(entry_hex, exe, nbytes)
                    self.disk_hits += 1
                    self.hits += 1
                    self.load_ms_total += dt_ns / 1e6
                    self._tl.hits += 1
                    self._tl.loaded_ns += dt_ns
                self._note_caps(key)
                self._note_mem(entry_hex, exe)
                self._m_hits.inc()
                self._m_load.inc(dt_ns / 1e6)
                self._m_resolve_ms.observe(dt_ns / 1e6, outcome="load")
                m = self.manifest
                if m is not None:
                    m.touch(entry_hex, dt_ns / 1e6)
                return exe
        # coplace (pd/registry ISSUE 16): cross-process in-flight
        # compile claims.  Before the expensive AOT compile, claim the
        # entry on the coordination store; when a LIVE peer already
        # holds the claim, poll the shared cache dir briefly for its
        # persisted result instead of compiling the same program
        # twice.  pd off/degraded => claim is None and nothing here
        # changes; a timed-out poll falls through and compiles anyway
        # (compile-once is an optimization, never a correctness gate).
        claim = None
        if self.cache_dir and self._persist_ok is not False:
            from ..pd import try_compile_claim
            claim = try_compile_claim(entry_hex)
            if claim is False:
                exe = self._wait_peer_entry(entry_hex, key)
                if exe is not None:
                    return exe
        # miss: explicit AOT staging so we HOLD the Compiled object —
        # calling the jit wrapper would compile the same program into a
        # cache we cannot serialize from
        t0 = time.perf_counter_ns()
        try:
            exe = jit_fn.lower(*args).compile()
        except Exception:   # noqa: BLE001 - AOT capability probe: the
            # plain jit path serves programs the staging API refuses
            with self._mu:
                self.uncacheable += 1
            if claim is True:
                from ..pd import release_compile_claim
                release_compile_claim(entry_hex)
            return None
        dt_ns = time.perf_counter_ns() - t0
        with self._mu:
            self.misses += 1
            self.compile_ms_total += dt_ns / 1e6
            self._tl.misses += 1
            self._tl.compiled_ns += dt_ns
        self._m_miss.inc()
        self._m_resolve_ms.observe(dt_ns / 1e6, outcome="compile")
        nbytes = self._persist(entry_hex, key, exe) or NOMINAL_EXE_BYTES
        with self._mu:
            self._pool_put_locked(entry_hex, exe, nbytes)
        self._note_caps(key)
        self._note_mem(entry_hex, exe)
        m = self.manifest
        if m is not None:
            with self._mu:
                quarantined = key.digest in self._quarantined
            # the manifest record spells the key triple explicitly —
            # digest + mesh fingerprint + donation plan — so the warm
            # replay can never resurrect a wrong-variant executable
            m.record(entry_hex,
                     {"digest": key.digest, "family": key.family,
                      "mesh_fp": key.mesh_fp,
                      "donation_sig": key.donation_sig,
                      "capacity": key.capacity},
                     nbytes, dt_ns / 1e6, quarantined=quarantined)
        if claim is True:
            # persisted (or at least pooled): peers polling on our
            # claim can stop early
            from ..pd import release_compile_claim
            release_compile_claim(entry_hex)
        return exe

    def _wait_peer_entry(self, entry_hex: str, key: CompileKey,
                         timeout_s: float = 1.5, poll_s: float = 0.05):
        """Bounded poll for the claim winner's persisted entry in the
        shared cache dir (coplace compile-once).  Returns the loaded
        executable or None (give up and compile locally) — never
        raises, never waits past ``timeout_s``."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            t0 = time.perf_counter_ns()
            loaded = self._load_entry(entry_hex, key.parts())
            if loaded is not None:
                exe, nbytes = loaded
                dt_ns = time.perf_counter_ns() - t0
                with self._mu:
                    self._pool_put_locked(entry_hex, exe, nbytes)
                    self.disk_hits += 1
                    self.hits += 1
                    self.load_ms_total += dt_ns / 1e6
                    self._tl.hits += 1
                    self._tl.loaded_ns += dt_ns
                self._note_caps(key)
                self._note_mem(entry_hex, exe)
                self._m_hits.inc()
                self._m_load.inc(dt_ns / 1e6)
                self._m_resolve_ms.observe(dt_ns / 1e6, outcome="load")
                m = self.manifest
                if m is not None:
                    m.refresh()      # adopt the winner's record too
                    m.touch(entry_hex, dt_ns / 1e6)
                return exe
            with self._mu:
                if entry_hex in self._bad_entries:
                    return None      # winner's entry is unreadable here
            time.sleep(poll_s)
        return None

    def load_warm(self, entry_hex: str) -> bool:
        """Boot warm pool: deserialize ONE manifest entry into the pool
        (no compile, no trace); False when missing/stale/corrupt."""
        with self._mu:
            if entry_hex in self._pool or entry_hex in self._bad_entries:
                return entry_hex in self._pool
        t0 = time.perf_counter_ns()
        loaded = self._load_entry(entry_hex, None)
        if loaded is None:
            return False
        exe, nbytes = loaded
        dt_ns = time.perf_counter_ns() - t0
        with self._mu:
            self._pool_put_locked(entry_hex, exe, nbytes)
            self.warm_loaded += 1
            self.load_ms_total += dt_ns / 1e6
        self._m_load.inc(dt_ns / 1e6)
        self._m_resolve_ms.observe(dt_ns / 1e6, outcome="warm")
        m = self.manifest
        if m is not None:
            m.touch(entry_hex, dt_ns / 1e6)
        return True

    def clear_pool(self) -> None:
        """Drop every in-process executable (restart simulation seam:
        tests and the bench coldwarm rung model a process death by
        clearing this plus the spmd builder caches; disk survives)."""
        with self._mu:
            self._pool.clear()
            self._pool_bytes = 0
            self._caps.clear()
            self._mem_info.clear()
            self._m_bytes.set(0)

    def stats(self) -> dict:
        with self._mu:
            out = {"enable": self.enable,
                   "cache_dir": self.cache_dir,
                   "pool_entries": len(self._pool),
                   "pool_bytes": self._pool_bytes,
                   "pool_cap_bytes": self.pool_cap_bytes,
                   "hits": self.hits, "misses": self.misses,
                   "disk_hits": self.disk_hits,
                   "warm_loaded": self.warm_loaded,
                   "uncacheable": self.uncacheable,
                   "rejected": self.rejected,
                   "persisted": self.persisted,
                   "evictions": self.evictions,
                   "fallback_calls": self.fallback_calls,
                   "persist_supported": self._persist_ok,
                   "compile_ms": round(self.compile_ms_total, 3),
                   "load_ms": round(self.load_ms_total, 3)}
        m = self.manifest
        if m is not None:
            out["manifest"] = m.stats()
        return out


class CachedProgram:
    """The per-builder resolve-through-cache call seam: one of these
    replaces every direct ``self._fn(...)`` invocation in the spmd
    builders.  The underlying jit object stays exposed (``prog._fn``)
    for AOT introspection; this wrapper only decides WHERE the
    executable comes from."""

    __slots__ = ("_jit", "key")

    def __init__(self, jit_fn, key: CompileKey):
        self._jit = jit_fn
        self.key = key

    def __call__(self, *args):
        cache = compile_cache()
        if not cache.enable:
            return self._jit(*args)
        exe = cache.resolve(self.key, self._jit, args)
        if exe is None:
            return self._jit(*args)
        try:
            return exe(*args)
        except (TypeError, ValueError):
            # a pooled executable may refuse args whose placement drifted
            # from the lowering (cross-sharding call on a strict backend):
            # serve through jit — correctness beats the cache win
            with cache._mu:
                cache.fallback_calls += 1
            return self._jit(*args)

    def warm(self, args) -> bool:
        """Compile-or-load WITHOUT executing: the background fusion
        warmup and boot replay pass ``jax.ShapeDtypeStruct`` trees here
        so no array is ever held by a warm prediction."""
        cache = compile_cache()
        if not cache.enable:
            return False
        return cache.resolve(self.key, self._jit, args) is not None


_CACHE: Optional[CompileCache] = None
_CACHE_MU = threading.Lock()


def compile_cache() -> CompileCache:
    global _CACHE
    with _CACHE_MU:
        if _CACHE is None:
            _CACHE = CompileCache()
        return _CACHE


def configure(enable=None, cache_dir=None, pool_bytes=None) -> None:
    compile_cache().configure(enable, cache_dir, pool_bytes)


def cached_call(jit_fn, dag, mesh, program: str, row_capacity: int = 0,
                n_slots: int = 0, donate_argnums=(),
                extra=()) -> CachedProgram:
    """Builder facade: derive the variant key (DonationPlan included by
    construction — analysis/compilekey) and wrap the jit object."""
    from ..analysis.compilekey import variant_key
    key = variant_key(dag, mesh, program, row_capacity=row_capacity,
                      n_slots=n_slots,
                      donate_argnums=tuple(donate_argnums),
                      extra=tuple(extra))
    return CachedProgram(jit_fn, key)


__all__ = ["CompileCache", "CachedProgram", "compile_cache", "configure",
           "cached_call", "ENTRY_SUFFIX", "FORMAT_VERSION", "MAGIC"]
