"""copforge: AOT compile cache + warm program pool (ISSUE 9).

Takes compile latency off the serving path: compiled spmd executables
persist across process restarts keyed by restart-stable variant keys
(analysis/compilekey — dag digest + mesh fingerprint + capacity +
DonationPlan + backend fingerprint), and a boot-time warm pool replays
the hot-program manifest through the admission queue at LOW priority so
a restarted server serves its first corpus-shaped query without
tracing or compiling anything.
"""

from .cache import (CachedProgram, CompileCache, cached_call,
                    compile_cache, configure)
from .manifest import WarmManifest
from .warmup import (maybe_warm_start, reset_warmed, simulate_restart,
                     warm_start)

__all__ = ["CompileCache", "CachedProgram", "compile_cache", "configure",
           "cached_call", "WarmManifest", "warm_start",
           "maybe_warm_start", "reset_warmed", "simulate_restart"]
