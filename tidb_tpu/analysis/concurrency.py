"""copsan: whole-program concurrency model (ISSUE 17).

The reference TiDB leans on ``go test -race`` and the Go runtime's
goroutine tooling; this rebuild gets neither from CPython, so the
analysis substrate models the thread plane statically the way shardflow
models the mesh plane.  Every module that imports ``threading`` joins
the model automatically — there is no hand-maintained list to drift
(``LOCK_EXCLUDES`` in lint.py is the only opt-out, and each entry must
carry a justification).

Model
-----
*Lock nodes*: every ``threading.Lock/RLock/Condition`` allocation site
becomes a named node — ``rel::Class.attr`` for instance locks (with
``Condition(self._mu)`` aliased onto the wrapped lock's node),
``rel::NAME`` for module-level locks, and dataclass
``field(default_factory=threading.Lock)`` class vars by field name.

*Acquisition edges*: ``with lock:`` nesting and paired
``lock.acquire()/release()`` calls yield directed edges held→acquired.
Call chains are followed intra-module (bounded depth) so a helper
called under a lock inherits the caller's lockset; cross-module seams
are resolved through imports, constructor-typed attributes
(``self.x = ImportedClass(...)``), and the singleton getters in
``SEAM_GETTERS`` — a call into module M while holding L conservatively
adds edges L→every lock of M, which keeps the static graph a superset
of anything the runtime sanitizer (utils/locksan) can observe.

*Thread roots*: where threads are born.  ``ROOT_ENTRIES`` pins the
known spawn points (the sched drain loop, copforge warm threads, the
ddl owner loop, status routes, weakref death callbacks, pool workers);
``threading.Thread(target=...)`` sites are auto-rooted as ``bg``; roots
propagate caller→callee to a fixpoint and any unreached function gets
its module's declared default (``MODULE_ROOTS``).  Roots in
``MULTI_ROOTS`` have many concurrent threads, so a single such root is
already a race party.

Finding families (baseline + ``# planlint: ok`` waivers like lint)
------------------------------------------------------------------
RACE-UNGUARDED-WRITE   read-modify-write of a shared attribute with an
                       empty lockset from ≥2 thread roots (or one
                       multi-thread root).  Plain assignments are
                       GIL-atomic and exempt.
RACE-GUARD-MIX         the same attribute guarded by disjoint locks at
                       different write sites — mutual exclusion in
                       name only.
LOCK-ORDER-CYCLE       a strongly-connected component in the global
                       acquisition graph (subsumes the pairwise
                       TPU-LOCK-ORDER check across modules).
LOCK-BLOCKING-HELD     file IO / flock / sleep / device sync while
                       holding a hot-path lock.
LOCK-CV-PREDICATE      ``Condition.wait()`` outside a ``while``
                       predicate loop, or ``notify`` under the lock
                       with no state write the waiter could re-check.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .lint import Finding, LOCK_EXCLUDES, module_imports_threading

RULE_UNGUARDED = "RACE-UNGUARDED-WRITE"
RULE_GUARD_MIX = "RACE-GUARD-MIX"
RULE_CYCLE = "LOCK-ORDER-CYCLE"
RULE_BLOCKING = "LOCK-BLOCKING-HELD"
RULE_CV = "LOCK-CV-PREDICATE"

CONCURRENCY_RULES = (RULE_UNGUARDED, RULE_GUARD_MIX, RULE_CYCLE,
                     RULE_BLOCKING, RULE_CV)

_WAIVER = re.compile(r"planlint:\s*ok")

# --------------------------------------------------------------------- #
# thread roots: where threads are born.  A root in MULTI_ROOTS runs
# many concurrent threads, so one such root already races with itself.
# --------------------------------------------------------------------- #

THREAD_ROOTS = {
    "statement": "session/connection statement threads (submit path, "
                 "pd coordinator tick, plan cache, catalog)",
    "drain":     "the sched-drain device launch loop (one per mesh)",
    "warm":      "copforge-predict fusion warm threads (bounded pool)",
    "status":    "status-server HTTP route threads",
    "owner":     "ddl owner job loop + election lease renewal",
    "timer":     "timer wheel ticks / profiler stop timers",
    "weakref":   "GC weakref death callbacks (hbm residents)",
    "pool":      "poolmgr / executor worker threads (copr chunks, "
                 "ddl backfill, dxf)",
    "bg":        "auto-discovered Thread(target=...) background sites",
}

MULTI_ROOTS = frozenset({"statement", "warm", "status", "pool"})

# declared thread spawn points: (root, module rel or "prefix/", qualname
# regex).  These are the seeds the intra-module call graph propagates.
ROOT_ENTRIES = [
    ("drain", "sched/scheduler.py", r"^DeviceScheduler\._loop$"),
    ("warm", "sched/scheduler.py",
     r"^DeviceScheduler\._predict_fusion\.warm$"),
    ("statement", "sched/scheduler.py",
     r"^DeviceScheduler\.(submit|configure|pause|resume|drain)"),
    ("statement", "sched/scheduler.py", r"^scheduler_for$"),
    ("status", "sched/scheduler.py", r"^DeviceScheduler\.stats$"),
    ("statement", "sched/scheduler.py", r"^DeviceScheduler\.stats$"),
    ("owner", "ddl/owner.py", r"^DDLExecutor\._owner_loop"),
    ("statement", "ddl/owner.py", r"^DDLExecutor\.(run_job|close|stats)$"),
    ("owner", "ddl/election.py", r"^OwnerManager\.start_renewal\."),
    ("status", "server/status.py", r".*"),
    ("statement", "server/mysql_server.py", r".*"),
    ("pool", "utils/poolmgr.py", r"^PoolManager\.submit\."),
    ("pool", "utils/poolmgr.py", r"^PoolManager\.resize\."),
    ("weakref", "obs/hbm.py", r"^HbmLedger\._resident_dead$"),
    ("timer", "timer/", r".*"),
]

# default root sets by module prefix (first match wins): the declared
# cross-module call seams in root space — who can be on this module's
# stack.  Leaf control-plane modules are reachable from the submit path
# AND the drain (rc debit, breaker, compile cache, calibration), obs is
# additionally on the status routes and weakref callbacks, pd ticks run
# on every statement thread and render on status routes.
MODULE_ROOTS = [
    ("sched/", frozenset({"statement"})),
    ("rc/", frozenset({"statement", "drain"})),
    ("faults/", frozenset({"statement", "drain"})),
    ("compilecache/", frozenset({"statement", "drain", "warm"})),
    ("analysis/calibrate.py", frozenset({"statement", "drain", "status"})),
    ("obs/hbm.py", frozenset({"statement", "drain", "status", "weakref"})),
    ("obs/", frozenset({"statement", "drain", "status"})),
    ("pd/", frozenset({"statement", "status"})),
    ("utils/metrics.py", frozenset({"statement", "drain", "status"})),
    ("utils/poolmgr.py", frozenset({"statement", "pool", "status"})),
    ("server/status.py", frozenset({"status"})),
    ("ddl/", frozenset({"statement", "owner"})),
    ("stats/", frozenset({"statement", "owner"})),
    ("store/", frozenset({"statement", "drain"})),
    ("timer/", frozenset({"statement", "timer"})),
    ("dxf/", frozenset({"statement", "pool"})),
    ("", frozenset({"statement"})),
]

# singleton getters: imported callables whose RESULT lives in another
# module — a method call on the result while holding a lock is a seam
# into that module's locks.
SEAM_GETTERS = {
    "correction_store": "analysis/calibrate.py",
    "compile_cache": "compilecache/cache.py",
    "global_registry": "utils/metrics.py",
    "ledger_for": "obs/hbm.py",
    "roofline_store": "obs/roofline.py",
    "scheduler_for": "sched/scheduler.py",
    "current_recorder": "obs/recorder.py",
}

# locks on the launch/admission hot path: blocking while holding one of
# these stalls the drain or every statement thread.
HOT_LOCK_PREFIXES = ("sched/", "rc/", "compilecache/", "faults/",
                     "obs/", "pd/", "analysis/calibrate.py",
                     "utils/metrics.py", "utils/poolmgr.py")

# calls that block the OS thread (sleep, file IO, device sync).
# Condition.wait is exempt — it releases the lock while sleeping.
_BLOCKING_NAMES = frozenset({
    "sleep", "flock", "lockf", "fsync", "fdatasync",
    "block_until_ready", "device_get", "urlopen",
})

_LOCK_FACTORIES = ("Lock", "RLock", "Condition")
_MUTATORS = frozenset({
    "pop", "append", "add", "remove", "discard", "clear", "update",
    "setdefault", "extend", "popitem", "insert", "appendleft",
})

_CTOR_NAMES = ("__init__", "__new__", "__post_init__")

_MAX_DEPTH = 5


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _is_self_attr(node) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


@dataclass(frozen=True)
class LockNode:
    name: str           # "rel::Class.attr" or "rel::NAME"
    rel: str
    line: int           # allocation-call line (locksan maps frames here)
    kind: str           # "lock" | "rlock" | "condition"
    reentrant: bool

    def hot(self) -> bool:
        return self.rel.startswith(HOT_LOCK_PREFIXES)


@dataclass
class _Write:
    cls: str
    attr: str
    line: int
    qual: str
    lockset: FrozenSet[str]
    rmw: bool


@dataclass
class ModuleModel:
    rel: str
    locks: Dict[str, LockNode] = field(default_factory=dict)
    edges: Set[Tuple[str, str]] = field(default_factory=set)
    # (held lockset, target rel or "pkg/" prefix, line) seam records
    ext_calls: List[Tuple[FrozenSet[str], str, int]] = \
        field(default_factory=list)
    writes: List[_Write] = field(default_factory=list)
    blocking: List[Tuple[str, str, int, str]] = field(default_factory=list)
    cv_issues: List[Tuple[int, str, str]] = field(default_factory=list)
    roots: Dict[str, FrozenSet[str]] = field(default_factory=dict)
    n_funcs: int = 0


class _ModuleScan:
    """One module's slice of the whole-program model."""

    def __init__(self, rel: str, src: str, tree: ast.Module,
                 all_rels: Set[str]):
        self.rel = rel
        self.lines = src.splitlines()
        self.tree = tree
        self.all_rels = all_rels
        self.m = ModuleModel(rel)
        self.imports: Dict[str, str] = {}       # local name -> rel|"pkg/"
        # (cls, attr) -> (node name, kind); "" cls = module level
        self.lock_attrs: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self.alloc_index: Dict[int, str] = {}   # line -> node name
        self.attr_mod: Dict[Tuple[str, str], str] = {}
        self.meth_mod: Dict[Tuple[str, str], str] = {}
        self.units: Dict[str, Tuple[ast.AST, str]] = {}  # qual->(fn, cls)
        self.calls: Dict[str, Set[str]] = {}
        self.thread_targets: Set[str] = set()
        self._visited: Set[Tuple[str, FrozenSet[str], bool]] = set()
        self._walked: Set[str] = set()
        self._ctor_ctx = False

    def waived(self, line: int) -> bool:
        return 1 <= line <= len(self.lines) and \
            bool(_WAIVER.search(self.lines[line - 1]))

    # ----------------------------------------------------------------- #
    # imports
    # ----------------------------------------------------------------- #
    def _scan_imports(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            modparts = [p for p in (node.module or "").split(".") if p]
            if node.level == 0:
                if not modparts or modparts[0] != "tidb_tpu":
                    continue
                target = modparts[1:]
            else:
                pkg = self.rel.split("/")[:-1]
                if node.level - 1 > len(pkg):
                    continue
                base = pkg[:len(pkg) - (node.level - 1)] if node.level > 1 \
                    else pkg
                target = base + modparts
            for a in node.names:
                name = a.asname or a.name
                cand = "/".join(target + [a.name]) + ".py"
                if cand in self.all_rels:
                    self.imports[name] = cand
                    continue
                owner = "/".join(target) + ".py"
                if owner in self.all_rels:
                    self.imports[name] = owner
                elif ("/".join(target) + "/__init__.py") in self.all_rels:
                    self.imports[name] = "/".join(target) + "/"

    # ----------------------------------------------------------------- #
    # lock allocation sites
    # ----------------------------------------------------------------- #
    def _lock_kind(self, call: ast.Call) -> Optional[str]:
        name = _call_name(call)
        if name in _LOCK_FACTORIES:
            return name.lower()
        if name == "field":  # dataclass field(default_factory=threading.X)
            for kw in call.keywords:
                if kw.arg == "default_factory" and \
                        isinstance(kw.value, (ast.Attribute, ast.Name)):
                    fn = kw.value.attr if isinstance(kw.value, ast.Attribute) \
                        else kw.value.id
                    if fn in _LOCK_FACTORIES:
                        return fn.lower()
        return None

    def _add_lock(self, cls: str, attr: str, kind: str,
                  call: ast.Call) -> None:
        if (cls, attr) in self.lock_attrs:
            return
        # Condition(self._mu) / Condition(_MU) aliases the wrapped lock
        if kind == "condition" and call.args and \
                _call_name(call) == "Condition":
            arg = call.args[0]
            wrapped = _is_self_attr(arg)
            if wrapped and (cls, wrapped) in self.lock_attrs:
                node, _k = self.lock_attrs[(cls, wrapped)]
                self.lock_attrs[(cls, attr)] = (node, "condition")
                self.alloc_index.setdefault(call.lineno, node)
                return
            if isinstance(arg, ast.Name) and \
                    ("", arg.id) in self.lock_attrs:
                node, _k = self.lock_attrs[("", arg.id)]
                self.lock_attrs[(cls, attr)] = (node, "condition")
                self.alloc_index.setdefault(call.lineno, node)
                return
        name = f"{self.rel}::{cls}.{attr}" if cls else f"{self.rel}::{attr}"
        # a bare Condition() wraps an RLock internally
        reentrant = kind == "rlock" or (kind == "condition" and
                                        not call.args)
        ln = LockNode(name, self.rel, call.lineno, kind, reentrant)
        self.m.locks[name] = ln
        self.lock_attrs[(cls, attr)] = (name, kind)
        self.alloc_index[call.lineno] = name

    def _scan_locks(self) -> None:
        # module level first so Condition(_MU) aliasing resolves
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                kind = self._lock_kind(node.value)
                if kind:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self._add_lock("", t.id, kind, node.value)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and \
                        isinstance(sub.value, ast.Call):
                    kind = self._lock_kind(sub.value)
                    if not kind:
                        continue
                    for t in sub.targets:
                        attr = _is_self_attr(t)
                        if attr:
                            self._add_lock(node.name, attr, kind,
                                           sub.value)
                        elif isinstance(t, ast.Name) and \
                                sub in node.body:
                            self._add_lock(node.name, t.id, kind,
                                           sub.value)
                elif isinstance(sub, ast.AnnAssign) and \
                        isinstance(sub.value, ast.Call) and \
                        isinstance(sub.target, ast.Name) and \
                        sub in node.body:
                    kind = self._lock_kind(sub.value)
                    if kind:
                        self._add_lock(node.name, sub.target.id, kind,
                                       sub.value)

    # ----------------------------------------------------------------- #
    # constructor-typed attributes: self.x = ImportedClass(...) means
    # calls on self.x land in ImportedClass's module
    # ----------------------------------------------------------------- #
    def _expr_module(self, expr, local_mod: Dict[str, str],
                     cls: str = "") -> Optional[str]:
        if isinstance(expr, ast.Name):
            return self.imports.get(expr.id) or local_mod.get(expr.id)
        if isinstance(expr, ast.Call):
            f = expr.func
            if isinstance(f, ast.Name):
                if f.id in SEAM_GETTERS:
                    return SEAM_GETTERS[f.id]
                return self.imports.get(f.id)
            if isinstance(f, ast.Attribute):
                attr = _is_self_attr(f.value)
                if attr is not None and (cls, attr) in self.attr_mod:
                    return self.attr_mod[(cls, attr)]
                if attr is None and isinstance(f.value, ast.Name):
                    got = self.imports.get(f.value.id) or \
                        local_mod.get(f.value.id)
                    if got:
                        return got
                if isinstance(f.value, ast.Call):
                    return self._expr_module(f.value, local_mod, cls)
        return None

    def _scan_attr_types(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for fn in node.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                local_mod: Dict[str, str] = {}
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                        mod = self._expr_module(sub.value, local_mod,
                                                node.name)
                        if not mod:
                            continue
                        t = sub.targets[0]
                        attr = _is_self_attr(t)
                        if attr:
                            self.attr_mod[(node.name, attr)] = mod
                        elif isinstance(t, ast.Name):
                            local_mod[t.id] = mod
                    elif isinstance(sub, ast.Return) and sub.value:
                        mod = self._expr_module(sub.value, local_mod,
                                                node.name)
                        if mod:
                            self.meth_mod[(node.name, fn.name)] = mod

    # ----------------------------------------------------------------- #
    # unit collection + intra-module call graph + thread roots
    # ----------------------------------------------------------------- #
    def _collect_units(self, body, prefix: str, cls: str) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + node.name
                self.units[qual] = (node, cls)
                self._collect_units(node.body, qual + ".", cls)
            elif isinstance(node, ast.ClassDef):
                self._collect_units(node.body, node.name + ".", node.name)

    def _scan_calls(self) -> None:
        for qual, (fn, cls) in self.units.items():
            out: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and node is not fn:
                    continue
                if isinstance(node, ast.Call):
                    f = node.func
                    if isinstance(f, ast.Attribute):
                        if isinstance(f.value, ast.Name) and \
                                f.value.id == "self" and \
                                f"{cls}.{f.attr}" in self.units:
                            out.add(f"{cls}.{f.attr}")
                    elif isinstance(f, ast.Name):
                        for cand in (f"{qual}.{f.id}", f"{cls}.{f.id}",
                                     f.id, f"{f.id}.__init__"):
                            if cand in self.units:
                                out.add(cand)
                                break
                    # Thread(target=fn) / Timer(..., fn) spawn sites
                    if _call_name(node) in ("Thread", "Timer"):
                        for kw in node.keywords:
                            if kw.arg == "target" and \
                                    isinstance(kw.value, ast.Name):
                                for cand in (f"{qual}.{kw.value.id}",
                                             f"{cls}.{kw.value.id}",
                                             kw.value.id):
                                    if cand in self.units:
                                        self.thread_targets.add(cand)
                                        break
            self.calls[qual] = out

    def _assign_roots(self) -> None:
        roots: Dict[str, Set[str]] = {q: set() for q in self.units}
        for root, relpat, rx in ROOT_ENTRIES:
            if relpat.endswith("/"):
                if not self.rel.startswith(relpat):
                    continue
            elif relpat != self.rel:
                continue
            pat = re.compile(rx)
            for q in self.units:
                if pat.search(q):
                    roots[q].add(root)
        for q in self.thread_targets:
            if not roots[q]:
                roots[q].add("bg")
        # propagate caller -> callee to a fixpoint
        changed = True
        while changed:
            changed = False
            for q, callees in self.calls.items():
                for c in callees:
                    if roots[q] - roots[c]:
                        roots[c] |= roots[q]
                        changed = True
        # nested defs with no roots inherit the enclosing function's
        # (callbacks handed out by the parent run where the parent ran)
        for q in sorted(self.units, key=len):
            if roots[q]:
                continue
            parent = q.rsplit(".", 1)[0] if "." in q else ""
            if parent in self.units and roots.get(parent):
                roots[q] |= roots[parent]
        default = next(r for p, r in MODULE_ROOTS
                       if self.rel.startswith(p) or p == "")
        for q in self.units:
            self.m.roots[q] = frozenset(roots[q] or default)
        self.m.n_funcs = len(self.units)

    # ----------------------------------------------------------------- #
    # lockset traversal
    # ----------------------------------------------------------------- #
    def _resolve_lock(self, expr, cls: str) -> Optional[str]:
        attr = _is_self_attr(expr)
        if attr is not None:
            got = self.lock_attrs.get((cls, attr))
            return got[0] if got else None
        if isinstance(expr, ast.Name):
            got = self.lock_attrs.get(("", expr.id)) or \
                self.lock_attrs.get((cls, expr.id))
            return got[0] if got else None
        return None

    def _lock_kind_of(self, expr, cls: str) -> Optional[str]:
        attr = _is_self_attr(expr)
        if attr is not None:
            got = self.lock_attrs.get((cls, attr))
            return got[1] if got else None
        if isinstance(expr, ast.Name):
            got = self.lock_attrs.get(("", expr.id)) or \
                self.lock_attrs.get((cls, expr.id))
            return got[1] if got else None
        return None

    def _resolve_target(self, call: ast.Call, cls: str,
                        local_mod: Dict[str, str]) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in SEAM_GETTERS:
                return SEAM_GETTERS[f.id]
            return self.imports.get(f.id)
        if isinstance(f, ast.Attribute):
            attr = _is_self_attr(f.value)
            if attr is not None:
                return self.attr_mod.get((cls, attr))
            if isinstance(f.value, ast.Name):
                return self.imports.get(f.value.id) or \
                    local_mod.get(f.value.id)
            if isinstance(f.value, ast.Call):
                inner = f.value.func
                if isinstance(inner, ast.Name):
                    if inner.id in SEAM_GETTERS:
                        return SEAM_GETTERS[inner.id]
                    return self.imports.get(inner.id)
                a = _is_self_attr(inner) if isinstance(inner, ast.Attribute) \
                    else None
                if isinstance(inner, ast.Attribute):
                    ia = _is_self_attr(inner.value)
                    if ia is not None:
                        return self.attr_mod.get((cls, ia))
                    if _is_self_attr(inner) is None and \
                            isinstance(inner.value, ast.Name) and \
                            inner.value.id == "self":
                        return self.meth_mod.get((cls, inner.attr))
                if a is not None:
                    return self.meth_mod.get((cls, a))
        return None

    def _record_edge(self, held: List[str], lock: str) -> None:
        for h in held:
            if h != lock:
                self.m.edges.add((h, lock))

    def _scan_exprs(self, exprs, held: List[str], cls: str, qual: str,
                    local_mod: Dict[str, str], while_depth: int) -> None:
        """Leaf-expression scan: acquire/release tracking, seam calls,
        blocking calls, cv waits."""
        for expr in exprs:
            if expr is None:
                continue
            for node in ast.walk(expr):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = _call_name(node)
                if isinstance(f, ast.Attribute) and \
                        name in ("acquire", "release"):
                    lk = self._resolve_lock(f.value, cls)
                    if lk:
                        if name == "acquire":
                            self._record_edge(held, lk)
                            held.append(lk)
                        elif lk in held:
                            held.remove(lk)
                        continue
                if isinstance(f, ast.Attribute) and \
                        name in ("wait", "wait_for"):
                    kind = self._lock_kind_of(f.value, cls)
                    if kind == "condition" and name == "wait" and \
                            while_depth == 0:
                        self.m.cv_issues.append((
                            node.lineno, qual,
                            "Condition.wait() outside a while predicate "
                            "loop — wakeups are advisory, re-check state"))
                    continue
                # intra-module call chain: inherit the caller's lockset
                target_unit = None
                if isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id == "self" and \
                        f"{cls}.{f.attr}" in self.units:
                    target_unit = f"{cls}.{f.attr}"
                elif isinstance(f, ast.Name):
                    for cand in (f"{qual}.{f.id}", f"{cls}.{f.id}", f.id,
                                 f"{f.id}.__init__"):
                        if cand in self.units:
                            target_unit = cand
                            break
                if target_unit:
                    self._walk_unit(target_unit, list(held),
                                    ctor=self._ctor_ctx)
                    continue
                if held:
                    if name in _BLOCKING_NAMES or \
                            (isinstance(f, ast.Name) and f.id == "open"):
                        hot = [h for h in held
                               if h in self.m.locks and
                               self.m.locks[h].hot()]
                        # cross-module: any held node counts (resolved
                        # at assembly); here only this module's
                        if hot:
                            self.m.blocking.append(
                                (hot[0], name or "open", node.lineno,
                                 qual))
                    target = self._resolve_target(node, cls, local_mod)
                    if target and target != self.rel:
                        self.m.ext_calls.append(
                            (frozenset(held), target, node.lineno))

    def _scan_writes(self, stmt, held: List[str], cls: str,
                     qual: str, ctor: bool) -> None:
        if not cls or ctor:
            return
        targets: List[Tuple[str, bool]] = []
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                attr = _is_self_attr(t)
                if attr:
                    rmw = any(_is_self_attr(n) == attr
                              for n in ast.walk(stmt.value))
                    targets.append((attr, rmw))
        elif isinstance(stmt, ast.AugAssign):
            attr = _is_self_attr(stmt.target)
            if attr:
                targets.append((attr, True))
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            attr = _is_self_attr(stmt.target)
            if attr:
                targets.append((attr, False))
        for attr, rmw in targets:
            if (cls, attr) in self.lock_attrs:
                continue  # the lock object itself
            self.m.writes.append(_Write(cls, attr, stmt.lineno, qual,
                                        frozenset(held), rmw))

    def _body_has_state_write(self, body) -> bool:
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign, ast.Delete)):
                    return True
                if isinstance(node, ast.Call) and \
                        _call_name(node) in _MUTATORS:
                    return True
        return False

    def _walk_body(self, body, held: List[str], cls: str, qual: str,
                   local_mod: Dict[str, str], while_depth: int,
                   depth: int) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate units
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = list(held)
                cv_locks = []
                for item in stmt.items:
                    self._scan_exprs([item.context_expr], inner, cls,
                                     qual, local_mod, while_depth)
                    lk = self._resolve_lock(item.context_expr, cls)
                    if lk:
                        self._record_edge(inner, lk)
                        inner.append(lk)
                        if self._lock_kind_of(item.context_expr,
                                              cls) == "condition":
                            cv_locks.append((item.context_expr, lk))
                self._walk_body(stmt.body, inner, cls, qual, local_mod,
                                while_depth, depth)
                for expr, _lk in cv_locks:
                    self._check_notify(stmt, expr, cls, qual)
                continue
            if isinstance(stmt, ast.While):
                self._scan_exprs([stmt.test], held, cls, qual,
                                 local_mod, while_depth)
                self._walk_body(stmt.body, held, cls, qual, local_mod,
                                while_depth + 1, depth)
                self._walk_body(stmt.orelse, held, cls, qual, local_mod,
                                while_depth, depth)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_exprs([stmt.iter], held, cls, qual,
                                 local_mod, while_depth)
                self._walk_body(stmt.body, held, cls, qual, local_mod,
                                while_depth, depth)
                self._walk_body(stmt.orelse, held, cls, qual, local_mod,
                                while_depth, depth)
                continue
            if isinstance(stmt, ast.If):
                self._scan_exprs([stmt.test], held, cls, qual,
                                 local_mod, while_depth)
                self._walk_body(stmt.body, held, cls, qual, local_mod,
                                while_depth, depth)
                self._walk_body(stmt.orelse, held, cls, qual, local_mod,
                                while_depth, depth)
                continue
            if isinstance(stmt, ast.Try):
                self._walk_body(stmt.body, held, cls, qual, local_mod,
                                while_depth, depth)
                for h in stmt.handlers:
                    self._walk_body(h.body, held, cls, qual, local_mod,
                                    while_depth, depth)
                self._walk_body(stmt.orelse, held, cls, qual, local_mod,
                                while_depth, depth)
                self._walk_body(stmt.finalbody, held, cls, qual,
                                local_mod, while_depth, depth)
                continue
            # leaf statement
            self._scan_writes(stmt, held, cls, qual, self._ctor_ctx)
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                mod = self._expr_module(stmt.value, local_mod, cls)
                if mod:
                    local_mod[stmt.targets[0].id] = mod
            exprs = [getattr(stmt, fld, None)
                     for fld in ("value", "test", "exc", "msg")]
            if isinstance(stmt, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign, ast.Return, ast.Expr,
                                 ast.Raise, ast.Assert, ast.Delete)):
                self._scan_exprs([e for e in exprs if e is not None],
                                 held, cls, qual, local_mod, while_depth)

    def _check_notify(self, with_stmt, cv_expr, cls: str,
                      qual: str) -> None:
        notifies = []
        for node in ast.walk(with_stmt):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("notify", "notify_all"):
                notifies.append(node)
        if notifies and not self._body_has_state_write(with_stmt.body):
            self.m.cv_issues.append((
                notifies[0].lineno, qual,
                "notify without a state write under the same lock — "
                "waiters have nothing new to observe"))

    def _walk_unit(self, qual: str, held: List[str], depth: int = 0,
                   ctor: bool = False) -> None:
        # a unit reached only through a constructor runs before the
        # object is shared — its writes are initialization, not races
        ctor = ctor or qual.rsplit(".", 1)[-1] in _CTOR_NAMES
        key = (qual, frozenset(held), ctor)
        if key in self._visited or depth > _MAX_DEPTH:
            return
        self._visited.add(key)
        self._walked.add(qual)
        fn, cls = self.units[qual]
        prev = self._ctor_ctx
        self._ctor_ctx = ctor
        try:
            self._walk_body(fn.body, list(held), cls, qual, {}, 0,
                            depth + 1)
        finally:
            self._ctor_ctx = prev

    def run(self) -> ModuleModel:
        self._scan_imports()
        self._scan_locks()
        self._scan_attr_types()
        self._collect_units(self.tree.body, "", "")
        self._scan_calls()
        self._assign_roots()
        called = set()
        for callees in self.calls.values():
            called |= callees
        for qual in self.units:
            if qual not in called:
                self._walk_unit(qual, [])
        for qual in self.units:   # call-graph cycles with no entry
            if qual not in self._walked:
                self._walk_unit(qual, [])
        return self.m


# --------------------------------------------------------------------- #
# whole-program assembly
# --------------------------------------------------------------------- #

@dataclass
class ConcurrencyModel:
    modules: Dict[str, "_ModuleScan"] = field(default_factory=dict)
    locks: Dict[str, LockNode] = field(default_factory=dict)
    edges: Set[Tuple[str, str]] = field(default_factory=set)
    alloc_index: Dict[Tuple[str, int], str] = field(default_factory=dict)
    findings: List[Finding] = field(default_factory=list)
    excluded: Dict[str, str] = field(default_factory=dict)

    def summary(self) -> dict:
        return {
            "modules": len(self.modules),
            "excluded": len(self.excluded),
            "locks": len(self.locks),
            "edges": len(self.edges),
            "roots": len(THREAD_ROOTS),
            "findings": len(self.findings),
        }


def discover_threaded_modules(root: Optional[str] = None):
    """(rel -> source) for every tidb_tpu module importing threading,
    plus the excluded map.  No hand list: the import IS the contract."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    srcs: Dict[str, str] = {}
    all_rels: Set[str] = set()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__", "native"))
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            full = os.path.join(dirpath, fname)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            all_rels.add(rel)
            with open(full, encoding="utf-8") as f:
                srcs[rel] = f.read()
    threaded: Dict[str, str] = {}
    excluded: Dict[str, str] = {}
    for rel, src in sorted(srcs.items()):
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue  # lint reports TPU-SYNTAX
        if not module_imports_threading(tree):
            continue
        if rel in LOCK_EXCLUDES:
            excluded[rel] = LOCK_EXCLUDES[rel]
            continue
        threaded[rel] = src
    return threaded, excluded, all_rels


def _expand_target(target: str, by_rel: Dict[str, List[str]]) -> List[str]:
    if target.endswith("/"):
        out: List[str] = []
        for rel, names in by_rel.items():
            if rel.startswith(target):
                out.extend(names)
        return out
    return by_rel.get(target, [])


def _tarjan_sccs(nodes, edges) -> List[List[str]]:
    adj: Dict[str, List[str]] = {n: [] for n in nodes}
    for a, b in edges:
        if a in adj and b in adj:
            adj[a].append(b)
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strong(v):  # iterative Tarjan
        work = [(v, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on.add(node)
            recurse = False
            for i in range(pi, len(adj[node])):
                w = adj[node][i]
                if w not in index:
                    work[-1] = (node, i + 1)
                    work.append((w, 0))
                    recurse = True
                    break
                if w in on:
                    low[node] = min(low[node], index[w])
            if recurse:
                continue
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])

    for n in sorted(nodes):
        if n not in index:
            strong(n)
    return sccs


def build_model(root: Optional[str] = None) -> ConcurrencyModel:
    threaded, excluded, all_rels = discover_threaded_modules(root)
    model = ConcurrencyModel(excluded=excluded)
    scans: Dict[str, _ModuleScan] = {}
    for rel, src in threaded.items():
        scan = _ModuleScan(rel, src, ast.parse(src), all_rels)
        scan.run()
        scans[rel] = scan
        model.modules[rel] = scan
        model.locks.update(scan.m.locks)
        for line, name in scan.alloc_index.items():
            model.alloc_index[(rel, line)] = name
    by_rel: Dict[str, List[str]] = {}
    for name, ln in model.locks.items():
        by_rel.setdefault(ln.rel, []).append(name)
    for rel, scan in scans.items():
        model.edges |= scan.m.edges
        for held, target, _line in scan.m.ext_calls:
            for tgt in _expand_target(target, by_rel):
                for h in held:
                    if h != tgt:
                        model.edges.add((h, tgt))
    model.findings = _emit_findings(model)
    return model


def _emit_findings(model: ConcurrencyModel) -> List[Finding]:
    findings: List[Finding] = []
    for rel, scan in sorted(model.modules.items()):
        m = scan.m
        # RACE-UNGUARDED-WRITE / RACE-GUARD-MIX
        groups: Dict[Tuple[str, str], List[_Write]] = {}
        for w in m.writes:
            groups.setdefault((w.cls, w.attr), []).append(w)
        for (cls, attr), ws in sorted(groups.items()):
            bad = [w for w in ws if w.rmw and not w.lockset and
                   (len(m.roots.get(w.qual, frozenset())) >= 2 or
                    m.roots.get(w.qual, frozenset()) & MULTI_ROOTS)]
            bad = [w for w in bad if not scan.waived(w.line)]
            if bad:
                w = min(bad, key=lambda w: w.line)
                roots = ",".join(sorted(m.roots.get(w.qual, frozenset())))
                findings.append(Finding(
                    RULE_UNGUARDED, rel, w.line, f"{cls}.{attr}",
                    f"read-modify-write of self.{attr} with no lock "
                    f"held, reachable from thread roots [{roots}] — "
                    f"lost updates under the free-threaded interpreter "
                    f"and racy even today"))
            locked = [w for w in ws if w.lockset]
            locksets = {w.lockset for w in locked}
            if len(locksets) >= 2:
                common = frozenset.intersection(*locksets)
                if not common:
                    sites = sorted(locked, key=lambda w: w.line)
                    if not any(scan.waived(w.line) for w in sites):
                        names = " vs ".join(sorted(
                            "{" + ",".join(s.split("::")[-1]
                                           for s in sorted(ls)) + "}"
                            for ls in locksets))
                        findings.append(Finding(
                            RULE_GUARD_MIX, rel, sites[0].line,
                            f"{cls}.{attr}",
                            f"self.{attr} written under disjoint locks "
                            f"({names}) — no common guard, mutual "
                            f"exclusion in name only"))
        # LOCK-BLOCKING-HELD
        seen_b = set()
        for node, call, line, qual in sorted(m.blocking):
            if scan.waived(line) or (node, qual, call) in seen_b:
                continue
            seen_b.add((node, qual, call))
            findings.append(Finding(
                RULE_BLOCKING, rel, line, qual,
                f"{call}() while holding hot-path lock "
                f"{node.split('::')[-1]} — stalls every thread queued "
                f"on it"))
        # LOCK-CV-PREDICATE
        seen_cv = set()
        for line, qual, msg in sorted(m.cv_issues):
            if scan.waived(line) or (qual, msg) in seen_cv:
                continue
            seen_cv.add((qual, msg))
            findings.append(Finding(RULE_CV, rel, line, qual, msg))
    # LOCK-ORDER-CYCLE: global SCCs over the full acquisition graph
    for scc in _tarjan_sccs(set(model.locks), model.edges):
        first = model.locks[scc[0]]
        sig = "~".join(n.split("::")[-1] for n in scc)
        findings.append(Finding(
            RULE_CYCLE, first.rel, first.line, sig,
            f"lock-order cycle across {len(scc)} locks "
            f"({' -> '.join(scc)}) — opposite acquisition orders can "
            f"deadlock"))
    seen, out = set(), []
    for f in findings:
        k = (f.rule, f.path, f.line, f.symbol)
        if k not in seen:
            seen.add(k)
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


_MODEL_CACHE: Dict[str, ConcurrencyModel] = {}


def cached_model(root: Optional[str] = None) -> ConcurrencyModel:
    key = root or "<pkg>"
    if key not in _MODEL_CACHE:
        _MODEL_CACHE[key] = build_model(root)
    return _MODEL_CACHE[key]


def concurrency_findings(root: Optional[str] = None) -> List[Finding]:
    return list(cached_model(root).findings)


def analyze_source(src: str, rel: str,
                   all_rels: Optional[Set[str]] = None) -> List[Finding]:
    """Single-module analysis (tests seed violations through this):
    same extraction + rules, no cross-module seams."""
    scan = _ModuleScan(rel, src, ast.parse(src), all_rels or {rel})
    scan.run()
    model = ConcurrencyModel()
    model.modules[rel] = scan
    model.locks.update(scan.m.locks)
    model.edges |= scan.m.edges
    return _emit_findings(model)


def race_report(root: Optional[str] = None) -> str:
    """Per-module locks/edges/roots/findings table (--race-report)."""
    model = cached_model(root)
    per_mod: Dict[str, int] = {}
    for f in model.findings:
        per_mod[f.path] = per_mod.get(f.path, 0) + 1
    lines = ["copsan concurrency model — auto-discovered threading "
             "modules", ""]
    lines.append(f"{'module':<34} {'locks':>5} {'edges':>5} "
                 f"{'funcs':>5} {'finds':>5}  roots")
    for rel in sorted(model.modules):
        m = model.modules[rel].m
        roots = sorted({r for rs in m.roots.values() for r in rs})
        lines.append(f"{rel:<34} {len(m.locks):>5} {len(m.edges):>5} "
                     f"{m.n_funcs:>5} {per_mod.get(rel, 0):>5}  "
                     f"{','.join(roots)}")
    s = model.summary()
    lines.append("")
    for rel, why in sorted(model.excluded.items()):
        lines.append(f"excluded: {rel} — {why}")
    lines.append(f"total: {s['modules']} modules, {s['locks']} locks, "
                 f"{s['edges']} acquisition edges, "
                 f"{s['findings']} findings")
    return "\n".join(lines)


__all__ = [
    "CONCURRENCY_RULES", "THREAD_ROOTS", "MULTI_ROOTS", "MODULE_ROOTS",
    "ROOT_ENTRIES", "SEAM_GETTERS", "LockNode", "ConcurrencyModel",
    "discover_threaded_modules", "build_model", "cached_model",
    "concurrency_findings", "analyze_source", "race_report",
    "RULE_UNGUARDED", "RULE_GUARD_MIX", "RULE_CYCLE", "RULE_BLOCKING",
    "RULE_CV",
]
