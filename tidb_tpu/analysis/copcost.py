"""copcost: static shape/memory abstract interpreter over cop contracts.

Reference analog: the cost-transparent mapped primitives of DrJAX
(arXiv:2403.07128) and the size/shape algebra linear-algebra query
processors run before execution (LAQP, arXiv:2306.08367).  With
XLA-compiled coprocessor programs the classic "this plan is slow"
failure mode becomes "this launch OOMs the device" or "this launch
silently pads 100x" — and on TPU those must be caught BEFORE
trace/compile, because the trace itself allocates and a compile takes
tens of seconds.

This module walks a built cop DAG using only the information PR 2's
plan contracts already pinned down — declared dtypes, DENSE
domain_sizes, SORT capacities, join out_capacities, the mesh
fingerprint — and computes, with NO tracing and NO device touch:

- per-node abstract buffers: padded device shape (the (S, C) stacked
  shard layout times the flattened per-device batch), physical dtype
  width, per-shard extent under the mesh,
- a per-launch ``LaunchCost`` rollup: peak HBM bytes (resident inputs +
  replicated aux + a no-fusion upper bound on intermediates + outputs),
  host<->device transfer bytes, a FLOP estimate, and the padded/live
  padding-waste ratio.

Consumers:

- the analysis gate (``python -m tidb_tpu.analysis``): COST-PAD-WASTE /
  COST-CAP-BLOWUP / COST-UNBOUNDED findings over the TPC-H plan corpus,
- sched admission: ``DeviceScheduler.submit`` rejects programs whose
  ``peak_hbm_bytes`` exceed the per-mesh budget with a structured
  ``CostError`` (a PlanContractError, so sessions surface it like any
  planner rejection) — pre-trace; the fusion drain caps groups by
  summed footprint,
- EXPLAIN (``est. device bytes`` footer) and ``--cost-report``,
- tests validate predictions against live device buffers and
  ``jax.stages.Compiled`` memory analysis on the 8-vdev CPU mesh.

Like contracts.py this module never imports jax: costs are pure
arithmetic over frozen DAG nodes and array *metadata* (shape/dtype/
nbytes attributes never force a device sync).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field, replace
from typing import Optional, Sequence, Tuple

import numpy as np

from ..copr import dag as D
from ..expr.ir import Expr, Func
from ..types import dtypes as dt
from .contracts import PlanContractError

# ------------------------------------------------------------------ #
# gate thresholds + validated tolerance (pinned by tests/test_copcost)
# ------------------------------------------------------------------ #

# COST-PAD-WASTE: padded/live row ratio above this on a corpus plan is a
# finding.  The floor capacity (min_capacity=1024) alone puts toy corpus
# tables around 16x, so the gate threshold targets genuine blow-ups.
PAD_WASTE_MAX = 64.0
# COST-CAP-BLOWUP: an expanding join whose out_capacity exceeds this
# multiple of its per-device probe rows is a capacity-product blow-up.
CAP_BLOWUP_MAX = 64.0
# COST-DENSE-BLOWUP: a DENSE aggregation whose group-state rows exceed
# this multiple of its per-device input rows AND the planner's dense
# ceiling (DENSE_BLOWUP_MIN_GROUPS mirrors executor/plan
# MAX_DENSE_GROUPS, so a planner-selected DENSE plan can never trip
# the rule) is the degenerate large-NDV dense plan — state vectors
# dwarf the data; the strategy that 1000x-cliffed and then crashed the
# real-TPU hndv rung at sf>=10.  A gate finding on corpus plans and a
# CostError at sched admission, so selection falls back to the SEGMENT
# strategy instead of faulting the device.
DENSE_BLOWUP_MAX = 16.0
DENSE_BLOWUP_MIN_GROUPS = 1_000_000
# Validated prediction band: on the 8-vdev CPU mesh, peak_hbm_bytes
# stays within this factor of (measured resident input buffers + D x
# compiled per-device output+temp sizes); measured ratios on the corpus
# run 0.8-1.6x (tests/test_copcost.py pins the band).
COST_TOLERANCE = 4.0

# per-mesh HBM budget defaults: fraction of the device-reported limit,
# CPU fallback when the backend reports no memory stats
HBM_BUDGET_FRACTION = 0.8
DEFAULT_CPU_HBM_BUDGET = 16 << 30     # 16 GiB of host "HBM" per mesh

_VALIDITY_BYTES = 1                   # bool mask lane per nullable column


class CostError(PlanContractError):
    """A launch's statically-derived device footprint violates the
    admission budget, or no static bound is derivable for one of its
    nodes.  Raised by sched admission BEFORE any trace/compile; a
    PlanError via PlanContractError, so it surfaces like a planner
    rejection with (rule, path, detail) intact."""


# ------------------------------------------------------------------ #
# layout + cost dataclasses
# ------------------------------------------------------------------ #

@dataclass(frozen=True)
class Layout:
    """Stacked-shard device layout of one scan input: S shards of pow2
    capacity C sharded over D devices (S padded to divide D, exactly as
    ColumnarSnapshot._put pads), with the statically-known live row
    count behind the padding."""
    n_shards: int
    capacity: int
    n_devices: int
    live_rows: int

    @property
    def rows_per_device(self) -> int:
        d = max(self.n_devices, 1)
        return (self.n_shards // d) * self.capacity

    @property
    def padded_rows(self) -> int:
        return self.n_shards * self.capacity


@dataclass(frozen=True)
class LaunchCost:
    """Static footprint of ONE device launch, all devices combined.

    ``peak_hbm_bytes`` = resident inputs + replicated aux + intermediate
    high-water (a no-fusion upper bound: every operator output counted)
    + output leaves, minus ``donated_bytes`` when a DonationPlan
    (analysis/lifetime) lets the launch alias its ephemeral inputs into
    outputs — in+out+temp drops toward max(in, out)+temp.
    ``transfer_bytes`` = H2D inputs/aux + D2H outputs.
    ``padding_waste`` = padded/live row ratio of the scan inputs."""
    input_bytes: int = 0
    aux_bytes: int = 0
    inter_bytes: int = 0
    output_bytes: int = 0
    flops: int = 0
    padded_cells: int = 0
    live_cells: int = 0
    # ((path, out_capacity, probe_rows_per_device), ...) per expanding join
    expanding_joins: tuple = ()
    # ((path, num_groups, rows_per_device), ...) per degenerate DENSE agg
    # (group states > DENSE_BLOWUP_MAX x the per-device input rows)
    dense_blowups: tuple = ()
    # ((path, passes, num_buckets), ...) per SCATTER agg whose priced
    # radix pass count exceeds MAX_RADIX_PASSES (COST-RADIX-PASSES)
    radix_blowups: tuple = ()
    # node paths for which no static bound could be derived
    unbounded: tuple = ()
    # ((label, bytes), ...) largest-first, for reports/EXPLAIN
    breakdown: tuple = ()
    # bytes a DonationPlan lets this launch alias input->output
    # (min(donated inputs, outputs): the donated buffer backs the
    # output instead of coexisting with it)
    donated_bytes: int = 0
    # per-link-class bytes (intra, ici, dci) — parallel/topology's
    # typed-link classification of this launch's traffic: intra carries
    # the host<->device transfer plus on-chip copies, ici/dci the
    # inter-chip collective payload (psum merges, all_to_all exchanges)
    # split by whether each hop crosses a host boundary.  Single-host
    # topologies price dci identically zero.
    transfer_breakdown: tuple = (0, 0, 0)

    @property
    def peak_hbm_bytes(self) -> int:
        return (self.input_bytes + self.aux_bytes + self.inter_bytes
                + self.output_bytes - self.donated_bytes)

    @property
    def transfer_bytes(self) -> int:
        return self.input_bytes + self.aux_bytes + self.output_bytes

    @property
    def ici_bytes(self) -> int:
        return self.transfer_breakdown[1] if self.transfer_breakdown else 0

    @property
    def dci_bytes(self) -> int:
        return self.transfer_breakdown[2] if self.transfer_breakdown else 0

    @property
    def padding_waste(self) -> float:
        return self.padded_cells / max(self.live_cells, 1)

    def combined(self, other: "LaunchCost") -> "LaunchCost":
        """Sum of two independent launches (plan-level rollup)."""
        a, b = self.transfer_breakdown or (0, 0, 0), \
            other.transfer_breakdown or (0, 0, 0)
        return LaunchCost(
            self.input_bytes + other.input_bytes,
            self.aux_bytes + other.aux_bytes,
            self.inter_bytes + other.inter_bytes,
            self.output_bytes + other.output_bytes,
            self.flops + other.flops,
            self.padded_cells + other.padded_cells,
            self.live_cells + other.live_cells,
            self.expanding_joins + other.expanding_joins,
            self.dense_blowups + other.dense_blowups,
            self.radix_blowups + other.radix_blowups,
            self.unbounded + other.unbounded,
            self.breakdown + other.breakdown,
            self.donated_bytes + other.donated_bytes,
            (a[0] + b[0], a[1] + b[1], a[2] + b[2]))


def format_bytes(n: int) -> str:
    f = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if f < 1024 or unit == "GiB":
            return f"{f:.1f}{unit}" if unit != "B" else f"{int(f)}B"
        f /= 1024
    return f"{int(n)}B"


# ------------------------------------------------------------------ #
# widths
# ------------------------------------------------------------------ #

def _width(t: Optional[dt.DataType]) -> int:
    """Logical on-device byte width of a value of type ``t`` — what an
    expression intermediate occupies after the compiler re-widens the
    narrowed scan representation (expr/compile._iwiden)."""
    if t is None:
        return 8
    try:
        return int(np.dtype(t.np_dtype()).itemsize)
    except TypeError:
        return 8        # host-object widths never ship; placeholder slot


def _schema_width(schema: Sequence[dt.DataType]) -> int:
    return sum(_width(t) + _VALIDITY_BYTES for t in schema)


def snapshot_scan_widths(snap) -> tuple:
    """Per stored column: (physical byte width as placed on device,
    mask lanes present) — mirrors ColumnarSnapshot._stacked_ranges
    (narrowed dtype, validity omitted when all rows are valid)."""
    out = []
    for c in snap.columns:
        if c.data.dtype == object:
            out.append((1, False))      # 1-byte placeholder upload
            continue
        out.append((int(c.narrowed().dtype.itemsize), not c.all_valid()))
    return tuple(out)


def snapshot_layout(snap, n_devices: int) -> Layout:
    """Device layout the snapshot's stacked upload will have on a mesh
    of ``n_devices`` — including the pad-to-divide shard padding."""
    s, cap, _counts = snap.shard_layout()
    d = max(int(n_devices), 1)
    s_pad = -(-s // d) * d
    return Layout(s_pad, cap, d, snap.num_rows)


def snapshot_input_bytes(snap, layout: Layout,
                         widths: Optional[tuple] = None) -> int:
    """Resident stacked bytes of the snapshot on device: every stored
    column ships (device_cols uploads the full snapshot, not just the
    scanned offsets), plus the per-shard live counts vector."""
    widths = snapshot_scan_widths(snap) if widths is None else widths
    per_row = sum(w + (_VALIDITY_BYTES if mask else 0) for w, mask in widths)
    return layout.padded_rows * per_row + layout.n_shards * 8


# ------------------------------------------------------------------ #
# the abstract interpreter (DAG walk)
# ------------------------------------------------------------------ #

def _expr_flops(e: Optional[Expr]) -> int:
    """Per-row op count of one expression tree (every Func node is one
    vector op lane; good enough for relative cost)."""
    if e is None or not isinstance(e, Func):
        return 0
    return 1 + sum(_expr_flops(a) for a in e.args)


class _Acc:
    """Per-device walk accumulator; totals multiply by D at rollup."""

    __slots__ = ("inter", "flops", "joins", "dense_blowups",
                 "radix_blowups", "unbounded", "breakdown")

    def __init__(self):
        self.inter = 0
        self.flops = 0
        self.joins = []         # (path, out_capacity, probe_rows)
        self.dense_blowups = []  # (path, num_groups, rows)
        self.radix_blowups = []  # (path, passes, num_buckets)
        self.unbounded = []
        self.breakdown = []     # (label, per-device bytes)

    def buf(self, label: str, nbytes: int) -> None:
        if nbytes > 0:
            self.inter += int(nbytes)
            self.breakdown.append((label, int(nbytes)))


# per-agg accumulator state width in bytes (the (hi, lo) limb split of
# int/decimal SUM doubles its state; a valueflow-proven narrow SUM keeps
# a single int64 word; MIN/MAX/FIRST carry a valid lane)
def _agg_state_width(a: D.AggDesc, narrow: bool = False) -> int:
    if a.func == D.AggFunc.SUM:
        k = a.arg.dtype.kind if a.arg is not None and a.arg.dtype else None
        if k in (dt.TypeKind.FLOAT64, dt.TypeKind.FLOAT32):
            return 8
        return 8 if narrow else 16
    if a.func == D.AggFunc.COUNT:
        return 8
    return 8 + _VALIDITY_BYTES      # MIN / MAX / FIRST: value + valid


def _agg_groups(agg: D.Aggregation, rows: int) -> int:
    """Static bound on the per-device group-state rows.  SORT/SEGMENT
    capacity 0 means "client starts at its default and regrows" — the
    static bound is the per-device row count itself (distinct groups
    cannot exceed contributing rows), so every corpus shape stays
    boundable."""
    if agg.strategy == D.GroupStrategy.SCALAR:
        return 1
    if agg.strategy == D.GroupStrategy.DENSE:
        return max(agg.num_groups, 1)
    cap = agg.state_capacity
    return cap if cap > 0 else max(min(rows, _default_group_capacity()), 1)


def _default_group_capacity() -> int:
    from ..store.client import DEFAULT_GROUP_CAPACITY
    return DEFAULT_GROUP_CAPACITY


def _log2(n: int) -> int:
    return max(int(n - 1).bit_length(), 1)


def _walk(node: D.CopNode, path: tuple, rows: int, layout: Layout,
          widths: Optional[tuple], acc: _Acc) -> Tuple[int, int]:
    """Abstract-interpret one node; returns (rows_out, width_out) of its
    per-device output batch.  ``rows`` is the per-device row count the
    node consumes; buffers are recorded per-device in ``acc``."""
    p = path + (type(node).__name__,)

    if isinstance(node, D.TableScan):
        # the flattened (S/D*C,) view aliases the resident upload — no
        # new buffer, but it fixes the chain's schema width
        if widths is not None:
            w = sum(widths[o][0] + (_VALIDITY_BYTES if widths[o][1] else 0)
                    for o in node.col_offsets if o < len(widths))
        else:
            w = _schema_width(node.col_dtypes)
        return rows, w

    kids = node.children()
    rows_in, w_in = (_walk(kids[0], p, rows, layout, widths, acc)
                     if kids else (rows, 0))

    if isinstance(node, D.Selection):
        for cond in node.conditions:
            acc.flops += _expr_flops(cond) * rows_in
        acc.buf("/".join(p) + ":mask", rows_in * _VALIDITY_BYTES)
        return rows_in, w_in

    if isinstance(node, D.Projection):
        w_out = _schema_width([e.dtype for e in node.exprs])
        for e in node.exprs:
            acc.flops += _expr_flops(e) * rows_in
        acc.buf("/".join(p), rows_in * w_out)
        return rows_in, w_out

    if isinstance(node, D.Expand):
        w_out = _schema_width(D.output_dtypes(node))
        rows_out = rows_in * max(node.levels, 1)
        acc.flops += rows_out
        acc.buf("/".join(p), rows_out * w_out)
        return rows_out, w_out

    if isinstance(node, D.Aggregation):
        groups = _agg_groups(node, rows_in)
        swidth = sum(_agg_state_width(a, narrow=(i in node.narrow_sums))
                     for i, a in enumerate(node.aggs))
        has_minmax = any(a.func in (D.AggFunc.MIN, D.AggFunc.MAX,
                                    D.AggFunc.FIRST) for a in node.aggs)
        for g in node.group_by:
            acc.flops += _expr_flops(g) * rows_in
        for a in node.aggs:
            acc.flops += (_expr_flops(a.arg) + 1) * rows_in
        if node.strategy == D.GroupStrategy.SORT:
            swidth += len(node.group_by) * 8 + 8       # keys + __ngroups__
            # device sort of (dead, nullflag/code per key, payload
            # index): the comparator carries 1 + 2*k lanes, and every
            # lane rides every compare-exchange stage — the cost the
            # radix strategies exist to shed (SURVEY.md §7)
            acc.buf("/".join(p) + ":sort",
                    rows_in * (len(node.group_by) + 1) * 8)
            acc.flops += rows_in * _log2(rows_in) * (
                1 + 2 * len(node.group_by))
        elif node.strategy == D.GroupStrategy.SEGMENT:
            swidth += len(node.group_by) * 8 + 8       # keys + __ngroups__
            # avalanche hash (constant lanes per key) + ONE single-key
            # radix partition pass of (hash, payload-index)
            acc.buf("/".join(p) + ":radix", rows_in * 2 * 8)
            acc.flops += rows_in * (6 * max(len(node.group_by), 1)
                                    + _log2(rows_in))
        elif node.strategy == D.GroupStrategy.SCATTER:
            swidth += len(node.group_by) * 8 + 8       # keys + __ngroups__
            passes = D.radix_passes(node.num_buckets
                                    or max(rows_in, 2))
            n_digits = 1 << D.RADIX_BITS
            n_tiles = max(rows_in // D.RADIX_TILE, 1)
            # per pass: a per-tile digit histogram, the tiny exclusive
            # cumsum of bucket offsets, and the gather/scatter reorder
            # of the int32 index permutation — O(passes * n) streaming
            # data movement, NO comparator lanes.  Buffers (reused
            # across passes, priced once): per-tile histograms +
            # offsets + the int32 permutation ping-pong — under half of
            # SEGMENT's (hash, index) int64 sort operands per row, the
            # bytes half of the acceptance comparison (flops being the
            # other: 3*passes streaming ops vs n*log2(n) comparator
            # stages).
            acc.buf("/".join(p) + ":radix-hist", n_tiles * n_digits * 4)
            acc.buf("/".join(p) + ":radix-cumsum",
                    n_tiles * n_digits * 4)
            acc.buf("/".join(p) + ":radix-scatter", rows_in * 2 * 4)
            # hash (6 lanes/key, as SEGMENT) + per pass: digit extract,
            # histogram add, scatter store (3 ops/row)
            acc.flops += rows_in * (6 * max(len(node.group_by), 1)
                                    + 3 * passes)
            if passes > D.MAX_RADIX_PASSES:
                acc.radix_blowups.append(
                    ("/".join(p), passes, node.num_buckets))
        acc.buf("/".join(p) + ":states", groups * swidth)
        if node.strategy == D.GroupStrategy.DENSE \
                and groups > DENSE_BLOWUP_MIN_GROUPS \
                and groups > DENSE_BLOWUP_MAX * max(rows_in, 1):
            # degenerate dense domain: the state vector dwarfs the data
            # it aggregates — large-NDV keys must take SEGMENT instead
            acc.dense_blowups.append(("/".join(p), groups, rows_in))
        if node.strategy not in D.HOST_MERGE_STRATEGIES:
            # psum-merged states come back replicated; MIN/MAX ride the
            # psum-gather trick whose slot array is Dx the state
            acc.buf("/".join(p) + ":merged", groups * swidth)
            if has_minmax:
                acc.buf("/".join(p) + ":psum-gather",
                        layout.n_devices * groups * swidth)
            acc.flops += groups * max(len(node.aggs), 1) * layout.n_devices
        return groups, swidth

    if isinstance(node, (D.TopN,)):
        keys = node.sort_keys or (((node.sort_key, node.desc),)
                                  if node.sort_key is not None else ())
        nk = max(len(keys), 1)
        for e, _desc in keys:
            acc.flops += _expr_flops(e) * rows_in
        acc.buf("/".join(p) + ":sort", rows_in * (nk + 1) * 8)
        acc.flops += rows_in * _log2(rows_in) * nk
        return min(max(node.limit, 0), rows_in), w_in

    if isinstance(node, D.Limit):
        return min(max(node.limit, 0), rows_in), w_in

    if isinstance(node, D.LookupJoin):
        build_w = _schema_width(node.build_dtypes)
        acc.flops += (_expr_flops(node.probe_key) + _log2(rows_in)) * rows_in
        if node.kind in ("semi", "anti"):
            acc.buf("/".join(p) + ":mask", rows_in * _VALIDITY_BYTES)
            return rows_in, w_in
        if node.unique:
            acc.buf("/".join(p) + ":gather", rows_in * build_w)
            return rows_in, w_in + build_w
        cap = max(node.out_capacity, 0)
        acc.joins.append(("/".join(p), cap, rows_in))
        acc.buf("/".join(p) + ":expand", cap * (w_in + build_w))
        return cap, w_in + build_w

    if isinstance(node, D.FusedDag):
        last = (rows_in, w_in)
        for m in node.members:
            last = _walk(m, p, rows, layout, widths, acc)
        return last

    # a device node this interpreter has no size algebra for: no static
    # bound derivable -> COST-UNBOUNDED (and a CostError at admission)
    acc.unbounded.append("/".join(p))
    return rows_in, w_in


@functools.lru_cache(maxsize=1024)
def _dag_walk_cached(dag: D.CopNode, layout: Layout,
                     widths: Optional[tuple]):
    """Memoized per-device walk result; DAG nodes are frozen (they
    already key the jit-program cache), so repeated admission of one
    program costs a dict hit."""
    acc = _Acc()
    rows0 = layout.rows_per_device
    # flatten preamble: the live-row mask every program materializes
    acc.buf("flatten:base_sel", rows0 * _VALIDITY_BYTES)
    rows_out, w_out = _walk(dag, (), rows0, layout, widths, acc)
    return (acc.inter, acc.flops, tuple(acc.joins),
            tuple(acc.dense_blowups), tuple(acc.radix_blowups),
            tuple(acc.unbounded), tuple(acc.breakdown), rows_out, w_out)


def chain_rows(dag: D.CopNode, layout: Layout,
               widths: Optional[tuple] = None) -> Tuple[int, int]:
    """(per-device output rows, output row width in bytes) of one cop
    chain — the size half shardflow's exchange attribution reuses so
    the verifier and the cost model cannot drift."""
    out = _dag_walk_cached(dag, layout, widths)
    return out[-2], out[-1]


def _default_topology(n_devices: int):
    from ..parallel.topology import single_host
    return single_host(n_devices)


def _collective_breakdown(dag: D.CopNode, layout: Layout,
                          widths: Optional[tuple], topology,
                          merge_route: str):
    """Inter-chip bytes of a program's merge collectives, classified
    per link (parallel/topology).  In-program psum merges (SCALAR/DENSE
    incl. the psum-gather MIN/MAX trick, whose constant factor
    calibration absorbs per digest) exchange each member's state table
    across the mesh; host-merged group tables (SORT/SEGMENT/SCATTER)
    leave the device over PCIe — their D2H bytes already ride
    ``output_bytes``, so per-host routing adds nothing here, while the
    coordinator anti-route is priced as DCI so reports can show what
    SHARD-MERGE-COORDINATOR saves."""
    from ..parallel import topology as T
    bd = T.TransferBreakdown()
    members = dag.members if isinstance(dag, D.FusedDag) else (dag,)
    for m in members:
        if not isinstance(m, D.Aggregation):
            continue
        rows_out, w_out = chain_rows(m, layout, widths)
        state_bytes = rows_out * w_out
        if m.strategy in D.HOST_MERGE_STRATEGIES:
            if merge_route == T.MERGE_COORDINATOR and topology.multi_host:
                bd = bd.combined(T.TransferBreakdown(
                    dci=(topology.n_devices - topology.devices_per_host)
                    * state_bytes))
            continue
        bd = bd.combined(topology.split_psum(state_bytes))
    return bd


def _rows_kind_capacity(dag: D.CopNode, layout: Layout,
                        row_capacity: int) -> int:
    """Per-device output capacity of a row-returning program: the
    caller-pinned capacity when given, else the client's first paging
    guess (store.client INITIAL_SELECTIVITY discipline)."""
    if row_capacity > 0:
        return row_capacity
    if isinstance(dag, (D.TopN, D.Limit)):
        return max(dag.limit, 16)
    from ..store.client import INITIAL_SELECTIVITY
    from ..store.columnar import _pow2_at_least
    per_shard = layout.capacity
    return max(_pow2_at_least(max(per_shard // INITIAL_SELECTIVITY, 1)),
               1024)


def dag_cost(dag: D.CopNode, layout: Layout,
             widths: Optional[tuple] = None, *, input_bytes: int = 0,
             aux_bytes: int = 0, row_capacity: int = 0,
             donation=None, topology=None,
             merge_route: str = "per_host") -> LaunchCost:
    """LaunchCost of one program over one stacked scan input.

    ``input_bytes`` is the resident upload (exact at admission, modeled
    via snapshot_input_bytes at plan time); ``aux_bytes`` the host-
    materialized replicated inputs PER DEVICE COPY (totals multiply by
    the mesh size here).  ``donation`` is an optional
    ``analysis.lifetime.DonationPlan``: donated input bytes alias into
    the output allocation, so the peak drops by min(donated, output).
    ``topology`` (parallel/topology.MeshTopology, default the
    single-host all-ICI view of the layout's mesh) classifies the
    launch's merge-collective bytes per link into
    ``transfer_breakdown`` — the seam that makes admission, pricing and
    fusion caps topology-aware with no runtime change."""
    d = max(layout.n_devices, 1)
    topo = topology if topology is not None else _default_topology(d)
    (inter_pd, flops_pd, joins, dense_blowups, radix_blowups, unbounded,
     breakdown, rows_out, w_out) = _dag_walk_cached(dag, layout, widths)
    root = dag.members[-1] if isinstance(dag, D.FusedDag) and dag.members \
        else dag
    if isinstance(root, D.Aggregation):
        if root.strategy in D.HOST_MERGE_STRATEGIES:
            out_bytes = d * rows_out * w_out      # per-device host merge
        else:
            out_bytes = rows_out * w_out          # replicated, one D2H copy
    else:
        cap = _rows_kind_capacity(root, layout, row_capacity)
        out_bytes = d * (cap * (w_out + _VALIDITY_BYTES) + 8)
    aux_total = int(aux_bytes) * d
    donated = 0
    if donation is not None and donation.donate_argnums:
        from .lifetime import ARG_AUX, ARG_COLS
        donatable = 0
        if ARG_COLS in donation.donate_argnums:
            donatable += int(input_bytes)         # cols + counts upload
        if ARG_AUX in donation.donate_argnums:
            donatable += aux_total
        donated = min(donatable, int(out_bytes))
    coll = _collective_breakdown(dag, layout, widths, topo, merge_route)
    transfer = int(input_bytes) + aux_total + int(out_bytes)
    return LaunchCost(
        input_bytes=int(input_bytes),
        aux_bytes=aux_total,
        inter_bytes=inter_pd * d,
        output_bytes=int(out_bytes),
        flops=flops_pd * d,
        padded_cells=layout.padded_rows,
        live_cells=min(layout.live_rows, layout.padded_rows)
        or layout.padded_rows,
        expanding_joins=joins,
        dense_blowups=dense_blowups,
        radix_blowups=radix_blowups,
        unbounded=unbounded,
        breakdown=tuple(sorted(breakdown, key=lambda kv: -kv[1])[:8]),
        donated_bytes=donated,
        transfer_breakdown=(transfer + coll.intra, coll.ici, coll.dci))


# ------------------------------------------------------------------ #
# admission-time cost (exact input metadata from the stacked arrays)
# ------------------------------------------------------------------ #

def task_cost(task) -> Optional[LaunchCost]:
    """LaunchCost of a structured CopTask, computed from array METADATA
    only (shape/dtype/nbytes — never a device sync) plus the memoized
    DAG walk.  None for opaque tasks (shuffle/window closures: their
    capacities are owned by the client's regrow loop)."""
    if task.dag is None or task.cols is None:
        return None
    s = c = 0
    input_bytes = 0
    widths = []
    for v, m in task.cols:
        if getattr(v, "ndim", 0) >= 2 and not s:
            s, c = int(v.shape[0]), int(v.shape[1])
        input_bytes += int(v.nbytes)
        widths.append((int(np.dtype(v.dtype).itemsize), m is not None))
        if m is not None:
            input_bytes += int(m.nbytes)
    if task.counts is not None:
        input_bytes += int(task.counts.nbytes)
    aux_bytes = 0
    for grp in task.aux or ():
        for v, m in grp:
            aux_bytes += int(v.nbytes)
            if m is not None:
                aux_bytes += int(m.nbytes)
    n_dev = int(task.mesh.devices.size) if task.mesh is not None else 1
    # live rows are a device-resident count; the padded extent is the
    # honest static bound (waste reads 1.0x at admission by design)
    layout = Layout(s or 1, c or 1, n_dev, (s or 1) * (c or 1))
    donation = None
    if getattr(task, "donate", False):
        # donating task: the lifetime plan's aliasing tightens the
        # admission bound (verify_task_donation already vetted safety)
        from .lifetime import donation_plan
        donation = donation_plan(task.dag, "solo")
    # typed-link classification of the merge collectives: the declared
    # host view (tidb_tpu_topology_hosts) splits ici/dci here, making
    # RU pricing and the HBM/fusion caps topology-aware at admission
    from ..parallel.topology import topology_for
    topo = topology_for(task.mesh) if task.mesh is not None else None
    return dag_cost(task.dag, layout, tuple(widths),
                    input_bytes=input_bytes, aux_bytes=aux_bytes,
                    row_capacity=task.row_capacity, donation=donation,
                    topology=topo)


def mesh_hbm_budget(mesh) -> int:
    """Default per-mesh HBM admission budget: a fraction of the
    device-reported memory limit times the mesh size, with a host-memory
    fallback when the backend exposes no stats (CPU meshes).  The raw
    poll routes through obs/hbm — the single sanctioned memory_stats
    seam (TPU-MEM-SOURCE)."""
    from ..obs.hbm import device_memory_stats
    stats = device_memory_stats(mesh)
    limit = int((stats or {}).get("bytes_limit", 0) or 0)
    n_dev = int(mesh.devices.size)
    if limit > 0:
        return int(HBM_BUDGET_FRACTION * limit) * n_dev
    return DEFAULT_CPU_HBM_BUDGET


# ------------------------------------------------------------------ #
# plan-level cost (EXPLAIN footer + the analysis gate's corpus pass)
# ------------------------------------------------------------------ #

def _est_rows(op) -> int:
    """Rough row estimate of a host build-side subtree: the first table
    snapshot found below it (filters only shrink it — an upper bound),
    else a small default."""
    tbl = getattr(op, "table", None)
    if tbl is not None:
        try:
            return int(tbl.snapshot().num_rows)
        except (AttributeError, TypeError):
            return 1024
    for c in getattr(op, "children", []) or []:
        if c is not None:
            n = _est_rows(c)
            if n:
                return n
    return 1024


def _op_snapshot(op):
    tbl = op.table
    if getattr(op, "as_of_snap", None) is not None:
        return op.as_of_snap
    if getattr(tbl, "partition", None) is not None and \
            hasattr(tbl, "partition_snapshot"):
        return tbl.partition_snapshot(getattr(op, "partitions", None))
    return tbl.snapshot()


def _cop_exec_cost(op, n_devices: int, donation=None,
                   topology=None) -> LaunchCost:
    snap = _op_snapshot(op)
    layout = snapshot_layout(snap, n_devices)
    widths = snapshot_scan_widths(snap)
    input_bytes = snapshot_input_bytes(snap, layout, widths)
    aux = 0
    dag = op.dag
    if type(op).__name__ == "CopJoinTaskExec":
        builds = (op.builds if op.builds
                  else [{"exec": op.build_exec}])
        joins = []

        def collect(n):
            if isinstance(n, D.LookupJoin):
                joins.append(n)
            for k in n.children():
                collect(k)
        collect(dag)
        for i, b in enumerate(builds):
            bx = b.get("exec")
            rows = _est_rows(bx) if bx is not None else 1024
            j = joins[i] if i < len(joins) else None
            bw = _schema_width(j.build_dtypes) if j is not None else 8
            aux += rows * (16 + bw)       # sorted keys + perm + columns
    return dag_cost(dag, layout, widths, input_bytes=input_bytes,
                    aux_bytes=aux, donation=donation, topology=topology)


def exchange_bucket_rows(rows_total: int, n_devices: int) -> int:
    """Per-(device, destination) send-bucket row capacity of one
    all_to_all exchange side — the client's initial formula (2x
    headroom over a uniform hash, pow2; store/client
    ``_shuffle_initial_caps``).  Shared with shardflow so the verifier's
    per-link prediction and the runtime caps agree by construction."""
    from ..store.columnar import _pow2_at_least
    d = max(n_devices, 1)
    return _pow2_at_least(max(2 * rows_total // max(d * d, 1) + 1, 1024))


def _exchange_cost(rows_side: int, width: int, layout: Layout) -> int:
    """Per-device all_to_all send-bucket bytes of one shuffle side."""
    d = max(layout.n_devices, 1)
    cap = exchange_bucket_rows(rows_side, d)
    return d * cap * (width + _VALIDITY_BYTES)


def shuffle_exchange_buckets(spec, llayout: Layout, rlayout: Layout,
                             lwidths, rwidths, n_devices: int) -> tuple:
    """Per-(device, destination) send-bucket BYTES of each exchange
    side of a shuffle join, from the CHAIN-output rows (an Expand in an
    exchange chain multiplies what the scan read — the COST-DCI-BLOWUP
    seam).  Row payload mirrors what _side actually ships: the chain's
    columns, the int64 key lane, and the key-ok + valid mask lanes.
    Shared by the plan cost model and shardflow's per-link attribution
    so prediction and verification cannot drift."""
    d = max(n_devices, 1)
    lrows, lwidth = chain_rows(spec.left, llayout, lwidths)
    rrows, rwidth = chain_rows(spec.right, rlayout, rwidths)
    return (exchange_bucket_rows(lrows * d, d)
            * (lwidth + 8 + 2 * _VALIDITY_BYTES),
            exchange_bucket_rows(rrows * d, d)
            * (rwidth + 8 + 2 * _VALIDITY_BYTES))


def _with_exchange(cost: LaunchCost, topo, bucket_bytes_sides) -> tuple:
    """Per-link split of one or more all_to_all exchange edges, summed
    onto a cost's transfer_breakdown tuple."""
    bd = cost.transfer_breakdown or (0, 0, 0)
    intra, ici, dci = bd
    for bucket_bytes in bucket_bytes_sides:
        s = topo.split_all_to_all(bucket_bytes)
        intra += s.intra
        ici += s.ici
        dci += s.dci
    return (intra, ici, dci)


def _shuffle_exec_cost(op, n_devices: int, topology=None) -> LaunchCost:
    spec = op.spec
    topo = topology if topology is not None \
        else _default_topology(n_devices)
    lsnap, rsnap = op.left_table.snapshot(), op.right_table.snapshot()
    llay = snapshot_layout(lsnap, n_devices)
    rlay = snapshot_layout(rsnap, n_devices)
    lw, rw = snapshot_scan_widths(lsnap), snapshot_scan_widths(rsnap)
    cost = dag_cost(spec.left, llay, lw, topology=topo,
                    input_bytes=snapshot_input_bytes(lsnap, llay, lw))
    cost = cost.combined(dag_cost(
        spec.right, rlay, rw, topology=topo,
        input_bytes=snapshot_input_bytes(rsnap, rlay, rw)))
    # exchange buckets + the joined partition the top chain consumes
    d = max(n_devices, 1)
    wl = _schema_width(spec.left_dtypes)
    wr = _schema_width(spec.right_dtypes)
    from ..store.columnar import _pow2_at_least
    ocap = _pow2_at_least(max(2 * lsnap.num_rows // d + 1, 1024))
    exch = (_exchange_cost(lsnap.num_rows, wl, llay)
            + _exchange_cost(rsnap.num_rows, wr, rlay)
            + ocap * (wl + wr))
    top_layout = Layout(d, ocap, d, min(lsnap.num_rows, d * ocap))
    top = dag_cost(spec.top, top_layout, None, topology=topo)
    cost = cost.combined(replace(top, input_bytes=0,
                                 inter_bytes=top.inter_bytes + exch * d,
                                 padded_cells=0, live_cells=0))
    # per-link exchange attribution, from the shared bucket algebra
    sides = shuffle_exchange_buckets(spec, llay, rlay, lw, rw, d)
    return replace(cost,
                   transfer_breakdown=_with_exchange(cost, topo, sides))


def _window_exec_cost(op, n_devices: int, topology=None) -> LaunchCost:
    snap = op.table.snapshot()
    topo = topology if topology is not None \
        else _default_topology(n_devices)
    layout = snapshot_layout(snap, n_devices)
    widths = snapshot_scan_widths(snap)
    spec = op.spec
    cost = dag_cost(spec.child, layout, widths, topology=topo,
                    input_bytes=snapshot_input_bytes(snap, layout, widths))
    d = max(n_devices, 1)
    wcap = exchange_bucket_rows(snap.num_rows, d)
    w_out = _schema_width(op.out_dtypes)
    # partition buckets + one multi-key sort + per-item segment tables
    extra = d * (d * wcap * w_out + d * wcap * 8 * 2
                 + d * wcap * 8 * max(len(spec.items), 1))
    cost = replace(cost, inter_bytes=cost.inter_bytes + extra)
    # the repartition ships child cols + partition/order/arg lanes
    return replace(cost, transfer_breakdown=_with_exchange(
        cost, topo, (wcap * (w_out + _VALIDITY_BYTES),)))


def plan_cost(phys, n_devices: int = 8, topology=None) -> LaunchCost:
    """Roll up the static device footprint of every launch a built
    physical plan implies.  Walks the operator tree (no execution, no
    trace); host operators contribute nothing — their working memory is
    governed by the statement quota, not HBM.  ``topology`` classifies
    transfer per link class (default: the single-host all-ICI view)."""
    total = LaunchCost()
    stack = [phys]
    while stack:
        op = stack.pop()
        name = type(op).__name__
        if name == "CopTaskExec" or name == "CopJoinTaskExec":
            total = total.combined(
                _cop_exec_cost(op, n_devices, topology=topology))
        elif name == "CopShuffleJoinExec":
            total = total.combined(
                _shuffle_exec_cost(op, n_devices, topology=topology))
        elif name == "CopWindowExec":
            total = total.combined(
                _window_exec_cost(op, n_devices, topology=topology))
        for c in getattr(op, "children", []) or []:
            if c is not None:
                stack.append(c)
        fb = getattr(op, "fallback", None)
        if fb is not None:
            stack.append(fb)
    return total


# ------------------------------------------------------------------ #
# gate rules over the TPC-H plan corpus
# ------------------------------------------------------------------ #

def cost_findings(plans, n_devices: int = 8) -> list:
    """COST-* findings over (sql, built-plan) pairs — the cost half of
    the analysis gate.  Finding keys are stable (corpus position + rule)
    so they baseline exactly like lint findings."""
    from .lint import Finding
    out = []
    for idx, (sql, phys) in enumerate(plans):
        qid = f"corpus/q{idx:02d}"
        one_line = " ".join(sql.split())[:60]
        cost = plan_cost(phys, n_devices)
        if cost.live_cells and cost.padding_waste > PAD_WASTE_MAX:
            out.append(Finding(
                "COST-PAD-WASTE", qid, 0, "scan",
                f"padded/live ratio {cost.padding_waste:.1f}x exceeds "
                f"{PAD_WASTE_MAX:.0f}x ({one_line})"))
        for path, cap, rows in cost.expanding_joins:
            if cap > CAP_BLOWUP_MAX * max(rows, 1):
                out.append(Finding(
                    "COST-CAP-BLOWUP", qid, 0, path.split("/")[-1],
                    f"expanding join out_capacity {cap} is "
                    f"{cap / max(rows, 1):.0f}x its per-device probe rows "
                    f"({one_line})"))
        for path, groups, rows in cost.dense_blowups:
            out.append(Finding(
                "COST-DENSE-BLOWUP", qid, 0, path.split("/")[-1],
                f"DENSE aggregation holds {groups} group states for "
                f"{rows} per-device rows "
                f"({groups / max(rows, 1):.0f}x > "
                f"{DENSE_BLOWUP_MAX:.0f}x): degenerate large-NDV dense "
                f"domain, use a radix strategy ({one_line})"))
        for path, passes, buckets in cost.radix_blowups:
            out.append(Finding(
                "COST-RADIX-PASSES", qid, 0, path.split("/")[-1],
                f"SCATTER aggregation over {buckets} buckets prices "
                f"{passes} radix passes (> {D.MAX_RADIX_PASSES}): each "
                "pass is a full-data reorder — a malformed bucket space "
                f"costs more movement than the sort it replaces "
                f"({one_line})"))
        for path in cost.unbounded:
            out.append(Finding(
                "COST-UNBOUNDED", qid, 0, path.split("/")[-1],
                f"no static device-footprint bound derivable ({one_line})"))
    return out


def cost_report(plans, n_devices: int = 8) -> str:
    """Per-corpus-query cost table (``--cost-report``) for bench
    comparisons: peak/transfer bytes, MFLOP estimate, padding ratio."""
    lines = [f"{'query':<44} {'peak':>10} {'xfer':>10} "
             f"{'MFLOP':>8} {'pad':>6}"]
    for idx, (sql, phys) in enumerate(plans):
        cost = plan_cost(phys, n_devices)
        one_line = " ".join(sql.split())
        label = f"q{idx:02d} {one_line[:39]}"
        lines.append(
            f"{label:<44} {format_bytes(cost.peak_hbm_bytes):>10} "
            f"{format_bytes(cost.transfer_bytes):>10} "
            f"{cost.flops / 1e6:>8.2f} {cost.padding_waste:>5.1f}x")
    return "\n".join(lines)


__all__ = ["CostError", "LaunchCost", "Layout", "dag_cost", "task_cost",
           "plan_cost", "cost_findings", "cost_report", "format_bytes",
           "mesh_hbm_budget", "snapshot_layout", "snapshot_scan_widths",
           "snapshot_input_bytes", "chain_rows", "exchange_bucket_rows",
           "shuffle_exchange_buckets",
           "PAD_WASTE_MAX", "CAP_BLOWUP_MAX",
           "DENSE_BLOWUP_MAX", "DENSE_BLOWUP_MIN_GROUPS", "COST_TOLERANCE",
           "DEFAULT_CPU_HBM_BUDGET", "HBM_BUDGET_FRACTION"]
