"""coplife: static buffer-lifetime & donation-safety analysis.

Reference analog: the compiler-first memory discipline of Flare (decide
buffer behavior statically, keep the runtime path dumb) applied to jax
buffer donation (``donate_argnums``, SNIPPETS.md [1-2]).  On TPU every
``jax.jit(shard_map(...))`` launch holds input + output + temp resident
simultaneously unless inputs are donated — but donating the WRONG input
is catastrophic: jax marks donated arrays deleted, so a donated
snapshot-cache column poisons every later query over that snapshot, and
a donated paging-loop input breaks the client's regrow re-launch.

This module classifies every device-program input slot from the PR-2
contract DAG alone (no tracing, no device touch, no jax import):

- ``PERSISTENT``  — snapshot-cache residents (``ColumnarSnapshot.
  device_cols`` returns the same arrays across queries and pages; the
  sched input token pins that identity).  Never donatable; a live
  resident registry backs the static class with a runtime guard.
- ``LOOP_CARRIED`` — inputs the client feeds back into the next launch
  of the same program (store/client.py regrow disciplines: the rows
  paging loop, SORT/SEGMENT group-capacity regrow, expanding-join
  capacity regrow).  Donating one would delete the array the next
  iteration re-reads.
- ``EPHEMERAL``   — dead after the launch: streamed HBM batches
  (``device_put_uncached`` + ``del`` after dispatch), the fresh stacked
  copies ``spmd._stack_slots`` builds per batched launch, one-shot aux
  build sides of extras-free in-program aggregations.

The result is a per-program-shape :class:`DonationPlan` — the ONLY
legitimate source of ``donate_argnums`` for the spmd builders (lint
rule TPU-DONATE rejects literals) — consumed by:

- ``parallel/spmd.py``: all five program builders derive their
  ``donate_argnums`` from the plan; explicit overrides are re-verified
  pre-trace (``verify_donation`` raises ``DonationError`` on a
  PERSISTENT/LOOP-CARRIED slot),
- sched admission: a donating task over a live snapshot resident or a
  non-EPHEMERAL program class is rejected pre-trace
  (``verify_task_donation`` via ``analysis.contracts.verify_task``),
- ``analysis/copcost``: ``LaunchCost.donated_bytes`` tightens
  ``peak_hbm_bytes`` from in+out+temp toward max(in, out)+temp for
  donation-eligible launches,
- the analysis gate: DONATE-UNSAFE / DONATE-MISSED findings over the
  TPC-H plan corpus and the ``--donation-report`` table.
"""

from __future__ import annotations

import enum
import functools
import weakref
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

from ..copr import dag as D
from .contracts import PlanContractError

# DONATE-MISSED floor: an EPHEMERAL scan slot smaller than this is not
# worth a finding (donation saves at most min(in, out) bytes; tiny
# inputs churn nothing)
DONATE_MISSED_MIN_BYTES = 1 << 20          # 1 MiB

# the jit signature every spmd builder compiles: (cols, counts, aux)
ARG_COLS, ARG_COUNTS, ARG_AUX = 0, 1, 2

# program shapes the spmd builders compile (one DonationPlan each)
PROGRAMS = ("solo", "batched", "batched-rows", "fused", "fused-rows")


class DonationError(PlanContractError):
    """A donation plan (or an explicit ``donate_argnums`` override)
    would donate a PERSISTENT or LOOP-CARRIED input slot.  Raised
    BEFORE any trace/compile — a deleted snapshot resident or regrow
    input surfaces later as an opaque 'Array has been deleted' five
    layers deep; this failure carries the slot and the lifetime class
    instead."""


class BufferClass(enum.Enum):
    PERSISTENT = "persistent"
    LOOP_CARRIED = "loop-carried"
    EPHEMERAL = "ephemeral"


@dataclass(frozen=True)
class SlotLife:
    """Lifetime of one jit argument slot of a device program."""
    name: str                  # cols | counts | aux
    argnum: int                # position in the builder's jit signature
    cls: BufferClass
    reason: str


@dataclass(frozen=True)
class DonationPlan:
    """Donation-safety verdict for ONE program shape over one DAG.

    ``donate_argnums`` is the set of jit positions that are safe to
    donate WHEN the caller's arrays are launch-unique (not snapshot
    residents) — the spmd builders apply it only on the donating
    program variant, and sched admission re-checks residency at
    runtime.  An empty tuple means the program class forbids donation
    outright (loop-carried regrow state)."""
    program: str
    slots: Tuple[SlotLife, ...]
    donate_argnums: Tuple[int, ...]

    @property
    def donatable(self) -> bool:
        return bool(self.donate_argnums)

    def slot(self, argnum: int) -> Optional[SlotLife]:
        for s in self.slots:
            if s.argnum == argnum:
                return s
        return None

    def describe(self) -> str:
        return ", ".join(f"{s.name}={s.cls.value}" for s in self.slots)


# ------------------------------------------------------------------ #
# DAG classification
# ------------------------------------------------------------------ #

def _lookup_joins(node: D.CopNode) -> list:
    return [n for n in D.iter_nodes(node)
            if isinstance(n, D.LookupJoin)]


def scan_lifetime(dag: D.CopNode) -> Tuple[BufferClass, str]:
    """Lifetime class of a program's scan inputs (cols + counts),
    derived from the regrow disciplines in store/client.py: any DAG the
    client may re-launch over the SAME input arrays is loop-carried."""
    if isinstance(dag, D.FusedDag):
        worst = (BufferClass.EPHEMERAL, "every member one-shot")
        for m in dag.members:
            cls, why = scan_lifetime(m)
            if cls is not BufferClass.EPHEMERAL:
                worst = (cls, f"member {type(m).__name__}: {why}")
        return worst
    if D.find_expand_join(dag) is not None:
        return (BufferClass.LOOP_CARRIED,
                "expanding-join capacity regrow re-feeds the inputs "
                "(store/client._grown_join_dag loop)")
    if not isinstance(dag, D.Aggregation):
        return (BufferClass.LOOP_CARRIED,
                "rows paging loop re-feeds the inputs on overflow "
                "(store/client._execute_rows_once)")
    if dag.strategy in D.HOST_MERGE_STRATEGIES:
        return (BufferClass.LOOP_CARRIED,
                "group-capacity regrow re-feeds the inputs "
                "(store/client._execute_sort_agg)")
    return (BufferClass.EPHEMERAL,
            "in-program aggregation launches once; inputs dead after")


def aux_lifetime(dag: D.CopNode) -> Tuple[BufferClass, str]:
    """Lifetime of the aux (host-materialized build sides) slot.  Aux
    arrays are built fresh per statement (executor/physical), so they
    share the scan's class — EXCEPT in a fused program where two
    members reading one aux slot must keep it alive for the unfused
    fallback (the scheduler serves refused groups as SEQUENTIAL solo
    launches over the same aux objects)."""
    if isinstance(dag, D.FusedDag):
        seen: set = set()
        for m in dag.members:
            for j in _lookup_joins(m):
                if j.aux_slot in seen:
                    return (BufferClass.PERSISTENT,
                            f"aux slot {j.aux_slot} shared by >= 2 fused "
                            "members: the unfused fallback re-reads it")
                seen.add(j.aux_slot)
    return scan_lifetime(dag)


@functools.lru_cache(maxsize=1024)
def donation_plan(dag: D.CopNode, program: str = "solo") -> DonationPlan:
    """The per-program-shape DonationPlan of a pushed cop DAG.  Frozen
    DAG nodes key the memo exactly like the jit-program cache.

    - ``solo`` / ``fused``:   class follows the DAG's regrow discipline.
    - ``batched`` / ``batched-rows``: the stacked (S, K, C) slot copies
      are built FRESH per launch by ``spmd._stack_slots`` (jnp.stack of
      the member inputs), so cols/counts are ephemeral by construction
      regardless of where the member arrays live — the stack is the
      copy that dies.
    - ``fused-rows``: members keep per-member paging loops; loop-carried.
    """
    if program not in PROGRAMS:
        raise ValueError(f"unknown program shape {program!r}")
    if program in ("batched", "batched-rows"):
        cls, why = (BufferClass.EPHEMERAL,
                    "slot-stacked copies built per launch "
                    "(spmd._stack_slots); the stack dies with the launch")
        aux_cls, aux_why = (BufferClass.EPHEMERAL,
                            "batched launches carry no aux")
    elif program == "fused-rows":
        cls, why = (BufferClass.LOOP_CARRIED,
                    "fused rows members keep per-member paging loops")
        aux_cls, aux_why = cls, why
    else:
        cls, why = scan_lifetime(dag)
        aux_cls, aux_why = aux_lifetime(dag)
    slots = (SlotLife("cols", ARG_COLS, cls, why),
             SlotLife("counts", ARG_COUNTS, cls, why),
             SlotLife("aux", ARG_AUX, aux_cls, aux_why))
    argnums = tuple(s.argnum for s in slots
                    if s.cls is BufferClass.EPHEMERAL)
    return DonationPlan(program, slots, argnums)


def verify_donation(dag: D.CopNode, donate_argnums: Sequence[int],
                    program: str = "solo") -> None:
    """Pre-trace donation-safety check: every donated position must be
    an EPHEMERAL slot of the program's DonationPlan.  The spmd builders
    run this on any explicit ``donate_argnums`` override, so a seeded
    unsafe plan is rejected BEFORE jax.jit could bake the aliasing in."""
    plan = donation_plan(dag, program)
    p = ("donation", program, type(dag).__name__)
    for a in donate_argnums:
        s = plan.slot(int(a))
        if s is None:
            raise DonationError(
                "donate-unsafe", p,
                f"donate_argnums names position {a}, not an input slot "
                f"of the {program} program signature (cols, counts, aux)")
        if s.cls is not BufferClass.EPHEMERAL:
            raise DonationError(
                "donate-unsafe", p,
                f"donating {s.name} (arg {a}) which is "
                f"{s.cls.value}: {s.reason}")


# ------------------------------------------------------------------ #
# live snapshot-resident registry (runtime backstop for PERSISTENT)
# ------------------------------------------------------------------ #

# id(counts array) -> weakref; a hit is valid only while the exact
# array object is alive (the result-cache weakref discipline), so a
# recycled id() can never false-positive.  The counts array is the
# registry token because every device_cols() result carries exactly one.
_RESIDENT: dict = {}
_RESIDENT_CAP = 128


def _sweep_residents() -> None:
    """Drop dead refs.  Runs on EVERY registration (copgauge satellite:
    the registry used to prune only when a donation check happened to
    walk it, so the ledger and ``/hbm`` could count dead entries
    between donations)."""
    dead = [k for k, r in _RESIDENT.items() if r() is None]
    for k in dead:
        del _RESIDENT[k]


def register_resident(counts, nbytes: int = 0,
                      fingerprint=None) -> None:
    """Mark one snapshot's device-resident counts array as PERSISTENT
    (called by ``ColumnarSnapshot.device_cols`` on cache fill).  With
    ``nbytes``/``fingerprint`` the registration also credits the live
    HBM ledger (obs/hbm): the weakref registry is the ledger's
    register/unregister event source — the ledger's own weakref death
    callback is the unregister half."""
    if counts is None:
        return
    try:
        ref = weakref.ref(counts)
    except TypeError:
        return
    _sweep_residents()
    _RESIDENT[id(counts)] = ref       # planlint: ok - weakref-guarded slot
    if nbytes > 0 and fingerprint is not None:
        from ..obs.hbm import ledger_for
        ledger_for(fingerprint).add_resident(counts, nbytes)


def residents() -> list:
    """The LIVE registered resident arrays (dead refs swept first) —
    the view the ledger and ``/hbm`` consume; never returns an entry
    whose array was collected."""
    _sweep_residents()
    return [r() for r in _RESIDENT.values() if r() is not None]


def is_resident(counts) -> bool:
    """Is this exact array object a live snapshot-cache resident?"""
    if counts is None:
        return False
    r = _RESIDENT.get(id(counts))     # planlint: ok - weakref-guarded slot
    return r is not None and r() is counts


def verify_task_donation(task) -> None:
    """Admission-time donation check for a structured CopTask (called
    from ``analysis.contracts.verify_task``): a donating task must be
    in an EPHEMERAL program class AND its input arrays must not be live
    snapshot residents.  Runs in the submitting thread, pre-trace."""
    if not getattr(task, "donate", False) or task.dag is None:
        return
    plan = donation_plan(task.dag, "solo")
    verify_donation(task.dag, plan.donate_argnums or (ARG_COLS,), "solo")
    if is_resident(task.counts):
        raise DonationError(
            "donate-unsafe", ("sched", type(task.dag).__name__),
            "task requests donation but its input token is a LIVE "
            "snapshot-cache resident (ColumnarSnapshot.device_cols "
            "reuses those arrays across queries and pages)")


# ------------------------------------------------------------------ #
# gate rules + reports over the TPC-H plan corpus
# ------------------------------------------------------------------ #

def _plan_cop_ops(phys) -> list:
    """(op, dag) pairs of every broadcast/solo cop exec in a built
    physical plan (shuffle/window programs are opaque to donation:
    their capacities are owned by the client's regrow loop)."""
    out = []
    stack = [phys]
    while stack:
        op = stack.pop()
        if type(op).__name__ in ("CopTaskExec", "CopJoinTaskExec"):
            out.append((op, op.dag))
        for c in getattr(op, "children", []) or []:
            if c is not None:
                stack.append(c)
        fb = getattr(op, "fallback", None)
        if fb is not None:
            stack.append(fb)
    return out


def _op_donation_cost(op, n_devices: int):
    """LaunchCost of one cop exec under its DonationPlan — the
    ephemeral-feed view: what the streaming/uncached path would save."""
    from .copcost import _cop_exec_cost
    return _cop_exec_cost(op, n_devices,
                          donation=donation_plan(op.dag, "solo"))


def donation_findings(plans, n_devices: int = 8) -> list:
    """DONATE-* findings over (sql, built-plan) pairs — the lifetime
    half of the analysis gate.  Keys are corpus-stable (position +
    rule) so they baseline exactly like lint/cost findings.

    - DONATE-UNSAFE: a derived plan donates a PERSISTENT/LOOP-CARRIED
      slot (only fires if plan derivation itself rots — the builders
      re-verify at construction time too).
    - DONATE-MISSED: an EPHEMERAL scan slot above the size floor left
      undonated by the derived plan (baseline-able: a deliberate
      opt-out gets a reviewed baseline.txt entry)."""
    from .copcost import snapshot_input_bytes, snapshot_layout
    from .lint import Finding
    out = []
    for idx, (sql, phys) in enumerate(plans):
        qid = f"corpus/q{idx:02d}"
        one_line = " ".join(sql.split())[:60]
        for op, dag in _plan_cop_ops(phys):
            plan = donation_plan(dag, "solo")
            try:
                verify_donation(dag, plan.donate_argnums, "solo")
            except DonationError as e:
                out.append(Finding(
                    "DONATE-UNSAFE", qid, 0, type(dag).__name__,
                    f"{e.detail} ({one_line})"))
                continue
            cls, _why = scan_lifetime(dag)
            if cls is not BufferClass.EPHEMERAL \
                    or ARG_COLS in plan.donate_argnums:
                continue
            try:
                from .copcost import _op_snapshot
                snap = _op_snapshot(op)
                layout = snapshot_layout(snap, n_devices)
                in_bytes = snapshot_input_bytes(snap, layout)
            except (AttributeError, TypeError):
                continue
            if in_bytes >= DONATE_MISSED_MIN_BYTES:
                out.append(Finding(
                    "DONATE-MISSED", qid, 0, type(dag).__name__,
                    f"EPHEMERAL scan input ({in_bytes} bytes) left "
                    f"undonated by the derived plan ({one_line})"))
    return out


def plan_donation(phys, n_devices: int = 8) -> Tuple[int, int]:
    """(donatable buffer count, donatable bytes) of every cop launch a
    built plan implies, under the ephemeral-feed view — the EXPLAIN
    ``donate:`` footer and the ``--donation-report`` table both read
    this.  Buffers count array leaves: one per shipped column, one per
    validity mask, one counts vector."""
    from .copcost import snapshot_scan_widths
    bufs = saved = 0
    for op, dag in _plan_cop_ops(phys):
        plan = donation_plan(dag, "solo")
        if not plan.donatable:
            continue
        don = _op_donation_cost(op, n_devices)
        saved += don.donated_bytes
        if don.donated_bytes <= 0:
            continue
        if ARG_COLS in plan.donate_argnums:
            try:
                from .copcost import _op_snapshot
                widths = snapshot_scan_widths(_op_snapshot(op))
                bufs += len(widths) + sum(1 for _w, m in widths if m)
            except (AttributeError, TypeError):
                bufs += 1
        if ARG_COUNTS in plan.donate_argnums:
            bufs += 1
    return bufs, saved


def donation_report(plans, n_devices: int = 8) -> str:
    """Per-corpus-query donation table (``--donation-report``): the
    scan-slot lifetime class, donated slot count, donatable bytes, and
    the donated peak next to the undonated one."""
    from .copcost import format_bytes, plan_cost
    lines = [f"{'query':<44} {'class':>12} {'bufs':>5} "
             f"{'donated':>10} {'peak':>10} {'peak(d)':>10}"]
    planned = 0
    for idx, (sql, phys) in enumerate(plans):
        one_line = " ".join(sql.split())
        label = f"q{idx:02d} {one_line[:39]}"
        ops = _plan_cop_ops(phys)
        classes = {scan_lifetime(dag)[0].value for _op, dag in ops}
        cls = ("host-only" if not ops
               else sorted(classes)[0] if len(classes) == 1 else "mixed")
        bufs, saved = plan_donation(phys, n_devices)
        cost = plan_cost(phys, n_devices)
        planned += 1
        lines.append(
            f"{label:<44} {cls:>12} {bufs:>5} "
            f"{format_bytes(saved):>10} "
            f"{format_bytes(cost.peak_hbm_bytes):>10} "
            f"{format_bytes(cost.peak_hbm_bytes - saved):>10}")
    lines.append(f"donation: {planned}/{len(plans)} corpus plans "
                 "planned finite")
    return "\n".join(lines)


__all__ = ["BufferClass", "DonationError", "DonationPlan", "SlotLife",
           "donation_plan", "scan_lifetime", "aux_lifetime",
           "verify_donation", "verify_task_donation",
           "register_resident", "residents", "is_resident",
           "donation_findings",
           "donation_report", "plan_donation",
           "DONATE_MISSED_MIN_BYTES", "ARG_COLS", "ARG_COUNTS", "ARG_AUX"]
