"""copmeter: closed-loop cost calibration for the static launch model.

Reference analog: Flare's split between a slow adaptive control path
and a fast compiled data path (PAPERS.md) — calibration runs host-side
and cheap, the launch path stays static and pre-priced.  PR 4 pinned a
static ``LaunchCost`` model at ``COST_TOLERANCE = 4.0`` and PR 5 landed
measured per-program-digest device-time attribution, but nothing
consumed the measurements: on a real TPU a drifting model silently
misprices RUs, mis-sizes the HBM admission budget, and can OOM a
perfectly healthy program into the PR 8 circuit breaker as if it were
poison.  This module closes the loop:

- a bounded, LRU-evicted per-program-digest correction store
  (``CorrectionStore``) keyed by the RESTART-STABLE dag digest
  (analysis/compilekey.stable_digest, the copforge key half), holding
  two EWMA factors per digest:

  * ``time_factor``  — measured launch wall time over the static
    model's predicted time; corrects the flops/bytes *work* terms that
    feed RU pricing and the micro-batch window,
  * ``mem_factor``   — bumped multiplicatively on every OOM-classified
    launch failure; corrects the modeled (non-exact) HBM terms that
    feed budget admission and fusion footprint caps,

  both HARD-CLAMPED to ``[CALIB_CLAMP_MIN, CALIB_CLAMP_MAX]`` =
  [1/8, 8]: measured feedback may bend the static model, never replace
  it (an unbounded factor would let one bad measurement starve or
  flood admission — the TPU-CALIB-CLAMP lint rule enforces that every
  factor multiply references these constants).

- persistence THROUGH the copforge manifest (compilecache/manifest):
  corrections ride the same JSON file as the warm-pool entries, so
  calibration survives restarts exactly as far as the compiled
  programs it describes — and a breaker-quarantined digest's
  corrections are purged WITH its manifest entries (no stale feedback
  laundering through a restart).

- consumers (sched/scheduler):  corrected ``LaunchCost`` feeds RU
  pricing at submit, HBM-budget admission, the fusion summed-footprint
  cap, the adaptive micro-batch window (a hold must stay small next to
  the digest's measured launch time), and deadline-aware early
  shedding (reject 8252/9003 at the queue HEAD when the corrected-cost
  backlog already exceeds the waiter's deadline).  EXPLAIN surfaces
  ``cost: static|calibrated (err N%)``.

Like copcost, this module never imports jax: corrections are pure
arithmetic over measured nanoseconds and frozen LaunchCost values.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, replace
from typing import Optional

# ------------------------------------------------------------------ #
# the clamp: measured feedback bends the static model, never replaces
# it.  TPU-CALIB-CLAMP (analysis/lint) fails the gate on any code path
# that multiplies a LaunchCost term by a correction factor without
# referencing these constants.
# ------------------------------------------------------------------ #
CALIB_CLAMP_MIN = 1.0 / 8.0
CALIB_CLAMP_MAX = 8.0
# EWMA step per observed launch (the PR 4 window-feedback idiom)
CALIB_ALPHA = 0.25
# bounded LRU cap on tracked digests — shared with the scheduler's
# per-digest device-time attribution map (one eviction policy)
CALIB_STORE_CAP = 256
# multiplicative memory-correction bump per OOM-classified failure:
# two OOMs quadruple the modeled footprint (still clamped)
CALIB_OOM_BUMP = 2.0
# gate acceptance: calibrated pricing error on the TPC-H corpus
CALIB_TARGET_ERR = 0.25
# throttle manifest writes: calibration persists at most this often
CALIB_PERSIST_S = 1.0

# nominal device throughput the static time prediction assumes; the
# time_factor absorbs (clamped) per-digest deviation from it.  These
# define the *unit* of the prediction, not a claim about any chip.
NOMINAL_BYTES_PER_MS = 32 << 20          # ~32 GB/s effective transfer
NOMINAL_FLOPS_PER_MS = 50_000_000        # ~50 GFLOP/s effective
DISPATCH_OVERHEAD_MS = 0.05              # per-launch fixed dispatch


def clamp_factor(f: float) -> float:
    """The ONLY sanctioned way to apply a measured correction factor:
    hard-clamped to [CALIB_CLAMP_MIN, CALIB_CLAMP_MAX]."""
    return min(max(float(f), CALIB_CLAMP_MIN), CALIB_CLAMP_MAX)


def predict_ms(cost) -> float:
    """Static launch-time prediction from a LaunchCost: transfer at the
    nominal bandwidth + flops at the nominal rate + fixed dispatch
    overhead.  The absolute scale is nominal by construction — the
    per-digest time_factor calibrates it against measured wall time."""
    return (DISPATCH_OVERHEAD_MS
            + cost.transfer_bytes / NOMINAL_BYTES_PER_MS
            + cost.flops / NOMINAL_FLOPS_PER_MS)


class BoundedLRU:
    """Thread-safe bounded map with LRU eviction — the ONE eviction
    policy shared by the correction store and the scheduler's
    per-digest device-time attribution map (ISSUE 10 satellite: the
    attribution map previously grew per digest for the life of the
    process)."""

    def __init__(self, cap: int = CALIB_STORE_CAP):
        self.cap = max(int(cap), 1)
        self._mu = threading.Lock()
        self._od: OrderedDict = OrderedDict()
        self.evictions = 0

    def _evict_locked(self) -> None:
        while len(self._od) > self.cap:
            self._od.popitem(last=False)
            self.evictions += 1

    def get(self, key, default=None):
        with self._mu:
            if key in self._od:
                self._od.move_to_end(key)
                return self._od[key]
            return default

    def put(self, key, value) -> None:
        with self._mu:
            self._od[key] = value
            self._od.move_to_end(key)
            self._evict_locked()

    def bump(self, key, delta) -> None:
        """Accumulate ``delta`` onto a numeric slot (the device-ns
        attribution idiom), LRU-touching the key."""
        with self._mu:
            self._od[key] = self._od.get(key, 0) + delta
            self._od.move_to_end(key)
            self._evict_locked()

    def pop(self, key, default=None):
        with self._mu:
            return self._od.pop(key, default)

    def clear(self) -> None:
        with self._mu:
            self._od.clear()

    def items(self) -> list:
        with self._mu:
            return list(self._od.items())

    def keys(self) -> list:
        with self._mu:
            return list(self._od.keys())

    def __iter__(self):
        return iter(self.keys())

    def __len__(self) -> int:
        with self._mu:
            return len(self._od)

    def __contains__(self, key) -> bool:
        with self._mu:
            return key in self._od


@dataclass
class Correction:
    """One digest's measured corrections (all EWMA, all clamped)."""
    time_factor: float = 1.0     # measured / predicted launch time
    mem_factor: float = 1.0      # measured-watermark footprint
                                 # correction (copgauge; the OOM x2
                                 # bump is its fast path)
    err: float = 0.0             # EWMA relative error of the
                                 # CALIBRATED prediction (EXPLAIN's N%)
    mem_err: float = 0.0         # EWMA relative error of the
                                 # calibrated HBM-peak prediction
    ewma_ms: float = 0.0         # EWMA measured launch wall time
    samples: int = 0
    mem_samples: int = 0         # measured-watermark observations
    oom_bumps: int = 0

    def payload(self) -> dict:
        return {"time_factor": round(self.time_factor, 4),
                "mem_factor": round(self.mem_factor, 4),
                "err": round(self.err, 4),
                "mem_err": round(self.mem_err, 4),
                "ewma_ms": round(self.ewma_ms, 4),
                "samples": self.samples,
                "mem_samples": self.mem_samples,
                "oom_bumps": self.oom_bumps}

    @classmethod
    def from_payload(cls, d: dict) -> "Correction":
        return cls(
            time_factor=clamp_factor(d.get("time_factor", 1.0)),
            mem_factor=clamp_factor(d.get("mem_factor", 1.0)),
            err=max(float(d.get("err", 0.0)), 0.0),
            mem_err=max(float(d.get("mem_err", 0.0)), 0.0),
            ewma_ms=max(float(d.get("ewma_ms", 0.0)), 0.0),
            samples=max(int(d.get("samples", 0)), 0),
            mem_samples=max(int(d.get("mem_samples", 0)), 0),
            oom_bumps=max(int(d.get("oom_bumps", 0)), 0))


def merge_correction_payloads(base: Optional[dict],
                              other: dict) -> dict:
    """coplace (pd/): observation-count-weighted merge of two
    correction payloads — the cross-process twin of the in-process
    EWMA.  The side with more observations dominates (w = n_other /
    (n_base + n_other)), every factor re-passes ``clamp_factor`` so
    the [CALIB_CLAMP_MIN, CALIB_CLAMP_MAX] invariant survives any
    merge order, and sample counts take the MAX of the two sides —
    summing would double-count the same launches on every sync round
    and let a stale payload outvote live measurement forever.
    Time and memory channels merge independently on their own counts;
    ``oom_bumps`` takes the max (each bump already multiplied the
    factor it describes)."""
    if not base:
        return dict(other)
    out = dict(base)
    n_b = max(base.get("samples", 0), 0)
    n_o = max(other.get("samples", 0), 0)
    if n_o > 0:
        w = n_o / max(n_b + n_o, 1)
        tf_b = base.get("time_factor", 1.0)
        out["time_factor"] = round(clamp_factor(
            tf_b + w * (other.get("time_factor", 1.0) - tf_b)), 4)
        for field in ("err", "ewma_ms"):
            v_b = max(base.get(field, 0.0), 0.0)
            out[field] = round(v_b + w * (max(other.get(field, 0.0),
                                              0.0) - v_b), 4)
        out["samples"] = max(n_b, n_o)
    m_b = max(base.get("mem_samples", 0), 0)
    m_o = max(other.get("mem_samples", 0), 0)
    if m_o > 0:
        w = m_o / max(m_b + m_o, 1)
        mf_b = base.get("mem_factor", 1.0)
        out["mem_factor"] = round(clamp_factor(
            mf_b + w * (other.get("mem_factor", 1.0) - mf_b)), 4)
        me_b = max(base.get("mem_err", 0.0), 0.0)
        out["mem_err"] = round(me_b + w * (max(other.get("mem_err",
                                                         0.0),
                                               0.0) - me_b), 4)
        out["mem_samples"] = max(m_b, m_o)
    out["oom_bumps"] = max(base.get("oom_bumps", 0),
                           other.get("oom_bumps", 0))
    if out["oom_bumps"] > base.get("oom_bumps", 0):
        # a peer saw OOMs we did not: adopt the larger (clamped)
        # memory correction outright — admission safety beats EWMA
        out["mem_factor"] = round(clamp_factor(
            max(out.get("mem_factor", 1.0),
                other.get("mem_factor", 1.0))), 4)
    return out


class CorrectionStore:
    """Bounded per-digest EWMA correction store (the control path).

    Keys are RESTART-STABLE dag digests (analysis/compilekey
    ``stable_digest`` hex), so persisted corrections match the same
    program after a restart.  All mutation happens under one leaf
    lock; readers get plain floats (never a live Correction to race
    on) via ``factors``/``expected_ns``."""

    def __init__(self, cap: int = CALIB_STORE_CAP):
        self._mu = threading.Lock()
        self._entries: BoundedLRU = BoundedLRU(cap)
        self._dirty = False
        self._last_persist = 0.0
        self._restored_dirs: set = set()
        self.observed = 0            # launches fed back (lifetime)
        self.mem_observed = 0        # measured watermarks fed back
        self.oom_events = 0          # OOM bumps recorded (lifetime)

    # ---- feedback ---------------------------------------------------- #

    def observe(self, digest: str, cost, measured_ns: int) -> None:
        """Feed one measured launch back: EWMA the digest's
        time_factor toward the clamped measured/predicted ratio and
        track the calibrated model's remaining relative error."""
        meas_ms = measured_ns / 1e6
        if cost is None or meas_ms <= 0:
            return
        pred = predict_ms(cost)
        ratio = clamp_factor(meas_ms / max(pred, 1e-9))
        with self._mu:
            ent = self._entries.get(digest)
            if ent is None:
                ent = Correction()
                self._entries.put(digest, ent)
            # error of the model as it stood BEFORE this update — the
            # honest "how wrong were we" number EXPLAIN reports
            rel = abs(pred * clamp_factor(ent.time_factor) - meas_ms) \
                / max(meas_ms, 1e-9)
            ent.err = rel if ent.samples == 0 else \
                (1.0 - CALIB_ALPHA) * ent.err + CALIB_ALPHA * rel
            ent.time_factor = clamp_factor(
                ent.time_factor + CALIB_ALPHA * (ratio - ent.time_factor))
            ent.ewma_ms = meas_ms if ent.samples == 0 else \
                (1.0 - CALIB_ALPHA) * ent.ewma_ms + CALIB_ALPHA * meas_ms
            ent.samples += 1
            self.observed += 1
            self._dirty = True

    def observe_mem(self, digest: str, cost, measured_bytes: int) -> None:
        """Measured launch watermark feedback (copgauge): EWMA the
        digest's ``mem_factor`` toward the clamped factor that would
        make the modeled (non-exact) HBM terms — inter_bytes +
        output_bytes, exactly what ``corrected_cost`` scales — match
        the measured peak.  The exact resident-input term is never
        corrected (copcost pins it byte-for-byte), so the target solves
        ``exact + f * modeled == measured`` for f.  This is the
        continuous twin of ``observe_oom``'s x2 bump: admission
        headroom now tightens AND loosens from evidence instead of
        waiting for a device fault."""
        if cost is None or measured_bytes <= 0:
            return
        modeled = int(cost.inter_bytes) + int(cost.output_bytes)
        if modeled <= 0:
            return
        exact = cost.peak_hbm_bytes - modeled
        target = clamp_factor((measured_bytes - exact) / modeled)
        with self._mu:
            ent = self._entries.get(digest)
            if ent is None:
                ent = Correction()
                self._entries.put(digest, ent)
            # error of the memory model as it stood BEFORE this update
            pred = exact + modeled * clamp_factor(ent.mem_factor)
            rel = abs(pred - measured_bytes) / max(measured_bytes, 1)
            ent.mem_err = rel if ent.mem_samples == 0 else \
                (1.0 - CALIB_ALPHA) * ent.mem_err + CALIB_ALPHA * rel
            ent.mem_factor = clamp_factor(
                ent.mem_factor + CALIB_ALPHA * (target - ent.mem_factor))
            ent.mem_samples += 1
            self.mem_observed += 1
            self._dirty = True

    def observe_oom(self, digest: str) -> None:
        """An OOM-classified launch failure: the modeled footprint was
        too small — bump the digest's memory correction (clamped) so
        budget admission and fusion caps see a bigger program next
        time (streaming / solo launches instead of a device fault)."""
        with self._mu:
            ent = self._entries.get(digest)
            if ent is None:
                ent = Correction()
                self._entries.put(digest, ent)
            ent.mem_factor = clamp_factor(ent.mem_factor * CALIB_OOM_BUMP)
            ent.oom_bumps += 1
            self.oom_events += 1
            self._dirty = True

    # ---- application ------------------------------------------------- #

    def get(self, digest: str) -> Optional[Correction]:
        with self._mu:
            ent = self._entries.get(digest)
            return replace(ent) if ent is not None else None

    def corrected_cost(self, digest: str, cost):
        """LaunchCost with this digest's measured corrections applied:
        time_factor scales the flops work term, mem_factor the modeled
        (non-exact) intermediate/output HBM terms.  Exact admission
        metadata — the resident input bytes — is never corrected.
        Unknown digests return ``cost`` unchanged (the static model)."""
        with self._mu:
            ent = self._entries.get(digest)
            if ent is None or (ent.samples == 0 and ent.oom_bumps == 0
                               and ent.mem_samples == 0):
                return cost
            tf = clamp_factor(ent.time_factor)
            mf = clamp_factor(ent.mem_factor)
        return replace(cost,
                       flops=int(cost.flops * tf),
                       inter_bytes=int(cost.inter_bytes * mf),
                       output_bytes=int(cost.output_bytes * mf))

    def expected_ns(self, digest: str) -> int:
        """EWMA measured launch time of this digest in ns (0 = never
        measured) — the deadline-shedding backlog unit and the
        micro-batch window's hold ceiling."""
        with self._mu:
            ent = self._entries.get(digest)
            if ent is None or ent.samples == 0:
                return 0
            return int(ent.ewma_ms * 1e6)

    def purge(self, digest: str) -> None:
        """Quarantine hygiene: a breaker-opened digest's corrections
        are dropped with its manifest entries — measured feedback from
        a poisoned program must not survive its quarantine."""
        with self._mu:
            if self._entries.pop(digest) is not None:
                self._dirty = True

    def reset(self) -> None:
        with self._mu:
            self._entries.clear()
            self._restored_dirs.clear()
            self._dirty = False

    # ---- persistence (through the copforge manifest) ----------------- #

    def entries_payload(self) -> dict:
        with self._mu:
            return {d: ent.payload() for d, ent in self._entries.items()}

    def restore(self, manifest) -> int:
        """Merge persisted corrections (digests not already observed
        live win nothing — live EWMA state is fresher than disk)."""
        loaded = manifest.load_calibration()
        n = 0
        with self._mu:
            for d, payload in sorted(loaded.items()):
                if self._entries.get(d) is None:
                    self._entries.put(d, Correction.from_payload(payload))
                    n += 1
        return n

    def merge_payload(self, digest: str, payload: dict) -> bool:
        """coplace (pd/ calibration sync): fold one shared payload
        into this store — observation-count-weighted EWMA merge
        (``merge_correction_payloads``), clamp preserved.  A digest
        never seen locally adopts the peer's payload outright (a
        digest measured hot in process A prices correctly in B before
        B ever launches it).  Returns True when the local entry
        actually moved — the pd sync counter's unit."""
        with self._mu:
            ent = self._entries.get(digest)
            if ent is None:
                fresh = Correction.from_payload(payload)
                if fresh.samples == 0 and fresh.mem_samples == 0 \
                        and fresh.oom_bumps == 0:
                    return False       # nothing measured: not worth a slot
                self._entries.put(digest, fresh)
                self._dirty = True
                return True
            merged = Correction.from_payload(
                merge_correction_payloads(ent.payload(), payload))
            changed = (abs(merged.time_factor - ent.time_factor) > 1e-6
                       or abs(merged.mem_factor - ent.mem_factor) > 1e-6
                       or merged.samples != ent.samples
                       or merged.mem_samples != ent.mem_samples
                       or merged.oom_bumps != ent.oom_bumps)
            if changed:
                self._entries.put(digest, merged)
                self._dirty = True
            return changed

    def sync_manifest(self, force: bool = False) -> None:
        """Throttled restore+persist against the copforge manifest (a
        no-op without a cache dir).  First sync per directory restores
        persisted corrections; later syncs write dirty state at most
        every CALIB_PERSIST_S."""
        from ..compilecache import compile_cache
        cache = compile_cache()
        m = cache.manifest
        if m is None:
            return
        with self._mu:
            fresh_dir = m.cache_dir not in self._restored_dirs
            if fresh_dir:
                self._restored_dirs.add(m.cache_dir)
            now = time.monotonic()
            due = force or (self._dirty
                            and now - self._last_persist >= CALIB_PERSIST_S)
            if due:
                self._dirty = False
                self._last_persist = now
        if fresh_dir:
            self.restore(m)
        if due:
            m.save_calibration(self.entries_payload())

    # ---- introspection ----------------------------------------------- #

    def stats(self) -> dict:
        with self._mu:
            items = self._entries.items()
            errs = [e.err for _d, e in items if e.samples > 0]
            merrs = [e.mem_err for _d, e in items if e.mem_samples > 0]
            return {
                "entries": len(items),
                "observed": self.observed,
                "mem_observed": self.mem_observed,
                "oom_events": self.oom_events,
                "evictions": self._entries.evictions,
                "mean_err_pct": round(100.0 * sum(errs) / len(errs), 2)
                if errs else None,
                "mean_mem_err_pct": round(
                    100.0 * sum(merrs) / len(merrs), 2)
                if merrs else None,
                "digests": {
                    d: e.payload() for d, e in sorted(
                        items, key=lambda kv: -kv[1].samples)[:8]},
            }


def arbitrated_ms(digest: str, cost) -> float:
    """Per-digest calibrated launch-time estimate for STRATEGY
    ARBITRATION (executor/plan picking SORT vs SEGMENT vs SCATTER for a
    high-NDV group-by): the static predict_ms bent by the digest's
    measured (clamped) time_factor when launches have been observed,
    the untouched static prediction otherwise.  A digest whose measured
    factor beats a rival's flips selection with NO code change — the
    closed-loop the ROADMAP names for the real-TPU hndv cliff."""
    pred = predict_ms(cost)
    ent = correction_store().get(digest)
    if ent is not None and ent.samples > 0:
        pred *= clamp_factor(ent.time_factor)
    return pred


_STORE: Optional[CorrectionStore] = None
_STORE_MU = threading.Lock()


def correction_store() -> CorrectionStore:
    """Process-wide correction store (one per process, like the metric
    registry and the compile cache)."""
    global _STORE
    with _STORE_MU:
        if _STORE is None:
            _STORE = CorrectionStore()
        return _STORE


# ------------------------------------------------------------------ #
# gate calibration pass (python -m tidb_tpu.analysis) — a deterministic
# closed-loop simulation over the REAL corpus costs: the "device" is
# the static prediction times a per-query drift factor, the loop feeds
# measurements through a fresh CorrectionStore, and the calibrated
# model must land within CALIB_TARGET_ERR of the drifted truth.
# ------------------------------------------------------------------ #

# per-query drift factors (cycled): spread across the clamp range so
# the pass proves convergence from both directions, incl. the extremes
_GATE_DRIFTS = (0.35, 2.6, 5.5, 0.18, 1.0, 3.2, 0.75, 7.1)
_GATE_ROUNDS = 16


def simulate_corpus_calibration(plans, n_devices: int = 8) -> list:
    """[(qid, sql, drift, static_err, calibrated_err), ...] for every
    device-bearing corpus plan, after _GATE_ROUNDS of closed-loop
    feedback against a synthetic drifted device."""
    from .copcost import plan_cost
    store = CorrectionStore()
    rows = []
    for idx, (sql, phys) in enumerate(plans):
        cost = plan_cost(phys, n_devices)
        if not cost.transfer_bytes and not cost.flops:
            continue                     # host-only: never device-priced
        drift = _GATE_DRIFTS[idx % len(_GATE_DRIFTS)]
        digest = f"gate/q{idx:02d}"
        pred = predict_ms(cost)
        true_ms = pred * drift
        for _ in range(_GATE_ROUNDS):
            store.observe(digest, cost, int(true_ms * 1e6))
        ent = store.get(digest)
        calibrated = pred * clamp_factor(ent.time_factor)
        rows.append((f"q{idx:02d}", " ".join(sql.split()), drift,
                     abs(pred - true_ms) / true_ms,
                     abs(calibrated - true_ms) / true_ms))
    return rows


def calibration_report(plans, n_devices: int = 8) -> str:
    """``--calibration-report``: the per-corpus-query closed-loop
    convergence table (static vs calibrated pricing error)."""
    rows = simulate_corpus_calibration(plans, n_devices)
    lines = [f"{'query':<46} {'drift':>6} {'static':>8} {'calib':>8}"]
    for qid, sql, drift, serr, cerr in rows:
        label = f"{qid} {sql[:41]}"
        lines.append(f"{label:<46} {drift:>5.2f}x {serr:>7.1%} "
                     f"{cerr:>7.1%}")
    if rows:
        mean = sum(r[4] for r in rows) / len(rows)
        worst = max(r[4] for r in rows)
        lines.append(f"calibrated pricing error: mean {mean:.1%}, "
                     f"max {worst:.1%} (target < {CALIB_TARGET_ERR:.0%})")
    return "\n".join(lines)


__all__ = ["CorrectionStore", "Correction", "BoundedLRU",
           "correction_store", "clamp_factor", "predict_ms",
           "arbitrated_ms", "merge_correction_payloads",
           "simulate_corpus_calibration", "calibration_report",
           "CALIB_CLAMP_MIN", "CALIB_CLAMP_MAX", "CALIB_ALPHA",
           "CALIB_STORE_CAP", "CALIB_OOM_BUMP", "CALIB_TARGET_ERR",
           "NOMINAL_BYTES_PER_MS", "NOMINAL_FLOPS_PER_MS",
           "DISPATCH_OVERHEAD_MS"]
