"""CI gate: ``python -m tidb_tpu.analysis``.

Runs three static passes and exits non-zero on any NEW finding:

1. TPU-hygiene lint over the whole tidb_tpu/ tree, diffed against the
   accepted-findings allowlist (analysis/baseline.txt) — pre-existing
   accepted findings pass, new ones fail.
2. Cost analysis (analysis/copcost) over the TPC-H plan corpus: every
   statement is planned (never executed — no trace, no compile, no
   device) and its static device footprint rolled up; COST-PAD-WASTE /
   COST-CAP-BLOWUP / COST-DENSE-BLOWUP / COST-UNBOUNDED findings
   baseline exactly like lint findings.  The buffer-lifetime pass
   (analysis/lifetime) rides the same corpus walk: DONATE-UNSAFE (a
   derived plan would donate a PERSISTENT/LOOP-CARRIED slot) and
   DONATE-MISSED (a large EPHEMERAL slot left undonated) findings.
3. Plan-contract verification over the same corpus plans
   (analysis.verify_plan); any PlanContractError fails the gate.
4. RU pricing over the same corpus (rc/pricing over the cost model's
   rollup): every device-bearing TPC-H plan must price to a finite
   RU value strictly above the per-task floor — guards pricing-model
   rot (a weight edit that zeroes or NaNs the terms) the same way
   --check-baseline guards waiver rot.
5. Closed-loop calibration (analysis/calibrate) over the same corpus:
   a deterministic simulation drifts each plan's true launch time
   across the clamp range and feeds measurements back through a fresh
   CorrectionStore; the calibrated model must land within
   CALIB_TARGET_ERR (< 25%) of the drifted truth on EVERY plan —
   guards the feedback loop (EWMA step, clamp, prediction terms) the
   way the pricing pass guards the static weights.
6. Sharding-flow analysis (analysis/shardflow) over the TPC-H corpus
   PLUS the MULTICHIP dryrun plan shapes: every device program's
   layouts and collectives flow clean against both the native
   single-host topology and the fake (host=2, device=4) multi-host
   view of the 8-vdev mesh, with finite per-link transfer bytes
   (intra / ici / dci).  SHARD-IMPLICIT-RESHARD / SHARD-AXIS-UNKNOWN /
   SHARD-MERGE-COORDINATOR / COST-DCI-BLOWUP findings baseline like
   every other corpus rule.
7. Coordination-plane schema (pd/store, coplace): every shared-store
   key family must declare an owner module, a positive TTL, and an
   epoch-fencing rule, and a live in-memory store must refuse writes
   from a released (dead) lease epoch — guards the schema the same
   way the pricing pass guards the static weights.
8. Value-range flow (analysis/valueflow, copnum): every corpus plan's
   device programs flow through the whole-plan abstract interpreter —
   per-column integer intervals seeded from ANALYZE stats (type
   domains when absent) carried through expression lowering, filters,
   joins and aggregation states.  NUM-OVERFLOW-DEVICE /
   NUM-FENCE-UNPROVEN / NUM-PRECISION-LOSS / NUM-DIV-PRESCALE findings
   baseline like every other corpus family; the verdict counts
   stats-proven plans and proven-narrow single-word SUM states.
9. Whole-program concurrency model (analysis/concurrency, copsan):
   every module importing threading is auto-discovered (no hand
   list), its lock allocation sites become named nodes, with/acquire
   nesting becomes a global acquisition graph, and per-class guard
   inference checks every shared-attribute write's lockset.
   RACE-UNGUARDED-WRITE / RACE-GUARD-MIX / LOCK-ORDER-CYCLE /
   LOCK-BLOCKING-HELD / LOCK-CV-PREDICATE findings baseline like
   every other family; utils/locksan validates the same edge set at
   runtime (sysvar tidb_tpu_lock_sanitizer).

Flags:
    --lint-only / --contracts-only   run one pass
    --concurrency-only               run just the copsan concurrency
                                     pass (RACE-/LOCK- families)
    --value-only                     run just the copnum value-range
                                     pass (NUM- family)
    --race-report                    print the per-module concurrency
                                     model table (locks, acquisition
                                     edges, thread roots, findings)
                                     and exit
    --update-baseline                rewrite baseline.txt from the
                                     current lint+cost findings
                                     (reviewed use only)
    --check-baseline                 fail when baseline.txt contains
                                     entries no current finding matches
                                     (waiver-rot hygiene)
    --cost-report                    print the per-corpus-query cost
                                     table (bytes/flops/padding) and exit
    --donation-report                print the per-corpus-query buffer
                                     lifetime / donation table and exit
    --cache-report                   print the per-corpus-query compile
                                     cache key/variant/bytes table
                                     (analysis/compilekey) and exit
    --calibration-report             print the per-corpus-query
                                     closed-loop calibration table
                                     (static vs calibrated pricing
                                     error, analysis/calibrate) and
                                     exit
    --transfer-report                print the per-corpus-query
                                     per-link transfer table
                                     (intra/ici/dci bytes under the
                                     host=2 view, analysis/shardflow)
                                     and exit
    --pd-report                      print the coplace shared-store
                                     schema (key family -> owner, TTL,
                                     epoch rule; pd/store) with the
                                     live fence check and exit
    --value-report                   print the per-corpus-query
                                     value-range flow table (device ops
                                     flowed, proven-narrow SUM states,
                                     verdict; analysis/valueflow) and
                                     exit
"""

from __future__ import annotations

import os
import sys

# plan building never needs a device, but imports touch jax; pin the CPU
# backend so the gate runs identically on dev boxes, CI, and TPU hosts
# (and never blocks on TPU acquisition).  8 virtual devices = the mesh
# the cost model's corpus predictions are validated against.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

GATE_DEVICES = 8


def _baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.txt")


def _corpus_plans(with_stats: bool = False):
    """Built corpus plans; ``with_stats=True`` also returns the plan
    session's stats handle (valueflow seeds its intervals from it)."""
    from ..testing.tpch import built_tpch_plans, tpch_plan_session
    session = tpch_plan_session()
    plans = list(built_tpch_plans(session))
    if with_stats:
        return plans, session.domain.stats
    return plans


def _gather_findings(lint_only: bool, contracts_only: bool,
                     concurrency_only: bool = False,
                     value_only: bool = False):
    """(findings, plans, stats): the baseline-diffable findings of the
    selected passes plus the corpus plans and the corpus stats handle
    (reused by the contracts/valueflow passes so the corpus is planned
    once per gate run)."""
    findings: list = []
    plans = stats = None
    if concurrency_only:
        from .concurrency import concurrency_findings
        return list(concurrency_findings()), None, None
    if value_only:
        from .valueflow import value_findings
        plans, stats = _corpus_plans(with_stats=True)
        return (list(value_findings(plans, stats,
                                    n_devices=GATE_DEVICES)),
                plans, stats)
    if not contracts_only:
        from .concurrency import concurrency_findings
        from .lint import lint_tree
        findings += lint_tree()
        findings += concurrency_findings()
    if not lint_only:
        from .copcost import cost_findings
        from .lifetime import donation_findings
        from .shardflow import shard_findings
        from .valueflow import value_findings
        plans, stats = _corpus_plans(with_stats=True)
        findings += cost_findings(plans, n_devices=GATE_DEVICES)
        findings += donation_findings(plans, n_devices=GATE_DEVICES)
        findings += shard_findings(plans, n_devices=GATE_DEVICES)
        findings += value_findings(plans, stats, n_devices=GATE_DEVICES)
    return findings, plans, stats


def _write_baseline(findings) -> int:
    keys = sorted({f.key() for f in findings})
    with open(_baseline_path(), "w", encoding="utf-8") as f:
        f.write("# planlint accepted findings (RULE path::symbol); "
                "regenerate with\n# python -m tidb_tpu.analysis "
                "--update-baseline, review the diff.\n")
        for k in keys:
            f.write(k + "\n")
    print(f"planlint: baseline rewritten with {len(keys)} keys")
    return 0


def _stale_keys(findings, baseline, lint_only: bool,
                contracts_only: bool,
                concurrency_only: bool = False,
                value_only: bool = False) -> set:
    """Baseline entries no current finding matches.  Partial runs only
    judge the rule families they actually computed, so --lint-only
    cannot misreport COST-* waivers as rotten (and vice versa)."""
    current = {f.key() for f in findings}
    stale = set()
    for k in baseline - current:
        # corpus-walk rule families (computed only on full/cost runs);
        # SHARD- joined with the shardflow pass (ISSUE 12), RACE-/LOCK-
        # with the copsan concurrency pass (ISSUE 17, lint-side runs),
        # NUM- with the copnum valueflow pass (ISSUE 19)
        is_cost = k.startswith(("COST-", "DONATE-", "SHARD-", "NUM-"))
        is_value = k.startswith("NUM-")
        is_conc = k.startswith(("RACE-", "LOCK-"))
        if value_only and not is_value:
            continue
        if concurrency_only and not is_conc:
            continue
        if lint_only and is_cost:
            continue
        if contracts_only and not is_cost:
            continue
        stale.add(k)
    return stale


def _run_findings(findings, baseline, stale) -> int:
    from .lint import new_findings
    fresh = new_findings(findings, baseline)
    for f in fresh:
        print(f"NEW {f}")
    if stale:
        print(f"planlint: WARNING: {len(stale)} baseline entries no "
              "longer fire (prune them; --check-baseline enforces)")
    print(f"planlint: {len(findings)} findings "
          f"({len(findings) - len(fresh)} baselined, {len(fresh)} new)")
    return 1 if fresh else 0


def _run_pricing(plans) -> int:
    """Every corpus plan must price to finite, nonzero RUs; device-
    bearing plans (transfer bytes > 0) must price strictly above the
    MIN_TASK_RU floor — i.e. the bytes/flops terms actually
    contribute, so a pricing-model regression cannot silently admit
    all work for free."""
    import math

    from ..rc.pricing import MIN_TASK_RU, cost_rus
    from .copcost import plan_cost
    bad = 0
    priced = 0
    for sql, phys in plans:
        cost = plan_cost(phys, n_devices=GATE_DEVICES)
        rus = cost_rus(cost)
        ok = math.isfinite(rus) and rus > 0
        if ok and cost.transfer_bytes > 0:
            ok = rus > MIN_TASK_RU
        if not ok:
            bad += 1
            one_line = " ".join(sql.split())
            print(f"PRICING {one_line[:72]}...\n  priced to {rus!r} "
                  f"(transfer {cost.transfer_bytes}B)")
        else:
            priced += 1
    print(f"rc pricing: {priced}/{len(plans)} corpus plans priced "
          f"finite+nonzero, {bad} violations")
    return 1 if bad else 0


def _run_calibration(plans) -> int:
    """Closed-loop convergence gate (copmeter, ISSUE 10 acceptance):
    after the deterministic drift simulation, EVERY device-bearing
    corpus plan's calibrated pricing error must land under
    CALIB_TARGET_ERR — a broken EWMA step, clamp, or prediction term
    fails here before it misprices a real deployment."""
    from .calibrate import CALIB_TARGET_ERR, simulate_corpus_calibration
    rows = simulate_corpus_calibration(plans, n_devices=GATE_DEVICES)
    bad = [(qid, sql, cerr) for qid, sql, _d, _s, cerr in rows
           if cerr >= CALIB_TARGET_ERR]
    for qid, sql, cerr in bad:
        print(f"CALIBRATION {qid} error {cerr:.1%} >= "
              f"{CALIB_TARGET_ERR:.0%} ({sql[:60]})")
    mean = sum(r[4] for r in rows) / len(rows) if rows else 0.0
    worst = max((r[4] for r in rows), default=0.0)
    print(f"calibration: {len(rows) - len(bad)}/{len(rows)} corpus "
          f"plans calibrated under {CALIB_TARGET_ERR:.0%} pricing "
          f"error (mean {mean:.1%}, max {worst:.1%}), "
          f"{len(bad)} violations")
    return 1 if bad else 0


def _run_shardflow(plans) -> int:
    """Sharding-flow pass (ISSUE 12 acceptance): the TPC-H corpus (incl.
    the shuffle queries) PLUS the MULTICHIP dryrun plan shapes must
    flow clean against both the single-host view and the fake
    (host=2, device=4) view of the gate mesh, with finite per-link
    transfer bytes — the static substrate the multi-host mesh work
    stands on."""
    from ..parallel.topology import MeshTopology, SHARD_AXIS
    from ..testing.tpch import built_multichip_plans, tpch_plan_session
    from .contracts import PlanContractError
    from .copcost import format_bytes, plan_cost
    from .shardflow import GATE_VIEW_HOSTS, verify_plan_sharding
    multichip = list(built_multichip_plans(tpch_plan_session()))
    topo1 = MeshTopology((SHARD_AXIS,), GATE_DEVICES, 1)
    topo2 = MeshTopology((SHARD_AXIS,), GATE_DEVICES, GATE_VIEW_HOSTS)
    bad = 0
    flowed = 0
    ici = dci = 0
    labelled = [("corpus", sql, phys) for sql, phys in plans] + \
        [("multichip", sql, phys) for sql, phys in multichip]
    for src, sql, phys in labelled:
        try:
            for topo in (topo1, topo2):
                flowed += verify_plan_sharding(phys, topo)
        except PlanContractError as e:
            bad += 1
            one_line = " ".join(sql.split())
            print(f"SHARDFLOW [{src}] {one_line[:64]}...\n  {e}")
            continue
        cost = plan_cost(phys, GATE_DEVICES, topology=topo2)
        if cost.ici_bytes < 0 or cost.dci_bytes < 0:
            bad += 1
            print(f"SHARDFLOW [{src}] non-finite per-link bytes: "
                  f"{cost.transfer_breakdown}")
            continue
        ici += cost.ici_bytes
        dci += cost.dci_bytes
    print(f"shardflow: {len(labelled) - bad}/{len(labelled)} plans "
          f"({len(plans)} corpus + {len(multichip)} multichip) flow "
          f"clean under 1-host and host={GATE_VIEW_HOSTS} views, "
          f"{flowed} device programs flowed "
          f"(ici {format_bytes(ici)} / dci {format_bytes(dci)} under "
          f"host={GATE_VIEW_HOSTS}), {bad} violations")
    return 1 if bad else 0


def _run_valueflow(plans, stats, findings, baseline) -> int:
    """Value-range verdict (copnum, ISSUE 19): every corpus plan must
    flow clean through the abstract interpreter with finite intervals
    and zero unbaselined NUM- findings; the verdict also counts the
    proven-narrow single-word SUM states (the perf payoff the proofs
    license).  The NUM- findings already rode _run_findings; this line
    is the per-pass verdict the gate tests pin."""
    from ..testing.tpch import built_multichip_plans, tpch_plan_session
    from .contracts import PlanContractError
    from .valueflow import plan_narrow_states, verify_plan_values
    multichip = list(built_multichip_plans(tpch_plan_session()))
    proven = 0
    narrow = 0
    bad = 0
    for src, group in (("corpus", plans), ("multichip", multichip)):
        for sql, phys in group:
            try:
                verify_plan_values(phys, stats)
                proven += 1
                narrow += plan_narrow_states(phys)
            except PlanContractError as e:
                bad += 1    # corpus ones already rode value_findings
                one_line = " ".join(sql.split())
                print(f"VALUEFLOW [{src}] {one_line[:64]}...\n  {e}")
    fresh = [f for f in findings
             if f.rule.startswith("NUM-") and f.key() not in baseline]
    print(f"values: {proven} plans proven, {narrow} narrow states, "
          f"{len(fresh)} findings")
    return 1 if fresh or bad else 0


def _run_concurrency(findings, baseline) -> int:
    """Whole-program concurrency verdict (copsan, ISSUE 17): the model
    must cover every threading-importing module with zero unbaselined
    RACE-/LOCK- findings.  The findings already rode _run_findings;
    this line is the per-pass verdict the gate tests pin."""
    from .concurrency import CONCURRENCY_RULES, cached_model
    s = cached_model().summary()
    fresh = [f for f in findings
             if f.rule in CONCURRENCY_RULES and f.key() not in baseline]
    print(f"concurrency: {s['modules']} threading modules "
          f"auto-discovered ({s['excluded']} excluded), "
          f"{s['locks']} locks, {s['edges']} acquisition edges, "
          f"{s['roots']} thread roots, {s['findings']} findings, "
          f"{len(fresh)} violations")
    return 1 if fresh else 0


def _run_pd() -> int:
    """Coordination-plane schema gate (coplace, ISSUE 16): every shared
    key family carries owner + TTL + epoch rule, and the in-memory
    store's fence refuses a dead epoch — the report's verdict line IS
    the gate (its violation count must be zero)."""
    from ..pd.store import KEY_FAMILIES, verify_key_families
    from ..pd.store import MemoryBackend, PdLeaseExpired, PdStore
    bad = list(verify_key_families())
    store = PdStore(MemoryBackend())
    epoch = store.grant("gate")
    if not store.cas("quota/gate", 0, {"v": 1}, epoch=epoch):
        bad.append("fresh epoch-carrying CAS refused")
    store.release("gate", epoch)
    try:
        store.cas("quota/gate", 1, {"v": 2}, epoch=epoch)
        bad.append("dead-epoch write accepted")
    except PdLeaseExpired:
        pass
    for v in bad:
        print(f"PD-SCHEMA {v}")
    print(f"pd: {len(KEY_FAMILIES)} key families verified "
          f"(owner+ttl+epoch), dead-epoch writes fenced, "
          f"{len(bad)} violations")
    return 1 if bad else 0


def _run_contracts(plans) -> int:
    from ..testing.tpch import TPCH_PLAN_QUERIES, TPCH_SHUFFLE_QUERIES
    from .contracts import PlanContractError, verify_plan
    total = len(TPCH_PLAN_QUERIES) + len(TPCH_SHUFFLE_QUERIES)
    bad = 0
    checked_ops = 0
    n = 0
    for sql, phys in plans:
        n += 1
        try:
            checked_ops += verify_plan(phys)
        except PlanContractError as e:
            bad += 1
            one_line = " ".join(sql.split())
            print(f"CONTRACT {one_line[:72]}...\n  {e}")
    print(f"plan contracts: {n}/{total} corpus plans verified, "
          f"{checked_ops} operators checked, {bad} violations")
    return 1 if bad else 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    lint_only = "--lint-only" in argv
    contracts_only = "--contracts-only" in argv
    concurrency_only = "--concurrency-only" in argv
    value_only = "--value-only" in argv
    update = "--update-baseline" in argv
    check_baseline = "--check-baseline" in argv
    if "--race-report" in argv:
        from .concurrency import race_report
        print(race_report())
        return 0
    if "--cost-report" in argv:
        from .copcost import cost_report
        print(cost_report(_corpus_plans(), n_devices=GATE_DEVICES))
        return 0
    if "--donation-report" in argv:
        from .lifetime import donation_report
        print(donation_report(_corpus_plans(), n_devices=GATE_DEVICES))
        return 0
    if "--cache-report" in argv:
        from .compilekey import cache_report
        print(cache_report(_corpus_plans(), n_devices=GATE_DEVICES))
        return 0
    if "--calibration-report" in argv:
        from .calibrate import calibration_report
        print(calibration_report(_corpus_plans(), n_devices=GATE_DEVICES))
        return 0
    if "--transfer-report" in argv:
        from .shardflow import transfer_report
        print(transfer_report(_corpus_plans(), n_devices=GATE_DEVICES))
        return 0
    if "--pd-report" in argv:
        from ..pd.store import pd_report
        out = pd_report()
        print(out)
        return 1 if "VIOLATION" in out else 0
    if "--value-report" in argv:
        from .valueflow import value_report
        plans, stats = _corpus_plans(with_stats=True)
        print(value_report(plans, stats))
        return 0
    if check_baseline:
        # hygiene pass: waivers must not rot silently — every baseline
        # entry must still match a current finding (full gather, so the
        # verdict covers every rule family, RACE-/LOCK- included)
        lint_only = contracts_only = concurrency_only = value_only = False
    findings, plans, stats = _gather_findings(lint_only, contracts_only,
                                              concurrency_only,
                                              value_only)
    if update:
        return _write_baseline(findings)
    from .lint import load_baseline
    baseline = load_baseline(_baseline_path())
    stale = _stale_keys(findings, baseline, lint_only, contracts_only,
                        concurrency_only, value_only)
    if check_baseline:
        for k in sorted(stale):
            print(f"STALE {k}")
        print(f"planlint: baseline {'rotten' if stale else 'clean'}: "
              f"{len(stale)} of {len(baseline)} entries match no "
              "current finding")
        return 1 if stale else 0
    rc = _run_findings(findings, baseline, stale)
    if value_only:
        rc |= _run_valueflow(plans, stats, findings, baseline)
        if rc == 0:
            print("analysis gate: ok")
        return rc
    if not contracts_only:
        rc |= _run_concurrency(findings, baseline)
    if not lint_only and not concurrency_only:
        rc |= _run_contracts(plans)
        rc |= _run_pricing(plans)
        rc |= _run_calibration(plans)
        rc |= _run_shardflow(plans)
        rc |= _run_valueflow(plans, stats, findings, baseline)
        rc |= _run_pd()
    if rc == 0:
        print("analysis gate: ok")
    return rc


if __name__ == "__main__":
    sys.exit(main())
