"""CI gate: ``python -m tidb_tpu.analysis``.

Runs both static passes and exits non-zero on any NEW finding:

1. TPU-hygiene lint over the whole tidb_tpu/ tree, diffed against the
   accepted-findings allowlist (analysis/baseline.txt) — pre-existing
   accepted findings pass, new ones fail.
2. Plan-contract verification over the TPC-H plan corpus
   (testing/tpch.TPCH_PLAN_QUERIES): every statement is planned (never
   executed — no trace, no compile, no device) and walked by
   analysis.verify_plan; any PlanContractError fails the gate.

Flags:
    --lint-only / --contracts-only   run one pass
    --update-baseline                rewrite baseline.txt from the
                                     current findings (reviewed use only)
"""

from __future__ import annotations

import os
import sys

# plan building never needs a device, but imports touch jax; pin the CPU
# backend so the gate runs identically on dev boxes, CI, and TPU hosts
# (and never blocks on TPU acquisition)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()


def _run_lint(update_baseline: bool) -> int:
    from .lint import lint_tree, load_baseline, new_findings
    findings = lint_tree()
    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.txt")
    if update_baseline:
        keys = sorted({f.key() for f in findings})
        with open(base_path, "w", encoding="utf-8") as f:
            f.write("# planlint accepted findings (RULE path::symbol); "
                    "regenerate with\n# python -m tidb_tpu.analysis "
                    "--update-baseline, review the diff.\n")
            for k in keys:
                f.write(k + "\n")
        print(f"planlint: baseline rewritten with {len(keys)} keys")
        return 0
    baseline = load_baseline(base_path)
    fresh = new_findings(findings, baseline)
    for f in fresh:
        print(f"NEW {f}")
    stale = baseline - {f.key() for f in findings}
    if stale:
        print(f"planlint: note: {len(stale)} baseline entries no longer "
              "fire (safe to prune)")
    print(f"planlint: {len(findings)} findings "
          f"({len(findings) - len(fresh)} baselined, {len(fresh)} new)")
    return 1 if fresh else 0


def _run_contracts() -> int:
    from ..testing.tpch import (TPCH_PLAN_QUERIES, TPCH_SHUFFLE_QUERIES,
                                built_tpch_plans, tpch_plan_session)
    from .contracts import PlanContractError, verify_plan
    session = tpch_plan_session()
    total = len(TPCH_PLAN_QUERIES) + len(TPCH_SHUFFLE_QUERIES)
    bad = 0
    checked_ops = 0
    n = 0
    for sql, phys in built_tpch_plans(session):
        n += 1
        try:
            checked_ops += verify_plan(phys)
        except PlanContractError as e:
            bad += 1
            one_line = " ".join(sql.split())
            print(f"CONTRACT {one_line[:72]}...\n  {e}")
    print(f"plan contracts: {n}/{total} corpus plans verified, "
          f"{checked_ops} operators checked, {bad} violations")
    return 1 if bad else 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    lint_only = "--lint-only" in argv
    contracts_only = "--contracts-only" in argv
    update = "--update-baseline" in argv
    rc = 0
    if not contracts_only:
        rc |= _run_lint(update)
    if not lint_only and not update:
        rc |= _run_contracts()
    if rc == 0:
        print("analysis gate: ok")
    return rc


if __name__ == "__main__":
    sys.exit(main())
