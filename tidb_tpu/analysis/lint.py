"""TPU-hygiene linter: AST rules for the failure modes a compiled
coprocessor engine actually hits.

General-purpose linters don't know that `int(x)` inside a traced device
function forces a host sync (ConcretizationTypeError at best, a silent
recompile-per-value at worst), that `id(...)` inside a cache-key builder
makes program dedup keys die with the process, or that the admission
scheduler's drain loop must never invert the lock order the pool manager
uses.  These rules do; they are scoped to the modules where each hazard
is real, and every pre-existing accepted finding lives in
analysis/baseline.txt so only NEW findings fail the gate.

Rules
-----
- TPU-TRACE-LEAK   float()/int()/bool()/np.asarray() on non-literal
                   values inside modules whose code is traced wholesale
                   into device programs (copr/exec, copr/join,
                   parallel/spmd|shuffle|window|exchange).  These force
                   tracer concretization / host round-trips.
- TPU-DIGEST       id(...) or unordered dict iteration inside a digest
                   context (a function or assignment target whose name
                   contains key/digest/token/fingerprint/signature):
                   process-local or order-unstable values poison
                   program/task cache keys across mesh rebuilds.
- TPU-HOST-SYNC    jax.device_get(...) / .item() in hot-path modules
                   (traced modules + sched/): a host sync inside the
                   admission/launch path serializes the device pipeline.
- TPU-BROAD-EXCEPT bare `except:` or `except Exception/BaseException:`
                   whose handler does not re-raise: swallows real codec/
                   arith/driver errors.  Waived by `# noqa: BLE001` with
                   a justification or a `planlint: ok` comment.
- TPU-LOCK-ORDER   across sched/scheduler.py, utils/poolmgr.py,
                   utils/rwlock.py, store/client.py: nested acquisition
                   of the same non-reentrant lock (self-deadlock, incl.
                   Condition(lock) aliasing) and inverted acquisition
                   order between two locks observed in the same class.
- TPU-PSUM-FENCE   lax.psum in a traced module whose module does not
                   also implement the 2^31 limb-exactness fence (a
                   `*psum_limb_fence*` guard plus an OverflowError
                   raise): int/decimal SUM (hi, lo) limb states merged
                   by an UNFENCED in-program psum silently wrap past
                   2^31 contributing rows — wrong answers, no error.
- TPU-DTYPE-X64    weak-typed jnp array creation in a traced module
                   (jnp.arange/zeros/ones/full/linspace/eye with no
                   dtype, or a jnp.int64/uint64/float64 scalar
                   constructor): these produce 64-bit values only
                   because tidb_tpu/__init__ turns jax_enable_x64 on.
                   An embedder that leaves JAX's x64-disabled default in
                   place gets silently truncated int32/float32 lanes on
                   TPU — wrong join keys and sums, green CPU tests.
                   Pin dtype= explicitly.
- TPU-RETRY-BUDGET an unconditional retry loop (`while True:`) in a
                   sched/ or store/ module that SLEEPS (time.sleep or
                   any *sleep* callable) without consulting a Backoffer
                   budget: a blind sleep-and-redispatch loop retries
                   forever with no typed budget, no attempt history and
                   no RetryBudgetExceeded surfacing — route every
                   re-dispatch sleep through store/backoff.Backoffer.
- TPU-DONATE       a ``donate_argnums=``/``donate_argnames=`` keyword in
                   a traced module whose value is a non-empty literal,
                   or an expression that does not reference a
                   DonationPlan-derived symbol (a name/attribute
                   containing ``donat``): donation deletes the caller's
                   arrays, so the ONLY legitimate source of argnums is
                   the statically verified analysis/lifetime
                   DonationPlan — a hand-written literal silently
                   deletes snapshot residents or regrow inputs.
- TPU-CALIB-CLAMP  a multiplication by a measured cost-correction
                   factor (``time_factor`` / ``mem_factor`` /
                   ``*correction_factor*``) in a function that never
                   references the clamp (``clamp_factor`` /
                   ``CALIB_CLAMP_*`` — analysis/calibrate): measured
                   feedback may BEND the static LaunchCost model,
                   never replace it — an unclamped factor lets one bad
                   measurement starve or flood admission, pricing, and
                   the HBM budget.  Applies repo-wide (any module may
                   grow a calibration consumer).
- TPU-COMPILE-KEY  a serialize/deserialize/cache-write seam in
                   compilecache/ whose enclosing function does not
                   reference the persistent-key triple — a ``digest``
                   symbol, a mesh fingerprint (``mesh``/``fingerprint``)
                   and the donation plan (``donat``): an executable
                   persisted (or loaded) without the full key anatomy
                   can silently deserialize a stale or wrong-variant
                   program after a restart (mirrors TPU-DIGEST for the
                   on-disk half of the program cache).
- TPU-SHARD-CONST  a collective call (lax.all_to_all / all_gather /
                   psum / pmin / pmax / ppermute / axis_index) or a
                   PartitionSpec in a traced module whose mesh-axis
                   argument is a raw string literal instead of a
                   reference to the mesh/topology symbol
                   (parallel/topology.SHARD_AXIS): a literal axis name
                   desynchronizes silently when the topology model
                   renames or factors an axis — the program traces fine
                   and exchanges over the wrong (or a stale) axis.
- TPU-SPAN-LEAK   a time.perf_counter[_ns]() latency measurement in
                   sched/, copr/, or compilecache/ whose enclosing
                   function feeds a latency counter (an augmented
                   ``+=`` into a ``*_ns``/``*_ms``/``*_us``/``*_total``
                   /``*_seconds`` target) WITHOUT recording through the
                   copscope obs API (a span/trace reference or a
                   histogram ``observe``): a latency number that only
                   lands in an ad-hoc counter is invisible to TRACE,
                   the flight recorder, and the latency histograms —
                   route every measured duration through obs/.
- TPU-PALLAS-SHAPE in copr/pallas/ (the hand-written TPU kernel
                   package): a ``pallas_call`` whose ``grid=`` or a
                   ``BlockSpec`` whose block shape contains a
                   non-static expression (any call besides the
                   shape-arithmetic allowlist cdiv/len/min/max), or
                   ANY host-callback use (pure_callback / io_callback /
                   host_callback / debug_callback).  A traced-value
                   grid recompiles per shape (or fails Mosaic
                   outright); a host callback inside a kernel stalls
                   the TPU pipeline on the host — both destroy exactly
                   the performance a hand-written kernel exists for.
- TPU-NARROW-CAST  a bit-narrowing ``.astype(...)`` (int8/16/32,
                   uint8/16/32, float16/bfloat16/float32 target) in a
                   traced module: a traced cast cannot raise on values
                   that do not fit — high bits (or mantissa digits)
                   vanish silently on device.  Every narrowing cast
                   must carry a ``# valueflow: ok - <why>`` proof
                   reference (the value-range argument that the lane's
                   interval fits the target, analysis/valueflow
                   discipline) or an explicit ``# planlint: ok``
                   waiver.  Widening casts (int64/uint64/float64) and
                   bool masks are exempt.
- TPU-PD-EPOCH     a shared-store write call (cas / txn_update /
                   delete / grant / renew / release) in pd/ whose
                   enclosing function never references the lease
                   ``epoch``: the coplace store fences dead writers
                   with lease epochs — every mutation of shared state
                   must ride a CAS carrying the member's epoch, or a
                   process whose lease expired (paused, partitioned,
                   half-dead) can clobber state the survivors already
                   repartitioned.

Inline waiver: any rule is suppressed by a `# planlint: ok` comment on
the offending line (give a reason after it).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Iterable, Optional

# modules (tidb_tpu-relative, /-separated) whose function bodies are
# traced into device programs wholesale — concretization calls there are
# tracer leaks.  expr/compile.py is deliberately NOT listed: it is the
# dual-backend (np|jnp) evaluator and its host-object op implementations
# legitimately concretize when xp is numpy.
TRACED_MODULES = {
    "copr/exec.py", "copr/join.py", "copr/segment.py", "copr/radix.py",
    "copr/pallas/radix_kernel.py",
    "parallel/spmd.py", "parallel/shuffle.py", "parallel/window.py",
    "parallel/exchange.py",
    # shardflow (ISSUE 12): the topology model and the sharding-flow
    # interpreter define/consume the collective axis symbols traced
    # programs bind — they obey the same hygiene rules (no stray
    # concretization, no literal axis names) so the analysis side can
    # never drift from the programs it verifies
    "parallel/topology.py", "analysis/shardflow.py",
    # coplace (ISSUE 16): the coordination plane runs on every
    # statement's tick and its payloads (quota shares, calib factors)
    # feed admission directly — same hygiene contract: no stray
    # concretization, no silent host round-trips smuggled in later
    "pd/store.py", "pd/lease.py", "pd/quota.py", "pd/registry.py",
    "pd/coordinator.py",
    # copnum (ISSUE 19): the value-range interpreter defines the
    # numeric-safety contracts traced lanes rely on (narrow SUM proofs,
    # overflow fences) — same hygiene rules as shardflow, for the same
    # reason: the analysis side must never drift from the programs it
    # verifies
    "analysis/valueflow.py",
}

# hot-path modules where a host sync stalls the launch pipeline
HOT_PATH_MODULES = TRACED_MODULES | {
    "sched/scheduler.py", "sched/task.py",
}

# copsan (ISSUE 17): the cross-layer lock-order contract is no longer
# a hand-curated module list — ANY module importing threading joins it
# automatically (module_imports_threading below; the whole-program
# model in analysis/concurrency.py consumes the same predicate).  The
# only opt-out is an explicit, justified entry here.
LOCK_EXCLUDES: dict = {
    # Add `"rel/path.py": "reason"` only when a module's thread model
    # is genuinely out of scope for the AST analysis, and say why.
    "utils/locksan.py": (
        "the sanitizer itself: it aliases the real threading factories "
        "(_REAL_LOCK = threading.Lock) and monkeypatches threading, so "
        "the AST model cannot see its _mu as a lock; its telemetry "
        "counters are deliberately approximate to keep per-acquire "
        "overhead inside the 5% budget"
    ),
}


def module_imports_threading(tree) -> bool:
    """True when the module imports threading (any form) — the auto-
    discovery predicate that retired the hand-maintained LOCK_MODULES
    set.  Importing threading IS joining the concurrency contract."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name == "threading" or
                   a.name.startswith("threading.") for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").split(".")[0] == "threading":
                return True
    return False

# modules whose retry/re-dispatch loops must spend a typed Backoffer
# budget (TPU-RETRY-BUDGET): the device dispatch + scheduler layers
RETRY_MODULE_PREFIXES = ("sched/", "store/")

# modules whose latency measurements must flow through the copscope
# obs span/histogram API (TPU-SPAN-LEAK): the launch-path layers whose
# timings TRACE and the flight recorder attribute
SPAN_MODULE_PREFIXES = ("sched/", "copr/", "compilecache/", "pd/")
# counter targets that smell like a latency/total accumulator
_LAT_COUNTER = re.compile(r"(_ns|_ms|_us|_total|_seconds)$")
_PERF_CALL = re.compile(r"^perf_counter(_ns)?$")
# the obs API surface: span trees / TraceCtx references or a histogram
# observe — any of these in the function means the measurement is
# recorded where TRACE/recorder/histograms can see it
_OBS_REF = re.compile(r"observe|span|trace", re.IGNORECASE)

# the AOT program cache (copforge): every seam where executable bytes
# hit or leave disk must carry the digest + mesh-fingerprint +
# donation-plan triple (TPU-COMPILE-KEY)
COMPILECACHE_PREFIX = "compilecache/"

# the coplace coordination plane (TPU-PD-EPOCH): every shared-store
# mutation in pd/ must sit in a function that references the lease
# epoch — the CAS fence that refuses writes from members whose lease
# lapsed.  Call names that ARE such mutations (PdStore's write surface;
# bare `set`/`put` deliberately excluded — Gauge.set and dict puts are
# not store writes).
PD_PREFIX = "pd/"
_PD_WRITE_CALLS = re.compile(
    r"^(cas|txn_update|delete|grant|renew|release)$")
_EPOCH_REF = re.compile(r"epoch")
# receivers that are threading primitives, not the store — their
# acquire/release is lock discipline (TPU-LOCK-ORDER's concern)
_PD_LOCK_RECV = re.compile(r"mu$|mutex|lock|cond|sem", re.IGNORECASE)

# copgauge (TPU-MEM-SOURCE): modules allowed to call the raw device
# memory introspection APIs.  obs/hbm.py owns the single sanctioned
# memory_stats poll (the ledger's reconcile + the copcost auto budget
# route through it) and compilecache/ owns the compiled
# memory_analysis of served executables (the measured-watermark seam);
# a call anywhere else forks the source of memory truth away from the
# ledger.
MEM_SOURCE_MODULES = ("obs/hbm.py",)
_MEM_SOURCE_CALLS = ("memory_stats", "memory_analysis")
# call names that ARE such seams (jax.experimental.serialize_executable
# entry points plus any persist_* helper grown later)
_CACHE_WRITE_CALLS = re.compile(
    r"^(serialize|deserialize_and_load|persist\w*|_persist\w*|"
    r"write_entry\w*)$")
_KEY_TRIPLE = (("digest", re.compile(r"digest")),
               ("mesh fingerprint", re.compile(r"mesh|fingerprint")),
               ("donation plan", re.compile(r"donat")))

_DIGEST_NAME = re.compile(r"key|digest|token|fingerprint|signature",
                          re.IGNORECASE)

# measured cost-correction factors (analysis/calibrate): multiplying a
# LaunchCost term by one of these without referencing the clamp is
# unbounded feedback (TPU-CALIB-CLAMP)
_CALIB_FACTOR = re.compile(r"^(time_factor|mem_factor)$"
                           r"|correction_factor")
_CLAMP_REF = re.compile(r"clamp", re.IGNORECASE)

# collective calls whose mesh-axis argument must reference the
# mesh/topology symbol, never a raw string literal (TPU-SHARD-CONST):
# call name -> 0-based positional slot the axis may occupy
_COLLECTIVE_AXIS_CALLS = {
    "all_to_all": 1, "all_gather": 1, "psum": 1, "pmin": 1, "pmax": 1,
    "pmean": 1, "ppermute": 1, "psum_scatter": 1, "axis_index": 0,
}
# PartitionSpec constructors: every positional argument is an axis name
_PSPEC_NAMES = ("P", "PartitionSpec")

# jnp creation calls whose result dtype rides the x64 flag when no dtype
# is given, and the positional slot (0-based) a dtype may occupy.  -1 =
# dtype only arrives by keyword (arange's positionals are start/stop/
# step; linspace's are start/stop/num).
_X64_CREATORS = {"arange": -1, "zeros": 1, "ones": 1, "empty": 1,
                 "full": 2, "linspace": -1, "eye": -1}
# 64-bit scalar constructors: silently 32-bit when x64 is off
_X64_SCALARS = {"int64", "uint64", "float64"}
_WAIVER = re.compile(r"planlint:\s*ok")
_BLE_WAIVER = re.compile(r"noqa:.*BLE001|planlint:\s*ok")
# TPU-NARROW-CAST: targets that lose bits from an int64/f64 lane, and
# the proof-reference comment that clears them (a value-range argument
# in the analysis/valueflow discipline); the generic waiver also works
_NARROW_CAST_TARGETS = {"int8", "int16", "int32", "uint8", "uint16",
                        "uint32", "float16", "bfloat16", "float32"}
_NARROW_CAST_OK = re.compile(r"valueflow:\s*ok|planlint:\s*ok")


def _cast_target_name(arg: ast.AST) -> str:
    """Dtype spelled as jnp.int32 / np.int32 / int32 / 'int32'."""
    if isinstance(arg, ast.Attribute):
        return arg.attr
    if isinstance(arg, ast.Name):
        return arg.id
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return ""


@dataclass
class Finding:
    rule: str
    path: str        # tidb_tpu-relative, /-separated
    line: int
    symbol: str      # enclosing Class.function qualname ('' = module)
    message: str

    def key(self) -> str:
        """Baseline identity: rule + file + enclosing symbol.  Line
        numbers are deliberately excluded so accepted findings survive
        unrelated edits to the same file."""
        return f"{self.rule} {self.path}::{self.symbol}"

    def __str__(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}: {self.rule}{sym} {self.message}"


# --------------------------------------------------------------------- #
# helpers
# --------------------------------------------------------------------- #

def _is_np_attr(node: ast.AST, names: Iterable[str]) -> Optional[str]:
    """node is np.<name> / numpy.<name> for name in names -> name."""
    if (isinstance(node, ast.Attribute) and node.attr in names
            and isinstance(node.value, ast.Name)
            and node.value.id in ("np", "numpy")):
        return node.attr
    return None


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class _Scoped(ast.NodeVisitor):
    """Visitor tracking the enclosing Class.function qualname and the
    per-line waiver set."""

    def __init__(self, rel: str, lines: list):
        self.rel = rel
        self.lines = lines
        self.scope: list = []
        self.findings: list = []

    def symbol(self) -> str:
        return ".".join(self.scope)

    def waived(self, lineno: int, pat=_WAIVER) -> bool:
        if 1 <= lineno <= len(self.lines):
            return bool(pat.search(self.lines[lineno - 1]))
        return False

    def add(self, rule: str, node: ast.AST, msg: str,
            pat=_WAIVER) -> None:
        if not self.waived(node.lineno, pat):
            self.findings.append(
                Finding(rule, self.rel, node.lineno, self.symbol(), msg))

    def visit_ClassDef(self, node):
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    def visit_FunctionDef(self, node):
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


# --------------------------------------------------------------------- #
# rules 1-4: expression-level
# --------------------------------------------------------------------- #

def _module_has_limb_fence(tree: ast.AST) -> bool:
    """The module implements the psum limb-exactness fence: somewhere it
    consults a `*psum_limb_fence*` guard AND raises OverflowError (the
    pre-launch capacity check of parallel/spmd.ShardedCopProgram)."""
    has_guard = False
    has_raise = False
    for node in ast.walk(tree):
        if isinstance(node, (ast.Attribute, ast.Name)):
            name = node.attr if isinstance(node, ast.Attribute) else node.id
            if "psum_limb_fence" in name:
                has_guard = True
        elif isinstance(node, ast.Raise) and node.exc is not None:
            exc = node.exc
            callee = exc.func if isinstance(exc, ast.Call) else exc
            if isinstance(callee, ast.Name) and \
                    callee.id == "OverflowError":
                has_raise = True
        if has_guard and has_raise:
            return True
    return False


class _ExprRules(_Scoped):
    def __init__(self, rel, lines, psum_fenced: bool = True):
        super().__init__(rel, lines)
        self.traced = rel in TRACED_MODULES
        self.hot = rel in HOT_PATH_MODULES
        self.retry_scope = rel.startswith(RETRY_MODULE_PREFIXES)
        self.mem_source_ok = (rel in MEM_SOURCE_MODULES
                              or rel.startswith(COMPILECACHE_PREFIX))
        self.psum_fenced = psum_fenced
        self._digest_fn = 0     # depth of digest-context functions
        self._sorted_ok: set = set()   # dict-iter calls under sorted()
        self._fn_nodes: list = []      # enclosing function AST nodes

    def visit_FunctionDef(self, node):
        # plain collection accessors named `keys`/`values`/`items` are
        # not digest builders even though the substring matches
        bump = bool(_DIGEST_NAME.search(node.name)
                    and node.name not in ("keys", "values", "items"))
        self._digest_fn += bump
        self._fn_nodes.append(node)
        super().visit_FunctionDef(node)
        self._fn_nodes.pop()
        self._digest_fn -= bump

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- digest contexts also arise from `key = (...)` assignments ---- #
    def visit_Assign(self, node):
        if self._digest_fn == 0 and any(
                isinstance(t, ast.Name) and _DIGEST_NAME.search(t.id)
                for t in node.targets):
            self._scan_digest_value(node.value)
        self.generic_visit(node)

    def _note_sorted(self, node: ast.Call) -> None:
        """sorted(d.items()) neutralizes iteration order — remember the
        wrapped call so the digest rule skips it."""
        if _call_name(node) == "sorted" and isinstance(node.func, ast.Name):
            for a in node.args:
                if isinstance(a, ast.Call):
                    self._sorted_ok.add(id(a))

    def _scan_digest_value(self, value: ast.AST) -> None:
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                self._note_sorted(sub)
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                self._check_digest_call(sub)

    def _check_digest_call(self, node: ast.Call) -> None:
        name = _call_name(node)
        if isinstance(node.func, ast.Name) and name == "id":
            self.add("TPU-DIGEST", node,
                     "id(...) feeds a cache key/digest: process-local "
                     "identity does not survive object rebuilds — use a "
                     "stable fingerprint of the value instead")
        elif (isinstance(node.func, ast.Attribute)
              and name in ("items", "keys", "values") and not node.args
              # AST-node memo, not key material  # planlint: ok
              and id(node) not in self._sorted_ok):
            self.add("TPU-DIGEST", node,
                     f".{name}() iteration feeds a digest: wrap in "
                     "sorted(...) so insertion order cannot change the key")

    def visit_Call(self, node):
        name = _call_name(node)
        self._note_sorted(node)    # parents visit before children
        # TPU-TRACE-LEAK: concretization in traced modules
        if self.traced:
            if (isinstance(node.func, ast.Name)
                    and name in ("int", "float", "bool") and node.args
                    and not isinstance(node.args[0], ast.Constant)):
                self.add("TPU-TRACE-LEAK", node,
                         f"{name}(...) on a non-literal inside a traced "
                         "module concretizes the tracer (host sync / "
                         "ConcretizationTypeError); keep values as jnp "
                         "arrays or hoist to program-build time")
            if _is_np_attr(node.func, ("asarray", "array")):
                self.add("TPU-TRACE-LEAK", node,
                         "np.asarray/np.array on a traced value pulls it "
                         "to host; use jnp inside device functions")
            # TPU-PSUM-FENCE: unfenced in-program limb merges
            if name == "psum" and not self.psum_fenced:
                self.add("TPU-PSUM-FENCE", node,
                         "lax.psum in a traced module without the 2^31 "
                         "limb-exactness fence: (hi, lo) SUM limb states "
                         "wrap silently past 2^31 contributing rows — "
                         "add a *_psum_limb_fence capacity check that "
                         "raises OverflowError before launch")
            # TPU-NARROW-CAST: a traced cast cannot raise on values
            # that do not fit — bit-narrowing needs a value-range proof
            if (isinstance(node.func, ast.Attribute) and name == "astype"
                    and node.args):
                tgt = _cast_target_name(node.args[0])
                if tgt in _NARROW_CAST_TARGETS:
                    self.add(
                        "TPU-NARROW-CAST", node,
                        f".astype({tgt}) in a traced module narrows "
                        "silently on device (no data-dependent raise); "
                        "state the value-range proof in a "
                        "'# valueflow: ok - <why>' comment or waive "
                        "with '# planlint: ok'",
                        pat=_NARROW_CAST_OK)
            # TPU-DTYPE-X64: dtype decided by the x64 flag, not the code
            self._check_x64(node, name)
            # TPU-DONATE: donation argnums must come from a DonationPlan
            self._check_donate(node)
            # TPU-SHARD-CONST: collective axes must reference the
            # mesh/topology symbol
            self._check_shard_const(node, name)
        # TPU-HOST-SYNC
        if self.hot:
            if name == "device_get" and isinstance(node.func,
                                                   ast.Attribute):
                self.add("TPU-HOST-SYNC", node,
                         "jax.device_get in a hot launch path blocks on "
                         "the device; move the sync to the result seam")
            elif (name == "item" and isinstance(node.func, ast.Attribute)
                  and not node.args):
                self.add("TPU-HOST-SYNC", node,
                         ".item() forces a device->host transfer in a "
                         "hot path")
        # TPU-MEM-SOURCE: raw device-memory introspection outside the
        # ledger (obs/hbm) + compile cache forks the memory truth
        if (not self.mem_source_ok and name in _MEM_SOURCE_CALLS
                and isinstance(node.func, ast.Attribute)):
            self.add("TPU-MEM-SOURCE", node,
                     f"{name}() outside obs/hbm.py + compilecache/: "
                     "the HBM ledger is the single source of device-"
                     "memory truth — route polls through "
                     "obs.hbm.device_memory_stats and measured "
                     "watermarks through the compile cache's "
                     "memory seam")
        # TPU-DIGEST inside digest-named functions
        if self._digest_fn > 0:
            self._check_digest_call(node)
        self.generic_visit(node)

    def _check_x64(self, node: ast.Call, name: str) -> None:
        """Weak-typed jnp creation in a traced module: the value is
        int64/float64 only while jax_enable_x64 stays on (tidb_tpu
        enables it at import); under JAX's default it silently narrows
        to 32 bits on TPU while CPU tests (same flag) stay green."""
        f = node.func
        if not (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "jnp"):
            return
        if name in _X64_SCALARS:
            self.add("TPU-DTYPE-X64", node,
                     f"jnp.{name}(...) yields a 32-bit value when "
                     "jax_enable_x64 is off — construct via jnp.asarray"
                     "(x, dtype=...) with an explicit np dtype")
            return
        slot = _X64_CREATORS.get(name)
        if slot is None:
            return
        if any(kw.arg == "dtype" for kw in node.keywords):
            return
        if 0 <= slot < len(node.args):
            return                      # dtype passed positionally
        self.add("TPU-DTYPE-X64", node,
                 f"jnp.{name}(...) without an explicit dtype is "
                 "x64-flag-dependent: int64/float64 only because "
                 "tidb_tpu enables jax_enable_x64 — pin dtype= so an "
                 "embedder's x64-off default cannot silently narrow "
                 "device lanes to 32 bits")

    def _check_shard_const(self, node: ast.Call, name: str) -> None:
        """A collective (or PartitionSpec) whose mesh-axis argument is a
        raw string literal: the axis name must reference the topology
        symbol (parallel/topology.SHARD_AXIS or a parameter derived
        from it) so a topology rename/refactor cannot silently leave a
        traced program exchanging over a stale axis."""
        def flag(what):
            self.add("TPU-SHARD-CONST", node,
                     f"{what} in {name}(...): collective mesh-axis "
                     "names must reference the mesh/topology symbol "
                     "(parallel/topology.SHARD_AXIS), not a raw string "
                     "literal — a topology rename would silently "
                     "desynchronize this program from the analysis")

        if name in _PSPEC_NAMES:
            for a in node.args:
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    flag(f"literal axis {a.value!r}")
                    return
            return
        slot = _COLLECTIVE_AXIS_CALLS.get(name)
        if slot is None:
            return
        cand = None
        if slot < len(node.args):
            cand = node.args[slot]
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis"):
                cand = kw.value
        if isinstance(cand, ast.Constant) and isinstance(cand.value, str):
            flag(f"literal axis {cand.value!r}")

    def _check_donate(self, node: ast.Call) -> None:
        """donate_argnums/donate_argnames in a traced module: jax bakes
        the aliasing into the executable and DELETES the caller's
        arrays, so the value must be derived from the statically
        verified DonationPlan (analysis/lifetime) — a literal (or any
        expression not referencing a donation-plan symbol) is a
        hand-rolled lifetime claim the gate refuses."""
        for kw in node.keywords:
            if kw.arg not in ("donate_argnums", "donate_argnames"):
                continue
            v = kw.value
            if isinstance(v, (ast.Tuple, ast.List)) and not v.elts:
                continue          # donating nothing is always safe
            literal = isinstance(v, ast.Constant) or (
                isinstance(v, (ast.Tuple, ast.List))
                and all(isinstance(e, ast.Constant) for e in v.elts))
            if literal:
                self.add("TPU-DONATE", node,
                         f"literal {kw.arg}= in a traced module: "
                         "donation argnums must come from a verified "
                         "analysis/lifetime DonationPlan, not a "
                         "hand-written position list")
                continue
            names = {n.id for n in ast.walk(v) if isinstance(n, ast.Name)}
            names |= {a.attr for a in ast.walk(v)
                      if isinstance(a, ast.Attribute)}
            if not any("donat" in s for s in names):
                self.add("TPU-DONATE", node,
                         f"{kw.arg}= value does not reference a "
                         "DonationPlan-derived symbol; route donation "
                         "through analysis/lifetime so the slot "
                         "lifetimes are verified pre-trace")

    # -- TPU-CALIB-CLAMP: unclamped measured-correction feedback ------- #

    @staticmethod
    def _refs_calib_factor(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and _CALIB_FACTOR.search(sub.id):
                return True
            if isinstance(sub, ast.Attribute) \
                    and _CALIB_FACTOR.search(sub.attr):
                return True
        return False

    def _check_calib_clamp(self, node: ast.AST) -> None:
        scope = self._fn_nodes[-1] if self._fn_nodes else node
        for sub in ast.walk(scope):
            name = None
            if isinstance(sub, ast.Name):
                name = sub.id
            elif isinstance(sub, ast.Attribute):
                name = sub.attr
            if name is not None and _CLAMP_REF.search(name):
                return
        self.add("TPU-CALIB-CLAMP", node,
                 "multiplies by a measured cost-correction factor "
                 "without referencing the clamp (clamp_factor / "
                 "CALIB_CLAMP_MIN/MAX, analysis/calibrate): unclamped "
                 "feedback lets one bad measurement starve or flood "
                 "admission — clamp every factor to [1/8, 8]")

    def visit_BinOp(self, node):
        if isinstance(node.op, ast.Mult) and (
                self._refs_calib_factor(node.left)
                or self._refs_calib_factor(node.right)):
            self._check_calib_clamp(node)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        if isinstance(node.op, ast.Mult) and (
                self._refs_calib_factor(node.value)
                or self._refs_calib_factor(node.target)):
            self._check_calib_clamp(node)
        self.generic_visit(node)

    def visit_While(self, node):
        # TPU-RETRY-BUDGET: a `while True:` re-dispatch loop in the
        # sched/store layers that sleeps blind retries forever; the
        # Backoffer is the only sanctioned sleep (typed curve, total
        # budget, attempt history, RetryBudgetExceeded surfacing)
        if self.retry_scope and isinstance(node.test, ast.Constant) \
                and bool(node.test.value):
            self._check_retry_budget(node)
        self.generic_visit(node)

    def _check_retry_budget(self, node: ast.While) -> None:
        sleep_call = None
        consults_budget = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                f = sub.func
                nm = f.attr if isinstance(f, ast.Attribute) else \
                    (f.id if isinstance(f, ast.Name) else "")
                if "sleep" in nm and sleep_call is None:
                    sleep_call = sub
            if isinstance(sub, ast.Name) and "backoff" in sub.id.lower():
                consults_budget = True
            elif isinstance(sub, ast.Attribute) \
                    and "backoff" in sub.attr.lower():
                consults_budget = True
        if sleep_call is not None and not consults_budget:
            self.add("TPU-RETRY-BUDGET", sleep_call,
                     "unbounded retry loop sleeps without a Backoffer "
                     "budget: blind sleep-and-redispatch retries "
                     "forever — back off through store/backoff."
                     "Backoffer so the attempt history and total sleep "
                     "budget are enforced")

    def visit_ExceptHandler(self, node):
        broad = node.type is None
        if isinstance(node.type, ast.Name):
            broad = node.type.id in ("Exception", "BaseException")
        elif isinstance(node.type, ast.Tuple):
            broad = any(isinstance(e, ast.Name)
                        and e.id in ("Exception", "BaseException")
                        for e in node.type.elts)
        if broad and not self._reraises(node):
            what = "bare except" if node.type is None else \
                f"except {ast.unparse(node.type)}"
            self.add("TPU-BROAD-EXCEPT", node,
                     f"{what} without re-raise swallows unexpected "
                     "errors (driver faults, codec bugs); catch the "
                     "specific exceptions and re-raise the rest",
                     pat=_BLE_WAIVER)
        self.generic_visit(node)

    @staticmethod
    def _reraises(handler: ast.ExceptHandler) -> bool:
        """Handler re-raises (bare `raise`, or raises a new error built
        from the caught one) somewhere in its body."""
        for sub in ast.walk(handler):
            if isinstance(sub, ast.Raise):
                return True
        return False


# --------------------------------------------------------------------- #
# rule: TPU-SPAN-LEAK (latency measurements must reach the obs API)
# --------------------------------------------------------------------- #

class _SpanLeakRules(_Scoped):
    """Per-function analysis: a function that measures wall time with
    time.perf_counter[_ns]() AND feeds a latency counter (``+=`` into
    a *_ns/*_ms/*_us/*_total/*_seconds target) must also reference the
    obs span/trace surface or a histogram ``observe`` — otherwise the
    measurement is invisible to TRACE, the flight recorder, and the
    latency histograms (copscope, ISSUE 13)."""

    def visit_FunctionDef(self, node):
        self.scope.append(node.name)
        self._check_fn(node)
        self.generic_visit(node)
        self.scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _check_fn(self, fn) -> None:
        has_perf = False
        obs_ref = False
        feeds: list = []
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Call) and \
                    _PERF_CALL.match(_call_name(sub)):
                has_perf = True
            name = None
            if isinstance(sub, ast.Name):
                name = sub.id
            elif isinstance(sub, ast.Attribute):
                name = sub.attr
            if name is not None and _OBS_REF.search(name):
                obs_ref = True
            if isinstance(sub, ast.AugAssign) \
                    and isinstance(sub.op, ast.Add):
                t = sub.target
                tn = t.attr if isinstance(t, ast.Attribute) else \
                    (t.id if isinstance(t, ast.Name) else "")
                if tn and _LAT_COUNTER.search(tn):
                    feeds.append((sub, tn))
        if not has_perf or obs_ref:
            return
        for node, tn in feeds:
            self.add("TPU-SPAN-LEAK", node,
                     f"perf_counter latency measurement feeds `{tn}` "
                     "without recording through the obs span/histogram "
                     "API: the duration is invisible to TRACE, the "
                     "flight recorder, and the latency histograms — "
                     "record a span (obs.trace) or observe() a "
                     "histogram next to the counter")


# --------------------------------------------------------------------- #
# rule: TPU-PALLAS-SHAPE (copr/pallas/ kernel hygiene)
# --------------------------------------------------------------------- #

# the hand-written TPU kernel package: every Pallas kernel lives here
PALLAS_PREFIX = "copr/pallas/"
# host-callback entry points that must never appear in a kernel module
_HOST_CALLBACKS = frozenset({
    "pure_callback", "io_callback", "host_callback", "debug_callback",
    "call_host",
})
# calls allowed inside a static grid/block-shape expression: pure shape
# arithmetic over module constants
_SHAPE_CALL_ALLOW = frozenset({"cdiv", "len", "min", "max"})


class _PallasRules(_Scoped):
    """Kernel-module hygiene for copr/pallas/: static grids/blocks and
    no host callbacks (see the rule table in the module docstring)."""

    def visit_Call(self, node):
        name = _call_name(node)
        if name in _HOST_CALLBACKS:
            self.add("TPU-PALLAS-SHAPE", node,
                     f"{name}(...) in a Pallas kernel module: a host "
                     "callback inside (or feeding) a TPU kernel stalls "
                     "the device pipeline on the host — keep kernel "
                     "modules callback-free")
        elif name == "pallas_call":
            for kw in node.keywords:
                if kw.arg == "grid":
                    self._check_static(kw.value, node, "grid")
        elif name == "BlockSpec" and node.args:
            self._check_static(node.args[0], node, "block shape")
        self.generic_visit(node)

    def _check_static(self, expr, node, what: str) -> None:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                sub_name = _call_name(sub)
                if sub_name not in _SHAPE_CALL_ALLOW:
                    self.add(
                        "TPU-PALLAS-SHAPE", node,
                        f"non-static {what} in pallas_call: "
                        f"{sub_name}(...) is not shape arithmetic — a "
                        "runtime-derived grid/block shape recompiles "
                        "per value (or fails Mosaic); derive shapes "
                        "from static module constants")
                    return


# --------------------------------------------------------------------- #
# rule: TPU-COMPILE-KEY (compilecache/ persistence seams)
# --------------------------------------------------------------------- #

class _CompileKeyRules(_Scoped):
    """Every serialize/deserialize/persist call in compilecache/ must
    sit in a function that references the persistent-key triple: a
    digest, a mesh fingerprint, and the donation plan.  Identifier
    check covers names, attributes, AND string constants (the header
    field names the loader re-verifies count as references)."""

    def __init__(self, rel, lines):
        super().__init__(rel, lines)
        self._fn_nodes: list = []

    def visit_FunctionDef(self, node):
        self._fn_nodes.append(node)
        super().visit_FunctionDef(node)
        self._fn_nodes.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        name = _call_name(node)
        if _CACHE_WRITE_CALLS.match(name) and self._fn_nodes:
            fn = self._fn_nodes[-1]
            blob = " ".join(self._identifiers(fn)).lower()
            missing = [lbl for lbl, pat in _KEY_TRIPLE
                       if not pat.search(blob)]
            if missing:
                self.add("TPU-COMPILE-KEY", node,
                         f"{name}(...) in a cache-write seam whose "
                         "enclosing function never references "
                         f"{' / '.join(missing)}: a persisted "
                         "executable keyed without the full digest + "
                         "mesh-fingerprint + donation-plan triple can "
                         "silently deserialize the wrong program "
                         "variant after a restart")
        self.generic_visit(node)

    @staticmethod
    def _identifiers(fn: ast.AST) -> set:
        out = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Name):
                out.add(sub.id)
            elif isinstance(sub, ast.Attribute):
                out.add(sub.attr)
            elif isinstance(sub, ast.Constant) and \
                    isinstance(sub.value, str):
                out.add(sub.value)
        return out


# --------------------------------------------------------------------- #
# rule: TPU-PD-EPOCH (pd/ shared-store mutation seams)
# --------------------------------------------------------------------- #

class _PdEpochRules(_Scoped):
    """Every shared-store write call in pd/ must sit in a function that
    references the lease epoch.  The coplace store's liveness contract
    is epoch-fenced CAS: a mutation path that never mentions the epoch
    is one a dead member (expired lease, paused process, partition
    survivor) could drive — the store would have no way to refuse it.
    Identifier check mirrors TPU-COMPILE-KEY: names, attributes, AND
    string constants (the ``"epoch"`` doc fields the backends
    round-trip count as references)."""

    def __init__(self, rel, lines):
        super().__init__(rel, lines)
        self._fn_nodes: list = []

    def visit_FunctionDef(self, node):
        self._fn_nodes.append(node)
        super().visit_FunctionDef(node)
        self._fn_nodes.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    @staticmethod
    def _lock_receiver(node: ast.Call) -> bool:
        recv = node.func.value if isinstance(node.func, ast.Attribute) \
            else None
        if isinstance(recv, ast.Attribute):
            return bool(_PD_LOCK_RECV.search(recv.attr))
        if isinstance(recv, ast.Name):
            return bool(_PD_LOCK_RECV.search(recv.id))
        return False

    def visit_Call(self, node):
        name = _call_name(node)
        if _PD_WRITE_CALLS.match(name) and self._fn_nodes \
                and not self._lock_receiver(node):
            fn = self._fn_nodes[-1]
            blob = " ".join(
                _CompileKeyRules._identifiers(fn)).lower()
            if not _EPOCH_REF.search(blob):
                self.add("TPU-PD-EPOCH", node,
                         f"{name}(...) mutates the shared pd store "
                         "from a function that never references the "
                         "lease epoch: without the epoch-fenced CAS a "
                         "member whose lease expired can clobber "
                         "state the surviving members already "
                         "repartitioned — thread the member epoch "
                         "through every write path")
        self.generic_visit(node)


# --------------------------------------------------------------------- #
# rule 5: lock acquisition order
# --------------------------------------------------------------------- #

class _LockRules(_Scoped):
    """Per-class lock-order analysis.

    Collects lock attributes (threading.Lock/RLock/Condition assigned to
    self._x in any method), resolves Condition(self._y) aliasing, then
    walks each function recording `with self._x:` nesting — directly and
    one call level deep within the class (with self._a: self.meth() where
    meth acquires self._b counts as a->b).  Findings: nested acquisition
    of one underlying non-reentrant lock, and any (a,b) order observed
    together with (b,a)."""

    def __init__(self, rel, lines, tree):
        super().__init__(rel, lines)
        self.tree = tree

    def run(self) -> list:
        for cls in [n for n in ast.walk(self.tree)
                    if isinstance(n, ast.ClassDef)]:
            self._check_class(cls)
        return self.findings

    def _check_class(self, cls: ast.ClassDef) -> None:
        locks: dict = {}     # attr -> canonical (aliased) attr
        reentrant: set = set()
        for sub in ast.walk(cls):
            if not (isinstance(sub, ast.Assign)
                    and isinstance(sub.value, ast.Call)):
                continue
            kind = _call_name(sub.value)
            if kind not in ("Lock", "RLock", "Condition"):
                continue
            for t in sub.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    canon = t.attr
                    if kind == "Condition" and sub.value.args:
                        a0 = sub.value.args[0]
                        if (isinstance(a0, ast.Attribute)
                                and isinstance(a0.value, ast.Name)
                                and a0.value.id == "self"):
                            canon = a0.attr   # Condition wraps that lock
                    locks[t.attr] = canon
                    if kind == "RLock":
                        reentrant.add(canon)
        if not locks:
            return
        # per-method: ordered list of (outer-lock-stack, acquired lock)
        per_method: dict = {}
        for fn in [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]:
            per_method[fn.name] = self._acquisitions(fn, locks)
        edges: dict = {}     # (a, b) -> lineno of first observation
        for fname, acqs in per_method.items():
            for held, lock, node in acqs:
                for h in held:
                    if h == lock and h not in reentrant:
                        self.add(
                            "TPU-LOCK-ORDER", node,
                            f"{cls.name}.{fname} re-acquires "
                            f"self.{lock} while already holding it "
                            "(non-reentrant: self-deadlock)")
                    elif h != lock:
                        edges.setdefault((h, lock), node)
                # one call level deep: self.meth() under a held lock
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and isinstance(sub.func.value, ast.Name)
                            and sub.func.value.id == "self"
                            and sub.func.attr in per_method):
                        for _h2, l2, _n2 in per_method[sub.func.attr]:
                            if lock == l2 and l2 not in reentrant:
                                self.add(
                                    "TPU-LOCK-ORDER", sub,
                                    f"{cls.name}.{fname} holds "
                                    f"self.{lock} and calls "
                                    f"self.{sub.func.attr}() which "
                                    "re-acquires it (self-deadlock)")
                            elif lock != l2:
                                edges.setdefault((lock, l2), sub)
        for (a, b), node in edges.items():
            if (b, a) in edges and a < b:    # report each cycle once
                self.add("TPU-LOCK-ORDER", node,
                         f"{cls.name} acquires self.{a} before self.{b} "
                         f"here but self.{b} before self.{a} at line "
                         f"{edges[(b, a)].lineno}: lock-order inversion")

    def _acquisitions(self, fn, locks) -> list:
        """All lock acquisitions in fn as (held-before, lock, with-node),
        via the with-statement nesting structure."""
        out: list = []

        def lock_of(item) -> Optional[str]:
            e = item.context_expr
            if isinstance(e, ast.Call):       # .acquire() is not a ctx mgr
                return None
            if (isinstance(e, ast.Attribute)
                    and isinstance(e.value, ast.Name)
                    and e.value.id == "self" and e.attr in locks):
                return locks[e.attr]
            return None

        def walk(stmts, held):
            for node in stmts:
                if isinstance(node, ast.With):
                    acquired = []
                    for item in node.items:
                        lk = lock_of(item)
                        if lk is not None:
                            out.append((tuple(held + acquired), lk, node))
                            acquired.append(lk)
                    walk(node.body, held + acquired)
                    continue
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue                 # nested defs run elsewhere
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(node, field, None)
                    if isinstance(sub, list):
                        walk(sub, held)
                for h in getattr(node, "handlers", None) or []:
                    walk(h.body, held)

        walk(fn.body, [])
        return out


# --------------------------------------------------------------------- #
# entry points
# --------------------------------------------------------------------- #

def lint_source(src: str, rel: str) -> list:
    """Lint one module's source; `rel` is its tidb_tpu-relative path
    (/-separated) — rules scope on it."""
    tree = ast.parse(src)
    lines = src.splitlines()
    fenced = rel not in TRACED_MODULES or _module_has_limb_fence(tree)
    v = _ExprRules(rel, lines, psum_fenced=fenced)
    v.visit(tree)
    findings = v.findings
    if rel.startswith(COMPILECACHE_PREFIX):
        ck = _CompileKeyRules(rel, lines)
        ck.visit(tree)
        findings += ck.findings
    if rel.startswith(PD_PREFIX):
        pe = _PdEpochRules(rel, lines)
        pe.visit(tree)
        findings += pe.findings
    if rel.startswith(PALLAS_PREFIX):
        pr = _PallasRules(rel, lines)
        pr.visit(tree)
        findings += pr.findings
    if rel.startswith(SPAN_MODULE_PREFIXES):
        sl = _SpanLeakRules(rel, lines)
        sl.visit(tree)
        findings += sl.findings
    if rel not in LOCK_EXCLUDES and module_imports_threading(tree):
        findings += _LockRules(rel, lines, tree).run()
    # collapse repeats on one line (e.g. three id() calls in one tuple)
    seen, out = set(), []
    for f in findings:
        k = (f.rule, f.path, f.line)
        if k not in seen:
            seen.add(k)
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def lint_tree(root: Optional[str] = None) -> list:
    """Lint every .py file under the tidb_tpu package."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings: list = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__", "native"))
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            full = os.path.join(dirpath, fname)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            with open(full, encoding="utf-8") as f:
                try:
                    findings += lint_source(f.read(), rel)
                except SyntaxError as e:
                    findings.append(Finding(
                        "TPU-SYNTAX", rel, e.lineno or 0, "",
                        f"file does not parse: {e.msg}"))
    return findings


def load_baseline(path: Optional[str] = None) -> set:
    """Accepted-findings allowlist: one `RULE path::symbol` key per line
    (comments with #).  Pre-existing findings listed here pass the gate;
    new ones fail it."""
    if path is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "baseline.txt")
    keys = set()
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if line:
                    keys.add(line)
    return keys


def new_findings(findings: list, baseline: set) -> list:
    return [f for f in findings if f.key() not in baseline]


__all__ = ["Finding", "lint_source", "lint_tree", "load_baseline",
           "new_findings", "TRACED_MODULES", "HOT_PATH_MODULES",
           "LOCK_EXCLUDES", "module_imports_threading",
           "RETRY_MODULE_PREFIXES",
           "COMPILECACHE_PREFIX", "PALLAS_PREFIX", "PD_PREFIX",
           "SPAN_MODULE_PREFIXES", "MEM_SOURCE_MODULES"]
