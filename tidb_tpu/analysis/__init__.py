"""Static analysis gate: plan-contract verifier + TPU-hygiene linter +
shape/memory cost model.

Three passes, all wired into CI as a zero-findings gate
(``python -m tidb_tpu.analysis``):

- contracts: every physical operator declares a contract (output dtypes,
  row-capacity shape, sharding, traceable-dense vs host locality); the
  verifier walks built plans edge-by-edge and rejects inconsistent ones
  with a structured PlanContractError BEFORE any jit/trace happens.
  Hooked into the session plan path, the sched admission path
  (verify_task), and EXPLAIN (verified plans report ``contract: ok``).
- lint: an AST linter over tidb_tpu/ with repo-specific TPU-hygiene
  rules (tracer leaks, digest instability, host transfers in hot paths,
  broad exception handlers, lock-order hazards, x64-flag-dependent
  dtypes).  Pre-existing accepted findings live in analysis/baseline.txt;
  anything new fails the gate.
- copcost: a static shape/memory abstract interpreter that walks built
  cop DAGs using only contracts (padded device shapes from DENSE
  domain_sizes / SORT capacities / SEGMENT bucket spaces, physical
  dtype widths, per-shard
  extents under the mesh) and rolls up a per-launch LaunchCost
  (peak HBM bytes, transfer bytes, flops, padding waste).  Gate rules
  COST-PAD-WASTE / COST-CAP-BLOWUP / COST-DENSE-BLOWUP /
  COST-UNBOUNDED ride the corpus;
  sched admission enforces peak_hbm_bytes against a per-mesh budget
  (CostError, pre-trace) and EXPLAIN surfaces the estimate.
- copmeter (analysis/calibrate): the closed-loop half of the cost
  model — a bounded per-digest EWMA correction store (clamped to
  [1/8, 8], persisted through the copforge manifest) corrects
  LaunchCost from measured launch times and OOM events; the scheduler
  feeds corrected costs into RU pricing, HBM-budget admission, fusion
  caps, the micro-batch window, and deadline-aware early shedding.
  The gate grows a calibration pass (deterministic drift simulation,
  < 25% corpus pricing error) and the TPU-CALIB-CLAMP lint rule.
- shardflow (analysis/shardflow + parallel/topology): a sharding-layout
  & collective-transfer abstract interpreter — the mesh modeled as
  typed links (intra-chip / same-host ICI / cross-host DCI from the
  declared host view), every collective verified against it pre-trace
  (implicit reshards, unknown axes, coordinator-routed host merges,
  psum limb-fence bounds, DCI blow-ups), and transfer bytes rolled up
  per link class into ``LaunchCost.transfer_breakdown`` so admission,
  RU pricing (a 4x DCI rate), and fusion caps stay honest at pod
  scale.  SHARD-*/COST-DCI-BLOWUP findings ride the corpus plus the
  MULTICHIP dryrun plan shapes under a fake (host=2, device=4) view.
- coplife (analysis/lifetime): a buffer-lifetime pass over the same
  contract DAGs classifying every device-program input slot as
  PERSISTENT (snapshot-cache residents) / LOOP-CARRIED (paging and
  regrow state the client re-feeds) / EPHEMERAL (dead after the
  launch), and emitting the per-program-shape DonationPlan the spmd
  builders derive ``donate_argnums`` from.  DONATE-UNSAFE /
  DONATE-MISSED gate rules ride the corpus; sched admission rejects a
  donating task over a live resident pre-trace, and donated bytes
  tighten LaunchCost.peak_hbm_bytes.

The motivation is the compiler-first failure mode: with XLA-compiled cop
programs a bad plan no longer fails with a type error at build time — it
fails deep inside tracing (shape mismatch, silent dtype promotion,
surprise recompile) or returns wrong rows.  Compiler-first engines
(Flare, LAQP) verify a typed IR before codegen; this package is that
gate between planner/build and jit.
"""

from .calibrate import (BoundedLRU, Correction, CorrectionStore,
                        clamp_factor, correction_store)
from .contracts import (PlanContractError, verify_dag, verify_plan,
                        verify_task)
from .copcost import CostError, LaunchCost, plan_cost, task_cost
from .lifetime import (BufferClass, DonationError, DonationPlan,
                       donation_plan, verify_donation)
from .lint import Finding, lint_source, lint_tree, load_baseline
from .shardflow import (plan_transfer, verify_dag_sharding,
                        verify_plan_sharding, verify_task_sharding)

__all__ = ["PlanContractError", "verify_plan", "verify_dag", "verify_task",
           "CostError", "LaunchCost", "plan_cost", "task_cost",
           "BufferClass", "DonationError", "DonationPlan",
           "donation_plan", "verify_donation",
           "BoundedLRU", "Correction", "CorrectionStore",
           "correction_store", "clamp_factor",
           "plan_transfer", "verify_dag_sharding", "verify_plan_sharding",
           "verify_task_sharding",
           "Finding", "lint_tree", "lint_source", "load_baseline"]
