"""valueflow: whole-plan value-range abstract interpreter.

Reference analog: the range/overflow contracts a compiling engine must
prove BEFORE it emits code — Flare's native-compilation split (PAPERS.md)
keeps the unprovable lane host-side and compiles only what it can prove;
TiDB's own expression layer raises "value is out of range" eagerly on the
host.  A traced jnp program can do neither: it cannot raise
data-dependently, so a scaled-int64 lane that wraps past 2^63 returns
WRONG DIGITS with no error (the gap ``expr/builders._arith_result_type``
documents).  The only correct move on a TPU-native coprocessor is the one
this repo keeps making — prove it pre-trace, over the frozen contract
DAG, with no device touch: the same abstract-interpretation discipline as
copcost (shapes/bytes), shardflow (layouts/collectives), and coplife
(buffer lifetime), now over VALUE INTERVALS.

The interpreter carries a per-column interval in the DEVICE integer
representation (decimals are scaled int64, dates are day counts, strings
are dictionary codes) seeded from ANALYZE stats min/max — the
``_stacked_ranges`` narrowing the store already trusts — and widened to
the type domain when stats are absent.  It flows through expression
lowering (add/sub/mul, the div pow10 pre-scale, CAST chains), filters
(comparisons against constants TIGHTEN on the true branch), joins
(expanding joins bound SUM row counts by ``out_capacity``), and
aggregation states, and emits structured findings:

- ``NUM-OVERFLOW-DEVICE``  a traced jnp lane whose result interval
                           escapes int64 at stats-attained inputs —
                           today's silent wrap; reroute host-side or
                           widen, never trace it,
- ``NUM-FENCE-UNPROVEN``   a SUM whose per-batch limb bound (or claimed
                           narrow single-word bound) cannot be proven
                           from row-count x interval — the value-aware
                           generalization of the hardcoded 2^31 row
                           fence,
- ``NUM-PRECISION-LOSS``   int64/decimal flowing through an f32-only
                           device lane losing >0 ulp at the proven
                           magnitude (the TPU-has-no-f64 cliff),
- ``NUM-DIV-PRESCALE``     the documented unguarded pow10 pre-scaling
                           multiply of the decimal division lowering.

``proven`` intervals are STATS-ATTAINED (ANALYZE observed both
endpoints), so a proven escape is evidence, not paranoia: findings fire
only on proven intervals, while type-domain/widened intervals stay
sound over-approximations used for safety proofs (narrow SUMs) without
ever false-flagging un-analyzed tables.

The payoff is also perf: a proven-narrow interval lets
``copr/exec._one_agg_state`` emit a SINGLE-WORD int64 SUM state instead
of (hi, lo) limbs — half the state bytes, no limb psum lanes, priced by
copcost, fused under the ``('agg-narrow', ...)`` contract class — bit
identical to the limb path by construction (sum(hi)<<32 + sum(lo) ==
sum(v) in two's complement, and the proof says sum(v) cannot wrap).

Wired at the three canonical seams: the analysis gate corpus pass
(``--value-report`` / ``--value-only``), ``Session._plan_select`` (the
per-digest proof REGISTRY records each verified plan), and
``contracts.verify_task`` at sched submit (registry hit replays the
plan-time verdict pre-trace; a poisoned digest stays rejected).  The
runtime half rides the copgauge tradition: ANALYZE stamps observed
min/max watermarks per column, and every launch's declared interval must
contain the observed range — a violation is STATS DRIFT, surfaced on
``/sched`` and as a span attr, never a wrong result (the proofs carry
``NARROW_HEADROOM_ROWS`` of append headroom precisely so drift is a
signal, not a cliff).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from ..copr import dag as D
from ..expr import ir
from ..parallel.topology import _as_int
from ..types import dtypes as dt
from .contracts import PlanContractError, _fail
from .shardflow import PSUM_LIMB_ROWS, _gate_topologies

K = dt.TypeKind

# ------------------------------------------------------------------ #
# rule ids (gate finding rules — the COST-*/SHARD-* discipline)
# ------------------------------------------------------------------ #

RULE_OVERFLOW = "NUM-OVERFLOW-DEVICE"
RULE_FENCE = "NUM-FENCE-UNPROVEN"
RULE_PRECISION = "NUM-PRECISION-LOSS"
RULE_PRESCALE = "NUM-DIV-PRESCALE"

I64_MIN = -2 ** 63
I64_MAX = 2 ** 63 - 1

# largest magnitude below which EVERY integer is exactly representable
# in float32 — the bound of the f32-only device lane (TPU has no f64:
# jax demotes every float lane to f32 there, so an int64/decimal value
# above this loses >0 ulp the moment it enters a float expression)
F32_EXACT_INT = 2 ** 24

# append headroom multiplied into the stats row count before a narrow
# proof: the proof must survive ordinary growth between ANALYZE runs
# (the watermark check catches drift beyond it, loudly, without a wrong
# result — the narrow state itself stays exact far past the proof line)
NARROW_HEADROOM_ROWS = 1024

# proven-narrow |sum| ceiling: one sign bit of spare room under int64 so
# every psum partial and host re-merge stays provably un-wrapped
NARROW_SUM_BOUND = 2 ** 62


# ------------------------------------------------------------------ #
# the abstract value: a closed interval in device representation
# ------------------------------------------------------------------ #

@dataclass(frozen=True)
class Interval:
    """[lo, hi] over a lane's DEVICE integer representation (scaled
    int64 for decimals, days for dates, codes for strings).  ``proven``
    marks STATS-ATTAINED endpoints (ANALYZE observed them): findings
    fire only on proven intervals; widened type-domain intervals remain
    sound upper bounds for safety proofs but never raise findings."""
    lo: int
    hi: int
    proven: bool = False

    @property
    def mag(self) -> int:
        return max(abs(self.lo), abs(self.hi))

    def union(self, other: "Interval") -> "Interval":
        return Interval(min(self.lo, other.lo), max(self.hi, other.hi),
                        self.proven and other.proven)


BOOL_IV = Interval(0, 1, False)

# integer-represented kinds valueflow tracks; float lanes (f64 on CPU,
# f32 on TPU) and host-object columns are untracked (interval = None)
_INT_FAMILY = (K.INT64, K.UINT64, K.DECIMAL, K.DATE, K.DATETIME, K.TIME,
               K.ENUM, K.SET, K.BIT, K.STRING, K.NULL)


def type_domain(t: Optional[dt.DataType]) -> Optional[Interval]:
    """Widest interval of a dtype's device representation — the sound
    fallback when stats are absent.  None = untracked lane (floats,
    vectors, host objects)."""
    if t is None or t.kind not in _INT_FAMILY:
        return None
    if t.kind == K.DECIMAL:
        if t.is_wide_decimal:
            return None                 # host object ints, exact
        p = t.prec if t.prec > 0 else dt.DECIMAL64_MAX_PRECISION
        m = 10 ** min(p, dt.DECIMAL64_MAX_PRECISION) - 1
        return Interval(-m, m)
    if t.kind == K.UINT64:
        return Interval(0, 2 ** 64 - 1)
    if t.kind in (K.DATE, K.STRING):
        ii = np.iinfo(np.int32)
        return Interval(_as_int(ii.min), _as_int(ii.max))
    if t.kind == K.ENUM:
        return Interval(0, len(t.members or ()))
    if t.kind == K.SET:
        return Interval(0, 2 ** len(t.members or ()) - 1)
    if t.kind == K.BIT:
        return Interval(0, 2 ** max(t.prec, 1) - 1)
    if t.kind == K.NULL:
        return Interval(0, 0)
    return Interval(I64_MIN, I64_MAX)


def _clamped(lo: int, hi: int, proven: bool, t: Optional[dt.DataType],
             p: tuple, what: str) -> Interval:
    """Result interval of one arithmetic step: a PROVEN escape past
    int64 is today's silent device wrap — fail loudly; an unproven
    escape clamps to the type domain (sound, silent)."""
    if lo < I64_MIN or hi > I64_MAX:
        if proven:
            _fail(RULE_OVERFLOW, p,
                  f"{what} interval [{lo}, {hi}] escapes int64 at "
                  "stats-attained inputs: the traced lane would wrap "
                  "silently — evaluate host-side, widen, or re-ANALYZE")
        dom = type_domain(t) or Interval(I64_MIN, I64_MAX)
        return Interval(max(lo, dom.lo), min(hi, dom.hi), False)
    return Interval(lo, hi, proven)


def _const_interval(e: ir.Const) -> Optional[Interval]:
    v = e.value
    if isinstance(v, bool):
        v = 1 if v else 0
    if isinstance(v, (int, np.integer)):
        v = _as_int(v)
        return Interval(v, v, True)
    return type_domain(e.dtype)


# ------------------------------------------------------------------ #
# expression lowering over intervals
# ------------------------------------------------------------------ #

def _mul_bounds(a: Interval, b: Interval) -> Tuple[int, int]:
    cands = (a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi)
    return min(cands), max(cands)


def expr_interval(e: ir.Expr, env: tuple, p: tuple) -> Optional[Interval]:
    """Interval of one device-lowered expression over ``env`` (one
    Optional[Interval] per input-schema position).  Mirrors the
    expr/compile lowering: decimal mul adds scales (values are already
    scaled ints, so plain interval multiply is the model), div
    pre-scales by pow10, casts rescale.  Raises PlanContractError on a
    proven violation; unknown ops widen to the type domain (sound)."""
    if isinstance(e, ir.ColumnRef):
        if 0 <= e.index < len(env) and env[e.index] is not None:
            return env[e.index]
        return type_domain(e.dtype)
    if isinstance(e, ir.Const):
        return _const_interval(e)
    if not isinstance(e, ir.Func):
        return type_domain(e.dtype)

    op = e.op
    if op in ("eq", "ne", "lt", "le", "gt", "ge", "and", "or", "xor",
              "not", "isnull", "in"):
        for a in e.args:
            expr_interval(a, env, p)       # flow args for their findings
        return BOOL_IV
    if op in ("add", "sub", "mul", "div", "intdiv", "mod", "neg", "abs",
              "if", "case", "coalesce", "greatest", "least", "cast",
              "round", "floor", "ceil", "truncate", "sign"):
        return _arith_interval(e, env, p)
    # unknown/other scalar functions (date extracts, string ops, ...):
    # the type domain of the result is the sound answer
    for a in e.args:
        expr_interval(a, env, p)
    return type_domain(e.dtype)


def _arith_interval(e: ir.Func, env: tuple, p: tuple) -> Optional[Interval]:
    op = e.op
    args = [expr_interval(a, env, p) for a in e.args]
    if op in ("if",):
        vals = [iv for iv in args[1:] if iv is not None]
        return functools.reduce(Interval.union, vals) if vals else None
    if op in ("case", "coalesce", "greatest", "least"):
        # case: (when, then)* [else] — value positions vary; union every
        # tracked arg (sound: the result is one of them, or NULL)
        vals = [iv for iv in args if iv is not None]
        if not vals or any(iv is None for iv in args):
            return type_domain(e.dtype)
        if op == "greatest":
            return Interval(max(iv.lo for iv in vals),
                            max(iv.hi for iv in vals),
                            all(iv.proven for iv in vals))
        if op == "least":
            return Interval(min(iv.lo for iv in vals),
                            min(iv.hi for iv in vals),
                            all(iv.proven for iv in vals))
        return functools.reduce(Interval.union, vals)
    if op == "cast":
        return _cast_interval(e, args[0], p)
    if op in ("round", "floor", "ceil", "truncate"):
        iv = args[0]
        if iv is None or e.dtype.kind not in _INT_FAMILY:
            return type_domain(e.dtype)
        # magnitude never grows past one scale unit; keep it sound and
        # un-proven (endpoints move by rounding)
        return _clamped(iv.lo - 1, iv.hi + 1, False, e.dtype, p, e.op)
    if op == "sign":
        return Interval(-1, 1, False)

    a = args[0] if args else None
    b = args[1] if len(args) > 1 else None
    if e.dtype.kind not in _INT_FAMILY:
        return None                     # float lane: untracked
    if op == "neg":
        if a is None:
            return type_domain(e.dtype)
        return _clamped(-a.hi, -a.lo, a.proven, e.dtype, p, "negate")
    if op == "abs":
        if a is None:
            return type_domain(e.dtype)
        lo = 0 if a.lo <= 0 <= a.hi else min(abs(a.lo), abs(a.hi))
        return _clamped(lo, a.mag, a.proven, e.dtype, p, "abs")
    if a is None or b is None:
        return type_domain(e.dtype)
    if op == "add":
        return _clamped(a.lo + b.lo, a.hi + b.hi, a.proven and b.proven,
                        e.dtype, p, "add")
    if op == "sub":
        return _clamped(a.lo - b.hi, a.hi - b.lo, a.proven and b.proven,
                        e.dtype, p, "subtract")
    if op == "mul":
        lo, hi = _mul_bounds(a, b)
        return _clamped(lo, hi, a.proven and b.proven, e.dtype, p,
                        "multiply")
    if op == "div":
        return _div_interval(e, a, b, p)
    if op == "intdiv":
        return Interval(-a.mag, a.mag, False)
    if op == "mod":
        m = max(b.mag - 1, 0)
        return Interval(-m, m, False)
    return type_domain(e.dtype)


def _div_interval(e: ir.Func, a: Interval, b: Interval,
                  p: tuple) -> Optional[Interval]:
    """The decimal division lowering pre-scales the dividend by
    pow10(result_scale - scale_a + scale_b) BEFORE the integer divide —
    the documented unguarded multiply (expr/builders.py).  A proven
    escape of that intermediate is NUM-DIV-PRESCALE; the quotient's
    magnitude is bounded by the scaled dividend's (|divisor| >= 1 in
    scaled units once nonzero)."""
    ea, eb = e.args[0], e.args[1]
    if e.dtype.kind != K.DECIMAL:
        return None                     # float division: untracked lane
    sa = ea.dtype.scale if ea.dtype.kind == K.DECIMAL else 0
    sb = eb.dtype.scale if eb.dtype.kind == K.DECIMAL else 0
    k = e.dtype.scale - sa + sb
    if k >= 0:
        lo, hi = a.lo * 10 ** k, a.hi * 10 ** k
        if (lo < I64_MIN or hi > I64_MAX) and a.proven:
            _fail(RULE_PRESCALE, p,
                  f"decimal division pre-scales the dividend by 10^{k} "
                  f"to [{lo}, {hi}], past int64, at stats-attained "
                  "inputs — the traced multiply wraps before the divide "
                  "(host lanes raise via _guard_dec_overflow; device "
                  "lanes cannot)")
        m = min(max(abs(lo), abs(hi)), I64_MAX)
    else:
        dlo, dhi = b.lo * 10 ** (-k), b.hi * 10 ** (-k)
        if (dlo < I64_MIN or dhi > I64_MAX) and b.proven:
            _fail(RULE_PRESCALE, p,
                  f"decimal division pre-scales the divisor by 10^{-k} "
                  f"to [{dlo}, {dhi}], past int64, at stats-attained "
                  "inputs — the traced multiply wraps before the divide")
        m = a.mag
    return Interval(-m, m, False)


def _cast_interval(e: ir.Func, a: Optional[Interval],
                   p: tuple) -> Optional[Interval]:
    src = e.args[0].dtype
    tgt = e.dtype
    if tgt.kind in (K.FLOAT32, K.FLOAT64):
        # the f32-only cliff: on TPU every float lane is f32, which
        # holds integers exactly only below 2^24 — a proven magnitude
        # past that loses real digits the moment it enters the lane
        if tgt.kind == K.FLOAT32 and a is not None and a.proven \
                and src.kind in _INT_FAMILY and a.mag > F32_EXACT_INT:
            _fail(RULE_PRECISION, p,
                  f"{src} value with stats-attained magnitude {a.mag} "
                  f"(> 2^24) cast into an f32-only device lane loses "
                  ">0 ulp — keep the lane integral or accept DOUBLE "
                  "host-side")
        return None
    if tgt.kind not in _INT_FAMILY:
        return None
    if a is None:
        return type_domain(tgt)
    ss = src.scale if src.kind == K.DECIMAL else 0
    ts = tgt.scale if tgt.kind == K.DECIMAL else 0
    d = ts - ss
    if src.kind in _INT_FAMILY and d > 0:
        return _clamped(a.lo * 10 ** d, a.hi * 10 ** d, a.proven, tgt, p,
                        f"cast rescale by 10^{d}")
    if src.kind in _INT_FAMILY and d < 0:
        s = 10 ** (-d)
        return Interval(-(a.mag // s) - 1, a.mag // s + 1, False)
    if src.kind in _INT_FAMILY:
        dom = type_domain(tgt) or Interval(I64_MIN, I64_MAX)
        return Interval(max(a.lo, dom.lo), min(a.hi, dom.hi), a.proven)
    return type_domain(tgt)


# ------------------------------------------------------------------ #
# filter tightening (true-branch comparison narrowing)
# ------------------------------------------------------------------ #

def _tighten(env: tuple, cond: ir.Expr) -> tuple:
    """Tighten column intervals under the TRUE branch of a pushed-down
    filter: ``col <op> const`` (either operand order) and conjunctions.
    Tightening intersects, so proven-ness is preserved — the surviving
    rows' attained range is a subset of the column's."""
    if not isinstance(cond, ir.Func):
        return env
    if cond.op == "and":
        for a in cond.args:
            env = _tighten(env, a)
        return env
    if cond.op not in ("eq", "lt", "le", "gt", "ge"):
        return env
    if len(cond.args) != 2:
        return env
    a, b = cond.args
    op = cond.op
    if isinstance(b, ir.ColumnRef) and isinstance(a, ir.Const):
        a, b = b, a
        op = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}.get(op, op)
    if not (isinstance(a, ir.ColumnRef) and isinstance(b, ir.Const)):
        return env
    c = _const_interval(b)
    if c is None or not c.proven or a.index >= len(env):
        return env
    iv = env[a.index] or type_domain(a.dtype)
    if iv is None:
        return env
    v = c.lo
    if op == "eq":
        new = Interval(max(iv.lo, v), min(iv.hi, v), iv.proven)
    elif op == "lt":
        new = Interval(iv.lo, min(iv.hi, v - 1), iv.proven)
    elif op == "le":
        new = Interval(iv.lo, min(iv.hi, v), iv.proven)
    elif op == "gt":
        new = Interval(max(iv.lo, v + 1), iv.hi, iv.proven)
    else:
        new = Interval(max(iv.lo, v), iv.hi, iv.proven)
    if new.lo > new.hi:            # contradiction: filter selects nothing
        new = Interval(new.hi, new.hi, False)
    out = list(env)
    out[a.index] = new
    return tuple(out)


# ------------------------------------------------------------------ #
# DAG flow (memoized on the frozen dag + seeded env)
# ------------------------------------------------------------------ #

def _flow(node: D.CopNode, scan_env: tuple, rows: int, strict: bool,
          path: tuple):
    """Flow one cop node; returns (env, row_bound).  ``scan_env`` is a
    frozen ((offset, Interval), ...) seeding for the leaf TableScan;
    ``rows`` the sound global contributing-row bound (0 = unknown)."""
    p = path + (type(node).__name__,)

    if isinstance(node, D.TableScan):
        seeded = dict(scan_env)
        env = tuple(seeded.get(off) or type_domain(t)
                    for off, t in zip(node.col_offsets, node.col_dtypes))
        return env, rows

    if isinstance(node, D.FusedDag):
        out = ((), rows)
        for m in node.members:
            out = _flow(m, scan_env, rows, strict, p)
        return out

    kids = node.children()
    env, rows = (_flow(kids[0], scan_env, rows, strict, p)
                 if kids else ((), rows))

    if isinstance(node, D.Selection):
        for cond in node.conditions:
            expr_interval(cond, env, p)
            env = _tighten(env, cond)
        return env, rows
    if isinstance(node, D.Projection):
        return tuple(expr_interval(e, env, p) for e in node.exprs), rows
    if isinstance(node, D.Expand):
        for e in node.keys:
            expr_interval(e, env, p)
        env = env + tuple(expr_interval(e, env, p) for e in node.keys)
        env = env + (Interval(0, max(node.levels - 1, 0), False),)
        return env, rows * max(node.levels, 1)
    if isinstance(node, D.LookupJoin):
        expr_interval(node.probe_key, env, p)
        env = env + tuple(type_domain(t) for t in node.build_dtypes)
        if not node.unique and node.out_capacity > 0:
            # the expanding join's regrown output capacity bounds the
            # rows any downstream SUM can consume
            rows = max(rows, node.out_capacity)
        return env, rows
    if isinstance(node, (D.TopN, D.Limit)):
        if isinstance(node, D.TopN):
            for e, _d in (node.sort_keys
                          or (((node.sort_key, node.desc),)
                              if node.sort_key is not None else ())):
                expr_interval(e, env, p)
        if node.limit > 0 and rows > 0:
            rows = min(rows, node.limit)
        return env, rows
    if isinstance(node, D.Aggregation):
        _check_agg(node, env, rows, strict, p)
        return (tuple(type_domain(t) for t in D.output_dtypes(node)),
                rows)
    return env, rows


def _check_agg(node: D.Aggregation, env: tuple, rows: int, strict: bool,
               p: tuple) -> None:
    for g in node.group_by:
        expr_interval(g, env, p)
    for i, a in enumerate(node.aggs):
        if a.arg is None:
            continue
        iv = expr_interval(a.arg, env, p)
        if a.func != D.AggFunc.SUM or a.arg.dtype is None \
                or a.arg.dtype.kind in (K.FLOAT64, K.FLOAT32):
            continue
        if iv is None:
            iv = type_domain(a.arg.dtype) or Interval(I64_MIN, I64_MAX)
        if i in node.narrow_sums:
            # a claimed narrow slot must re-prove under the seeded env:
            # |sum| <= rows x mag must clear the single-word ceiling
            if strict and (rows <= 0
                           or rows * iv.mag >= NARROW_SUM_BOUND):
                _fail(RULE_FENCE, p,
                      f"narrow SUM claim on slot {i} is unprovable: "
                      f"{rows} rows x magnitude {iv.mag} does not clear "
                      f"the 2^62 single-word bound — re-ANALYZE or drop "
                      "the narrow stamp")
        elif strict and rows >= PSUM_LIMB_ROWS \
                and node.strategy not in D.HOST_MERGE_STRATEGIES:
            # value-aware generalization of the 2^31 row fence: past it,
            # the (hi, lo) limb psum stays exact only if the interval
            # proves the hi-limb sum cannot wrap
            if rows * ((iv.mag >> 32) + 1) >= 2 ** 63:
                _fail(RULE_FENCE, p,
                      f"limb-split SUM over {rows} global rows (>= 2^31) "
                      f"with magnitude {iv.mag}: the per-batch limb "
                      "bound is unprovable from row-count x interval — "
                      "repartition, host-merge, or narrow the column")


@functools.lru_cache(maxsize=1024)
def _flow_cached(dag: D.CopNode, scan_env: tuple, rows: int, strict: bool,
                 path: tuple):
    return _flow(dag, scan_env, rows, strict, path)


def verify_dag_values(dag: D.CopNode, scan_env: tuple = (), *,
                      rows: int = 0, strict: bool = False,
                      path: tuple = ()) -> tuple:
    """Flow one cop DAG over value intervals; raises PlanContractError
    with a NUM-* rule on the first proven violation, returns the DAG's
    output env (one Optional[Interval] per output column).  Memoized on
    the frozen (dag, seeding) pair — repeated admission of one program
    costs a dict hit."""
    env, _rows = _flow_cached(dag, tuple(scan_env), _as_int(rows),
                              strict is True, path)
    return env


def narrow_sum_count(dag: D.CopNode) -> int:
    """Proven-narrow SUM slots stamped anywhere in one cop DAG."""
    return sum(len(n.narrow_sums) for n in D.iter_nodes(dag)
               if isinstance(n, D.Aggregation))


# ------------------------------------------------------------------ #
# stats seeding + the narrow proof (planner seam)
# ------------------------------------------------------------------ #

def _table_key(table) -> int:
    # mirror of stats.handle.StatsHandle._key — the registry and the
    # watermark store must agree with the stats cache on identity
    return getattr(table, "table_id", 0) or id(table)   # planlint: ok - stats-cache identity contract


def scan_stats_env(scan: D.TableScan, table, handle) -> tuple:
    """((offset, Interval), ...) seeding for one TableScan from the
    table's ANALYZE stats: int-family columns with a device-kernel
    min/max get PROVEN attained intervals; everything else widens to
    its type domain at flow time."""
    if table is None or handle is None:
        return ()
    ts = handle.get(table)
    if ts is None:
        return ()
    names = getattr(table, "col_names", None)
    if names is None:
        return ()
    out = []
    for off, t in zip(scan.col_offsets, scan.col_dtypes):
        if off >= len(names) or t.kind not in _INT_FAMILY:
            continue
        cs = ts.col(names[off])
        if cs is None or cs.count <= 0:
            continue
        h = cs.hist
        if h.min_val is None or len(h.bounds) == 0:
            continue
        out.append((off, Interval(_as_int(h.min_val),
                                  _as_int(h.bounds[-1]), True)))
    return tuple(out)


def _scan_of(node: D.CopNode) -> Optional[D.TableScan]:
    for n in D.iter_nodes(node):
        if isinstance(n, D.TableScan):
            return n
    return None


def prove_narrow_sums(agg: D.Aggregation, table, handle) -> tuple:
    """SUM slots of one SCALAR/DENSE aggregation provably safe as
    single-word int64 states: stats row count (with append headroom) x
    the flowed argument interval must clear the 2^62 ceiling.  Returns
    the provable slot indexes (empty when stats are absent — the proof
    never speculates).  Called by the planner while stamping the frozen
    DAG; the watermark check guards the proof's stats against drift at
    every launch."""
    if agg.strategy not in (D.GroupStrategy.SCALAR, D.GroupStrategy.DENSE):
        return ()
    if table is None or handle is None:
        return ()
    ts = handle.get(table)
    if ts is None or ts.count <= 0:
        return ()
    scan = _scan_of(agg.child)
    if scan is None:
        return ()
    seed = scan_stats_env(scan, table, handle)
    if not seed:
        return ()
    rows = max(ts.realtime_count, ts.count, 1) * NARROW_HEADROOM_ROWS
    try:
        env, rows = _flow_cached(agg.child, seed, rows, False, ("narrow",))
    except PlanContractError:
        return ()       # the verify pass will surface it; never stamp
    proved = []
    for i, a in enumerate(agg.aggs):
        if a.func != D.AggFunc.SUM or a.arg is None \
                or a.arg.dtype is None \
                or a.arg.dtype.kind in (K.FLOAT64, K.FLOAT32):
            continue
        try:
            iv = expr_interval(a.arg, env, ("narrow",))
        except PlanContractError:
            continue
        if iv is None or not iv.proven:
            continue
        if rows > 0 and rows * iv.mag < NARROW_SUM_BOUND:
            proved.append(i)
    return tuple(proved)


# ------------------------------------------------------------------ #
# per-digest proof registry (plan-verify time -> sched submit time)
# ------------------------------------------------------------------ #

# dag digest -> ("ok", declared) | ("rejected", PlanContractError);
# declared = ((table_key, column, lo, hi), ...) — the intervals the
# plan's proof assumed, compared against observed watermarks per launch
_REGISTRY: dict = {}
_REGISTRY_CAP = 4096


def _register(dag: D.CopNode, verdict: tuple) -> None:
    if len(_REGISTRY) >= _REGISTRY_CAP:
        _REGISTRY.clear()
    _REGISTRY[D.dag_digest(dag)] = verdict


def _declared_of(scan_env: tuple, table, names) -> tuple:
    tk = _table_key(table) if table is not None else 0
    if not tk or names is None:
        return ()
    return tuple((tk, names[off], iv.lo, iv.hi)
                 for off, iv in scan_env if off < len(names))


def registry_verdict(dag: D.CopNode):
    """(verdict, payload) the plan-verify pass recorded for this digest,
    or None — tests and the sched seam read this."""
    return _REGISTRY.get(D.dag_digest(dag))


def clear_registry() -> None:
    _REGISTRY.clear()
    _flow_cached.cache_clear()


# ------------------------------------------------------------------ #
# observed watermarks (the runtime half; ANALYZE stamps, launches check)
# ------------------------------------------------------------------ #

# (table_key, column(lower)) -> (observed_min, observed_max) in device
# representation — stamped by StatsHandle.analyze_table from the SAME
# device-built histogram the proofs read, so declared vs observed can
# only diverge when the data moved after the plan's stats snapshot
_WATERMARKS: dict = {}
_WATERMARKS_CAP = 8192

# lifetime drift counter (read by /sched via the scheduler mirror and
# by the stress smoke)
_DRIFTS = [0]


def stamp_watermarks(ts) -> None:
    """Record per-column observed min/max watermarks from a fresh
    ANALYZE (TableStats).  Called by stats/handle at the end of every
    analyze_table — the runtime validation half of the value proofs."""
    if len(_WATERMARKS) >= _WATERMARKS_CAP:
        _WATERMARKS.clear()
    for name, cs in ts.cols.items():
        h = cs.hist
        if cs.count <= 0 or h.min_val is None or len(h.bounds) == 0:
            continue
        _WATERMARKS[(ts.table_id, name)] = (_as_int(h.min_val),
                                            _as_int(h.bounds[-1]))


def watermark_violations(declared: tuple) -> list:
    """Columns whose CURRENT observed watermark escapes the declared
    plan-time interval — stats drift.  Never an error: the narrow proof
    carries NARROW_HEADROOM_ROWS of slack and the limb path is exact
    regardless; drift is surfaced (span attr, /sched counter) so the
    operator re-ANALYZEs before the slack erodes."""
    out = []
    for tk, name, lo, hi in declared:
        obs = _WATERMARKS.get((tk, str(name).lower()))
        if obs is None:
            continue
        if obs[0] < lo or obs[1] > hi:
            out.append((name, (lo, hi), obs))
    return out


def drift_count() -> int:
    return _DRIFTS[0]


# ------------------------------------------------------------------ #
# admission-time verification (sched submit, via contracts.verify_task)
# ------------------------------------------------------------------ #

def verify_task_values(task) -> None:
    """Admission-time valueflow check of a structured CopTask, BEFORE
    the drain could resolve (trace) a program.  A digest the session
    verified replays its plan-time verdict (a poisoned plan stays
    rejected at submit even if the caller skipped the session seam) and
    checks declared-vs-observed watermarks; an unknown digest flows
    from type domains — sound, find-nothing-spurious."""
    if task.dag is None:
        return
    rec = _REGISTRY.get(D.dag_digest(task.dag))
    if rec is not None:
        if rec[0] == "rejected":
            e = rec[1]
            _fail(e.rule, ("sched",) + tuple(e.path), e.detail)
        drifted = watermark_violations(rec[1])
        if drifted:
            _DRIFTS[0] += len(drifted)
            try:
                task.value_drift = len(drifted)
            except AttributeError:
                pass
        return
    global_rows = 0
    for v, _m in task.cols or ():
        if getattr(v, "ndim", 0) >= 2:
            global_rows = v.shape[0] * v.shape[1]
            break
    verify_dag_values(task.dag, (), rows=global_rows, path=("sched",))


# ------------------------------------------------------------------ #
# plan-level verification (session / gate / EXPLAIN)
# ------------------------------------------------------------------ #

def _verify_cop_op(op, handle, path: tuple) -> int:
    table = getattr(op, "table", None)
    scan = _scan_of(op.dag)
    seed = (scan_stats_env(scan, table, handle)
            if scan is not None else ())
    rows = 0
    if table is not None and handle is not None:
        ts = handle.get(table)
        if ts is not None:
            rows = max(ts.realtime_count, ts.count)
    names = getattr(table, "col_names", None) if table is not None else None
    try:
        verify_dag_values(op.dag, seed, rows=rows, strict=len(seed) > 0,
                          path=path)
    except PlanContractError as e:
        _register(op.dag, ("rejected", e))
        raise
    _register(op.dag, ("ok", _declared_of(seed, table, names)))
    return 1


def verify_plan_values(phys, handle=None, path: tuple = ()) -> int:
    """Flow every device-program operator of a built physical plan over
    value intervals (stats-seeded when ``handle`` has the table
    analyzed, type domains otherwise).  Returns the number of device
    operators flowed; raises PlanContractError on the first proven
    violation.  Each flowed digest lands in the proof registry so sched
    admission replays the verdict and every launch checks watermarks.
    Topology-invariant by construction: intervals bound VALUES, and the
    row bounds are global — the same proof holds under every declared
    host view."""
    flowed = 0
    stack = [phys]
    while stack:
        op = stack.pop()
        name = type(op).__name__
        p = path + (name,)
        if name in ("CopTaskExec", "CopJoinTaskExec"):
            flowed += _verify_cop_op(op, handle, p)
        elif name == "CopShuffleJoinExec":
            spec = op.spec
            for side, tbl in ((spec.left, getattr(op, "left_table", None)),
                              (spec.right,
                               getattr(op, "right_table", None))):
                scan = _scan_of(side)
                seed = (scan_stats_env(scan, tbl, handle)
                        if scan is not None else ())
                verify_dag_values(side, seed, strict=len(seed) > 0,
                                  path=p)
            verify_dag_values(spec.top, (), path=p)
            flowed += 1
        elif name == "CopWindowExec":
            verify_dag_values(op.spec.child, (), path=p)
            flowed += 1
        for c in getattr(op, "children", []) or []:
            if c is not None:
                stack.append(c)
        fb = getattr(op, "fallback", None)
        if fb is not None:
            stack.append(fb)
    return flowed


def plan_narrow_states(phys) -> int:
    """Proven-narrow SUM states across a built plan's device DAGs."""
    total = 0
    stack = [phys]
    while stack:
        op = stack.pop()
        if type(op).__name__ in ("CopTaskExec", "CopJoinTaskExec"):
            total += narrow_sum_count(op.dag)
        for c in getattr(op, "children", []) or []:
            if c is not None:
                stack.append(c)
        fb = getattr(op, "fallback", None)
        if fb is not None:
            stack.append(fb)
    return total


# ------------------------------------------------------------------ #
# gate pass + report
# ------------------------------------------------------------------ #

def value_findings(plans, handle=None, n_devices: int = 8) -> list:
    """NUM-* findings over (sql, built-plan) pairs — the valueflow half
    of the analysis gate, run under both gate topology views for parity
    with shardflow (the value proofs are topology-invariant; the loop
    documents that invariance at zero cost through the memoized flow).
    Finding keys are stable (corpus position + rule) so they baseline
    exactly like lint/cost/shard findings."""
    from .lint import Finding
    out = []
    for idx, (sql, phys) in enumerate(plans):
        qid = f"corpus/q{idx:02d}"
        one_line = " ".join(sql.split())[:60]
        for topo in _gate_topologies(n_devices):
            try:
                verify_plan_values(phys, handle)
            except PlanContractError as e:
                sym = e.path[-1] if e.path else "plan"
                out.append(Finding(
                    e.rule, qid, 0, sym,
                    f"[hosts={topo.n_hosts}] {e.detail} ({one_line})"))
                break
    return out


def value_report(plans, handle=None) -> str:
    """Per-corpus-query value table (``--value-report``): flowed device
    ops, stats-proven scan columns, narrow SUM states, and the verdict
    — the static half of the proven-narrow payoff next to
    --transfer-report's link attribution."""
    lines = ["value-range flow over the plan corpus "
             "(stats-seeded where ANALYZEd, type domains otherwise)",
             f"{'query':<44} {'ops':>4} {'narrow':>7} {'verdict':>9}"]
    for idx, (sql, phys) in enumerate(plans):
        one_line = " ".join(sql.split())
        label = f"q{idx:02d} {one_line[:39]}"
        try:
            flowed = verify_plan_values(phys, handle)
            narrow = plan_narrow_states(phys)
            lines.append(f"{label:<44} {flowed:>4} {narrow:>7} "
                         f"{'proven':>9}")
        except PlanContractError as e:
            lines.append(f"{label:<44} {'-':>4} {'-':>7} {e.rule:>9}")
    return "\n".join(lines)


__all__ = ["Interval", "type_domain", "expr_interval",
           "verify_dag_values", "verify_plan_values",
           "verify_task_values", "prove_narrow_sums", "scan_stats_env",
           "narrow_sum_count", "plan_narrow_states", "value_findings",
           "value_report", "stamp_watermarks", "watermark_violations",
           "drift_count", "registry_verdict", "clear_registry",
           "RULE_OVERFLOW", "RULE_FENCE", "RULE_PRECISION",
           "RULE_PRESCALE", "F32_EXACT_INT", "NARROW_HEADROOM_ROWS",
           "NARROW_SUM_BOUND", "I64_MIN", "I64_MAX"]
