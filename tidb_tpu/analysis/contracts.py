"""Plan-contract verification: typed-IR checks BEFORE tracing.

Reference analog: the typed IR verification compiler-first query engines
run between planning and codegen (Flare's native pipeline for Spark; the
LA-rewrite checks of "Accelerating Machine Learning Queries with Linear
Algebra Query Processing").  Here the "IR" is the physical plan tree
(executor/physical.py operators) plus the pushed cop DAG (copr/dag.py);
the contract of each operator is its declared output schema, locality
(traceable-dense device program vs host numpy operator), sharding spec,
and static capacity shape.

The verifier walks a built plan edge-by-edge and rejects inconsistencies
with a structured PlanContractError — a PlanError subclass, so the
session surfaces it like any other planner rejection, crucially *before*
`jax.jit` tracing starts (where the same bug would surface as a shape
error five layers deep, or not at all):

- column references must be in range and dtype-consistent with the child
  operator's declared output schema,
- dtype changes only through declared `cast` nodes (no silent promotion
  riding jnp broadcasting rules),
- device DAG nodes must be traceable-dense: no host-object (wide
  decimal / vector) columns, no unlowered string constants, only
  device-whitelisted ops,
- aggregation capacity shapes must be well-formed (DENSE domain sizes
  aligned with group keys, SORT group capacity sane),
- exchange boundaries must agree: a shuffle-join spec's per-side schemas
  and its post-join `top` chain's leaf scan must describe the same
  columns (the mesh/sharding handshake of an MPP exchange),
- sched admission (verify_task): stacked device input shapes must match
  the task key's capacity signature and divide over the mesh — the
  precondition for batch-slot coalescing to be shape-safe.

Checks are structural and cheap (no device touch, no jax import); DAG
verification is memoized on the frozen DAG node itself.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

from ..copr import dag as D
from ..expr.ir import ColumnRef, Const, Expr, Func
from ..planner.build import PlanError
from ..types import dtypes as dt

K = dt.TypeKind


class PlanContractError(PlanError):
    """A built plan violates an operator contract.

    Raised by the verifier before any tracing/compilation; carries the
    violated rule, the operator path from the plan root, and a detail
    message so tests and EXPLAIN can assert on structure, not text."""

    def __init__(self, rule: str, path: Sequence[str], detail: str):
        self.rule = rule
        self.path = tuple(path)
        self.detail = detail
        super().__init__(
            f"plan contract violation [{rule}] at "
            f"{' > '.join(self.path) or '<root>'}: {detail}")


def _fail(rule: str, path, detail: str):
    raise PlanContractError(rule, path, detail)


# --------------------------------------------------------------------- #
# dtype compatibility
# --------------------------------------------------------------------- #

def _family(t: Optional[dt.DataType]) -> str:
    """Coarse representation family: what the value IS on device/host.
    Promotion across families without a declared cast is the silent-
    promotion hazard this gate exists to catch."""
    if t is None:
        return "?"
    if t.is_host_object:
        return "obj"
    if t.is_string:
        return "str"
    if t.kind in (K.FLOAT64, K.FLOAT32):
        return "float"
    return "int"    # ints, scaled decimal64, temporal, enum/set/bit, null


def _compatible(declared: dt.DataType, actual: dt.DataType) -> bool:
    """A ColumnRef's declared dtype vs the producing schema slot.
    Nullability and collation may legitimately drift through rewrites
    (outer-join null extension, collation coercion); kind and physical
    representation may not."""
    if declared.kind == K.NULL or actual.kind == K.NULL:
        return True       # untyped NULL literal slots match anything
    if declared.kind == actual.kind:
        if declared.kind == K.DECIMAL:
            # scaled-int encoding: a scale mismatch reads 1.00 as 100
            return (declared.scale == actual.scale
                    or declared.is_wide_decimal != actual.is_wide_decimal)
        return True
    # distinct kinds: allowed only within one physical family (e.g.
    # DATE read as bigint by a fold) — never int<->float or <->object
    return (_family(declared) == _family(actual)
            and declared.np_dtype() == actual.np_dtype())


# arithmetic ops: int/float/decimal mixing IS declared in this engine —
# the evaluator rescales/promotes from the arg dtypes and the inferred
# result dtype (expr/builders._arith_result_type + expr/compile.py).
# The undeclared promotion the verifier rejects is arithmetic that
# consumes STRING-family args while producing a NON-string: dictionary
# codes are arbitrary ordinals, and the planner routes string operands
# into numeric arithmetic only through a declared cast (or dict_lut).
# String-OUT code arithmetic is the legitimate dictionary-lowering idiom
# (lower_strings combines codes as code1*K+code2 with a derived output
# dictionary) and passes.
_ARITH_OPS = frozenset({"add", "sub", "mul", "div", "intdiv", "mod"})


def _check_expr(e: Expr, schema: Tuple[dt.DataType, ...], path,
                device: bool = False, lowered: bool = False) -> None:
    """One expression tree against its input schema.  `device=True` adds
    the traceable-dense rules (whitelisted ops, lowered strings, no
    host-object values).  `lowered=True` marks a subtree under a
    dict_map/dict_lut (or a node carrying a derived dictionary): there
    the dictionary-lowering idiom legitimately treats codes as ints."""
    if isinstance(e, ColumnRef):
        if not (0 <= e.index < len(schema)):
            _fail("column-ref", path,
                  f"{e} references column {e.index} of a "
                  f"{len(schema)}-column input")
        if not _compatible(e.dtype, schema[e.index]):
            _fail("dtype-mismatch", path,
                  f"{e} declares {e.dtype} but the input schema produces "
                  f"{schema[e.index]} at column {e.index}")
        if device and e.dtype.is_host_object:
            _fail("host-object-on-device", path,
                  f"{e} ({e.dtype}) is a host object array and cannot be "
                  "stacked into device shards")
        return
    if isinstance(e, Const):
        if device and isinstance(e.value, str):
            _fail("unlowered-string", path,
                  f"raw string constant {e.value!r} reached a device "
                  "expression (dictionary lowering did not apply)")
        return
    if isinstance(e, Func):
        if device:
            from ..executor.physical import DEVICE_OPS
            if e.op not in DEVICE_OPS:
                _fail("op-not-device", path,
                      f"op {e.op!r} is not in the device capability "
                      "registry but was pushed into a cop DAG")
            if e.dtype is not None and e.dtype.is_host_object:
                _fail("host-object-on-device", path,
                      f"{e.op} produces {e.dtype}, a host-object type")
        if e.op in _ARITH_OPS and not lowered \
                and _family(e.dtype) != "str":
            for a in e.args:
                if a.dtype is not None and _family(a.dtype) == "str":
                    _fail("undeclared-promotion", path,
                          f"{e.op} produces {e.dtype} from a string-"
                          f"family argument ({a.dtype}) without a "
                          "declared cast — dictionary codes are not "
                          "numbers")
        sub_lowered = (lowered or e.op in ("dict_map", "dict_lut")
                       or getattr(e, "_derived_dict", None) is not None)
        for a in e.args:
            _check_expr(a, schema, path, device, sub_lowered)


# --------------------------------------------------------------------- #
# device DAG verification (memoized on the frozen DAG)
# --------------------------------------------------------------------- #

def verify_dag(root: D.CopNode) -> None:
    """Verify a pushed cop DAG bottom-up.  Memoized: DAG nodes are frozen
    dataclasses (they already key the jit-program cache), so repeated
    admission of the same program costs one dict hit."""
    _verify_dag_cached(root)


@functools.lru_cache(maxsize=1024)
def _verify_dag_cached(root: D.CopNode) -> bool:
    _verify_dag(root, ())
    return True


def _verify_dag(node: D.CopNode, path) -> None:
    p = path + (type(node).__name__,)
    for c in node.children():
        if c is None:
            _fail("arity", p, "missing child node")
        _verify_dag(c, p)

    if isinstance(node, D.TableScan):
        if len(node.col_offsets) != len(node.col_dtypes):
            _fail("arity", p,
                  f"{len(node.col_offsets)} column offsets vs "
                  f"{len(node.col_dtypes)} dtypes")
        if any(o < 0 for o in node.col_offsets):
            _fail("column-ref", p, "negative column offset")
        for t in node.col_dtypes:
            if t.is_host_object:
                _fail("host-object-on-device", p,
                      f"scan reads {t}, a host-object column that never "
                      "ships to device")
        return

    schema = D.output_dtypes(node.children()[0]) if node.children() else ()

    if isinstance(node, D.Selection):
        for cond in node.conditions:
            _check_expr(cond, schema, p, device=True)
    elif isinstance(node, D.Projection):
        if not node.exprs:
            _fail("arity", p, "projection with no expressions")
        for e in node.exprs:
            _check_expr(e, schema, p, device=True)
    elif isinstance(node, D.Expand):
        if node.levels < 1 or node.levels > len(node.keys) + 1:
            _fail("capacity-shape", p,
                  f"levels={node.levels} out of range for "
                  f"{len(node.keys)} rollup keys")
        for e in node.keys:
            _check_expr(e, schema, p, device=True)
    elif isinstance(node, D.Aggregation):
        for g in node.group_by:
            _check_expr(g, schema, p, device=True)
        for a in node.aggs:
            if a.arg is not None:
                _check_expr(a.arg, schema, p, device=True)
            elif a.func not in (D.AggFunc.COUNT,):
                _fail("agg-arg", p, f"{a.func.value} requires an argument")
        if node.strategy == D.GroupStrategy.SCALAR:
            if node.group_by:
                _fail("capacity-shape", p,
                      "SCALAR aggregation with group-by keys")
        elif node.strategy == D.GroupStrategy.DENSE:
            if len(node.domain_sizes) != len(node.group_by):
                _fail("capacity-shape", p,
                      f"DENSE domain_sizes arity {len(node.domain_sizes)} "
                      f"!= group_by arity {len(node.group_by)}")
            if any(s <= 0 for s in node.domain_sizes):
                _fail("capacity-shape", p,
                      f"non-positive dense domain size in "
                      f"{node.domain_sizes}")
        elif node.strategy == D.GroupStrategy.SORT:
            if not node.group_by:
                _fail("capacity-shape", p, "SORT aggregation without keys")
            if node.group_capacity < 0:
                _fail("capacity-shape", p,
                      f"negative group capacity {node.group_capacity}")
        elif node.strategy in D.RADIX_STRATEGIES:
            sname = node.strategy.value.upper()
            if not node.group_by:
                _fail("capacity-shape", p,
                      f"{sname} aggregation without keys")
            b = node.num_buckets
            if b <= 0 or (b & (b - 1)) != 0:
                # the radix partition masks the top log2(B) hash bits and
                # the state table is (B,): a malformed bucket count would
                # trace a garbage-shaped program
                _fail("capacity-shape", p,
                      f"{sname} num_buckets {b} is not a positive power "
                      "of two")
            if node.strategy is D.GroupStrategy.SCATTER \
                    and D.radix_passes(b) > D.MAX_RADIX_PASSES:
                # pass well-formedness: each pass is a full-data
                # reorder, so a bucket space whose bit span prices more
                # than MAX_RADIX_PASSES passes would cost more data
                # movement than the comparator sort it replaces
                _fail("capacity-shape", p,
                      f"SCATTER num_buckets {b} prices "
                      f"{D.radix_passes(b)} radix passes "
                      f"(> {D.MAX_RADIX_PASSES}): malformed bucket "
                      "space")
            if node.prehashed:
                _verify_prehashed(node, schema, p)
        if node.prehashed and node.strategy not in D.RADIX_STRATEGIES:
            _fail("capacity-shape", p,
                  f"prehashed set on a {node.strategy.value} "
                  "aggregation: only the radix strategies "
                  "(SEGMENT/SCATTER) read a hoisted hash column")
        if node.narrow_sums:
            # valueflow-proven single-word SUM states: only in-program
            # (psum-merged) strategies carry them, and only int/decimal
            # SUM slots qualify — a narrow float or COUNT slot would
            # trace a program whose state layout disagrees with the
            # merge/finalize contract
            if node.strategy not in (D.GroupStrategy.SCALAR,
                                     D.GroupStrategy.DENSE):
                _fail("capacity-shape", p,
                      f"narrow_sums on a {node.strategy.value} "
                      "aggregation: only SCALAR/DENSE (in-program psum) "
                      "states take the single-word layout")
            from ..types.dtypes import TypeKind as _K
            for i in node.narrow_sums:
                if i < 0 or i >= len(node.aggs):
                    _fail("capacity-shape", p,
                          f"narrow_sums index {i} out of range for "
                          f"{len(node.aggs)} aggregates")
                a = node.aggs[i]
                if a.func != D.AggFunc.SUM or a.arg is None \
                        or a.arg.dtype is None \
                        or a.arg.dtype.kind in (_K.FLOAT64, _K.FLOAT32):
                    _fail("capacity-shape", p,
                          f"narrow_sums index {i} is not an int/decimal "
                          "SUM: only limb-split SUM states have a narrow "
                          "twin")
    elif isinstance(node, D.TopN):
        keys = node.sort_keys or (((node.sort_key, node.desc),)
                                  if node.sort_key is not None else ())
        if not keys:
            _fail("arity", p, "TopN without sort keys")
        for e, _desc in keys:
            _check_expr(e, schema, p, device=True)
        if node.limit < 0:
            _fail("capacity-shape", p, f"negative limit {node.limit}")
    elif isinstance(node, D.Limit):
        if node.limit < 0:
            _fail("capacity-shape", p, f"negative limit {node.limit}")
    elif isinstance(node, D.LookupJoin):
        if node.kind not in ("inner", "left", "semi", "anti"):
            _fail("arity", p, f"unknown join kind {node.kind!r}")
        _check_expr(node.probe_key, schema, p, device=True)
        if not node.unique and node.out_capacity <= 0:
            _fail("capacity-shape", p,
                  "expanding (non-unique) lookup join without a positive "
                  "out_capacity")
        if node.aux_slot < 0:
            _fail("capacity-shape", p, f"negative aux_slot {node.aux_slot}")
        if node.kind in ("inner", "left"):
            for t in node.build_dtypes:
                if t.is_host_object:
                    _fail("host-object-on-device", p,
                          f"broadcast build column of type {t}")


def _verify_prehashed(node: D.Aggregation, schema, p) -> None:
    """Contract of the prehash hoist (store/client + copr/radix): the
    LAST scan column is the hoisted int64 key hash, the chain below the
    aggregation is a plain TableScan(+Selection) (anything reshaping
    the batch would strand the appended column), and no group key may
    read the hash column itself."""
    cur = node.child
    while isinstance(cur, D.Selection):
        cur = cur.child
    if not isinstance(cur, D.TableScan):
        _fail("capacity-shape", p,
              "prehashed aggregation over a non-scan chain: the hoisted "
              "hash column only rides a TableScan(+Selection) batch")
    if not schema or _family(schema[-1]) != "int":
        _fail("dtype-mismatch", p,
              "prehashed aggregation whose last scan column is not an "
              "int64-family hash lane")
    hash_idx = len(schema) - 1
    for g in node.group_by:
        for ref in (x for x in _walk_refs(g)):
            if ref.index == hash_idx:
                _fail("column-ref", p,
                      "group key reads the hoisted hash column "
                      f"(index {hash_idx}) — keys must read data "
                      "columns only")


def _walk_refs(e: Expr):
    if isinstance(e, ColumnRef):
        yield e
    elif isinstance(e, Func):
        for a in e.args:
            yield from _walk_refs(a)


# --------------------------------------------------------------------- #
# physical-plan verification
# --------------------------------------------------------------------- #

def verify_plan(plan) -> int:
    """Walk a built physical plan and check every operator's declared
    contract against its children's.  Returns the number of operators
    checked; raises PlanContractError on the first violation.  Called
    from the session plan path (before any execute/trace) and from the
    analysis gate over the TPC-H plan corpus."""
    from ..executor import physical as X
    return _verify_op(plan, (), X)


def _schema_of(op) -> Tuple[dt.DataType, ...]:
    return tuple(op.out_dtypes)


def _verify_op(op, path, X) -> int:
    c = op.contract() if hasattr(op, "contract") else {}
    p = path + (c.get("op", type(op).__name__),)
    n = 1
    for child in getattr(op, "children", []) or []:
        if child is not None:
            n += _verify_op(child, p, X)

    out = tuple(c.get("out_dtypes", ()))
    names = tuple(c.get("out_names", ()))
    if names and out and len(names) != len(out):
        _fail("arity", p,
              f"{len(names)} output names vs {len(out)} output dtypes")

    if isinstance(op, X.CopTaskExec):
        verify_dag(op.dag)
        if isinstance(op.dag, D.Aggregation):
            want = len(op.key_meta) + len(op.dag.aggs)
            if names and len(names) != want:
                _fail("arity", p,
                      f"aggregation produces {want} columns "
                      f"({len(op.key_meta)} keys + {len(op.dag.aggs)} "
                      f"aggs) but the contract declares {len(names)}")
        else:
            dag_out = D.output_dtypes(op.dag)
            if out and len(out) != len(dag_out):
                _fail("arity", p,
                      f"DAG emits {len(dag_out)} columns but the "
                      f"contract declares {len(out)}")
            for i, (a, b) in enumerate(zip(out, dag_out)):
                if not _compatible(a, b):
                    _fail("dtype-mismatch", p,
                          f"output column {i}: contract declares {a}, "
                          f"DAG produces {b}")
    elif isinstance(op, X.CopJoinTaskExec):
        verify_dag(op.dag)
        builds = (op.builds if op.builds
                  else [{"exec": op.build_exec,
                         "key_index": op.build_key_index}])
        for b in builds:
            bx = b["exec"]
            if bx is None:
                _fail("arity", p, "broadcast join without a build plan")
            ki = b.get("key_index", 0)
            if not (0 <= ki < len(bx.out_dtypes)):
                _fail("column-ref", p,
                      f"build key index {ki} out of range for the "
                      f"{len(bx.out_dtypes)}-column build side")
        if op.fallback is not None:
            n += _verify_op(op.fallback, p, X)
    elif isinstance(op, X.CopShuffleJoinExec):
        n += _verify_shuffle_spec(op.spec, p)
    elif isinstance(op, X.HostSelection):
        schema = _schema_of(op.child)
        for cond in op.conditions:
            _check_expr(cond, schema, p)
    elif isinstance(op, X.HostProjection):
        schema = _schema_of(op.child)
        for e in op.exprs:
            _check_expr(e, schema, p)
    elif isinstance(op, (X.HostSort, X.HostTopN)):
        schema = _schema_of(op.child)
        for e, _desc in op.keys:
            _check_expr(e, schema, p)
    elif isinstance(op, X.HostHashJoin):   # + merge/index-lookup subclasses
        ls, rs = _schema_of(op.left), _schema_of(op.right)
        for lk, rk in op.eq_keys:
            if not (0 <= lk < len(ls)):
                _fail("column-ref", p, f"left join key {lk} out of range")
            if not (0 <= rk < len(rs)):
                _fail("column-ref", p, f"right join key {rk} out of range")
            lf, rf = _family(ls[lk]), _family(rs[rk])
            if lf != rf and "?" not in (lf, rf) \
                    and ls[lk].kind != K.NULL and rs[rk].kind != K.NULL:
                _fail("dtype-mismatch", p,
                      f"join keys disagree on representation family: "
                      f"{ls[lk]} vs {rs[rk]}")
        if out:
            if op.kind in ("semi", "anti"):
                want = len(ls)
            elif op.kind in ("inner", "left", "right", "cross"):
                want = len(ls) + len(rs)
            else:
                want = len(out)
            if len(out) != want:
                _fail("arity", p,
                      f"{op.kind} join of {len(ls)}+{len(rs)} columns "
                      f"declares {len(out)} outputs (expected {want})")
    elif isinstance(op, X.HostSetOp):
        kids = [k for k in op.children if k is not None]
        widths = {len(k.out_dtypes) for k in kids}
        if len(widths) > 1:
            _fail("arity", p,
                  f"set-operation children disagree on column count: "
                  f"{sorted(widths)}")
    return n


def _verify_shuffle_spec(spec: D.ShuffleJoinSpec, path) -> int:
    """Exchange-boundary agreement: both sides' chains, their declared
    schemas, the key exprs, and the post-exchange `top` chain must all
    describe the same columns — the mesh handshake of an MPP shuffle.
    The schema/boundary half lives in analysis/shardflow (the single
    source both this pass and the sharding-flow pass consume — thin
    delegation so the two passes report the same rule and never
    drift)."""
    p = path + ("ShuffleJoinSpec",)
    verify_dag(spec.left)
    verify_dag(spec.right)
    ls, rs = D.output_dtypes(spec.left), D.output_dtypes(spec.right)
    from .shardflow import verify_shuffle_boundary
    verify_shuffle_boundary(spec, path)
    _check_expr(spec.left_key, ls, p, device=True)
    _check_expr(spec.right_key, rs, p, device=True)
    verify_dag(spec.top)
    return 1


# --------------------------------------------------------------------- #
# sched admission verification (capacity-shape handshake)
# --------------------------------------------------------------------- #

def verify_task(task) -> None:
    """Admission-time contract check for a structured CopTask: the
    stacked device inputs must match the task key's capacity signature
    (the precondition for in-flight dedup and batch-slot coalescing to
    be shape-safe) and divide evenly over the mesh's shard axis.  Cheap:
    tuple/shape comparisons plus a memoized DAG walk — runs before the
    scheduler resolves (and thus traces/compiles) the program."""
    if task.key is None or task.dag is None:
        return
    p = ("sched", type(task.dag).__name__)
    verify_dag(task.dag)
    from ..sched.task import _shape_sig, mesh_fingerprint
    if task.key[1] != mesh_fingerprint(task.mesh):
        _fail("mesh-mismatch", p,
              "task key was built against a different mesh than the one "
              "it is being admitted to")
    if task.row_capacity < 0:
        _fail("capacity-shape", p,
              f"negative row capacity {task.row_capacity}")
    sig = _shape_sig(task.cols, task.counts)
    if task.key[3] != sig:
        _fail("capacity-shape", p,
              f"stacked input shapes {sig} disagree with the task key's "
              f"capacity signature {task.key[3]}")
    n_dev = int(task.mesh.devices.size)
    shapes = {tuple(v.shape[:2]) for v, _m in task.cols
              if getattr(v, "ndim", 0) >= 2}
    if len(shapes) > 1:
        _fail("capacity-shape", p,
              f"stacked columns disagree on (shards, capacity): "
              f"{sorted(shapes)}")
    for s, _cap in shapes:
        if n_dev and s % n_dev != 0:
            _fail("capacity-shape", p,
                  f"{s} shards do not divide over {n_dev} devices on the "
                  "shard axis")
    # sharding-flow handshake (analysis/shardflow): the task's mesh must
    # carry the exchange axis and its DAG must flow clean against the
    # mesh's typed-link topology (implicit reshards, merge routing,
    # psum limb-fence bound) — still pre-trace, still memoized
    from .shardflow import verify_task_sharding
    verify_task_sharding(task)
    # value-range handshake (analysis/valueflow): the task's DAG must
    # flow finite, int64-safe intervals — a digest the session proved at
    # plan time is a registry hit; an unknown digest re-flows from type
    # domains.  Still pre-trace, still memoized.
    from .valueflow import verify_task_values
    verify_task_values(task)
    if getattr(task, "donate", False):
        # donation-safety handshake (analysis/lifetime): a donating
        # task must be in an EPHEMERAL program class and its inputs
        # must not be live snapshot-cache residents
        from .lifetime import verify_task_donation
        verify_task_donation(task)


# --------------------------------------------------------------------- #
# cross-query fusion verification (the scheduler's fusion-group seam)
# --------------------------------------------------------------------- #

# rows-chain node kinds that may join a rows fusion group: pure scan
# chains only — joins bring aux inputs / extras the fused launch cannot
# carry per member
_ROWS_FUSABLE_NODES = (D.TableScan, D.Selection, D.Projection, D.Expand,
                       D.TopN, D.Limit)


def _rows_fusable(node: D.CopNode) -> bool:
    if not isinstance(node, _ROWS_FUSABLE_NODES):
        return False
    return all(_rows_fusable(c) for c in node.children())


def fusion_signature(dag: D.CopNode) -> Optional[tuple]:
    """Contract-level fusion class of a pushed cop DAG, or None when the
    plan cannot join a cross-query fusion group.  Structural only — no
    trace, no jax import: this is exactly the "checkable without tracing"
    substrate PR 2's contracts were built for.

    Fusable classes (all members of one group share the returned tuple):

    - ``('inprog-agg',)`` — an Aggregation whose whole merge happens
      in-program (SCALAR/DENSE) with no expanding join in the chain
      (extras drive a per-task regrow loop).
    - ``('segment-agg', num_buckets)`` — a SEGMENT (radix-partitioned
      high-NDV) aggregation: host-merged group tables fuse via a
      per-member sharded out_spec, but ONLY among identical bucket
      spaces — the bucket count is part of the signature, so tasks with
      incompatible bucket shapes refuse to group at the key level
      instead of silently degrading to per-program launches.
    - ``('scatter-agg', num_buckets, passes)`` — the SCATTER (multi-
      pass scatter radix partition) twin: bucket space AND priced pass
      count are both part of the class, so members always agree on the
      partition program shape (a regrown bucket space changes both).
    - ``('sort-agg', group_capacity)`` — a SORT aggregation whose
      group-table capacity is a concrete power of two (the capacity-
      bucketed shape classes of the fusion-breadth follow-on: the
      client's regrow discipline only ever produces pow2 capacities,
      so regrow-sized tasks land in shared classes instead of none).
      Capacity 0 (planner left sizing to the client) or a non-pow2
      capacity has no static shape class and stays unfusable.
    - ``('rows',)`` — an extras-free pure scan chain returning rows
      (fusion-breadth follow-on): members fuse with per-member output
      capacities (spmd.FusedRowsProgram)."""
    if not isinstance(dag, D.Aggregation):
        if not _rows_fusable(dag):
            return None
        try:
            verify_dag(dag)
        except PlanContractError:
            return None
        return ("rows",)
    if D.find_expand_join(dag) is not None:
        return None
    if dag.strategy == D.GroupStrategy.SORT:
        cap = dag.group_capacity
        if cap <= 0 or (cap & (cap - 1)) != 0:
            return None     # no static shape class to share
    try:
        verify_dag(dag)
    except PlanContractError:
        return None
    if dag.strategy == D.GroupStrategy.SORT:
        return ("sort-agg", dag.group_capacity)
    if dag.strategy == D.GroupStrategy.SCATTER:
        return ("scatter-agg", dag.num_buckets,
                D.radix_passes(dag.num_buckets))
    if dag.strategy == D.GroupStrategy.SEGMENT:
        return ("segment-agg", dag.num_buckets)
    if dag.narrow_sums:
        # proven-narrow members only fuse with members proving the SAME
        # slots narrow: the fused leaves' state layouts (single word vs
        # limb pair) are baked into the traced program
        return ("agg-narrow", dag.narrow_sums)
    return ("inprog-agg",)


def verify_fusion_group(tasks: Sequence) -> None:
    """Pre-launch contract check of a fusion group: every member must be
    individually fusable and all members must agree on mesh fingerprint,
    capacity signature (stacked input shapes + dtypes), shared scan
    inputs, and empty aux — the preconditions for computing N payloads
    from one scan pass to be shape-safe AND bit-identical to N solo
    runs.  Raises PlanContractError; the scheduler falls back to
    unfused per-program launches on refusal."""
    p = ("sched", "FusedDag")
    if len(tasks) < 2:
        _fail("fusion-group", p, "fusion group needs >= 2 members")
    lead = tasks[0]
    lead_sig = fusion_signature(lead.dag) if lead.dag is not None else None
    for t in tasks:
        if t.key is None or t.dag is None:
            _fail("fusion-group", p, "opaque task in a fusion group")
        sig = fusion_signature(t.dag)
        if sig is None:
            _fail("fusion-class", p,
                  f"member {type(t.dag).__name__} is not in a fusable "
                  "contract class")
        if sig != lead_sig:
            # e.g. a SEGMENT member whose bucket space differs from the
            # group's: refuse loudly instead of silently degrading
            _fail("fusion-class", p,
                  f"member fusion signature {sig} disagrees with the "
                  f"group's {lead_sig} (incompatible strategy or bucket "
                  "shape)")
        if t.key[1] != lead.key[1]:
            _fail("mesh-mismatch", p,
                  "fusion group members were keyed against different "
                  "meshes")
        if t.key[3] != lead.key[3]:
            _fail("capacity-shape", p,
                  f"member capacity signature {t.key[3]} disagrees with "
                  f"the group's {lead.key[3]} (shapes/dtypes must be "
                  "byte-identical to share one scan)")
        if t.input_token != lead.input_token:
            _fail("fusion-input", p,
                  "members read different snapshot residents — a fused "
                  "program computes every payload from ONE scan")
        if t.aux != ():
            _fail("fusion-input", p,
                  "host-materialized aux inputs (join builds) do not "
                  "fuse across queries")


__all__ = ["PlanContractError", "verify_plan", "verify_dag", "verify_task",
           "fusion_signature", "verify_fusion_group"]
