"""shardflow: sharding-layout & collective-transfer abstract interpreter.

Reference analog: TiFlash's MPP exchange discipline (PAPER.md) — a plan
fragment is only well-formed against the cluster it runs on: every
ExchangeSender's partition column set, schema, and target topology are
checked when the fragment tree is cut, not discovered mid-stream.  Here
the "cluster" is a jax mesh (plus the declared host factorization of
parallel/topology), the fragments are cop DAGs / shuffle / window specs,
and the exchanges are collectives — so the checks move BEFORE trace
time, the same no-device-touch discipline as copcost (shape/memory) and
coplife (buffer lifetime).  DrJAX (PAPERS.md) is the reference for
keeping the MapReduce-style collective decomposition visible to static
analysis instead of burying it in the compiled program.

The interpreter walks built cop/exchange DAGs edge-by-edge carrying an
abstract ``Layout`` per buffer (which mesh axes partition its rows,
which it is replicated over, how much shard padding it carries) and
verifies every collective against the topology:

- ``SHARD-AXIS-UNKNOWN``      a collective's mesh axis does not exist on
                              the topology the program will launch onto,
- ``SHARD-IMPLICIT-RESHARD``  an operator consumes a layout other than
                              the one its child produced (e.g. a
                              row-wise operator over post-psum
                              replicated states) — the hidden
                              all-to-all XLA would silently insert,
- ``SHARD-MERGE-COORDINATOR`` a host-merged group table routed through
                              ONE coordinator host on a multi-host
                              topology instead of per host,
- ``SHARD-SPLIT-INDIVISIBLE`` the all_to_all split/concat factorization
                              does not divide the device space evenly,
- ``SHARD-PSUM-FENCE``        an in-program (hi, lo) limb psum whose
                              global row capacity exceeds the 2^31
                              int64-exactness bound — the runtime
                              OverflowError fence, proven pre-trace,
- ``COST-DCI-BLOWUP``         a shuffle exchange whose statically
                              priced cross-host bytes dwarf the data it
                              repartitions (an Expand/blow-up in an
                              exchange chain ships the table across DCI
                              many times over).

All rules raise structured ``PlanContractError``s, so the session plan
path (``_plan_select``) and sched admission (``submit`` ->
``contracts.verify_task``) reject violating plans exactly like every
other contract violation — before any jit/trace.  The same walk rolls
transfer bytes up PER LINK CLASS (intra / ici / dci) through
``copcost.LaunchCost.transfer_breakdown``, which makes HBM admission,
RU pricing (rc/pricing's DCI rate), fusion caps, and calibration
topology-aware with no runtime change.

The shuffle-spec exchange-boundary checks (side schema vs top-chain
leaf scan) moved here from contracts.py as the single source — the
verify_plan pass delegates, so the two passes cannot drift.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

from ..copr import dag as D
from ..parallel.topology import (MERGE_COORDINATOR, MERGE_PER_HOST,
                                 SHARD_AXIS, MeshTopology,
                                 TransferBreakdown, _as_int, topology_for)
from ..types import dtypes as dt
from .contracts import PlanContractError, _compatible, _fail
from . import copcost as C

# ------------------------------------------------------------------ #
# rule ids (doubling as gate finding rules — the COST-* discipline)
# ------------------------------------------------------------------ #

RULE_AXIS_UNKNOWN = "SHARD-AXIS-UNKNOWN"
RULE_IMPLICIT_RESHARD = "SHARD-IMPLICIT-RESHARD"
RULE_MERGE_COORDINATOR = "SHARD-MERGE-COORDINATOR"
RULE_SPLIT_INDIVISIBLE = "SHARD-SPLIT-INDIVISIBLE"
RULE_PSUM_FENCE = "SHARD-PSUM-FENCE"
RULE_DCI_BLOWUP = "COST-DCI-BLOWUP"

# a shuffle whose cross-host exchange bytes exceed this multiple of the
# resident bytes it repartitions ships the table across DCI many times
# over — a repartition storm, not a join (gate finding + pre-trace
# rejection; baseline-able like every COST- rule)
DCI_BLOWUP_MAX = 16.0

# the (hi, lo) limb psum stays int64-exact only below this many global
# contributing rows — the runtime fence (spmd/shuffle OverflowError)
# proven statically when the layout's global capacity is known
PSUM_LIMB_ROWS = 2 ** 31

# validated prediction band: predicted per-link exchange bytes of the
# shuffle-join path vs the traced program's live send buffers on the
# 8-vdev mesh (tests/test_shardflow.py pins it — the copcost
# exact-resident-bytes precedent, loosened for capacity regrow)
SHARD_TOLERANCE = 4.0

# the fake multi-host factorization tier-1 and the gate analyze under:
# a reshaped (host=2, device=4) view of the 8-vdev CPU mesh
GATE_VIEW_HOSTS = 2


# ------------------------------------------------------------------ #
# the abstract layout
# ------------------------------------------------------------------ #

@dataclass(frozen=True)
class Layout:
    """Abstract device layout of one buffer while it flows through a
    program: ``axes`` are the mesh axes partitioning its rows (empty =
    every device holds the whole buffer), ``replicated`` the axes it is
    replicated over (post-psum states), ``shard_pad`` the
    pad-to-divide rows placement added."""
    axes: Tuple[str, ...] = (SHARD_AXIS,)
    replicated: Tuple[str, ...] = ()
    shard_pad: int = 0

    @property
    def row_sharded(self) -> bool:
        return SHARD_AXIS in self.axes


ROW_SHARDED = Layout()
REPLICATED = Layout(axes=(), replicated=(SHARD_AXIS,))


def _layout_str(layout: Layout) -> str:
    if layout.row_sharded:
        return f"sharded({','.join(layout.axes)})"
    if layout.replicated:
        return f"replicated({','.join(layout.replicated)})"
    return "unpartitioned"


# ------------------------------------------------------------------ #
# DAG flow (memoized on the frozen dag + topology)
# ------------------------------------------------------------------ #

def _agg_needs_limb_fence(agg: D.Aggregation) -> bool:
    """Mirror of the spmd/shuffle program predicate: an in-program psum
    of (hi, lo) SUM limb states needs the 2^31 global-capacity fence;
    float sums, counts, host-merged programs, and valueflow-proven
    narrow SUMs (whole-table no-wrap proof subsumes the row fence) are
    exempt."""
    if agg.strategy in D.HOST_MERGE_STRATEGIES:
        return False
    K = dt.TypeKind
    return any(a.func == D.AggFunc.SUM and a.arg is not None
               and a.arg.dtype is not None
               and a.arg.dtype.kind not in (K.FLOAT64, K.FLOAT32)
               and i not in agg.narrow_sums
               for i, a in enumerate(agg.aggs))


def _flow(node: D.CopNode, topo: MeshTopology, path: tuple,
          merge_route: str, global_rows: int) -> Layout:
    """Flow one node: verify its consumed layout against what its child
    produced, return the layout it emits."""
    p = path + (type(node).__name__,)

    if isinstance(node, D.TableScan):
        # the scan aliases the stacked resident upload: row-sharded
        return ROW_SHARDED

    if isinstance(node, D.FusedDag):
        out = ROW_SHARDED
        for m in node.members:
            out = _flow(m, topo, p, merge_route, global_rows)
        return out

    kids = node.children()
    child_layout = (_flow(kids[0], topo, p, merge_route, global_rows)
                    if kids else ROW_SHARDED)

    # every cop operator below computes row-wise over the sharded flat
    # batch; consuming anything else is a hidden reshard XLA would
    # silently lower to an all-to-all/all-gather behind the plan's back
    if not child_layout.row_sharded:
        _fail(RULE_IMPLICIT_RESHARD, p,
              f"operator consumes a row-sharded({SHARD_AXIS}) batch but "
              f"its child produces {_layout_str(child_layout)} — an "
              "undeclared reshard XLA would insert as a hidden "
              "collective; route the exchange explicitly")

    if isinstance(node, D.Aggregation):
        if node.strategy in D.HOST_MERGE_STRATEGIES:
            # per-device group tables leave the device for the host
            # merge: on a multi-host topology the merge must route per
            # host — one coordinator host pulling every remote device's
            # states over DCI recreates the single-coordinator
            # bottleneck the MPP exchange layer exists to avoid
            if topo.multi_host and merge_route == MERGE_COORDINATOR:
                _fail(RULE_MERGE_COORDINATOR, p,
                      f"host-merged {node.strategy.value} group table "
                      f"routed through one coordinator host on a "
                      f"{topo.n_hosts}-host topology: "
                      f"{topo.n_devices - topo.devices_per_host} of "
                      f"{topo.n_devices} device states would cross DCI "
                      "— route the merge per host")
            return Layout(axes=(SHARD_AXIS,))   # (D, ...) state tables
        # in-program merge: a psum collective over the shard axis
        if not topo.has_axis(SHARD_AXIS):
            _fail(RULE_AXIS_UNKNOWN, p,
                  f"aggregate merge collective runs over mesh axis "
                  f"{SHARD_AXIS!r} but the target topology only has "
                  f"axes {topo.axis_names} — the program would fail "
                  "at trace (or bind the wrong axis) on this mesh")
        if _agg_needs_limb_fence(node) and global_rows >= PSUM_LIMB_ROWS:
            _fail(RULE_PSUM_FENCE, p,
                  f"in-program (hi, lo) limb psum over {global_rows} "
                  f"global rows exceeds the {PSUM_LIMB_ROWS} "
                  "int64-exactness bound — the runtime fence would "
                  "refuse this launch; repartition or host-merge")
        return REPLICATED

    return child_layout


@functools.lru_cache(maxsize=1024)
def _flow_cached(dag: D.CopNode, topo: MeshTopology, merge_route: str,
                 global_rows: int, path: tuple) -> Layout:
    return _flow(dag, topo, path, merge_route, global_rows)


def verify_dag_sharding(dag: D.CopNode, topo: MeshTopology, *,
                        merge_route: str = MERGE_PER_HOST,
                        global_rows: int = 0, path: tuple = ()) -> Layout:
    """Flow one cop DAG against a topology; raises PlanContractError
    with a SHARD-* rule on the first violation, returns the DAG's
    output Layout.  Memoized on the frozen (dag, topo) pair — repeated
    admission of one program costs a dict hit."""
    _verify_topology(topo, path)
    return _flow_cached(dag, topo, merge_route, _as_int(global_rows), path)


def _verify_topology(topo: MeshTopology, path: tuple) -> None:
    if topo.n_devices % topo.n_hosts != 0:
        # MeshTopology's ctor refuses this; the check stays for
        # hand-built views that bypassed it
        _fail(RULE_SPLIT_INDIVISIBLE, path,
              f"{topo.n_devices} devices do not divide over "
              f"{topo.n_hosts} hosts: all_to_all split/concat would "
              "mis-route whole buckets")
    if not topo.has_axis(SHARD_AXIS):
        _fail(RULE_AXIS_UNKNOWN, path,
              f"programs exchange over mesh axis {SHARD_AXIS!r} but "
              f"the target topology only has axes {topo.axis_names}")


# ------------------------------------------------------------------ #
# exchange-boundary agreement (single source; contracts delegates)
# ------------------------------------------------------------------ #

def verify_shuffle_boundary(spec: D.ShuffleJoinSpec, path: tuple) -> None:
    """Exchange-boundary agreement of a shuffle-join spec: both sides'
    declared schemas must match their chains' outputs, and the
    post-exchange ``top`` chain's leaf scan must read the joined schema
    — the mesh handshake of an MPP shuffle.  Moved here from
    contracts._verify_shuffle_spec (PR 2) as the single source; the
    plan-contract pass delegates, so the two passes report the same
    ``exchange-mismatch`` rule and can never drift."""
    p = path + ("ShuffleJoinSpec",)
    ls, rs = D.output_dtypes(spec.left), D.output_dtypes(spec.right)
    if tuple(spec.left_dtypes) != tuple(ls):
        _fail("exchange-mismatch", p,
              f"declared left exchange schema ({len(spec.left_dtypes)} "
              f"cols) != left chain output ({len(ls)} cols)")
    if tuple(spec.right_dtypes) != tuple(rs):
        _fail("exchange-mismatch", p,
              f"declared right exchange schema ({len(spec.right_dtypes)} "
              f"cols) != right chain output ({len(rs)} cols)")
    joined = ls + rs if spec.kind in ("inner", "left") else ls
    top_leaf = spec.top
    while top_leaf.children():
        top_leaf = top_leaf.children()[0]
    if isinstance(top_leaf, D.TableScan):
        for off, t in zip(top_leaf.col_offsets, top_leaf.col_dtypes):
            if off >= len(joined):
                _fail("exchange-mismatch", p,
                      f"post-join chain reads column {off} of a "
                      f"{len(joined)}-column joined schema")
            if not _compatible(t, joined[off]):
                _fail("exchange-mismatch", p,
                      f"post-join chain reads column {off} as {t} but "
                      f"the exchange produces {joined[off]}")


# ------------------------------------------------------------------ #
# exchange transfer attribution (shared size algebra with copcost)
# ------------------------------------------------------------------ #

def _scan_of(node: D.CopNode) -> Optional[D.TableScan]:
    for n in D.iter_nodes(node):
        if isinstance(n, D.TableScan):
            return n
    return None


def shuffle_transfer(spec: D.ShuffleJoinSpec, llayout, rlayout,
                     lwidths, rwidths,
                     topo: MeshTopology) -> TransferBreakdown:
    """Per-link bytes of the two all_to_all exchange edges of one
    shuffle join, from contracts alone: each side ships its CHAIN
    OUTPUT rows (an Expand in the chain multiplies what the scan read),
    bucketed by the client's capacity formula so the prediction matches
    the runtime send buffers (SHARD_TOLERANCE-validated)."""
    lb, rb = C.shuffle_exchange_buckets(spec, llayout, rlayout,
                                        lwidths, rwidths, topo.n_devices)
    return topo.split_all_to_all(lb).combined(topo.split_all_to_all(rb))


def _resident_bytes(spec: D.ShuffleJoinSpec, llayout, rlayout) -> int:
    """Resident scan bytes of both shuffle sides — the denominator of
    the DCI-blowup ratio (how many times over does the exchange ship
    the data it repartitions?)."""
    total = 0
    for chain, layout in ((spec.left, llayout), (spec.right, rlayout)):
        scan = _scan_of(chain)
        w = C._schema_width(scan.col_dtypes) if scan is not None else 8
        total += layout.padded_rows * w
    return total


def verify_spec_sharding(spec: D.ShuffleJoinSpec, topo: MeshTopology, *,
                         llayout=None, rlayout=None,
                         lwidths=None, rwidths=None,
                         merge_route: str = MERGE_PER_HOST,
                         path: tuple = ()) -> TransferBreakdown:
    """Flow a shuffle-join spec: boundary agreement, both chains, the
    exchange edges (axis + divisibility), the post-exchange top chain
    (incl. its merge routing), and — when the side layouts are known —
    the DCI-blowup ratio.  Returns the exchange's per-link bytes."""
    p = path + ("ShuffleJoinSpec",)
    _verify_topology(topo, p)
    verify_shuffle_boundary(spec, path)
    for side in (spec.left, spec.right):
        _flow_cached(side, topo, merge_route, 0, p)
    # the exchange re-shards rows by hash(key): the top chain consumes
    # a row-sharded partition whatever the sides produced
    _flow_cached(spec.top, topo, merge_route, 0, p)
    if llayout is None or rlayout is None:
        return TransferBreakdown()
    bd = shuffle_transfer(spec, llayout, rlayout, lwidths, rwidths, topo)
    resident = _resident_bytes(spec, llayout, rlayout)
    if topo.multi_host and bd.dci > DCI_BLOWUP_MAX * max(resident, 1):
        _fail(RULE_DCI_BLOWUP, p,
              f"shuffle exchange ships {bd.dci} cross-host bytes for "
              f"{resident} resident bytes "
              f"({bd.dci / max(resident, 1):.0f}x > "
              f"{DCI_BLOWUP_MAX:.0f}x): the repartition crosses DCI "
              "many times over the data it moves — broadcast the small "
              "side or pre-aggregate before the exchange")
    return bd


def verify_window_sharding(spec: D.WindowShuffleSpec, topo: MeshTopology,
                           *, merge_route: str = MERGE_PER_HOST,
                           path: tuple = ()) -> None:
    """Flow a window-repartition spec: the child chain feeds an
    all_to_all keyed on PARTITION BY; the post-exchange sort/segment
    work is device-local row-sharded output."""
    p = path + ("WindowShuffleSpec",)
    _verify_topology(topo, p)
    _flow_cached(spec.child, topo, merge_route, 0, p)


# ------------------------------------------------------------------ #
# admission-time verification (sched submit, via contracts.verify_task)
# ------------------------------------------------------------------ #

def verify_task_sharding(task) -> None:
    """Admission-time shardflow check of a structured CopTask: the
    task's mesh must carry the exchange axis, and its DAG must flow
    clean against the mesh's topology (declared host view included) —
    before the drain could resolve (trace) a program.  Cheap: one
    memoized flow walk."""
    if task.dag is None or task.mesh is None:
        return
    topo = topology_for(task.mesh)
    global_rows = 0
    for v, _m in task.cols or ():
        if getattr(v, "ndim", 0) >= 2:
            # array METADATA only — shapes are host ints, no sync
            global_rows = v.shape[0] * v.shape[1]
            break
    verify_dag_sharding(task.dag, topo, global_rows=global_rows,
                        path=("sched",))


# ------------------------------------------------------------------ #
# plan-level verification + transfer rollup (session / gate / EXPLAIN)
# ------------------------------------------------------------------ #

def verify_plan_sharding(phys, topo: Optional[MeshTopology] = None,
                         n_devices: int = 8,
                         merge_route: str = MERGE_PER_HOST) -> int:
    """Flow every device-program operator of a built physical plan
    against ``topo`` (default: the declared host view over
    ``n_devices``).  Returns the number of device operators flowed;
    raises PlanContractError on the first violation.  Host-only plans
    flow zero operators and always pass."""
    if topo is None:
        topo = topology_for(n_devices=n_devices)
    flowed = 0
    stack = [phys]
    while stack:
        op = stack.pop()
        name = type(op).__name__
        p = (name,)
        if name in ("CopTaskExec", "CopJoinTaskExec"):
            # layout sizing is best-effort (a snapshot may not be
            # materializable at plan time); the structural flow checks
            # never depend on it
            try:
                snap = C._op_snapshot(op)
                rows = C.snapshot_layout(snap, topo.n_devices).padded_rows
            except (AttributeError, TypeError, KeyError):
                rows = 0
            verify_dag_sharding(op.dag, topo, merge_route=merge_route,
                                global_rows=rows, path=p)
            flowed += 1
        elif name == "CopShuffleJoinExec":
            try:
                lsnap = op.left_table.snapshot()
                rsnap = op.right_table.snapshot()
                layouts = dict(
                    llayout=C.snapshot_layout(lsnap, topo.n_devices),
                    rlayout=C.snapshot_layout(rsnap, topo.n_devices),
                    lwidths=C.snapshot_scan_widths(lsnap),
                    rwidths=C.snapshot_scan_widths(rsnap))
            except (AttributeError, TypeError, KeyError):
                layouts = {}
            verify_spec_sharding(op.spec, topo, merge_route=merge_route,
                                 path=p, **layouts)
            flowed += 1
        elif name == "CopWindowExec":
            verify_window_sharding(op.spec, topo,
                                   merge_route=merge_route, path=p)
            flowed += 1
        for c in getattr(op, "children", []) or []:
            if c is not None:
                stack.append(c)
        fb = getattr(op, "fallback", None)
        if fb is not None:
            stack.append(fb)
    return flowed


def plan_transfer(phys, topo: Optional[MeshTopology] = None,
                  n_devices: int = 8) -> TransferBreakdown:
    """Per-link transfer bytes of a whole built plan under ``topo`` —
    the rollup the EXPLAIN footer, --transfer-report, and the bench
    attribution read."""
    if topo is None:
        topo = topology_for(n_devices=n_devices)
    cost = C.plan_cost(phys, topo.n_devices, topology=topo)
    return TransferBreakdown.from_tuple(cost.transfer_breakdown)


# ------------------------------------------------------------------ #
# gate pass + report
# ------------------------------------------------------------------ #

def _gate_topologies(n_devices: int):
    """The single-host view plus the fake multi-host view the gate and
    tier-1 analyze under (host=2 over the 8-vdev CPU mesh)."""
    views = [MeshTopology((SHARD_AXIS,), n_devices, 1)]
    if n_devices % GATE_VIEW_HOSTS == 0:
        views.append(MeshTopology((SHARD_AXIS,), n_devices,
                                  GATE_VIEW_HOSTS))
    return views


def shard_findings(plans, n_devices: int = 8) -> list:
    """SHARD-*/COST-DCI-BLOWUP findings over (sql, built-plan) pairs —
    the shardflow half of the analysis gate, under both the native
    single-host view and the host=2 view.  Finding keys are stable
    (corpus position + rule) so they baseline exactly like lint/cost
    findings."""
    from .lint import Finding
    out = []
    for idx, (sql, phys) in enumerate(plans):
        qid = f"corpus/q{idx:02d}"
        one_line = " ".join(sql.split())[:60]
        for topo in _gate_topologies(n_devices):
            try:
                verify_plan_sharding(phys, topo)
            except PlanContractError as e:
                sym = e.path[-1] if e.path else "plan"
                out.append(Finding(
                    e.rule, qid, 0, sym,
                    f"[hosts={topo.n_hosts}] {e.detail} ({one_line})"))
                break
    return out


def transfer_report(plans, n_devices: int = 8) -> str:
    """Per-corpus-query per-link transfer table (``--transfer-report``)
    under the host=2 view — the static half of the ROADMAP multi-host
    success metric (per-link transfer attribution)."""
    topo = MeshTopology((SHARD_AXIS,), n_devices,
                        GATE_VIEW_HOSTS
                        if n_devices % GATE_VIEW_HOSTS == 0 else 1)
    fmt = C.format_bytes
    lines = [f"per-link transfer under a (host={topo.n_hosts}, "
             f"device={topo.devices_per_host}) view of {n_devices} "
             "devices",
             f"{'query':<44} {'intra':>10} {'ici':>10} {'dci':>10}"]
    for idx, (sql, phys) in enumerate(plans):
        bd = plan_transfer(phys, topo)
        one_line = " ".join(sql.split())
        label = f"q{idx:02d} {one_line[:39]}"
        lines.append(f"{label:<44} {fmt(bd.intra):>10} "
                     f"{fmt(bd.ici):>10} {fmt(bd.dci):>10}")
    return "\n".join(lines)


__all__ = ["Layout", "ROW_SHARDED", "REPLICATED",
           "verify_dag_sharding", "verify_spec_sharding",
           "verify_window_sharding", "verify_task_sharding",
           "verify_plan_sharding", "verify_shuffle_boundary",
           "shuffle_transfer", "plan_transfer", "shard_findings",
           "transfer_report",
           "RULE_AXIS_UNKNOWN", "RULE_IMPLICIT_RESHARD",
           "RULE_MERGE_COORDINATOR", "RULE_SPLIT_INDIVISIBLE",
           "RULE_PSUM_FENCE", "RULE_DCI_BLOWUP",
           "DCI_BLOWUP_MAX", "PSUM_LIMB_ROWS", "SHARD_TOLERANCE",
           "GATE_VIEW_HOSTS"]
