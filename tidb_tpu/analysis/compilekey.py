"""copforge key derivation: restart-stable program variant keys.

Reference analog: the digest-keyed persisted-executable pattern of
compiler-first serving engines (PAPERS.md: Flare keeps compilation off
the hot path; the O(1)-caching inference stack keys persisted
executables by a content digest).  ``copr.dag.dag_digest`` is ``hash()``
of a frozen dataclass tree — perfect for the in-process jit cache, but
Python salts string hashes per process, so that digest DIES with the
process.  A compiled executable persisted across restarts needs a key
every field of which is derivable from content alone.

This module lives next to ``lifetime.py`` deliberately: the DonationPlan
is part of the variant key BY CONSTRUCTION (``variant_key`` derives the
donation signature itself from the dag + program shape), so a donating
and a non-donating build of the same plan can never collide in the
persistent cache — jax bakes input aliasing into the executable, and
loading the wrong variant would delete the caller's arrays.

Key anatomy (every part checked again at load time — a stale or
mismatched entry is rejected, never silently deserialized):

- ``digest``        restart-stable sha256 of the canonical dag encoding
- ``family``        same, with regrow capacities (group_capacity /
                    num_buckets / join out_capacity) zeroed — the warm
                    pool's capacity-reuse index
- ``mesh_fp``       axis names + shape + device ids (sched/task
                    fingerprint, hashed)
- ``capacity_sig``  program shape class: builder kind, row capacity,
                    batch slot count
- ``donation_sig``  DonationPlan slot classes + donate_argnums actually
                    baked into the executable
- ``backend_fp``    jax/jaxlib versions + platform + device kind +
                    device count (an XLA upgrade invalidates everything)
"""

from __future__ import annotations

import dataclasses
import enum
import functools
import hashlib
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from ..copr import dag as D

# fields that only size regrow loops: two dags differing ONLY here run
# the same plan family, so the client's paging/regrow re-entry can round
# up to a capacity the warm pool already holds
_CAPACITY_FIELDS = ("group_capacity", "num_buckets", "out_capacity")


def _encode(obj, h, skip_capacity: bool) -> None:
    """Feed one canonical byte stream per value into hasher ``h``.
    Deterministic across processes: no ``id()``, no ``hash()``, no
    unsorted dict iteration — the TPU-DIGEST discipline."""
    if obj is None:
        h.update(b"N")
    elif isinstance(obj, bool):
        h.update(b"b1" if obj else b"b0")
    elif isinstance(obj, int):
        h.update(b"i" + str(obj).encode())
    elif isinstance(obj, float):
        h.update(b"f" + repr(obj).encode())
    elif isinstance(obj, str):
        h.update(b"s" + obj.encode("utf-8", "surrogatepass"))
    elif isinstance(obj, bytes):
        h.update(b"y" + obj)
    elif isinstance(obj, enum.Enum):
        h.update(b"e" + type(obj).__name__.encode())
        _encode(obj.value, h, skip_capacity)
    elif isinstance(obj, np.ndarray):
        h.update(b"a" + str(obj.shape).encode() + obj.dtype.str.encode())
        h.update(np.ascontiguousarray(obj).tobytes())
    elif isinstance(obj, np.generic):
        h.update(b"g" + obj.dtype.str.encode() + obj.tobytes())
    elif isinstance(obj, (tuple, list)):
        h.update(b"t" + str(len(obj)).encode())
        for v in obj:
            _encode(v, h, skip_capacity)
    elif isinstance(obj, (set, frozenset)):
        h.update(b"S")
        for v in sorted(repr(x) for x in obj):
            h.update(v.encode())
    elif dataclasses.is_dataclass(obj):
        h.update(b"d" + type(obj).__name__.encode())
        for f in dataclasses.fields(obj):
            if skip_capacity and f.name in _CAPACITY_FIELDS:
                continue
            h.update(b"." + f.name.encode())
            _encode(getattr(obj, f.name), h, skip_capacity)
    else:
        # last resort (plain value objects): repr is assumed canonical
        h.update(b"r" + repr(obj).encode())


@functools.lru_cache(maxsize=2048)
def stable_digest(dag: D.CopNode) -> str:
    """Restart-stable content digest of a cop DAG (hex, 16 chars) —
    the persistent twin of ``copr.dag.dag_digest``."""
    h = hashlib.sha256()
    _encode(dag, h, skip_capacity=False)
    return h.hexdigest()[:16]


@functools.lru_cache(maxsize=2048)
def family_digest(dag: D.CopNode) -> str:
    """Digest with regrow capacities zeroed: every capacity variant of
    one plan shares a family, so the client can prefer a capacity the
    warm pool already compiled over the minimal pow2 regrow step."""
    h = hashlib.sha256()
    _encode(dag, h, skip_capacity=True)
    return h.hexdigest()[:16]


def mesh_fingerprint_hex(mesh) -> str:
    """Hashed form of the sched/task mesh fingerprint (axis names +
    shape + global device ids) — two Mesh objects over the same chips
    fingerprint identically across rebuilds AND restarts."""
    if mesh is None:
        return "nomesh"
    fp = (tuple(mesh.axis_names), tuple(mesh.devices.shape),
          tuple(int(d.id) for d in mesh.devices.reshape(-1)))
    return hashlib.sha256(repr(fp).encode()).hexdigest()[:16]


def backend_fingerprint(mesh=None) -> str:
    """jax/jaxlib versions + platform + device kind + device count: an
    XLA or topology change invalidates every persisted executable."""
    import jax
    try:
        import jaxlib
        jl = getattr(jaxlib, "__version__", "?")
    except ImportError:       # pragma: no cover - jaxlib rides jax
        jl = "?"
    devs = (mesh.devices.reshape(-1) if mesh is not None
            else np.array(jax.devices()).reshape(-1))
    d0 = devs[0]
    return "/".join((jax.__version__, jl, d0.platform,
                     str(getattr(d0, "device_kind", "")), str(len(devs))))


@dataclass(frozen=True)
class CompileKey:
    """Builder-level variant key of one cacheable device program.  The
    per-call input shapes are appended by the cache (``entry_hex``), so
    one key covers every shape the builder is invoked with."""
    digest: str          # stable dag digest
    family: str          # capacity-stripped digest (warm-capacity index)
    mesh_fp: str
    capacity_sig: str    # program kind / row capacity / slot count
    donation_sig: str    # DonationPlan classes + baked donate_argnums
    backend_fp: str
    capacity: int = 0    # regrow knob value (family capacity index)

    def parts(self) -> dict:
        """Header fields re-verified at load time — the digest +
        mesh-fingerprint + donation-plan triple the TPU-COMPILE-KEY
        gate rule requires every cache write to carry."""
        return {"digest": self.digest, "family": self.family,
                "mesh_fp": self.mesh_fp,
                "capacity_sig": self.capacity_sig,
                "donation_sig": self.donation_sig,
                "backend_fp": self.backend_fp,
                "capacity": self.capacity}

    def entry_hex(self, shape_sig: str) -> str:
        """Identity of ONE compiled executable: the variant key plus the
        concrete call signature (leaf shapes/dtypes + pytree structure)."""
        h = hashlib.sha256()
        for part in (self.digest, self.family, self.mesh_fp,
                     self.capacity_sig, self.donation_sig,
                     self.backend_fp, shape_sig):
            h.update(part.encode())
            h.update(b"|")
        return h.hexdigest()[:32]


def shape_signature(args) -> str:
    """Canonical call signature: pytree structure + per-leaf
    (shape, dtype, weak_type).  Shardings are deliberately excluded —
    a Compiled executable accepts matching avals whatever the arrays'
    placement, and the cache falls back to the jit path on the rare
    backend that refuses."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = [str(treedef)]
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is None:
            arr = np.asarray(leaf)
            shape, dt_, weak = arr.shape, arr.dtype.str, True
        else:
            dt_ = str(getattr(leaf, "dtype", ""))
            weak = bool(getattr(leaf, "weak_type", False))
        sig.append(f"{tuple(shape)}:{dt_}:{int(weak)}")
    return ";".join(sig)


def variant_key(dag: D.CopNode, mesh, program: str,
                row_capacity: int = 0, n_slots: int = 0,
                donate_argnums: Tuple[int, ...] = (),
                extra: Tuple = (),
                n_devices: Optional[int] = None) -> CompileKey:
    """Derive the persistent variant key of one spmd builder.  The
    donation signature comes from the DAG's own DonationPlan — callers
    cannot omit it, so the donating variant keys apart by construction.
    ``extra`` carries builder knobs outside the dag (fused-rows member
    capacities)."""
    from .lifetime import donation_plan
    plan = donation_plan(dag, program)
    donation_sig = (f"{plan.describe()}|argnums="
                    f"{tuple(int(a) for a in donate_argnums)}")
    if isinstance(dag, D.Aggregation):
        capacity = dag.state_capacity or 0
    elif isinstance(dag, D.FusedDag):
        capacity = 0
    else:
        capacity = int(row_capacity)
    cap_sig = (f"{program}/rc={int(row_capacity)}/k={int(n_slots)}"
               f"/x={tuple(extra)}")
    mesh_fp = (mesh_fingerprint_hex(mesh) if mesh is not None
               else f"plan/{n_devices or 0}")
    backend = (backend_fingerprint(mesh) if mesh is not None
               else f"plan/{n_devices or 0}")
    return CompileKey(digest=stable_digest(dag), family=family_digest(dag),
                      mesh_fp=mesh_fp, capacity_sig=cap_sig,
                      donation_sig=donation_sig, backend_fp=backend,
                      capacity=capacity)


# ------------------------------------------------------------------ #
# gate report (--cache-report)
# ------------------------------------------------------------------ #

def cache_report(plans, n_devices: int = 8) -> str:
    """Per-corpus-query key/variant/bytes table: what the compile cache
    would key each device program on, from built plans alone (no trace,
    no device).  Rides ``python -m tidb_tpu.analysis --cache-report``."""
    from .copcost import format_bytes, plan_cost
    from .lifetime import _plan_cop_ops
    lines = [f"{'query':<40} {'digest':>16} {'family':>16} "
             f"{'variant':>24} {'bytes':>10}"]
    keyed = 0
    for idx, (sql, phys) in enumerate(plans):
        one_line = " ".join(sql.split())
        label = f"q{idx:02d} {one_line[:35]}"
        ops = _plan_cop_ops(phys)
        cost = plan_cost(phys, n_devices)
        if not ops:
            lines.append(f"{label:<40} {'-':>16} {'-':>16} "
                         f"{'host-only':>24} {'-':>10}")
            continue
        for _op, dag in ops:
            from .lifetime import donation_plan
            plan = donation_plan(dag, "solo")
            key = variant_key(dag, None, "solo", n_devices=n_devices,
                              donate_argnums=plan.donate_argnums)
            keyed += 1
            variant = f"solo cap={key.capacity} don={len(plan.donate_argnums)}"
            lines.append(
                f"{label:<40} {key.digest:>16} {key.family:>16} "
                f"{variant:>24} {format_bytes(cost.peak_hbm_bytes):>10}")
            label = ""
    lines.append(f"compile keys: {keyed} device programs keyed over "
                 f"{len(plans)} corpus plans")
    return "\n".join(lines)


__all__ = ["CompileKey", "stable_digest", "family_digest",
           "mesh_fingerprint_hex", "backend_fingerprint",
           "shape_signature", "variant_key", "cache_report"]
