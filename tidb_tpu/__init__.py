"""tidb_tpu — a TPU-native distributed SQL framework with TiDB's capabilities.

A MySQL-compatible SQL layer whose coprocessor pushdown path executes as
XLA-compiled kernels on TPU: vectorized expression evaluation and the
Selection/HashAgg/TopN operator pipeline run over Arrow-layout column shards,
region-level cop tasks fan out as SPMD (``shard_map``) programs across a TPU
mesh with partial aggregates merged via ``jax.lax.psum``.

This is an idiomatic JAX/XLA design, not a port of the Go reference
(jebter/tidb).  Layer map (reference analog in parens):

- :mod:`tidb_tpu.types`     — MySQL type system (pkg/types)
- :mod:`tidb_tpu.chunk`     — Arrow-layout columnar data plane (pkg/util/chunk)
- :mod:`tidb_tpu.expr`      — expression IR + JAX compiler (pkg/expression)
- :mod:`tidb_tpu.copr`      — coprocessor DAG execution on device
                              (unistore/cophandler, closure_exec.go)
- :mod:`tidb_tpu.parallel`  — mesh / shard_map SPMD fan-out + collectives
                              (pkg/store/copr fan-out, MPP exchanges)
- :mod:`tidb_tpu.store`     — shard catalog, columnar shards, KV/MVCC/txn
                              (pkg/store, unistore)
- :mod:`tidb_tpu.sql`       — lexer/parser/AST (pkg/parser)
- :mod:`tidb_tpu.planner`   — logical/physical optimizer + pushdown split
                              (pkg/planner)
- :mod:`tidb_tpu.executor`  — host-side root Volcano executors (pkg/executor)
- :mod:`tidb_tpu.session`   — session, catalog, DDL (pkg/session, pkg/meta)
- :mod:`tidb_tpu.utils`     — tracing, metrics, config/sysvars (pkg/util)
"""

import os

import jax

# SQL semantics need 64-bit ints (BIGINT) and doubles end-to-end.  TPU
# emulates i64/f64 with 32-bit pairs; hot kernels downcast internally where
# provably safe (see copr/kernels.py).
jax.config.update("jax_enable_x64", True)

# Explicit platform override for embedders.  The JAX_PLATFORMS env var is
# not enough in environments whose interpreter boot registers a PJRT
# plugin and forces its platform in-process (observed with the axon TPU
# plugin's sitecustomize); jax.config.update after import is the only
# binding override.  Device acquisition itself is lazy (parallel/mesh is
# only resolved at first device dispatch — see session.Domain), so merely
# importing this package or running host-only statements never blocks on
# TPU backend initialization.
_platform = os.environ.get("TIDB_TPU_PLATFORM")
if _platform:
    jax.config.update("jax_platforms", _platform)

__version__ = "0.1.0"
