"""Online DDL owner worker: F1 state machine + parallel index backfill.

Reference analog: pkg/ddl job_scheduler.go/job_worker.go (owner loop,
transitOneJobStep), index.go state machine none -> delete-only ->
write-only -> write-reorganization -> public (index.go:880-888), and the
DXF-style distributed backfill (backfilling_dist_*.go): the handle space
splits into subtask ranges executed by a worker pool, with progress
checkpointed per job so a restarted owner resumes mid-backfill.

Single-process adaptation: schema-version waits collapse (every session
sees the bumped version immediately — the <=1-lease F1 wait is a no-op
with one node), but state transitions, job persistence, checkpointing,
and the concurrent-write contract (write path honors index states) are
kept, because they are the correctness surface the tests exercise.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from ..session.catalog import DuplicateKeyError, IndexInfo
from ..store.kv import KVError
from .jobs import DDLJob, JobStorage

BATCH = 256          # rows per backfill txn (tidb_ddl_reorg_batch_size)
SUBTASK = 4096       # handles per subtask range (DXF subtask granularity)


class DDLError(RuntimeError):
    pass


class DDLExecutor:
    """Owner-side DDL executor: one background worker drains the job
    queue; sessions block on their job (the reference's session wait on
    job done, ddl/executor.go doDDLJob)."""

    def __init__(self, domain):
        self.domain = domain
        self.storage = JobStorage(domain.kv)
        self._queue: "queue.Queue[DDLJob]" = queue.Queue()
        self._events: dict[int, threading.Event] = {}
        self._excs: dict[int, BaseException] = {}
        self._next_job_id = 0
        self._mu = threading.Lock()
        self._closed = False
        self._worker = threading.Thread(target=self._owner_loop,
                                        name="ddl-owner", daemon=True)
        self._worker.start()
        # owner-failover resume (reorg.go analog): re-queue jobs that were
        # queued/running when the previous owner stopped; their reorg
        # checkpoint makes the backfill skip completed subtask ranges
        for done_job in self.storage.history():
            self._next_job_id = max(self._next_job_id, done_job.job_id)
        for job in self.storage.pending():
            self._next_job_id = max(self._next_job_id, job.job_id)
            self._queue.put(job)

    def close(self):
        self._closed = True
        self._queue.put(None)

    # ---------------- enqueue + wait ---------------- #

    def run_job(self, job_type: str, db: str, table: str, args: dict,
                timeout: float = 120.0) -> DDLJob:
        with self._mu:
            self._next_job_id += 1
            job = DDLJob(self._next_job_id, job_type, db, table, args,
                         start_time=time.time())
            ev = self._events[job.job_id] = threading.Event()
        self.storage.save(job)
        self._queue.put(job)
        if not ev.wait(timeout):
            # deregister the waiter so the eventually-finishing job doesn't
            # leak _events/_excs entries; the job itself keeps running and
            # its completion lands in history (ADMIN SHOW DDL JOBS)
            with self._mu:
                self._events.pop(job.job_id, None)
                self._excs.pop(job.job_id, None)
            raise DDLError(f"DDL job {job.job_id} timed out")
        with self._mu:
            del self._events[job.job_id]
            exc = self._excs.pop(job.job_id, None)
        if job.state == "failed":
            if exc is not None:
                raise exc           # original type (e.g. DuplicateKeyError)
            raise DDLError(job.error)
        return job

    # ---------------- owner loop ---------------- #

    def _owner_loop(self):
        while not self._closed:
            job = self._queue.get()
            if job is None:
                return
            try:
                job.state = "running"
                self.storage.save(job)
                self._run_one(job)
                job.state = "done"
            except Exception as e:  # job failure -> error surfaced to waiter
                job.state = "failed"
                job.error = f"{type(e).__name__}: {e}"
                with self._mu:
                    if job.job_id in self._events:  # waiter still present
                        self._excs[job.job_id] = e
            job.finish_time = time.time()
            job.schema_state = ("public" if job.state == "done"
                                and job.job_type.startswith("add")
                                else job.schema_state)
            self.storage.archive(job)
            ev = self._events.get(job.job_id)
            if ev is not None:
                ev.set()

    def _bump_schema(self, job: DDLJob, state: str):
        """One F1 transition: set state, bump schema version.  (The
        multi-node wait-for-lease is a no-op in-process.)"""
        job.schema_state = state
        self.domain.schema_version += 1
        self.storage.save(job)
        try:
            tbl = self.domain.catalog.get_table(job.db, job.table)
        except Exception:
            tbl = None            # table dropped mid-job
        if tbl is not None:
            tbl.schema_ver += 1
            # MDL (pkg/ddl/mdl, F1 wait-for-version-ack): before running
            # under the NEW version, drain every open txn still using an
            # older version of THIS table.  On timeout the transition
            # proceeds and the straggler txn aborts at commit instead
            # (session._finish_txn per-table schema validation).
            timeout = float(self.domain.sysvars.get(
                "tidb_mdl_wait_timeout", 10.0) or 10.0)
            drained = self.domain.mdl.wait_drain(
                tbl.table_id, tbl.schema_ver, timeout_s=timeout)
            if not drained:
                # straggler txns are now >=2 versions behind: doomed to
                # abort at commit, so stop re-waiting on them
                self.domain.mdl.evict_below(tbl.table_id, tbl.schema_ver)
                job.mdl_timeouts = getattr(job, "mdl_timeouts", 0) + 1
            tbl._persist_meta()   # catalog-on-KV: index states survive
            # (persistence failures propagate — silently losing an index
            # state transition would corrupt the restart view)

    def _run_one(self, job: DDLJob):
        tbl = self.domain.catalog.get_table(job.db, job.table)
        if job.job_type == "add index":
            self._add_index(job, tbl)
        elif job.job_type == "drop index":
            self._drop_index(job, tbl)
        else:
            raise DDLError(f"unknown DDL job type {job.job_type!r}")

    # ---------------- ADD INDEX ---------------- #

    def _add_index(self, job: DDLJob, tbl):
        a = job.args
        if tbl.index_by_name(a["name"]) is not None:
            if a.get("if_not_exists"):
                return
            raise DDLError(f"index {a['name']!r} already exists")
        for c in a["columns"]:
            if c not in tbl.col_names:
                raise DDLError(f"unknown column {c!r} in index {a['name']!r}")
        if tbl.kv is None:
            raise DDLError("indexes require a KV-backed table")
        tbl._next_index_id += 1
        ix = IndexInfo(a["name"], tbl._next_index_id, list(a["columns"]),
                       a["unique"], state="none")
        tbl.indexes.append(ix)
        try:
            # F1 ladder: each transition drains in-flight writers via the
            # table's schema gate (the wait-all-nodes-ack analog), so no
            # statement straddles two states
            for state in ("delete only", "write only",
                          "write reorganization"):
                with tbl.schema_gate.write():
                    ix.state = state
                self._bump_schema(job, state)
            self._backfill(job, tbl, ix)
            with tbl.schema_gate.write():
                ix.state = "public"
            self._bump_schema(job, "public")
            tbl._invalidate()
        except Exception:
            # rollback under the write gate: writers iterating
            # tbl.indexes must not observe the removal mid-statement, and
            # none may still write entries when the wipe scans
            with tbl.schema_gate.write():
                tbl.indexes.remove(ix)
            self._wipe_index(tbl, ix)
            raise

    def _backfill(self, job: DDLJob, tbl, ix):
        """Write-reorg backfill: snapshot-scan existing rows, write index
        entries in parallel subtask ranges (DXF); the checkpoint only
        advances over the contiguous completed prefix of subtasks, so a
        resumed job never skips an unfinished range."""
        from ..store.codec import (decode_record_key, decode_row, record_key,
                                   record_prefix, record_prefix_end)
        kv = tbl.kv
        ts = kv.alloc_ts()
        handles = [decode_record_key(k)[1] for k, _ in kv.scan(
            record_prefix(tbl.table_id), record_prefix_end(tbl.table_id), ts)]
        start = job.reorg_handle          # resume point
        todo = [int(h) for h in handles if h > start]
        if not todo:
            return
        pool = getattr(self.domain, "dxf_pool", None)
        if pool is not None and pool.live_nodes():
            return self._backfill_distributed(job, tbl, ix, todo, pool)
        workers = int(self.domain.sysvars.get(
            "tidb_ddl_reorg_worker_cnt", 4))
        subtasks = [todo[i:i + SUBTASK] for i in range(0, len(todo), SUBTASK)]

        def run_subtask(chunk):
            done = 0
            for off in range(0, len(chunk), BATCH):
                batch = chunk[off:off + BATCH]
                for attempt in range(12):
                    txn = kv.begin()
                    written = 0
                    try:
                        for h in batch:
                            # re-read the row at this txn's snapshot (not
                            # the stale scan): a concurrent DELETE must not
                            # leave an orphan entry, and a concurrent
                            # UPDATE's values must win.  Re-putting the
                            # record key forces a write-write conflict at
                            # commit with any racing row mutation (the
                            # reference locks the row key during backfill),
                            # so a mutation that lands between this read
                            # and commit aborts the batch instead of
                            # silently racing it.
                            rk = record_key(tbl.table_id, h)
                            rv = txn.get(rk)
                            if rv is None:
                                continue
                            txn.put(rk, rv)
                            row = decode_row(rv, tbl.col_types)
                            tbl._put_index_entry(txn, ix, tuple(row), h)
                            written += 1
                        txn.commit()
                        break
                    except DuplicateKeyError:
                        txn.rollback()
                        raise
                    except KVError:
                        # write conflict with a concurrent DML txn: the
                        # region-error/Backoffer retry analog.  Capped
                        # exponential backoff, same discipline as the
                        # session's _retry_write_conflict: a sustained
                        # DML stream over the batch's range can keep
                        # colliding for >20ms, which the old 5-attempt
                        # linear budget couldn't ride out.
                        txn.rollback()
                        if attempt == 11:
                            raise
                        time.sleep(min(0.002 * (2 ** attempt), 0.1))
                done += written
                with self._mu:
                    job.rows_backfilled += written
            return done

        with ThreadPoolExecutor(max_workers=max(workers, 1),
                                thread_name_prefix="ddl-backfill") as pool:
            # map() yields in submission order: after subtask k completes,
            # subtasks 0..k are all done -> checkpoint may advance to its
            # last handle (per-subtask durability, DXF subtask states)
            for k, _n in enumerate(pool.map(run_subtask, subtasks)):
                with self._mu:
                    job.reorg_handle = subtasks[k][-1]
                    self.storage.save(job)

    def _backfill_distributed(self, job: DDLJob, tbl, ix, todo, pool):
        """DXF multi-node backfill: subtask ranges fan out over the store
        RPC nodes (disttask framework balancer, doc.go:15-80); workers
        encode the index entries, the owner commits them with the same
        conflict discipline as the local path.  A node dying mid-reorg
        rebalances its subtasks onto survivors (dxf/balancer.py)."""
        from ..store.codec import decode_row, record_key
        kv = tbl.kv
        offs = tbl._index_cols(ix)
        # more subtasks than nodes so the work-stealing pool balances
        # (the reference splits by region for the same reason)
        n_nodes = max(len(pool.live_nodes()), 1)
        size = max(min(SUBTASK, -(-len(todo) // (4 * n_nodes))), 64)
        subtasks = [todo[i:i + size] for i in range(0, len(todo), size)]
        chunk_rows: dict[int, dict] = {}       # subtask idx -> {h: rv0}
        tagged = list(enumerate(subtasks))

        def make_msg(st):
            idx, chunk = st
            txn = kv.begin()
            rows = []
            try:
                for h in chunk:
                    rv = txn.get(record_key(tbl.table_id, h))
                    if rv is not None:
                        rows.append((h, rv))
            finally:
                txn.rollback()
            chunk_rows[idx] = dict(rows)
            return ("dxf_backfill", tbl.table_id, ix.index_id, ix.unique,
                    list(offs), list(tbl.col_types), rows)

        completed: set = set()

        def handle_resp(st, resp):
            idx, _chunk = st
            if not resp or resp[0] != "entries":
                raise DDLError(f"dxf worker error: {resp!r}")
            rv0 = chunk_rows.pop(idx, {})
            entries = resp[1]
            for off in range(0, len(entries), BATCH):
                batch = entries[off:off + BATCH]
                written = self._commit_entries(tbl, ix, batch, rv0)
                with self._mu:
                    job.rows_backfilled += written
            with self._mu:
                completed.add(idx)
                # contiguous-prefix checkpoint (same rule as local path)
                k = job_ck = 0
                while k in completed:
                    job_ck = k
                    k += 1
                if k:                  # at least subtask 0 done
                    job.reorg_handle = subtasks[job_ck][-1]
                    self.storage.save(job)

        pool.run_subtasks(tagged, make_msg, handle_resp)

    def _commit_entries(self, tbl, ix, batch, rv0) -> int:
        """Commit one batch of worker-encoded entries; rows that changed
        since the worker saw them are re-encoded at this txn's snapshot
        (the backfill-vs-DML race discipline of the local path)."""
        from ..store.codec import decode_row, record_key
        kv = tbl.kv
        for attempt in range(5):
            txn = kv.begin()
            written = 0
            try:
                for h, key, val in batch:
                    rk = record_key(tbl.table_id, h)
                    rv = txn.get(rk)
                    if rv is None:
                        continue       # row deleted since the scan
                    if rv != rv0.get(h):
                        # row mutated since encode: recompute locally
                        row = decode_row(rv, tbl.col_types)
                        key, val = tbl._index_entry(ix, tuple(row), h)
                    txn.put(rk, rv)    # conflict fence vs racing DML
                    if ix.unique and val and txn.get(key) is not None:
                        from ..session.catalog import DuplicateKeyError
                        raise DuplicateKeyError(
                            f"Duplicate entry for key "
                            f"'{tbl.name}.{ix.name}'")
                    txn.put(key, val)
                    written += 1
                txn.commit()
                return written
            except DuplicateKeyError:
                txn.rollback()
                raise
            except KVError:
                txn.rollback()
                if attempt == 4:
                    raise
                time.sleep(0.002 * (attempt + 1))
        return 0

    # ---------------- DROP INDEX ---------------- #

    def _drop_index(self, job: DDLJob, tbl):
        a = job.args
        ix = tbl.index_by_name(a["name"])
        if ix is None:
            if a.get("if_exists"):
                return
            raise DDLError(f"unknown index {a['name']!r}")
        # reverse ladder: public -> write only -> delete only -> none
        for state in ("write only", "delete only"):
            with tbl.schema_gate.write():
                ix.state = state
            self._bump_schema(job, state)
        with tbl.schema_gate.write():
            tbl.indexes.remove(ix)
        self._bump_schema(job, "none")
        self._wipe_index(tbl, ix)
        tbl._invalidate()

    def _wipe_index(self, tbl, ix):
        from ..store.codec import index_prefix, index_prefix_end
        kv = tbl.kv
        if kv is None:
            return
        txn = kv.begin()
        for k, _ in kv.scan(index_prefix(tbl.table_id, ix.index_id),
                            index_prefix_end(tbl.table_id, ix.index_id),
                            txn.start_ts):
            txn.delete(k)
        txn.commit()


__all__ = ["DDLExecutor", "DDLError"]
