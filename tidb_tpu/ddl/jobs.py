"""DDL job model + KV-persisted job queue/history.

Reference analog: pkg/ddl job handling — jobs enqueued to the DDL job
table (mysql.tidb_ddl_job), processed by the owner, archived to
tidb_ddl_history; reorg progress checkpointed in tidb_ddl_reorg
(ddl/reorg.go) so backfill resumes after failover.  Here jobs persist as
JSON under the meta prefix of the native KV store ('m' keyspace,
pkg/meta/meta.go:78 analog).
"""

from __future__ import annotations

import json
import time
from dataclasses import asdict, dataclass, field
from typing import Optional

from ..store.codec import encode_int_key

META_JOB = b"m_ddl_job_"        # + int key: queued/running jobs
META_HIST = b"m_ddl_hist_"      # + int key: finished jobs


@dataclass
class DDLJob:
    job_id: int = 0
    job_type: str = ""          # 'add index' | 'drop index' | ...
    db: str = ""
    table: str = ""
    args: dict = field(default_factory=dict)
    state: str = "queueing"     # queueing | running | done | failed
    schema_state: str = "none"  # F1 states (ddl/index.go:880-888)
    error: str = ""
    reorg_handle: int = 0       # backfill checkpoint (tidb_ddl_reorg)
    rows_backfilled: int = 0
    start_time: float = 0.0
    finish_time: float = 0.0

    def to_json(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_json(cls, b: bytes) -> "DDLJob":
        return cls(**json.loads(b.decode()))


class JobStorage:
    """Persist jobs/history in the KV meta keyspace."""

    def __init__(self, kv):
        self.kv = kv

    def _put(self, prefix: bytes, job: DDLJob):
        t = self.kv.begin()
        t.put(prefix + encode_int_key(job.job_id), job.to_json())
        t.commit()

    def save(self, job: DDLJob):
        self._put(META_JOB, job)

    def archive(self, job: DDLJob):
        t = self.kv.begin()
        t.delete(META_JOB + encode_int_key(job.job_id))
        t.put(META_HIST + encode_int_key(job.job_id), job.to_json())
        t.commit()

    def _scan(self, prefix: bytes) -> list[DDLJob]:
        ts = self.kv.alloc_ts()
        end = prefix[:-1] + bytes([prefix[-1] + 1])
        return [DDLJob.from_json(v)
                for _, v in self.kv.scan(prefix, end, ts)]

    def pending(self) -> list[DDLJob]:
        return self._scan(META_JOB)

    def history(self) -> list[DDLJob]:
        return self._scan(META_HIST)

    def all_jobs(self) -> list[DDLJob]:
        return sorted(self.pending() + self.history(),
                      key=lambda j: j.job_id)


__all__ = ["DDLJob", "JobStorage", "META_JOB", "META_HIST"]
