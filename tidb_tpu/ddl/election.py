"""Owner election: single-writer lease over the KV meta keyspace.

Reference analog: pkg/owner (etcd campaign/lease, ownerManager).  With
no etcd, the lease lives at a KV meta key as (owner_id, expires_at);
campaign is an atomic compare-and-claim through a KV transaction (the
engine's write-write conflict detection makes concurrent campaigns
serialize), renewal extends the expiry, and a crashed owner's lease
simply times out for the next campaigner — the same liveness contract,
one process or many.
"""

from __future__ import annotations

import json
import threading
import time
import uuid

LEASE_KEY = b"m_owner_"


class OwnerManager:
    def __init__(self, kv, key: str = "ddl", lease_sec: float = 3.0,
                 owner_id: str = ""):
        self.kv = kv
        self.key = LEASE_KEY + key.encode()
        self.lease_sec = lease_sec
        self.owner_id = owner_id or uuid.uuid4().hex[:12]
        self._renew_thread = None
        self._stop = threading.Event()

    # -- lease primitives --------------------------------------------- #

    def _read_lease(self):
        ts = self.kv.alloc_ts()
        raw = self.kv.get(self.key, ts)
        if raw is None:
            return None, 0.0
        d = json.loads(raw.decode())
        return d["id"], d["exp"]

    def _claim(self, require_held: bool) -> bool:
        """Atomic compare-and-claim: the lease READ and WRITE share one
        KV transaction, so two racing campaigns overlap on the key and
        write-write conflict detection aborts one — exactly one winner."""
        txn = self.kv.begin()
        try:
            raw = txn.get(self.key)
            if raw is not None:
                d = json.loads(raw.decode())
                held_by_me = d["id"] == self.owner_id
                live = d["exp"] > time.time()
                if require_held and not (held_by_me and live):
                    txn.rollback()
                    return False
                if not require_held and live and not held_by_me:
                    txn.rollback()
                    return False
            elif require_held:
                txn.rollback()
                return False
            txn.put(self.key, json.dumps(
                {"id": self.owner_id,
                 "exp": time.time() + self.lease_sec}).encode())
            txn.commit()
            return True
        except Exception:
            txn.rollback()
            return False

    # -- API ----------------------------------------------------------- #

    def campaign(self) -> bool:
        """Claim ownership if the lease is free or expired."""
        return self._claim(require_held=False)

    def is_owner(self) -> bool:
        holder, exp = self._read_lease()
        return holder == self.owner_id and exp > time.time()

    def renew(self) -> bool:
        return self._claim(require_held=True)

    def resign(self) -> None:
        """Atomic compare-and-delete: the ownership check and the delete
        share one txn, so resign can never remove a lease another node
        claimed after our check (same serialization as campaign)."""
        txn = self.kv.begin()
        try:
            raw = txn.get(self.key)
            if raw is None or json.loads(raw.decode())["id"] != self.owner_id:
                txn.rollback()
                return
            txn.delete(self.key)
            txn.commit()
        except Exception:
            txn.rollback()

    # -- background renewal (the etcd keepalive analog) ---------------- #

    def start_renewal(self) -> None:
        if self._renew_thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.lease_sec / 3):
                try:
                    self.renew()
                except Exception:
                    pass

        self._renew_thread = threading.Thread(target=loop, daemon=True)
        self._renew_thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._renew_thread is not None:
            self._renew_thread.join(timeout=2)
            self._renew_thread = None
        self.resign()


__all__ = ["OwnerManager"]
