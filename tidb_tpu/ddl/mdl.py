"""Metadata locks: online DDL vs open transactions.

Reference analog: /root/reference/pkg/ddl/mdl/ (+ the design doc
docs/design/2021-09-22-data-consistency.md): a transaction that has USED
a table under schema version V holds a metadata lock on it; a DDL state
transition publishing version V+1 must wait until every transaction
still on a version < V+1 for that table drains (commits or rolls back)
before running the next transition — the F1 "wait for all nodes to ack
the new version" step.  The commit-time schema validation
(kv.go:533 SchemaVar analog in session._finish_txn) remains the backstop
for the wait-timeout path.
"""

from __future__ import annotations

import threading
import time


class MDLRegistry:
    """table_id -> {txn_token: schema_ver held}.  Tokens are the session
    txn objects; a token registers the version it FIRST saw (re-acquire
    keeps the oldest), and releases all its tables at txn end."""

    def __init__(self):
        self._cv = threading.Condition()
        self._holders: dict[int, dict[object, int]] = {}

    def acquire(self, table_id: int, token: object, ver: int) -> None:
        with self._cv:
            h = self._holders.setdefault(table_id, {})
            if token not in h or h[token] > ver:
                h[token] = ver

    def release_all(self, token: object) -> None:
        with self._cv:
            changed = False
            for h in self._holders.values():
                if h.pop(token, None) is not None:
                    changed = True
            if changed:
                self._cv.notify_all()

    def evict_below(self, table_id: int, ver: int) -> int:
        """Drop holders stuck below `ver` after a drain timeout: they are
        doomed to abort at commit (>=2-version gap), so later transitions
        must not re-wait on them.  Returns how many were evicted."""
        with self._cv:
            h = self._holders.get(table_id, {})
            stale = [t for t, v in h.items() if v < ver]
            for t in stale:
                del h[t]
            if stale:
                self._cv.notify_all()
            return len(stale)

    def holders_below(self, table_id: int, ver: int) -> int:
        with self._cv:
            h = self._holders.get(table_id, {})
            return sum(1 for v in h.values() if v < ver)

    def wait_drain(self, table_id: int, below_ver: int,
                   timeout_s: float = 10.0) -> bool:
        """Block until no txn holds `table_id` at a version < below_ver.
        Returns False on timeout (caller proceeds; the commit-time
        validation aborts any straggler instead)."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while True:
                h = self._holders.get(table_id, {})
                if not any(v < below_ver for v in h.values()):
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)


__all__ = ["MDLRegistry"]
