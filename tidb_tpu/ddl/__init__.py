from .jobs import DDLJob, JobStorage
from .owner import DDLExecutor, DDLError

__all__ = ["DDLJob", "JobStorage", "DDLExecutor", "DDLError"]
