"""etcd-style watch/broadcast plane over the KV store.

Reference analog: the etcd watch channels the reference hangs off
pkg/domain (domain.go GlobalVarsWatcher / bindinfo + privilege update
channels, owner/manager.go notifications).  PD's etcd is replaced here by
the MVCC store itself: each channel is a revisioned log under a meta key
prefix, writers bump the channel revision transactionally, and watchers
either receive the payload in-process (same Domain: immediate callback)
or poll the revision counter cheaply (~one KV get) from other processes
sharing the store — the same delivery model etcd gives the reference,
minus the gRPC stream.

Channels in use: "sysvar" (SET GLOBAL fan-out), "privilege"
(GRANT/REVOKE/CREATE USER cache invalidation).
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from typing import Any, Callable, Optional

M_WATCH = b"m\x00watch\x00"        # <channel>\x00rev -> int; \x00log\x00<rev8> -> payload


def _rev_key(channel: str) -> bytes:
    return M_WATCH + channel.encode() + b"\x00rev"


def _log_key(channel: str, rev: int) -> bytes:
    return M_WATCH + channel.encode() + b"\x00log\x00" + rev.to_bytes(8, "big")


class WatchHub:
    """Per-Domain pub/sub with KV-persisted revision log."""

    def __init__(self, kv=None, origin: Optional[str] = None):
        self.kv = kv
        self.origin = origin or f"{id(self):x}.{time.time_ns():x}"
        self._subs: dict[str, list[Callable]] = defaultdict(list)
        self._seen: dict[str, int] = {}
        self._mu = threading.Lock()
        self._poller: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.poll_interval = 0.2

    # ---------------- write side ---------------- #

    def notify(self, channel: str, payload: dict) -> int:
        """Publish: persist to the channel log (new revision) and deliver
        to in-process subscribers immediately.  Returns the revision."""
        payload = dict(payload, _origin=self.origin)
        rev = 0
        if self.kv is not None:
            for _ in range(16):            # txn-conflict retry
                try:
                    txn = self.kv.begin()
                    cur = self.kv.get(_rev_key(channel), txn.start_ts)
                    rev = (int(cur) if cur else 0) + 1
                    txn.put(_rev_key(channel), str(rev).encode())
                    txn.put(_log_key(channel, rev),
                            json.dumps(payload, default=str).encode())
                    txn.commit()
                    break
                except Exception:
                    time.sleep(0.001)
            else:
                raise RuntimeError(f"watch notify on {channel} kept "
                                   "conflicting")
            with self._mu:
                self._seen[channel] = max(self._seen.get(channel, 0), rev)
        self._deliver(channel, payload)
        return rev

    # ---------------- read side ---------------- #

    def subscribe(self, channel: str, cb: Callable[[dict], Any]) -> None:
        with self._mu:
            self._subs[channel].append(cb)
            if channel not in self._seen:
                self._seen[channel] = self.revision(channel)
        if self.kv is not None:
            self._ensure_poller()

    def revision(self, channel: str) -> int:
        if self.kv is None:
            return 0
        cur = self.kv.get(_rev_key(channel), self.kv.alloc_ts())
        return int(cur) if cur else 0

    def poll(self, channel: str, since: int) -> tuple[int, list[dict]]:
        """(latest revision, payloads after `since`) — the cross-process
        read path; one cheap get when nothing changed."""
        rev = self.revision(channel)
        if rev <= since or self.kv is None:
            return rev, []
        lo = _log_key(channel, since + 1)
        hi = _log_key(channel, rev) + b"\xff"
        out = []
        for _k, v in self.kv.scan(lo, hi, self.kv.alloc_ts()):
            try:
                out.append(json.loads(v))
            except ValueError:
                pass
        return rev, out

    # ---------------- poller ---------------- #

    def _deliver(self, channel: str, payload: dict) -> None:
        for cb in list(self._subs.get(channel, ())):
            try:
                cb(payload)
            except Exception:
                pass

    def _ensure_poller(self) -> None:
        with self._mu:
            if self._poller is not None and self._poller.is_alive():
                return
            self._stop.clear()
            self._poller = threading.Thread(target=self._poll_loop,
                                            daemon=True,
                                            name="watch-poller")
            self._poller.start()
            # the store joins this poller BEFORE freeing its native
            # handle (KVStore.close closers), preventing use-after-free
            closers = getattr(self.kv, "_closers", None)
            if closers is not None and self._shutdown not in closers:
                closers.append(self._shutdown)

    def _shutdown(self) -> None:
        self._stop.set()
        p = self._poller
        if p is not None and p.is_alive() \
                and p is not threading.current_thread():
            p.join(timeout=5.0)

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            if not getattr(self.kv, "_h", None):
                return                 # store closed under us
            with self._mu:
                channels = list(self._subs)
            for ch in channels:
                try:
                    rev, payloads = self.poll(ch, self._seen.get(ch, 0))
                except Exception:
                    continue
                with self._mu:
                    self._seen[ch] = max(self._seen.get(ch, 0), rev)
                for p in payloads:
                    if p.get("_origin") == self.origin:
                        continue       # already delivered in-process
                    self._deliver(ch, p)

    def close(self) -> None:
        self._stop.set()


__all__ = ["WatchHub"]
