"""Intra-statement tracing: named regions -> span tree.

Reference analog: pkg/util/tracing (StartRegionEx wrapping opentracing
spans at every major phase — session.go:2114, adapter, copr) and the
TRACE statement renderer (executor/trace.go).

Since copscope (ISSUE 13) this module is a compatibility shim over
``tidb_tpu.obs``: the old depth-counter model is gone — regions carry
EXPLICIT parent span ids on a lock-protected ``obs.SpanTree``, and
``region`` re-points ``obs.TRACE_CTX`` for its dynamic extent so device
work dispatched inside (scheduler drain, copforge resolve, transfer)
records real spans into the same tree from other threads.  The old
surface (``Tracer.region`` / ``Tracer.spans`` with ``.depth`` /
``Tracer.rows``) keeps working.
"""

from __future__ import annotations

from contextlib import contextmanager
from dataclasses import dataclass

from ..obs.trace import TRACE_CTX, SpanTree, TraceCtx


@dataclass
class Span:
    """Back-compat render view (the live spans are ``obs.Span``)."""

    name: str
    start_ns: int
    end_ns: int = 0
    depth: int = 0

    @property
    def duration_us(self) -> float:
        return (self.end_ns - self.start_ns) / 1e3


class Tracer:
    """Per-statement span collector over an ``obs.SpanTree``.

    Regions nest via explicit parent ids (cross-thread safe); the
    legacy depth-counter API is preserved as a derived view."""

    def __init__(self, tree: SpanTree | None = None):
        self.tree = tree or SpanTree()
        self._t0 = self.tree.t0

    @contextmanager
    def region(self, name: str, **attrs):
        """Open a child region under the innermost active region and
        bind it as the thread's active trace context, so any device
        work dispatched inside stitches under it."""
        ctx = TRACE_CTX.get()
        parent = ctx.span_id if ctx is not None \
            and ctx.tree is self.tree else None
        sid = self.tree.begin(name, parent_id=parent, **attrs)
        tok = TRACE_CTX.set(TraceCtx(self.tree, sid))
        try:
            yield sid
        finally:
            TRACE_CTX.reset(tok)
            self.tree.end(sid)

    @property
    def spans(self) -> list[Span]:
        """Depth-annotated spans in tree (render) order — the legacy
        shape tests and embedders consume."""
        return [Span(sp.name, sp.start_ns, sp.end_ns, depth)
                for sp, depth in self.tree.ordered()]

    def rows(self) -> list[tuple]:
        """(span, start_us_rel, duration_us) rows, indented by depth."""
        return self.tree.rows()


__all__ = ["Span", "Tracer"]
