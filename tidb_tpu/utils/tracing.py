"""Intra-statement tracing: named regions -> span tree.

Reference analog: pkg/util/tracing (StartRegionEx wrapping opentracing
spans at every major phase — session.go:2114, adapter, copr) and the
TRACE statement renderer (executor/trace.go).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class Span:
    name: str
    start_ns: int
    end_ns: int = 0
    depth: int = 0

    @property
    def duration_us(self) -> float:
        return (self.end_ns - self.start_ns) / 1e3


class Tracer:
    """Per-statement span collector.  Regions nest via a depth counter —
    single-threaded statement execution, so no context propagation needed."""

    def __init__(self):
        self.spans: list[Span] = []
        self._depth = 0
        self._t0 = time.perf_counter_ns()

    @contextmanager
    def region(self, name: str):
        sp = Span(name, time.perf_counter_ns(), depth=self._depth)
        self.spans.append(sp)
        self._depth += 1
        try:
            yield sp
        finally:
            self._depth -= 1
            sp.end_ns = time.perf_counter_ns()

    def rows(self) -> list[tuple]:
        """(span, start_us_rel, duration_us) rows, indented by depth."""
        return [("  " * sp.depth + sp.name,
                 round((sp.start_ns - self._t0) / 1e3, 1),
                 round(sp.duration_us, 1))
                for sp in self.spans]
