"""Telemetry: opt-in local usage reporting.

Reference analog: pkg/telemetry (feature-usage collection reported on an
interval; excised of any network egress here — reports are written as
local JSON only, and collection is OFF unless tidb_enable_telemetry is
set).  The collected shape mirrors the reference's report: instance
info, uptime, feature-usage flags, statement counts.
"""

from __future__ import annotations

import json
import time
from typing import Optional


def collect(domain) -> dict:
    """Assemble one telemetry report from live Domain state."""
    import jax
    try:
        devs = jax.devices()
        hw = {"platform": devs[0].platform, "device_count": len(devs)}
    except Exception:
        hw = {"platform": "unknown", "device_count": 0}
    tables = sum(len(t) for t in domain.catalog.databases.values())
    indexes = sum(len(getattr(t, "indexes", []))
                  for ts in domain.catalog.databases.values()
                  for t in ts.values())
    stmt_rows = domain.stmt_summary.summary_rows()
    features = {
        "bindings": bool(domain.bindings.rows()),
        "resource_groups": len(domain.resource_groups.rows()) > 1,
        "ddl_jobs": domain._ddl is not None,
        "durable_store": domain.meta is not None,
    }
    return {
        "report_time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "version": "0.2.0",
        "hardware": hw,
        "schema": {"tables": tables, "indexes": indexes},
        "workload": {
            "distinct_digests": len(stmt_rows),
            "total_execs": sum(r[1] for r in stmt_rows),
        },
        "features": features,
    }


def report(domain, path: Optional[str] = None) -> Optional[str]:
    """Write one report to `path` (JSON) if telemetry is enabled.
    Returns the path written, or None when disabled."""
    from .memory import sysvar_bool
    if not sysvar_bool(domain.sysvars.get("tidb_enable_telemetry"), False):
        return None
    out = path or "telemetry-report.json"
    with open(out, "w") as f:
        json.dump(collect(domain), f, indent=2)
    return out


__all__ = ["collect", "report"]
