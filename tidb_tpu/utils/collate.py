"""Collation sortkeys + rank LUTs over string dictionaries.

Reference analog: pkg/util/collate (20.5k LoC of per-collation Compare/Key
implementations).  The TPU redesign needs none of the per-row compare code:
strings are dictionary codes, so a collation becomes ONE host-side pass
over the (small) dictionary producing an int rank LUT — device compares
stay integer compares, exactly like the binary path (SURVEY.md §7).

The registry mirrors the reference's collation matrix
(pkg/util/collate/collate.go newCollationEnabled set):

============================  ====  ======  ======  ==========
collation                     case  accent  pad     expansion
============================  ====  ======  ======  ==========
*_bin / binary                 yes   yes    PAD*     —
utf8mb4_general_ci             no    no     PAD      per-char (ß='s')
utf8mb4_unicode_ci / 520_ci    no    no     PAD      full (ß='ss')
utf8mb4_0900_ai_ci             no    no     NO PAD   full (ß='ss')
utf8mb4_0900_as_ci             no    yes    NO PAD   —
utf8mb4_0900_as_cs/_bin        yes   yes    NO PAD   —
latin1_swedish_ci etc.         no    no     PAD      per-char
============================  ====  ======  ======  ==========

(*) binary collations compare raw bytes; PAD is irrelevant.
"""

from __future__ import annotations

import unicodedata
from bisect import bisect_left, bisect_right
from dataclasses import dataclass

import numpy as np

from ..chunk.column import StringDict

BINARY = ("binary", "utf8mb4_bin", "utf8_bin", "latin1_bin", "ascii_bin",
          "utf8mb4_0900_bin", "utf8mb4_0900_as_cs")


@dataclass(frozen=True)
class CollationSpec:
    name: str
    charset: str
    binary: bool = False       # raw code order (case+accent sensitive)
    accent_ci: bool = True     # strip accents (NFKD, drop combining)
    pad: bool = True           # PAD SPACE (trailing spaces ignored)
    expand: bool = True        # full casefold (ß -> ss); else per-char
    is_default: bool = False


def _c(name, charset, **kw):
    return CollationSpec(name, charset, **kw)


COLLATIONS: dict[str, CollationSpec] = {c.name: c for c in [
    _c("binary", "binary", binary=True, pad=False, is_default=True),
    _c("utf8mb4_bin", "utf8mb4", binary=True, is_default=True),
    _c("utf8_bin", "utf8", binary=True, is_default=True),
    _c("latin1_bin", "latin1", binary=True),
    _c("ascii_bin", "ascii", binary=True, is_default=True),
    _c("utf8mb4_general_ci", "utf8mb4", expand=False),
    _c("utf8_general_ci", "utf8", expand=False),
    _c("utf8mb4_unicode_ci", "utf8mb4"),
    _c("utf8_unicode_ci", "utf8"),
    _c("utf8mb4_unicode_520_ci", "utf8mb4"),
    _c("utf8mb4_0900_ai_ci", "utf8mb4", pad=False),
    _c("utf8mb4_0900_as_ci", "utf8mb4", accent_ci=False, pad=False,
       expand=False),
    _c("utf8mb4_0900_as_cs", "utf8mb4", binary=True, pad=False),
    _c("utf8mb4_0900_bin", "utf8mb4", binary=True, pad=False),
    _c("latin1_swedish_ci", "latin1", expand=False, is_default=True),
    _c("ascii_general_ci", "ascii", expand=False),
    _c("gbk_bin", "gbk", binary=True),
    _c("gbk_chinese_ci", "gbk", expand=False),
]}


def spec_of(name: str) -> CollationSpec:
    got = COLLATIONS.get(name)
    if got is not None:
        return got
    # unknown names: _bin/_cs suffixes behave binary, _ci case-fold —
    # tolerant like the reference's fallback to binary collator
    if name.endswith("_ci"):
        return CollationSpec(name, "utf8mb4", expand=False)
    return CollationSpec(name, "utf8mb4", binary=True)


def is_binary(name: str) -> bool:
    return spec_of(name).binary


def _strip_accents(s: str) -> str:
    return "".join(c for c in unicodedata.normalize("NFKD", s)
                   if not unicodedata.combining(c))


def _fold_per_char(s: str) -> str:
    """general_ci-style single-weight fold: each character maps to ONE
    weight (the first char of its uppercase form), so 'ß' folds to 'S'
    ('ß'='s' under general_ci, != 'ss' — MySQL's documented quirk)."""
    out = []
    for ch in s:
        u = ch.upper()
        out.append(u[0] if u else ch)
    return "".join(out)


def sortkey(s: str, collation: str) -> str:
    """Collation sort key: equal keys collate equal; key order == collation
    order (codec.Key analog, computed per dictionary value not per row)."""
    spec = spec_of(collation)
    if spec.binary:
        return s
    if spec.pad:
        s = s.rstrip(" ")                  # PAD SPACE
    if spec.accent_ci:
        s = _strip_accents(s)
    return s.casefold() if spec.expand else _fold_per_char(s).lower()


class RankTable:
    """Dense ranks of a dictionary's values under a collation: codes with
    equal sortkeys share a rank, rank order == collation order."""

    def __init__(self, d: StringDict, collation: str):
        self.collation = collation
        keys = [sortkey(v, collation) for v in d.values]
        self.sorted_keys = sorted(set(keys))
        idx = {k: i for i, k in enumerate(self.sorted_keys)}
        self.ranks = (np.fromiter((idx[k] for k in keys), np.int32,
                                  count=len(keys))
                      if keys else np.zeros(1, np.int32))

    def rank_of(self, s: str) -> int:
        """Exact rank of a literal's sortkey, or -1 if absent."""
        k = sortkey(s, self.collation)
        i = bisect_left(self.sorted_keys, k)
        if i < len(self.sorted_keys) and self.sorted_keys[i] == k:
            return i
        return -1

    def lower_bound(self, s: str) -> int:
        return bisect_left(self.sorted_keys, sortkey(s, self.collation))

    def upper_bound(self, s: str) -> int:
        return bisect_right(self.sorted_keys, sortkey(s, self.collation))


def rank_table(d: StringDict, collation: str) -> "RankTable":
    """Per-dictionary cached RankTable (dictionaries are immutable and
    shared across chunks; streaming paths ask per chunk per key)."""
    rt = d._rank_cache.get(collation)
    if rt is None:
        rt = d._rank_cache[collation] = RankTable(d, collation)
    return rt


def like_key(s: str, collation: str) -> str:
    """LIKE-compare normalization: MySQL LIKE is character-wise with NO
    pad-space (unlike ordinary ci compares), so only casefold — never
    rstrip, and no NFKD expansion (it would change `_` wildcard widths)."""
    if is_binary(collation):
        return s
    return s.casefold()


def merged_rank_maps(da: StringDict, db: StringDict, collation: str):
    """Rank maps for two dictionaries into one shared collation-rank
    space (cross-dictionary ci compares/joins)."""
    ka = [sortkey(v, collation) for v in da.values]
    kb = [sortkey(v, collation) for v in db.values]
    merged = sorted(set(ka) | set(kb))
    idx = {k: i for i, k in enumerate(merged)}
    ma = (np.fromiter((idx[k] for k in ka), np.int32, count=len(ka))
          if ka else np.zeros(1, np.int32))
    mb = (np.fromiter((idx[k] for k in kb), np.int32, count=len(kb))
          if kb else np.zeros(1, np.int32))
    return ma, mb


CHARSET_MAXLEN = {"utf8mb4": 4, "utf8": 3, "latin1": 1, "ascii": 1,
                  "binary": 1, "gbk": 2}


def all_collations() -> list[CollationSpec]:
    """SHOW COLLATION / information_schema.collations rows."""
    return list(COLLATIONS.values())


def collation_rows() -> list[tuple]:
    """(name, charset, id, default, compiled, sortlen, pad) — the ONE
    row builder behind SHOW COLLATION and information_schema.COLLATIONS."""
    return [(c.name, c.charset, i + 1, "Yes" if c.is_default else "",
             "Yes", 1, "PAD SPACE" if c.pad else "NO PAD")
            for i, c in enumerate(sorted(all_collations(),
                                         key=lambda c: c.name))]


def charset_rows() -> list[tuple]:
    """(charset, default_collation, description, maxlen) — behind SHOW
    CHARACTER SET and information_schema.CHARACTER_SETS."""
    seen: dict[str, str] = {}
    for c in sorted(all_collations(), key=lambda c: c.name):
        if c.charset not in seen or c.is_default:
            seen[c.charset] = c.name
    return [(cs, dflt, f"{cs} charset", CHARSET_MAXLEN.get(cs, 4))
            for cs, dflt in sorted(seen.items())]


__all__ = ["sortkey", "is_binary", "RankTable", "rank_table", "like_key",
           "merged_rank_maps", "CollationSpec", "COLLATIONS", "spec_of",
           "all_collations", "collation_rows", "charset_rows",
           "CHARSET_MAXLEN"]
