"""Collation sortkeys + rank LUTs over string dictionaries.

Reference analog: pkg/util/collate (20.5k LoC of per-collation Compare/Key
implementations).  The TPU redesign needs none of the per-row compare code:
strings are dictionary codes, so a collation becomes ONE host-side pass
over the (small) dictionary producing an int rank LUT — device compares
stay integer compares, exactly like the binary path (SURVEY.md §7).

Supported: binary / utf8mb4_bin (raw code order, no LUT needed),
utf8mb4_general_ci (case-insensitive), utf8mb4_unicode_ci and
utf8mb4_0900_ai_ci (case- and accent-insensitive, NFKD approximation).
Non-binary collations use MySQL PAD SPACE semantics (trailing spaces
ignored); 0900 collations are NO PAD in MySQL, approximated the same way.
"""

from __future__ import annotations

import unicodedata
from bisect import bisect_left, bisect_right

import numpy as np

from ..chunk.column import StringDict

BINARY = ("binary", "utf8mb4_bin", "utf8_bin", "latin1_bin", "ascii_bin")


def is_binary(name: str) -> bool:
    return name in BINARY or not name.endswith("_ci")


def _strip_accents(s: str) -> str:
    return "".join(c for c in unicodedata.normalize("NFKD", s)
                   if not unicodedata.combining(c))


def sortkey(s: str, collation: str) -> str:
    """Collation sort key: equal keys collate equal; key order == collation
    order (codec.Key analog, computed per dictionary value not per row)."""
    if is_binary(collation):
        return s
    s = s.rstrip(" ")                      # PAD SPACE
    if "unicode" in collation or "_ai_" in collation or "0900" in collation:
        s = _strip_accents(s)
    return s.casefold()


class RankTable:
    """Dense ranks of a dictionary's values under a collation: codes with
    equal sortkeys share a rank, rank order == collation order."""

    def __init__(self, d: StringDict, collation: str):
        self.collation = collation
        keys = [sortkey(v, collation) for v in d.values]
        self.sorted_keys = sorted(set(keys))
        idx = {k: i for i, k in enumerate(self.sorted_keys)}
        self.ranks = (np.fromiter((idx[k] for k in keys), np.int32,
                                  count=len(keys))
                      if keys else np.zeros(1, np.int32))

    def rank_of(self, s: str) -> int:
        """Exact rank of a literal's sortkey, or -1 if absent."""
        k = sortkey(s, self.collation)
        i = bisect_left(self.sorted_keys, k)
        if i < len(self.sorted_keys) and self.sorted_keys[i] == k:
            return i
        return -1

    def lower_bound(self, s: str) -> int:
        return bisect_left(self.sorted_keys, sortkey(s, self.collation))

    def upper_bound(self, s: str) -> int:
        return bisect_right(self.sorted_keys, sortkey(s, self.collation))


def rank_table(d: StringDict, collation: str) -> "RankTable":
    """Per-dictionary cached RankTable (dictionaries are immutable and
    shared across chunks; streaming paths ask per chunk per key)."""
    rt = d._rank_cache.get(collation)
    if rt is None:
        rt = d._rank_cache[collation] = RankTable(d, collation)
    return rt


def like_key(s: str, collation: str) -> str:
    """LIKE-compare normalization: MySQL LIKE is character-wise with NO
    pad-space (unlike ordinary ci compares), so only casefold — never
    rstrip, and no NFKD expansion (it would change `_` wildcard widths)."""
    if is_binary(collation):
        return s
    return s.casefold()


def merged_rank_maps(da: StringDict, db: StringDict, collation: str):
    """Rank maps for two dictionaries into one shared collation-rank
    space (cross-dictionary ci compares/joins)."""
    ka = [sortkey(v, collation) for v in da.values]
    kb = [sortkey(v, collation) for v in db.values]
    merged = sorted(set(ka) | set(kb))
    idx = {k: i for i, k in enumerate(merged)}
    ma = (np.fromiter((idx[k] for k in ka), np.int32, count=len(ka))
          if ka else np.zeros(1, np.int32))
    mb = (np.fromiter((idx[k] for k in kb), np.int32, count=len(kb))
          if kb else np.zeros(1, np.int32))
    return ma, mb


__all__ = ["sortkey", "is_binary", "RankTable", "rank_table", "like_key",
           "merged_rank_maps"]
