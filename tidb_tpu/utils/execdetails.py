"""Per-operator runtime statistics for EXPLAIN ANALYZE.

Reference analog: pkg/util/execdetails RuntimeStatsColl — every executor
records wall time + produced rows; cop tasks additionally record device
dispatch details (select_result.go:605 updateCopRuntimeStats).  Here the
collection is a tree-walk wrapper around PhysOp.execute: child calls go
through instance attribute lookup, so binding a timing closure on each
node intercepts the whole Volcano tree without touching operator code.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class OpStats:
    op_id: int
    label: str
    time_ns: int = 0        # inclusive wall time (children included)
    rows: int = 0
    loops: int = 0
    # free-form execution detail an operator annotates itself with (e.g.
    # CopTask's `schedWait: ...` — the cop-task execution-info analog of
    # the reference's copr_cache/scan_detail strings)
    detail: str = ""

    @property
    def time_ms(self) -> float:
        return self.time_ns / 1e6


@dataclass
class RuntimeStatsColl:
    stats: dict = field(default_factory=dict)   # op_id -> OpStats

    def get(self, op_id: int) -> OpStats:
        return self.stats.get(op_id)


def instrument_tree(root, coll: RuntimeStatsColl) -> None:
    """Assign op ids depth-first and wrap each node's execute with a
    timing/row-counting closure (instance-level override)."""
    next_id = [0]

    def visit(op):
        op_id = next_id[0]
        next_id[0] += 1
        op._rt_id = op_id
        st = OpStats(op_id, op.describe())
        coll.stats[op_id] = st
        orig = op.execute     # bound method (class-level)

        def timed(ctx, _orig=orig, _st=st, _op=op):
            t0 = time.perf_counter_ns()
            chunk = _orig(ctx)
            _st.time_ns += time.perf_counter_ns() - t0
            _st.loops += 1
            _st.rows += chunk.num_rows
            d = getattr(_op, "_rt_detail", "")
            if d:
                _st.detail = d
            return chunk

        op.execute = timed
        for c in getattr(op, "children", []):
            visit(c)

    visit(root)


def explain_analyze_text(root, coll: RuntimeStatsColl) -> list[tuple]:
    """(operator, actRows, time, loops) rows in plan-tree order."""
    out = []

    def visit(op, depth):
        st = coll.get(getattr(op, "_rt_id", -1))
        pad = "  " * depth
        if st is None:
            out.append((pad + op.describe(), None, None, None))
        else:
            # re-describe at RENDER time: execution may have annotated the
            # operator (cop-cache hit, runtime join strategy, ...)
            label = pad + op.describe()
            if st.detail:
                label += f" [{st.detail}]"
            out.append((label, st.rows, f"{st.time_ms:.3f}ms", st.loops))
        for c in getattr(op, "children", []):
            visit(c, depth + 1)

    visit(root, 0)
    return out
