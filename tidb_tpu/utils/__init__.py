"""Shared utilities: runtime stats, tracing, statement summary, memory.

Reference analog: pkg/util/{execdetails,tracing,stmtsummary,memory}.
"""
