"""JSON builtin implementations over JSON text values.

Reference analog: pkg/types/json_binary*.go + pkg/expression/builtin_json*.
JSON columns store normalized text dict-encoded like VARCHAR, so every
JSON_* builtin evaluates ONCE per distinct value over the dictionary
(expr/lower_strings.py) and runs as a gather on device — the same
per-distinct-value trick as the string builtins.

Path grammar (subset of MySQL JSON path): `$`, `.member`, `."quoted"`,
`[N]`.  Multiple-path and wildcard forms are not supported.
"""

from __future__ import annotations

import json
import re
from typing import Any, Optional

_STEP = re.compile(
    r"""\.(?:([A-Za-z_][A-Za-z0-9_]*)|"((?:[^"\\]|\\.)*)")|\[(\d+)\]""")


class JSONPathError(ValueError):
    pass


def parse_path(path: str):
    if not path.startswith("$"):
        raise JSONPathError(f"bad JSON path {path!r}")
    steps = []
    i = 1
    while i < len(path):
        m = _STEP.match(path, i)
        if m is None:
            raise JSONPathError(f"bad JSON path {path!r} at {i}")
        if m.group(3) is not None:
            steps.append(int(m.group(3)))
        else:
            steps.append(m.group(1) if m.group(1) is not None
                         else m.group(2).encode().decode("unicode_escape"))
        i = m.end()
    return steps


def _loads(text: str):
    return json.loads(text)


def _walk(doc: Any, steps) -> tuple[bool, Any]:
    for s in steps:
        if isinstance(s, int):
            if isinstance(doc, list) and 0 <= s < len(doc):
                doc = doc[s]
            elif s == 0 and not isinstance(doc, list):
                continue         # MySQL: $[0] of a scalar is the scalar
            else:
                return False, None
        else:
            if isinstance(doc, dict) and s in doc:
                doc = doc[s]
            else:
                return False, None
    return True, doc


def _dump(v: Any) -> str:
    return json.dumps(v, separators=(", ", ": "), ensure_ascii=False)


def extract(text: str, path: str) -> Optional[str]:
    """JSON text of the value at `path`, or None (SQL NULL) on a miss or
    invalid input document."""
    try:
        doc = _loads(text)
    except ValueError:
        return None
    ok, v = _walk(doc, parse_path(path))
    return _dump(v) if ok else None


def unquote(text: str) -> str:
    """JSON_UNQUOTE: strip quotes of a JSON string literal; other values
    pass through unchanged."""
    t = text.strip()
    if len(t) >= 2 and t[0] == '"' and t[-1] == '"':
        try:
            v = _loads(t)
            if isinstance(v, str):
                return v
        except ValueError:
            pass
    return text


def jtype(text: str) -> Optional[str]:
    try:
        v = _loads(text)
    except ValueError:
        return None
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "BOOLEAN"
    if isinstance(v, int):
        return "INTEGER"
    if isinstance(v, float):
        return "DOUBLE"
    if isinstance(v, str):
        return "STRING"
    if isinstance(v, list):
        return "ARRAY"
    return "OBJECT"


def valid(text: str) -> int:
    try:
        _loads(text)
        return 1
    except ValueError:
        return 0


def jlength(text: str, path: str = "$") -> Optional[int]:
    try:
        doc = _loads(text)
    except ValueError:
        return None
    ok, v = _walk(doc, parse_path(path))
    if not ok:
        return None
    if isinstance(v, dict) or isinstance(v, list):
        return len(v)
    return 1


def _contained(target: Any, cand: Any) -> bool:
    """MySQL JSON_CONTAINS semantics."""
    if isinstance(target, list):
        if isinstance(cand, list):
            return all(any(_contained(t, c) for t in target) for c in cand)
        return any(_contained(t, cand) for t in target)
    if isinstance(target, dict) and isinstance(cand, dict):
        return all(k in target and _contained(target[k], v)
                   for k, v in cand.items())
    return type(target) is type(cand) and target == cand or \
        (isinstance(target, (int, float))
         and isinstance(cand, (int, float))
         and not isinstance(target, bool) and not isinstance(cand, bool)
         and target == cand)


def contains(text: str, candidate: str, path: str = "$") -> Optional[int]:
    try:
        doc = _loads(text)
        cand = _loads(candidate)
    except ValueError:
        return None
    ok, v = _walk(doc, parse_path(path))
    if not ok:
        return None
    return int(_contained(v, cand))


__all__ = ["extract", "unquote", "jtype", "valid", "jlength", "contains",
           "parse_path", "JSONPathError"]


# ------------------------------------------------------------------ #
# modification + search family (reference: pkg/types/json_binary_functions.go)

def _set_at(doc, steps, value, mode: str):
    """Set value at path; mode 'set'|'insert'|'replace'.  Returns doc."""
    if not steps:
        return value if mode in ("set", "replace") else doc
    cur = doc
    for i, s in enumerate(steps[:-1]):
        ok, nxt = _walk(cur, [s])
        if not ok:
            return doc           # intermediate missing: no-op (MySQL)
        cur = nxt
    last = steps[-1]
    if isinstance(last, int):
        if isinstance(cur, list):
            if 0 <= last < len(cur):
                if mode in ("set", "replace"):
                    cur[last] = value
            elif mode in ("set", "insert"):
                cur.append(value)
        return doc
    if isinstance(cur, dict):
        if last in cur:
            if mode in ("set", "replace"):
                cur[last] = value
        elif mode in ("set", "insert"):
            cur[last] = value
    return doc


def _parse_value(v):
    """A const argument as a JSON value.  SQL strings stay JSON STRINGS
    (MySQL: JSON_SET('{}','$.a','[1,2]') stores the text, not an array);
    non-string scalars pass through."""
    if isinstance(v, (int, float, bool)) or v is None:
        return v
    return str(v)


def modify(text: str, mode: str, *pairs) -> Optional[str]:
    """JSON_SET/INSERT/REPLACE: pairs = (path, value, path, value...)."""
    try:
        doc = _loads(text)
    except ValueError:
        return None
    for i in range(0, len(pairs) - 1, 2):
        try:
            steps = parse_path(str(pairs[i]))
        except JSONPathError:
            return None
        doc = _set_at(doc, steps, _parse_value(pairs[i + 1]), mode)
    return _dump(doc)


def remove(text: str, *paths) -> Optional[str]:
    try:
        doc = _loads(text)
    except ValueError:
        return None
    for p in paths:
        try:
            steps = parse_path(str(p))
        except JSONPathError:
            return None
        if not steps:
            return None          # MySQL errors on '$'; NULL here
        ok, parent = _walk(doc, steps[:-1])
        if not ok:
            continue
        last = steps[-1]
        if isinstance(last, int) and isinstance(parent, list) \
                and 0 <= last < len(parent):
            del parent[last]
        elif isinstance(last, str) and isinstance(parent, dict) \
                and last in parent:
            del parent[last]
    return _dump(doc)


def keys(text: str, path: str = "$") -> Optional[str]:
    try:
        doc = _loads(text)
        ok, v = _walk(doc, parse_path(path))
    except (ValueError, JSONPathError):
        return None
    if not ok or not isinstance(v, dict):
        return None
    return _dump(list(v.keys()))


def depth(text: str) -> Optional[int]:
    try:
        doc = _loads(text)
    except ValueError:
        return None

    def d(v):
        if isinstance(v, dict):
            return 1 + max((d(x) for x in v.values()), default=0)
        if isinstance(v, list):
            return 1 + max((d(x) for x in v), default=0)
        return 1
    return d(doc)


def search(text: str, one_or_all: str, target: str,
           escape=None, *paths) -> Optional[str]:
    """JSON_SEARCH(doc, one|all, pattern[, escape[, path...]]): % / _
    wildcards with an optional escape char, scoped to `paths`."""
    import re as _re
    try:
        doc = _loads(text)
    except ValueError:
        return None
    esc = str(escape) if escape not in (None, "") else "\\"
    out = []
    i = 0
    while i < len(target):
        c = target[i]
        if c == esc and i + 1 < len(target):
            out.append(_re.escape(target[i + 1]))
            i += 2
            continue
        out.append(".*" if c == "%" else "." if c == "_"
                   else _re.escape(c))
        i += 1
    rx = _re.compile("^" + "".join(out) + "$", _re.S)
    scopes = None
    if paths:
        try:
            scopes = [parse_path(str(p)) for p in paths]
        except JSONPathError:
            return None
    hits: list[str] = []

    def in_scope(steps) -> bool:
        if scopes is None:
            return True
        return any(steps[:len(sc)] == sc for sc in scopes)

    def render(steps) -> str:
        out = "$"
        for s in steps:
            if isinstance(s, int):
                out += f"[{s}]"
            elif _re.search(r"\W", s):
                out += f'."{s}"'
            else:
                out += f".{s}"
        return out

    def walk(v, steps):
        if isinstance(v, str) and rx.match(v) and in_scope(steps):
            hits.append(render(steps))
        elif isinstance(v, dict):
            for k, x in v.items():
                walk(x, steps + [k])
        elif isinstance(v, list):
            for i2, x in enumerate(v):
                walk(x, steps + [i2])
    walk(doc, [])
    if not hits:
        return None
    if one_or_all.lower() == "one":
        return _dump(hits[0])
    return _dump(hits[0] if len(hits) == 1 else hits)


def merge_patch(text: str, *others) -> Optional[str]:
    def patch(a, b):
        if not isinstance(b, dict):
            return b
        if not isinstance(a, dict):
            a = {}
        for k, v in b.items():
            if v is None:
                a.pop(k, None)
            else:
                a[k] = patch(a.get(k), v)
        return a
    try:
        doc = _loads(text)
        for o in others:
            doc = patch(doc, _loads(str(o)))
    except ValueError:
        return None
    return _dump(doc)


def merge_preserve(text: str, *others) -> Optional[str]:
    def merge(a, b):
        if isinstance(a, dict) and isinstance(b, dict):
            for k, v in b.items():
                a[k] = merge(a[k], v) if k in a else v
            return a
        la = a if isinstance(a, list) else [a]
        lb = b if isinstance(b, list) else [b]
        return la + lb
    try:
        doc = _loads(text)
        for o in others:
            doc = merge(doc, _loads(str(o)))
    except ValueError:
        return None
    return _dump(doc)


def array_append(text: str, *pairs) -> Optional[str]:
    try:
        doc = _loads(text)
    except ValueError:
        return None
    for i in range(0, len(pairs) - 1, 2):
        try:
            steps = parse_path(str(pairs[i]))
        except JSONPathError:
            return None
        ok, v = _walk(doc, steps)
        if not ok:
            continue
        val = _parse_value(pairs[i + 1])
        if isinstance(v, list):
            v.append(val)
        elif not steps:
            doc = [doc, val]
        else:
            _set_at(doc, steps, [v, val], "replace")
    return _dump(doc)


def contains_path(text: str, one_or_all: str, *paths) -> Optional[int]:
    try:
        doc = _loads(text)
    except ValueError:
        return None
    hits = 0
    for p in paths:
        try:
            ok, _v = _walk(doc, parse_path(str(p)))
        except JSONPathError:
            return None
        hits += bool(ok)
    return int(hits == len(paths) if one_or_all.lower() == "all"
               else hits > 0)


def pretty(text: str) -> Optional[str]:
    try:
        return json.dumps(_loads(text), indent=2, ensure_ascii=False)
    except ValueError:
        return None


def storage_size(text: str) -> Optional[int]:
    try:
        _loads(text)
    except ValueError:
        return None
    return len(text.encode())


def quote(text: str) -> str:
    return json.dumps(text, ensure_ascii=False)


def overlaps(text: str, other: str) -> Optional[int]:
    try:
        a, b = _loads(text), _loads(str(other))
    except ValueError:
        return None
    la = a if isinstance(a, list) else [a]
    lb = b if isinstance(b, list) else [b]
    if isinstance(a, dict) and isinstance(b, dict):
        return int(any(k in b and b[k] == v for k, v in a.items()))
    return int(any(x in lb for x in la))


def value_at(text: str, path: str) -> Optional[str]:
    """JSON_VALUE default (RETURNING omitted): unquoted scalar text."""
    try:
        got = extract(text, path)
    except JSONPathError:
        return None
    return None if got is None else unquote(got)
