"""JSON builtin implementations over JSON text values.

Reference analog: pkg/types/json_binary*.go + pkg/expression/builtin_json*.
JSON columns store normalized text dict-encoded like VARCHAR, so every
JSON_* builtin evaluates ONCE per distinct value over the dictionary
(expr/lower_strings.py) and runs as a gather on device — the same
per-distinct-value trick as the string builtins.

Path grammar (subset of MySQL JSON path): `$`, `.member`, `."quoted"`,
`[N]`.  Multiple-path and wildcard forms are not supported.
"""

from __future__ import annotations

import json
import re
from typing import Any, Optional

_STEP = re.compile(
    r"""\.(?:([A-Za-z_][A-Za-z0-9_]*)|"((?:[^"\\]|\\.)*)")|\[(\d+)\]""")


class JSONPathError(ValueError):
    pass


def parse_path(path: str):
    if not path.startswith("$"):
        raise JSONPathError(f"bad JSON path {path!r}")
    steps = []
    i = 1
    while i < len(path):
        m = _STEP.match(path, i)
        if m is None:
            raise JSONPathError(f"bad JSON path {path!r} at {i}")
        if m.group(3) is not None:
            steps.append(int(m.group(3)))
        else:
            steps.append(m.group(1) if m.group(1) is not None
                         else m.group(2).encode().decode("unicode_escape"))
        i = m.end()
    return steps


def _loads(text: str):
    return json.loads(text)


def _walk(doc: Any, steps) -> tuple[bool, Any]:
    for s in steps:
        if isinstance(s, int):
            if isinstance(doc, list) and 0 <= s < len(doc):
                doc = doc[s]
            elif s == 0 and not isinstance(doc, list):
                continue         # MySQL: $[0] of a scalar is the scalar
            else:
                return False, None
        else:
            if isinstance(doc, dict) and s in doc:
                doc = doc[s]
            else:
                return False, None
    return True, doc


def _dump(v: Any) -> str:
    return json.dumps(v, separators=(", ", ": "), ensure_ascii=False)


def extract(text: str, path: str) -> Optional[str]:
    """JSON text of the value at `path`, or None (SQL NULL) on a miss or
    invalid input document."""
    try:
        doc = _loads(text)
    except ValueError:
        return None
    ok, v = _walk(doc, parse_path(path))
    return _dump(v) if ok else None


def unquote(text: str) -> str:
    """JSON_UNQUOTE: strip quotes of a JSON string literal; other values
    pass through unchanged."""
    t = text.strip()
    if len(t) >= 2 and t[0] == '"' and t[-1] == '"':
        try:
            v = _loads(t)
            if isinstance(v, str):
                return v
        except ValueError:
            pass
    return text


def jtype(text: str) -> Optional[str]:
    try:
        v = _loads(text)
    except ValueError:
        return None
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "BOOLEAN"
    if isinstance(v, int):
        return "INTEGER"
    if isinstance(v, float):
        return "DOUBLE"
    if isinstance(v, str):
        return "STRING"
    if isinstance(v, list):
        return "ARRAY"
    return "OBJECT"


def valid(text: str) -> int:
    try:
        _loads(text)
        return 1
    except ValueError:
        return 0


def jlength(text: str, path: str = "$") -> Optional[int]:
    try:
        doc = _loads(text)
    except ValueError:
        return None
    ok, v = _walk(doc, parse_path(path))
    if not ok:
        return None
    if isinstance(v, dict) or isinstance(v, list):
        return len(v)
    return 1


def _contained(target: Any, cand: Any) -> bool:
    """MySQL JSON_CONTAINS semantics."""
    if isinstance(target, list):
        if isinstance(cand, list):
            return all(any(_contained(t, c) for t in target) for c in cand)
        return any(_contained(t, cand) for t in target)
    if isinstance(target, dict) and isinstance(cand, dict):
        return all(k in target and _contained(target[k], v)
                   for k, v in cand.items())
    return type(target) is type(cand) and target == cand or \
        (isinstance(target, (int, float))
         and isinstance(cand, (int, float))
         and not isinstance(target, bool) and not isinstance(cand, bool)
         and target == cand)


def contains(text: str, candidate: str, path: str = "$") -> Optional[int]:
    try:
        doc = _loads(text)
        cand = _loads(candidate)
    except ValueError:
        return None
    ok, v = _walk(doc, parse_path(path))
    if not ok:
        return None
    return int(_contained(v, cand))


__all__ = ["extract", "unquote", "jtype", "valid", "jlength", "contains",
           "parse_path", "JSONPathError"]
