"""Metrics registry: counters, gauges, histograms + Prometheus text.

Reference analog: pkg/metrics (metrics.go RegisterMetrics; per-subsystem
counter/histogram vectors scraped from the status port).  A tiny
label-aware registry; updates take a per-metric lock (connection threads
bump concurrently — read-modify-write is not GIL-atomic).
"""

from __future__ import annotations

import bisect
import threading
from typing import Optional, Sequence


class Counter:
    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self.values: dict[tuple, float] = {}
        self._mu = threading.Lock()

    def inc(self, n: float = 1, **labels):
        key = tuple(labels.get(ln, "") for ln in self.label_names)
        with self._mu:
            self.values[key] = self.values.get(key, 0) + n

    def get(self, **labels) -> float:
        key = tuple(labels.get(ln, "") for ln in self.label_names)
        return self.values.get(key, 0)


class Gauge(Counter):
    def set(self, v: float, **labels):
        key = tuple(labels.get(ln, "") for ln in self.label_names)
        with self._mu:
            self.values[key] = v

    def dec(self, n: float = 1, **labels):
        self.inc(-n, **labels)


class Histogram:
    DEFAULT_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5,
                       10, 60)

    def __init__(self, name: str, help_: str,
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.n = 0
        self._mu = threading.Lock()

    def observe(self, v: float):
        with self._mu:
            self.counts[bisect.bisect_left(self.buckets, v)] += 1
            self.total += v
            self.n += 1

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket upper bounds."""
        if self.n == 0:
            return 0.0
        target = q * self.n
        acc = 0
        for i, c in enumerate(self.counts[:-1]):
            acc += c
            if acc >= target:
                return self.buckets[i]
        return self.buckets[-1]


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.metrics: dict[str, object] = {}

    def counter(self, name: str, help_: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_make(name, lambda: Counter(name, help_, labels))

    def gauge(self, name: str, help_: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_make(name, lambda: Gauge(name, help_, labels))

    def histogram(self, name: str, help_: str = "",
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_make(name, lambda: Histogram(name, help_, buckets))

    def _get_or_make(self, name, make):
        with self._lock:
            m = self.metrics.get(name)
            if m is None:
                m = self.metrics[name] = make()
            return m

    def prometheus_text(self) -> str:
        out = []
        for name in sorted(self.metrics):
            m = self.metrics[name]
            if isinstance(m, Histogram):
                out.append(f"# TYPE {name} histogram")
                with m._mu:
                    counts, total, n = list(m.counts), m.total, m.n
                acc = 0
                for ub, c in zip(m.buckets, counts):
                    acc += c
                    out.append(f'{name}_bucket{{le="{ub}"}} {acc}')
                out.append(f'{name}_bucket{{le="+Inf"}} {n}')
                out.append(f"{name}_sum {total}")
                out.append(f"{name}_count {n}")
            else:
                kind = "gauge" if isinstance(m, Gauge) else "counter"
                out.append(f"# TYPE {name} {kind}")
                with m._mu:
                    values = dict(m.values)
                if not values:
                    out.append(f"{name} 0")
                for key, v in sorted(values.items()):
                    if m.label_names:
                        lbl = ",".join(f'{ln}="{kv}"' for ln, kv
                                       in zip(m.label_names, key))
                        out.append(f"{name}{{{lbl}}} {v}")
                    else:
                        out.append(f"{name} {v}")
        return "\n".join(out) + "\n"


_global: Optional[Registry] = None


def global_registry() -> Registry:
    global _global
    if _global is None:
        _global = Registry()
    return _global


__all__ = ["Registry", "Counter", "Gauge", "Histogram", "global_registry"]
