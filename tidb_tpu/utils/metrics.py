"""Metrics registry: counters, gauges, histograms + Prometheus text.

Reference analog: pkg/metrics (metrics.go RegisterMetrics; per-subsystem
counter/histogram vectors scraped from the status port).  A tiny
label-aware registry; updates take a per-metric lock (connection threads
bump concurrently — read-modify-write is not GIL-atomic).
"""

from __future__ import annotations

import bisect
import threading
from typing import Optional, Sequence


class Counter:
    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self.values: dict[tuple, float] = {}
        self._mu = threading.Lock()

    def inc(self, n: float = 1, **labels):
        key = tuple(labels.get(ln, "") for ln in self.label_names)
        with self._mu:
            self.values[key] = self.values.get(key, 0) + n

    def get(self, **labels) -> float:
        key = tuple(labels.get(ln, "") for ln in self.label_names)
        return self.values.get(key, 0)


class Gauge(Counter):
    def set(self, v: float, **labels):
        key = tuple(labels.get(ln, "") for ln in self.label_names)
        with self._mu:
            self.values[key] = v

    def dec(self, n: float = 1, **labels):
        self.inc(-n, **labels)


class _HistSeries:
    """One (label-set) series of a Histogram: bucket counts + sum."""

    __slots__ = ("counts", "total", "n")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)
        self.total = 0.0
        self.n = 0


class Histogram:
    """Label-aware prometheus-text histogram (copscope ISSUE 13 grew
    labels + millisecond buckets + interpolated quantiles so the sched
    latency histograms — ``tidb_tpu_sched_{wait,launch,compile}_ms``
    and the per-strategy agg launch histogram — replace the ad-hoc
    p50/p99 rings in bench/status surfaces)."""

    DEFAULT_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5,
                       10, 60)
    # millisecond-scale latency buckets for the *_ms histograms: queue
    # waits sit in the 0.01-10ms band on a warm process, launches in the
    # 1-500ms band, compiles in the 100ms-10s band
    MS_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50,
                  100, 250, 500, 1000, 2500, 5000, 10000)

    def __init__(self, name: str, help_: str,
                 buckets: Optional[Sequence[float]] = None,
                 label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self.label_names = tuple(label_names)
        self._series: dict[tuple, _HistSeries] = {}
        self._mu = threading.Lock()

    def _key(self, labels: dict) -> tuple:
        return tuple(labels.get(ln, "") for ln in self.label_names)

    def observe(self, v: float, **labels):
        key = self._key(labels)
        with self._mu:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _HistSeries(len(self.buckets))
            s.counts[bisect.bisect_left(self.buckets, v)] += 1
            s.total += v
            s.n += 1

    # -- back-compat views over the unlabeled (or merged) series ------ #

    def _merged(self) -> _HistSeries:
        out = _HistSeries(len(self.buckets))
        with self._mu:
            for s in self._series.values():
                for i, c in enumerate(s.counts):
                    out.counts[i] += c
                out.total += s.total
                out.n += s.n
        return out

    @property
    def counts(self) -> list:
        return self._merged().counts

    @property
    def total(self) -> float:
        return self._merged().total

    @property
    def n(self) -> int:
        return self._merged().n

    def quantile(self, q: float, **labels) -> float:
        """Quantile estimate, linearly interpolated WITHIN the landing
        bucket (the old estimator snapped to bucket upper bounds, which
        made p50 of a tight distribution report the whole bucket).
        Without labels, merges every series."""
        if self.label_names and labels:
            with self._mu:
                s = self._series.get(self._key(labels))
            if s is None:
                return 0.0
            counts, n = list(s.counts), s.n
        else:
            m = self._merged()
            counts, n = m.counts, m.n
        if n == 0:
            return 0.0
        target = q * n
        acc = 0
        for i, c in enumerate(counts[:-1]):
            if acc + c >= target and c > 0:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                frac = (target - acc) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            acc += c
        return self.buckets[-1]

    def series_items(self) -> list:
        """[(label_key_tuple, counts, total, n)] snapshot for render."""
        with self._mu:
            return [(key, list(s.counts), s.total, s.n)
                    for key, s in sorted(self._series.items())]


def escape_label(v) -> str:
    """Prometheus exposition-format label-value escaping: backslash,
    double quote, and newline MUST be escaped (in that order — the
    backslash first, or the other escapes double up) or the scrape
    line is invalid.  A program digest or strategy label containing
    ``"`` / ``\\`` previously emitted a broken exposition line."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.metrics: dict[str, object] = {}

    def counter(self, name: str, help_: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_make(name, lambda: Counter(name, help_, labels))

    def gauge(self, name: str, help_: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_make(name, lambda: Gauge(name, help_, labels))

    def histogram(self, name: str, help_: str = "",
                  buckets: Optional[Sequence[float]] = None,
                  labels: Sequence[str] = ()) -> Histogram:
        return self._get_or_make(
            name, lambda: Histogram(name, help_, buckets, labels))

    def _get_or_make(self, name, make):
        with self._lock:
            m = self.metrics.get(name)
            if m is None:
                m = self.metrics[name] = make()
            return m

    def prometheus_text(self) -> str:
        out = []
        for name in sorted(self.metrics):
            m = self.metrics[name]
            if isinstance(m, Histogram):
                out.append(f"# TYPE {name} histogram")
                series = m.series_items()
                if not series:
                    series = [((), [0] * (len(m.buckets) + 1), 0.0, 0)]
                for key, counts, total, n in series:
                    base = ",".join(f'{ln}="{escape_label(kv)}"'
                                    for ln, kv
                                    in zip(m.label_names, key))
                    sep = "," if base else ""
                    acc = 0
                    for ub, c in zip(m.buckets, counts):
                        acc += c
                        out.append(f'{name}_bucket{{{base}{sep}le="{ub}"}}'
                                   f' {acc}')
                    out.append(f'{name}_bucket{{{base}{sep}le="+Inf"}} {n}')
                    lbl = f"{{{base}}}" if base else ""
                    out.append(f"{name}_sum{lbl} {total}")
                    out.append(f"{name}_count{lbl} {n}")
            else:
                kind = "gauge" if isinstance(m, Gauge) else "counter"
                out.append(f"# TYPE {name} {kind}")
                with m._mu:
                    values = dict(m.values)
                if not values:
                    out.append(f"{name} 0")
                for key, v in sorted(values.items()):
                    if m.label_names:
                        lbl = ",".join(f'{ln}="{escape_label(kv)}"'
                                       for ln, kv
                                       in zip(m.label_names, key))
                        out.append(f"{name}{{{lbl}}} {v}")
                    else:
                        out.append(f"{name} {v}")
        return "\n".join(out) + "\n"


_global: Optional[Registry] = None


def global_registry() -> Registry:
    global _global
    if _global is None:
        _global = Registry()
    return _global


__all__ = ["Registry", "Counter", "Gauge", "Histogram",
           "global_registry", "escape_label"]
