"""Memory governance: hierarchical tracker + action-on-exceed chain.

Reference analog: pkg/util/memory — Tracker (tracker.go:77) forms a tree
(statement -> operator), consumption propagates to the root where the
query quota (tidb_mem_quota_query) lives; on exceed the ActionOnExceed
chain (action.go:30) fires: softer actions first (spill to disk), then
cancel (the "Out Of Memory Quota!" error).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

_TRACK_MU = threading.RLock()


class MemoryExceededError(RuntimeError):
    """executor.ErrMemoryExceedForQuery analog."""

    def __init__(self, label: str, quota: int):
        super().__init__(
            f"Out Of Memory Quota! quota={quota} bytes, tracker={label}")


class Tracker:
    def __init__(self, label: str, limit: int = -1,
                 parent: Optional["Tracker"] = None):
        self.label = label
        self.limit = limit            # -1 = unlimited
        self.parent = parent
        self.consumed = 0
        self.max_consumed = 0
        self.actions = []             # softest first; last should cancel

    def attach_child(self, label: str) -> "Tracker":
        return Tracker(label, parent=self)

    def consume(self, n: int):
        # parallel host operators charge from worker threads (P10): the
        # shared counter update takes the module lock
        with _TRACK_MU:
            t = self
            while t is not None:
                t.consumed += n
                t.max_consumed = max(t.max_consumed, t.consumed)
                if 0 <= t.limit < t.consumed and n > 0:
                    t._on_exceed()
                t = t.parent

    def release(self, n: int):
        self.consume(-n)

    def _on_exceed(self):
        # softer actions first; any progress (e.g. a spill was triggered)
        # lets execution continue — the freed memory shows up via
        # release().  Only when no action can help does the query die.
        for action in self.actions:
            if action.act(self):
                return
        raise MemoryExceededError(self.label, self.limit)


class SpillDiskAction:
    """Asks registered spillable operators to move data to disk; succeeds
    if any of them frees memory (chunk/row_container.go:397 analog)."""

    def __init__(self):
        self._spillables = []

    def register(self, spillable):
        self._spillables.append(spillable)

    def act(self, tracker: Tracker) -> bool:
        progressed = False
        for sp in self._spillables:
            if sp.offer_spill():
                progressed = True
        return progressed


def sysvar_bool(v, default: bool = True) -> bool:
    """MySQL boolean sysvar forms: ON/OFF/TRUE/FALSE/1/0 (any case)."""
    if v is None:
        return default
    if isinstance(v, str):
        return v.strip().upper() in ("1", "ON", "TRUE", "YES")
    return bool(int(v))


def nbytes_of(columns) -> int:
    """Approximate bytes held by a list of chunk Columns."""
    total = 0
    for c in columns:
        total += c.data.nbytes + c.validity.nbytes
    return total
