"""Small reader-writer lock.

Used as the in-process analog of the F1 schema-lease wait (pkg/ddl
syncer): DML statements hold the read side for their duration; a DDL
state transition takes the write side, which drains in-flight writers
before the next schema state becomes visible (SURVEY.md §3.4: "after
EACH transition: wait all nodes ack").
"""

from __future__ import annotations

import threading
from contextlib import contextmanager


class RWLock:
    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    @contextmanager
    def read(self):
        with self._cond:
            # writer preference: new readers also yield to WAITING writers,
            # else a steady DML stream starves DDL transitions forever
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1
        try:
            yield
        finally:
            with self._cond:
                self._readers -= 1
                if self._readers == 0:
                    self._cond.notify_all()

    @contextmanager
    def write(self):
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
                self._writer = True
            finally:
                self._writers_waiting -= 1
        try:
            yield
        finally:
            with self._cond:
                self._writer = False
                self._cond.notify_all()


__all__ = ["RWLock"]
