"""Disk-backed chunk container + external (spilling) algorithms.

Reference analog: pkg/util/chunk/row_container.go + chunk_in_disk.go (the
spill containers) and the per-operator spill paths (sortexec
parallel_sort_spill_helper.go, aggregate/agg_spill.go,
join/hash_join_spill.go) — SURVEY.md §5.7.  Partitions are written as
compressed .npz files (dense numpy buffers — the same buffers the device
path zero-copies, so spill/restore is cheap and exact).
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass

import numpy as np


class SpilledPartition:
    """One partition of columns on disk."""

    def __init__(self, path: str, dtypes, dictionaries):
        self.path = path
        self.dtypes = dtypes
        self.dictionaries = dictionaries

    @classmethod
    def write(cls, tmpdir: str, tag: str, columns) -> "SpilledPartition":
        arrays = {}
        for i, c in enumerate(columns):
            arrays[f"d{i}"] = c.data
            arrays[f"v{i}"] = c.validity
        path = os.path.join(tmpdir, f"{tag}.npz")
        np.savez(path, **arrays)
        return cls(path, [c.dtype for c in columns],
                   [c.dictionary for c in columns])

    def read(self):
        from ..chunk.column import Column
        with np.load(self.path, allow_pickle=False) as z:
            return [Column(t, z[f"d{i}"], z[f"v{i}"], d)
                    for i, (t, d) in enumerate(zip(self.dtypes,
                                                   self.dictionaries))]

    def delete(self):
        try:
            os.unlink(self.path)
        except OSError:
            pass


def partition_to_disk(columns, part_of: np.ndarray, n_parts: int,
                      tmpdir: str, tag: str):
    """Split rows by partition id, spill each partition; returns the list
    of SpilledPartitions (empty partitions omitted, index kept)."""
    parts = []
    for p in range(n_parts):
        idx = np.nonzero(part_of == p)[0]
        if len(idx) == 0:
            parts.append(None)
            continue
        parts.append(SpilledPartition.write(
            tmpdir, f"{tag}-{p}", [c.take(idx) for c in columns]))
    return parts


def external_sort_index(ranks, tmpdir: str, block_rows: int) -> np.ndarray:
    """Row order for lexsort(ranks) computed run-at-a-time: each block is
    sorted independently (bounded working set) and spilled as raw .npy
    files; the k-way merge streams the runs back through memory-mapped
    views, so peak RAM stays O(block) + the output index, never the full
    rank matrix (sortexec/multi_way_merge.go analog)."""
    n = len(ranks[0]) if ranks else 0
    if n == 0:
        return np.arange(0)
    nk = len(ranks)
    runs = []     # list of per-run dirs holding k0..k{nk-1}.npy + idx.npy
    for start in range(0, n, block_rows):
        sl = slice(start, min(start + block_rows, n))
        blk = [r[sl] for r in ranks]
        order = np.lexsort(tuple(reversed(blk)))
        rd = os.path.join(tmpdir, f"run-{len(runs)}")
        os.makedirs(rd)
        for i, k in enumerate(blk):
            np.save(os.path.join(rd, f"k{i}.npy"), k[order])
        np.save(os.path.join(rd, "idx.npy"),
                np.arange(sl.start, sl.stop, dtype=np.int64)[order])
        runs.append(rd)
    if len(runs) == 1:
        return np.load(os.path.join(runs[0], "idx.npy"))
    # k-way merge over memmapped runs (OS pages blocks in and out)
    import heapq
    keys = [[np.load(os.path.join(rd, f"k{i}.npy"), mmap_mode="r")
             for i in range(nk)] for rd in runs]
    idxs = [np.load(os.path.join(rd, "idx.npy"), mmap_mode="r")
            for rd in runs]
    heap = [(tuple(k[0].item() for k in keys[r]), r)
            for r in range(len(runs)) if len(idxs[r])]
    heapq.heapify(heap)
    out = np.empty(n, np.int64)
    pos = [0] * len(runs)
    w = 0
    while heap:
        _, r = heapq.heappop(heap)
        out[w] = idxs[r][pos[r]]
        w += 1
        pos[r] += 1
        if pos[r] < len(idxs[r]):
            heapq.heappush(
                heap, (tuple(k[pos[r]].item() for k in keys[r]), r))
    return out


class SortedRun:
    """One sorted run on disk: row columns + the sort-rank matrix, both in
    sorted order, stored as raw .npy files so the merge can memory-map
    them (sortexec/parallel_sort_spill_helper.go run analog).  Unlike
    external_sort_index, the run carries the ROWS — the producer may drop
    its input chunks after spilling (streaming sort)."""

    def __init__(self, path: str, n: int, nk: int, dtypes, dicts):
        self.path = path
        self.n = n
        self.nk = nk
        self.dtypes = dtypes
        self.dicts = dicts

    @classmethod
    def write(cls, tmpdir: str, tag: str, columns, ranks) -> "SortedRun":
        order = np.lexsort(tuple(reversed(ranks)))
        rd = os.path.join(tmpdir, tag)
        os.makedirs(rd)
        for i, c in enumerate(columns):
            np.save(os.path.join(rd, f"d{i}.npy"), c.data[order])
            np.save(os.path.join(rd, f"v{i}.npy"), c.validity[order])
        for j, k in enumerate(ranks):
            np.save(os.path.join(rd, f"k{j}.npy"), k[order])
        return cls(rd, len(order), len(ranks),
                   [c.dtype for c in columns],
                   [c.dictionary for c in columns])

    def open(self):
        """(rank memmaps, [(data, validity) memmaps])."""
        ks = [np.load(os.path.join(self.path, f"k{j}.npy"), mmap_mode="r")
              for j in range(self.nk)]
        cs = [(np.load(os.path.join(self.path, f"d{i}.npy"), mmap_mode="r"),
               np.load(os.path.join(self.path, f"v{i}.npy"), mmap_mode="r"))
              for i in range(len(self.dtypes))]
        return ks, cs


def merge_sorted_runs(runs, out_rows: int):
    """Streaming k-way merge of SortedRuns: yields lists of Columns of up
    to out_rows rows in globally sorted order.  Peak RAM is O(out_rows)
    plus the OS page cache over the memmapped runs — the keep-order
    streaming-merge seam (sortexec/multi_way_merge.go,
    distsql SelectResult keep-order merge analog)."""
    import heapq

    from ..chunk.column import Column

    if not runs:
        return
    opened = [r.open() for r in runs]
    dtypes, dicts = runs[0].dtypes, runs[0].dicts
    heap = [(tuple(k[0].item() for k in opened[r][0]), r)
            for r in range(len(runs)) if runs[r].n]
    heapq.heapify(heap)
    pos = [0] * len(runs)
    rid_buf: list[int] = []
    pos_buf: list[int] = []

    def gather():
        rid = np.asarray(rid_buf, np.int64)
        p = np.asarray(pos_buf, np.int64)
        cols = []
        for i, t in enumerate(dtypes):
            out = np.empty(len(rid), opened[0][1][i][0].dtype)
            val = np.empty(len(rid), bool)
            for r in set(rid_buf):
                m = rid == r
                out[m] = opened[r][1][i][0][p[m]]
                val[m] = opened[r][1][i][1][p[m]]
            cols.append(Column(t, out, val, dicts[i]))
        rid_buf.clear()
        pos_buf.clear()
        return cols

    while heap:
        _, r = heapq.heappop(heap)
        rid_buf.append(r)
        pos_buf.append(pos[r])
        pos[r] += 1
        if pos[r] < runs[r].n:
            heapq.heappush(
                heap, (tuple(k[pos[r]].item() for k in opened[r][0]), r))
        if len(rid_buf) >= out_rows:
            yield gather()
    if rid_buf:
        yield gather()


def spill_dir() -> tempfile.TemporaryDirectory:
    return tempfile.TemporaryDirectory(prefix="tidb-tpu-spill-")
