"""Disk-backed chunk container + external (spilling) algorithms.

Reference analog: pkg/util/chunk/row_container.go + chunk_in_disk.go (the
spill containers) and the per-operator spill paths (sortexec
parallel_sort_spill_helper.go, aggregate/agg_spill.go,
join/hash_join_spill.go) — SURVEY.md §5.7.  Partitions are written as
compressed .npz files (dense numpy buffers — the same buffers the device
path zero-copies, so spill/restore is cheap and exact).
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass

import numpy as np


class SpilledPartition:
    """One partition of columns on disk."""

    def __init__(self, path: str, dtypes, dictionaries):
        self.path = path
        self.dtypes = dtypes
        self.dictionaries = dictionaries

    @classmethod
    def write(cls, tmpdir: str, tag: str, columns) -> "SpilledPartition":
        arrays = {}
        for i, c in enumerate(columns):
            arrays[f"d{i}"] = c.data
            arrays[f"v{i}"] = c.validity
        path = os.path.join(tmpdir, f"{tag}.npz")
        np.savez(path, **arrays)
        return cls(path, [c.dtype for c in columns],
                   [c.dictionary for c in columns])

    def read(self):
        from ..chunk.column import Column
        with np.load(self.path, allow_pickle=False) as z:
            return [Column(t, z[f"d{i}"], z[f"v{i}"], d)
                    for i, (t, d) in enumerate(zip(self.dtypes,
                                                   self.dictionaries))]

    def delete(self):
        try:
            os.unlink(self.path)
        except OSError:
            pass


def partition_to_disk(columns, part_of: np.ndarray, n_parts: int,
                      tmpdir: str, tag: str):
    """Split rows by partition id, spill each partition; returns the list
    of SpilledPartitions (empty partitions omitted, index kept)."""
    parts = []
    for p in range(n_parts):
        idx = np.nonzero(part_of == p)[0]
        if len(idx) == 0:
            parts.append(None)
            continue
        parts.append(SpilledPartition.write(
            tmpdir, f"{tag}-{p}", [c.take(idx) for c in columns]))
    return parts


def external_sort_index(ranks, tmpdir: str, block_rows: int) -> np.ndarray:
    """Row order for lexsort(ranks) computed run-at-a-time: each block is
    sorted independently (bounded working set) and spilled as raw .npy
    files; the k-way merge streams the runs back through memory-mapped
    views, so peak RAM stays O(block) + the output index, never the full
    rank matrix (sortexec/multi_way_merge.go analog)."""
    n = len(ranks[0]) if ranks else 0
    if n == 0:
        return np.arange(0)
    nk = len(ranks)
    runs = []     # list of per-run dirs holding k0..k{nk-1}.npy + idx.npy
    for start in range(0, n, block_rows):
        sl = slice(start, min(start + block_rows, n))
        blk = [r[sl] for r in ranks]
        order = np.lexsort(tuple(reversed(blk)))
        rd = os.path.join(tmpdir, f"run-{len(runs)}")
        os.makedirs(rd)
        for i, k in enumerate(blk):
            np.save(os.path.join(rd, f"k{i}.npy"), k[order])
        np.save(os.path.join(rd, "idx.npy"),
                np.arange(sl.start, sl.stop, dtype=np.int64)[order])
        runs.append(rd)
    if len(runs) == 1:
        return np.load(os.path.join(runs[0], "idx.npy"))
    # k-way merge over memmapped runs (OS pages blocks in and out)
    import heapq
    keys = [[np.load(os.path.join(rd, f"k{i}.npy"), mmap_mode="r")
             for i in range(nk)] for rd in runs]
    idxs = [np.load(os.path.join(rd, "idx.npy"), mmap_mode="r")
            for rd in runs]
    heap = [(tuple(k[0].item() for k in keys[r]), r)
            for r in range(len(runs)) if len(idxs[r])]
    heapq.heapify(heap)
    out = np.empty(n, np.int64)
    pos = [0] * len(runs)
    w = 0
    while heap:
        _, r = heapq.heappop(heap)
        out[w] = idxs[r][pos[r]]
        w += 1
        pos[r] += 1
        if pos[r] < len(idxs[r]):
            heapq.heappush(
                heap, (tuple(k[pos[r]].item() for k in keys[r]), r))
    return out


def spill_dir() -> tempfile.TemporaryDirectory:
    return tempfile.TemporaryDirectory(prefix="tidb-tpu-spill-")
