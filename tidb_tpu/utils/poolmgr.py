"""Global CPU-aware worker-pool manager.

Reference analog: pkg/resourcemanager (resourcemanager.go GlobalResourceManager)
— one process-wide registry of named thread pools sized from the host's
core count, so components BORROW execution slots instead of each owning a
private pool (the reference's "pool of pools" discipline).  Pools are
created on first use, shared across queries/operators, resized live, and
export usage stats to metrics + information_schema (pool introspection).

numpy/XLA host kernels release the GIL, so thread pools scale the
vectorized per-chunk work across cores exactly like the reference's
goroutine pools scale its row-loop work.
"""

from __future__ import annotations

import concurrent.futures as cf
import math
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class PoolStats:
    name: str
    workers: int
    weight: float
    submitted: int = 0
    completed: int = 0
    busy: int = 0                  # tasks currently running
    total_wait_s: float = 0.0      # queue wait accumulated
    total_run_s: float = 0.0


@dataclass
class _Pool:
    executor: cf.ThreadPoolExecutor
    stats: PoolStats
    rank: int = 0                  # creation order (nesting DAG order)
    mu: threading.Lock = field(default_factory=threading.Lock)


class PoolManager:
    """Process singleton owning every named worker pool."""

    def __init__(self, cpu: Optional[int] = None,
                 retire_grace_s: float = 5.0):
        self.cpu = cpu or os.cpu_count() or 1
        self._pools: dict[str, _Pool] = {}
        self._retired: list = []       # resized-away executors (draining)
        self._mu = threading.Lock()
        # how long a replaced executor stays submittable before its idle
        # threads are released (covers submit() callers racing a resize)
        self.retire_grace_s = retire_grace_s

    # ---------------- pool lifecycle ---------------- #

    def pool(self, name: str, weight: float = 1.0,
             max_workers: Optional[int] = None) -> cf.ThreadPoolExecutor:
        """Get-or-create the shared pool `name`, sized
        ceil(cpu * weight) capped by max_workers.  Never shut down by
        callers — the manager owns lifecycle."""
        p = self._pools.get(name)
        if p is not None:
            return p.executor
        with self._mu:
            p = self._pools.get(name)
            if p is None:
                n = max(1, math.ceil(self.cpu * weight))
                if max_workers:
                    n = min(n, max_workers)
                ex = cf.ThreadPoolExecutor(
                    max_workers=n, thread_name_prefix=f"pool-{name}")
                p = self._pools[name] = _Pool(
                    ex, PoolStats(name, n, weight),
                    rank=len(self._pools))
            return p.executor

    def ensure(self, name: str, min_workers: int) -> None:
        """Grow (never shrink) a pool to at least `min_workers` — callers
        whose concurrency knob exceeds the default sizing."""
        self.pool(name)
        with self._mu:
            need = self._pools[name].stats.workers < min_workers
        if need:
            self.resize(name, min_workers)

    def resize(self, name: str, workers: int) -> None:
        """Live resize (the reference's pool.Tune): swap in a new
        executor.  The old one stays submittable for a grace window — a
        concurrent submit() that fetched it must not hit 'cannot
        schedule new futures after shutdown' — then a reaper drains it
        with shutdown(wait=False), which lets already-queued work finish
        while releasing the idle worker threads (ADVICE r5: the previous
        retain-forever policy leaked a full thread set per resize)."""
        workers = max(1, workers)
        with self._mu:
            p = self._pools.get(name)
            if p is None:
                return
            old = p.executor
            self._retired.append(old)
            p.executor = cf.ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix=f"pool-{name}")
            p.stats.workers = workers

        def _reap(ex=old, grace=self.retire_grace_s):
            time.sleep(grace)
            ex.shutdown(wait=False)
            with self._mu:
                try:
                    self._retired.remove(ex)
                except ValueError:
                    pass
        threading.Thread(target=_reap, name=f"pool-reap-{name}",
                         daemon=True).start()

    # ---------------- instrumented submission ---------------- #

    def submit(self, name: str, fn, /, *args, weight: float = 1.0,
               **kw) -> cf.Future:
        # deadlock-free nesting rule: a pool worker's submission QUEUES
        # only when the target pool ranks strictly higher (creation
        # order) — queued-and-awaited edges then form a DAG, so no
        # worker cycle (executor -> apply -> executor) can ever block on
        # itself; same-pool and downhill submissions run caller-inline.
        cur = threading.current_thread().name
        if cur.startswith("pool-"):
            cur_pool = cur[5:].rsplit("_", 1)[0]
            p_cur = self._pools.get(cur_pool)
            p_tgt = self._pools.get(name)
            uphill = (p_cur is not None and p_tgt is not None
                      and p_tgt.rank > p_cur.rank)
            if not uphill:
                f: cf.Future = cf.Future()
                try:
                    f.set_result(fn(*args, **kw))
                except BaseException as e:  # noqa: BLE001 future contract
                    f.set_exception(e)
                return f
        ex = self.pool(name, weight)
        p = self._pools[name]
        t0 = time.monotonic()
        with p.mu:
            p.stats.submitted += 1

        def run():
            t1 = time.monotonic()
            with p.mu:
                p.stats.busy += 1
                p.stats.total_wait_s += t1 - t0
            try:
                return fn(*args, **kw)
            finally:
                with p.mu:
                    p.stats.busy -= 1
                    p.stats.completed += 1
                    p.stats.total_run_s += time.monotonic() - t1
        try:
            return ex.submit(run)
        except RuntimeError:
            # raced a resize past the retire grace: the fetched executor
            # was reaped; the swapped-in one accepts the work
            return self._pools[name].executor.submit(run)

    # ---------------- introspection ---------------- #

    def stats_rows(self) -> list[tuple]:
        """(name, workers, submitted, completed, busy, wait_ms, run_ms)
        for information_schema / metrics."""
        out = []
        with self._mu:
            pools = list(self._pools.values())
        for p in pools:
            with p.mu:
                s = p.stats
                out.append((s.name, s.workers, s.submitted, s.completed,
                            s.busy, round(s.total_wait_s * 1e3, 1),
                            round(s.total_run_s * 1e3, 1)))
        return sorted(out)


MANAGER = PoolManager()

__all__ = ["PoolManager", "MANAGER", "PoolStats"]
