"""locksan: runtime lock sanitizer (copsan's live half, ISSUE 17).

The static model (analysis/concurrency) predicts every acquisition
edge the program can take; this module checks the prediction against
reality.  While armed, ``threading.Lock/RLock/Condition`` allocations
from tidb_tpu code return instrumented wrappers that record per-thread
acquisition stacks.  On every acquire of B with A held, the edge A→B
is checked against the static graph: a novel edge between mapped nodes
means the model's seam tables have drifted (or a thread is taking
locks the analysis never predicted — the exact precondition of an
unseen deadlock); a cycle in the *observed* graph is an actual
lock-order inversion caught live.

Wiring: sysvar ``tidb_tpu_lock_sanitizer`` (global, default off) arms
it; the 32-session stress smoke and the bench ``stress`` rung run with
it armed and assert zero reports at ≤5% overhead.  Locks allocated
while disarmed are real primitives — arming only affects allocations
made after it (build the domain AFTER arm()), so production code pays
nothing when off.

Allocation sites are mapped to static node names by caller frame
(file, line); sites the model does not know (locals, test scaffolding)
still get instrumented stacks but are exempt from novel-edge reports —
they count in ``stats()['unmapped']`` instead.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["LockSanitizer", "arm", "disarm", "sanitizer", "reports",
           "stats"]

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition


class _SanLock:
    """Instrumented lock: forwards to the real primitive, records the
    per-thread holder stack, and checks each new edge against the
    static graph.  Recursion on an RLock records the first acquire
    only, so re-entry never fabricates self-edges."""

    __slots__ = ("_real", "node", "san", "_reentrant")

    def __init__(self, real, node: str, san: "LockSanitizer",
                 reentrant: bool):
        self._real = real
        self.node = node
        self.san = san
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._real.acquire(blocking, timeout)
        if got:
            self.san._on_acquire(self)
        return got

    def release(self):
        self.san._on_release(self)
        self._real.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._real.locked()

    # Condition support: a real Condition wrapping a _SanLock calls
    # these.  _release_save drops the whole holder record (wait sleeps
    # without the lock); _acquire_restore re-records, re-checking edges
    # (the re-acquire edges exist statically — the with-statement that
    # holds the cv produced them).
    def _release_save(self):
        self.san._on_release(self, all_depths=True)
        if hasattr(self._real, "_release_save"):
            return self._real._release_save()
        self._real.release()
        return None

    def _acquire_restore(self, state):
        if hasattr(self._real, "_acquire_restore"):
            self._real._acquire_restore(state)
        else:
            self._real.acquire()
        self.san._on_acquire(self)

    def _is_owned(self):
        if hasattr(self._real, "_is_owned"):
            return self._real._is_owned()
        return self.san._held_depth(self) > 0

    def __repr__(self):
        return f"<SanLock {self.node} wrapping {self._real!r}>"


class LockSanitizer:
    def __init__(self, static_edges: Optional[Set[Tuple[str, str]]] = None,
                 alloc_index: Optional[Dict[Tuple[str, int], str]] = None):
        self._tls = threading.local()
        self._mu = _REAL_LOCK()           # guards the shared maps below
        self.static_edges: Set[Tuple[str, str]] = set(static_edges or ())
        self.alloc_index: Dict[Tuple[str, int], str] = \
            dict(alloc_index or {})
        self.static_nodes: Set[str] = \
            {n for e in self.static_edges for n in e} | \
            set(self.alloc_index.values())
        self.observed: Set[Tuple[str, str]] = set()
        self._adj: Dict[str, Set[str]] = {}
        self._reports: List[dict] = []
        self._reported: Set[Tuple[str, str, str]] = set()
        self.armed = False
        self.n_locks = 0
        self.n_acquires = 0
        self.n_unmapped = 0

    # ------------------------------------------------------------- #
    # factory patching
    # ------------------------------------------------------------- #
    def _alloc_node(self) -> Optional[str]:
        """Map the allocation site (caller of the patched factory) to a
        static node name; None for non-tidb_tpu allocations."""
        frame = sys._getframe(2)
        fname = frame.f_code.co_filename
        try:
            rel = os.path.relpath(fname, _PKG_ROOT)
        except ValueError:
            return None
        if rel.startswith(".."):
            return None
        rel = rel.replace(os.sep, "/")
        node = self.alloc_index.get((rel, frame.f_lineno))
        if node is None:
            node = f"{rel}:{frame.f_lineno}"   # unmapped: exempt
        return node

    def _make_lock(self):
        node = self._alloc_node()
        if node is None or not self.armed:
            return _REAL_LOCK()
        self.n_locks += 1
        return _SanLock(_REAL_LOCK(), node, self, False)

    def _make_rlock(self):
        node = self._alloc_node()
        if node is None or not self.armed:
            return _REAL_RLOCK()
        self.n_locks += 1
        return _SanLock(_REAL_RLOCK(), node, self, True)

    def _make_condition(self, lock=None):
        node = self._alloc_node()
        if node is None or not self.armed:
            return _REAL_CONDITION(lock)
        if lock is None:
            # bare Condition() wraps an RLock; give the wrapper this
            # allocation site's node so waits/notifies are attributed
            self.n_locks += 1
            lock = _SanLock(_REAL_RLOCK(), node, self, True)
        return _REAL_CONDITION(lock)

    def arm(self) -> None:
        with self._mu:
            if self.armed:
                return
            self.armed = True
        threading.Lock = self._make_lock
        threading.RLock = self._make_rlock
        threading.Condition = _PatchedCondition(self)

    def disarm(self) -> None:
        with self._mu:
            self.armed = False
        threading.Lock = _REAL_LOCK
        threading.RLock = _REAL_RLOCK
        threading.Condition = _REAL_CONDITION

    # ------------------------------------------------------------- #
    # holder stacks + edge checking
    # ------------------------------------------------------------- #
    def _stack(self) -> List[_SanLock]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _held_depth(self, lk: _SanLock) -> int:
        return sum(1 for h in self._stack() if h is lk)

    def _on_acquire(self, lk: _SanLock) -> None:
        st = self._stack()
        if not self.armed:
            st.append(lk)
            return
        self.n_acquires += 1
        if lk._reentrant and any(h is lk for h in st):
            st.append(lk)   # recursion: no new edge
            return
        held_nodes = []
        seen = set()
        for h in st:
            if h.node not in seen and h is not lk:
                seen.add(h.node)
                held_nodes.append(h.node)
        st.append(lk)
        if not held_nodes:
            return
        with self._mu:
            for hn in held_nodes:
                if hn == lk.node:
                    continue   # two instances sharing an alloc site
                edge = (hn, lk.node)
                if edge in self.observed:
                    continue
                self.observed.add(edge)
                self._adj.setdefault(hn, set()).add(lk.node)
                mapped = hn in self.static_nodes and \
                    lk.node in self.static_nodes
                if not mapped:
                    self.n_unmapped += 1
                elif edge not in self.static_edges:
                    self._report("novel-edge", hn, lk.node)
                # a cycle in the observed graph is a live inversion
                # regardless of mapping
                if self._reaches(lk.node, hn):
                    self._report("cycle", hn, lk.node)

    def _on_release(self, lk: _SanLock, all_depths: bool = False) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] is lk:
                del st[i]
                if not all_depths:
                    return

    def _reaches(self, src: str, dst: str) -> bool:
        """Observed-graph reachability src→dst (caller holds _mu)."""
        seen = {src}
        work = [src]
        while work:
            n = work.pop()
            for m in self._adj.get(n, ()):
                if m == dst:
                    return True
                if m not in seen:
                    seen.add(m)
                    work.append(m)
        return False

    def _report(self, kind: str, src: str, dst: str) -> None:
        key = (kind, src, dst)
        if key in self._reported:
            return
        self._reported.add(key)
        self._reports.append({
            "kind": kind, "src": src, "dst": dst,
            "thread": threading.current_thread().name,
        })

    # ------------------------------------------------------------- #
    # results
    # ------------------------------------------------------------- #
    def reports(self) -> List[dict]:
        with self._mu:
            return list(self._reports)

    def stats(self) -> dict:
        with self._mu:
            return {
                "armed": self.armed,
                "locks_instrumented": self.n_locks,
                "acquisitions": self.n_acquires,
                "edges_observed": len(self.observed),
                "unmapped_edges": self.n_unmapped,
                "reports": len(self._reports),
            }


class _PatchedCondition:
    """Callable standing in for threading.Condition while armed; also
    passes isinstance checks via __instancecheck__-free duck typing
    (nothing in-tree isinstance-checks Condition)."""

    def __init__(self, san: LockSanitizer):
        self._san = san

    def __call__(self, lock=None):
        return self._san._make_condition(lock)


_SAN: Optional[LockSanitizer] = None
_SAN_MU = _REAL_LOCK()


def sanitizer() -> Optional[LockSanitizer]:
    return _SAN


def arm(static_edges: Optional[Set[Tuple[str, str]]] = None,
        alloc_index: Optional[Dict[Tuple[str, int], str]] = None,
        ) -> LockSanitizer:
    """Arm the global sanitizer.  With no arguments the static graph is
    built from the whole-program model (analysis/concurrency); tests
    pass explicit edge sets to seed violations."""
    global _SAN
    with _SAN_MU:
        if _SAN is not None and _SAN.armed:
            return _SAN
        if static_edges is None or alloc_index is None:
            from ..analysis.concurrency import cached_model
            model = cached_model()
            if static_edges is None:
                static_edges = set(model.edges)
            if alloc_index is None:
                alloc_index = dict(model.alloc_index)
        _SAN = LockSanitizer(static_edges, alloc_index)
        _SAN.arm()
        return _SAN


def disarm() -> Optional[LockSanitizer]:
    """Disarm and restore the real threading factories.  Locks already
    instrumented keep working (their wrappers just stop judging)."""
    with _SAN_MU:
        if _SAN is not None:
            _SAN.disarm()
        return _SAN


def reports() -> List[dict]:
    return _SAN.reports() if _SAN is not None else []


def stats() -> dict:
    return _SAN.stats() if _SAN is not None else {"armed": False}
