"""Back-compat shim: resource control moved to the ``tidb_tpu.rc``
package (PR 5 — LaunchCost-priced RU admission, group isolation,
runaway enforcement at the device drain).  Import from ``tidb_tpu.rc``
in new code; this module re-exports the stable surface so existing
importers (session, infoschema, tests) keep working."""

from __future__ import annotations

from ..rc.controller import (PRIORITY_WEIGHTS, ResourceExhaustedError,
                             ResourceGroup, ResourceGroupManager,
                             charge_statement)
from ..rc.runaway import RunawayError

__all__ = ["ResourceGroup", "ResourceGroupManager", "RunawayError",
           "ResourceExhaustedError", "charge_statement",
           "PRIORITY_WEIGHTS"]
