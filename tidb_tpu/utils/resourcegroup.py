"""Resource control: resource groups with RU token buckets + runaway
watch.

Reference analog: pkg/resourcegroup + the TiKV-side RU limiter and
pkg/resourcegroup/runaway (SURVEY §2.7).  A statement charges request
units (RUs ~ rows touched / 100 + 1) against its session's group AFTER
execution; when the bucket is empty the NEXT statement blocks until
refill (post-paid debt, like the reference's token client).  A QUERY_LIMIT
with EXEC_ELAPSED marks statements exceeding the wall-time budget as
runaway: ACTION=KILL raises, ACTION=COOLDOWN demotes the charge priority
(here: doubles the statement's RU cost).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional


class RunawayError(RuntimeError):
    """Statement exceeded the group's EXEC_ELAPSED budget with
    ACTION=KILL (runaway detector)."""


# PRIORITY -> device-scheduler fair-share weight (stride scheduling in
# sched/scheduler.py; the reference's resource-group PRIORITY feeds
# tikv's unified read pool the same way)
PRIORITY_WEIGHTS = {"low": 1.0, "medium": 8.0, "high": 16.0}


@dataclass
class ResourceGroup:
    name: str
    ru_per_sec: int = 0            # 0 = unlimited
    burstable: bool = False
    exec_elapsed_sec: float = 0.0  # 0 = no runaway watch
    runaway_action: str = "kill"   # kill | cooldown
    priority: str = "medium"       # low | medium | high (sched weight)
    # token bucket state (guarded by _mu: the server is thread-per-
    # connection and every session in the group shares this bucket)
    tokens: float = 0.0
    last_refill: float = field(default_factory=time.monotonic)
    runaway_count: int = 0
    _mu: threading.Lock = field(default_factory=threading.Lock)

    def _refill(self, now: float) -> None:
        if self.ru_per_sec <= 0:
            return
        dt = now - self.last_refill
        cap = float(self.ru_per_sec)       # 1s burst capacity
        if self.burstable:
            cap *= 10
        self.tokens = min(self.tokens + dt * self.ru_per_sec, cap)
        self.last_refill = now

    @property
    def sched_weight(self) -> float:
        return PRIORITY_WEIGHTS.get(self.priority, 8.0)

    def note_runaway(self) -> None:
        with self._mu:
            self.runaway_count += 1

    def consume(self, rus: float, max_wait_sec: float = 5.0) -> float:
        """Charge `rus`; blocks (bounded) while the bucket is in debt.
        Returns seconds slept — the throttle the reference applies via
        its token client.  Sleeps happen OUTSIDE the lock."""
        if self.ru_per_sec <= 0:
            return 0.0
        slept = 0.0
        while True:
            with self._mu:
                now = time.monotonic()
                self._refill(now)
                if self.tokens > 0:
                    self.tokens -= rus  # post-paid: may go negative (debt)
                    return slept
                need = min((-self.tokens + rus) / self.ru_per_sec,
                           max_wait_sec - slept)
                if need <= 0:
                    self.tokens -= rus  # waited long enough; take the debt
                    return slept
            time.sleep(min(need, 0.05))
            slept += min(need, 0.05)


class ResourceGroupManager:
    """Domain-level group registry (resource group meta + runaway
    settings; infoschema RESOURCE_GROUPS analog)."""

    def __init__(self):
        self._groups: dict[str, ResourceGroup] = {
            "default": ResourceGroup("default")}
        self._lock = threading.Lock()

    def create(self, name: str, ru_per_sec: Optional[int],
               burstable: Optional[bool] = None,
               exec_elapsed_sec: Optional[float] = None,
               action: Optional[str] = None,
               if_not_exists: bool = False,
               priority: Optional[str] = None) -> ResourceGroup:
        if priority is not None and priority not in PRIORITY_WEIGHTS:
            raise ValueError(f"bad PRIORITY {priority!r}")
        with self._lock:
            if name in self._groups:
                if if_not_exists:
                    return self._groups[name]    # no-op, keep the group
                raise ValueError(f"resource group {name!r} exists")
            g = ResourceGroup(name, ru_per_sec or 0, bool(burstable),
                              exec_elapsed_sec or 0.0, action or "kill",
                              priority or "medium")
            self._groups[name] = g
            return g

    def alter(self, name: str, ru_per_sec: Optional[int],
              burstable: Optional[bool], exec_elapsed_sec: Optional[float],
              action: Optional[str],
              priority: Optional[str] = None) -> ResourceGroup:
        """Merge only the options named in the statement; state
        (bucket/runaway counters) is preserved."""
        if priority is not None and priority not in PRIORITY_WEIGHTS:
            raise ValueError(f"bad PRIORITY {priority!r}")
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                raise ValueError(f"unknown resource group {name!r}")
            if ru_per_sec is not None:
                g.ru_per_sec = ru_per_sec
            if burstable is not None:
                g.burstable = burstable
            if exec_elapsed_sec is not None:
                g.exec_elapsed_sec = exec_elapsed_sec
            if action is not None:
                g.runaway_action = action
            if priority is not None:
                g.priority = priority
            return g

    def drop(self, name: str, if_exists: bool = False) -> None:
        with self._lock:
            if name == "default":
                raise ValueError("cannot drop the default resource group")
            if name not in self._groups:
                if if_exists:
                    return
                raise ValueError(f"unknown resource group {name!r}")
            del self._groups[name]

    def get(self, name: str) -> Optional[ResourceGroup]:
        with self._lock:
            return self._groups.get(name)

    def rows(self) -> list[tuple]:
        with self._lock:
            return [(g.name, g.ru_per_sec or None,
                     "YES" if g.burstable else "NO",
                     g.exec_elapsed_sec or None, g.runaway_action.upper(),
                     g.runaway_count, g.priority.upper())
                    for g in self._groups.values()]


def charge_statement(group: ResourceGroup, rows_touched: int,
                     elapsed_sec: float) -> None:
    """Post-execution accounting: RU charge + runaway watch."""
    rus = rows_touched / 100.0 + 1.0
    if group.exec_elapsed_sec and elapsed_sec > group.exec_elapsed_sec:
        group.note_runaway()
        if group.runaway_action == "kill":
            raise RunawayError(
                f"query exceeded EXEC_ELAPSED "
                f"{group.exec_elapsed_sec}s (resource group "
                f"{group.name!r})")
        rus *= 2.0                  # cooldown: demoted priority = pricier
    group.consume(rus)


__all__ = ["ResourceGroup", "ResourceGroupManager", "RunawayError",
           "charge_statement", "PRIORITY_WEIGHTS"]
