"""Statement summary + slow query log.

Reference analog: pkg/util/stmtsummary (per-digest aggregated workload
stats behind information_schema.statements_summary) and the slow-query
log (executor/adapter_slow_log.go, slow_query.go).  Digest = the SQL text
with literals normalized out, like pkg/parser/digester.go.
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, field

_NUM = re.compile(r"\b\d+(?:\.\d+)?\b")
_WS = re.compile(r"\s+")
_IN_LIST = re.compile(r"\(\s*\?(?:\s*,\s*\?)+\s*\)")


def _strip_strings_and_comments(sql: str) -> str:
    """One left-to-right pass replacing string literals with ? and
    removing comments — regex passes cannot order these correctly (a
    quote inside a comment, or comment markers inside a string, corrupt
    each other's extents)."""
    out = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c in "'\"":
            q = c
            i += 1
            while i < n:
                if sql[i] == "\\":
                    i += 2
                    continue
                if sql[i] == q:
                    if i + 1 < n and sql[i + 1] == q:   # '' escape
                        i += 2
                        continue
                    i += 1
                    break
                i += 1
            out.append("?")
            continue
        if (sql.startswith("--", i)
                and (i + 2 >= n or sql[i + 2].isspace())) or c == "#":
            # MySQL: '--' starts a comment only when followed by
            # whitespace — 'a--1' is subtraction, not a comment
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            out.append(" ")
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            i = n if j < 0 else j + 2
            out.append(" ")
            continue
        out.append(c)
        i += 1
    return "".join(out)


def normalize_sql(sql: str) -> str:
    """Literal-free normalized form (digester.go analog).  Comments —
    including /*+ hint */ blocks — do not participate in the digest, so a
    hinted statement matches its unhinted original (bindinfo contract)."""
    s = _strip_strings_and_comments(sql)
    s = _NUM.sub("?", s)
    s = _WS.sub(" ", s).strip().lower()
    s = _IN_LIST.sub("(...)", s)   # collapse IN/VALUES lists
    return s


@dataclass
class StmtStats:
    digest: str
    sample_sql: str
    exec_count: int = 0
    sum_latency_ns: int = 0
    max_latency_ns: int = 0
    sum_rows: int = 0
    first_seen: float = 0.0
    last_seen: float = 0.0
    # Top-SQL attribution (pkg/util/topsql): CPU time + plan digest so
    # the hottest (sql, plan) pairs rank by actual processor cost
    sum_cpu_ns: int = 0
    plan_digest: str = ""
    sample_plan: str = ""
    # device-scheduler admission wait (sched/): how long this digest's
    # cop tasks queued before launching
    sum_sched_wait_ns: int = 0
    # priced request units this digest's device work debited (rc/):
    # fused launches attribute per member, shared scan priced once
    sum_rus: float = 0.0
    # program resolve/compile time the digest's launches paid (copforge
    # compile cache): the compile_wait_ms split out of schedWait, so a
    # cache win shows up as Avg_compile_ms -> ~0 while Avg_sched_wait_ms
    # keeps the queueing story
    sum_compile_ns: int = 0
    # copscope: device tasks this digest admitted and how many of them
    # rode a cross-query fused launch — surfaced next to the wait/RU
    # columns so EXPLAIN ANALYZE and statements_summary tell one story
    sum_sched_tasks: int = 0
    sum_fused: int = 0

    @property
    def avg_latency_ms(self) -> float:
        return self.sum_latency_ns / max(self.exec_count, 1) / 1e6

    @property
    def avg_sched_wait_ms(self) -> float:
        return self.sum_sched_wait_ns / max(self.exec_count, 1) / 1e6

    @property
    def avg_ru(self) -> float:
        return self.sum_rus / max(self.exec_count, 1)

    @property
    def avg_compile_ms(self) -> float:
        return self.sum_compile_ns / max(self.exec_count, 1) / 1e6


@dataclass
class SlowQuery:
    sql: str
    latency_ms: float
    ts: float
    rows: int
    # copscope (ISSUE 13): per-entry evidence — where the latency went
    # (admission wait, compile), what it cost (RUs), whether it was
    # retried, and the flight-recorder trace id so the slow-log line
    # links straight to its span tree at /trace/<id>
    sched_wait_ms: float = 0.0
    compile_ms: float = 0.0
    ru: float = 0.0
    retried: int = 0
    trace_id: str = ""


class StmtSummary:
    """Per-Domain workload summary + slow log ring.

    ``slow_threshold_ms`` is live state plumbed from the
    ``tidb_tpu_slow_threshold_ms`` sysvar (session -> Domain) — the
    constructor default only seeds it."""

    DEFAULT_SLOW_THRESHOLD_MS = 300.0

    def __init__(self, slow_threshold_ms: float = DEFAULT_SLOW_THRESHOLD_MS,
                 max_slow: int = 256):
        self._stats: dict[str, StmtStats] = {}
        self._slow: list[SlowQuery] = []
        self._lock = threading.Lock()
        self.slow_threshold_ms = slow_threshold_ms
        self.max_slow = max_slow

    def record(self, sql: str, latency_ns: int, rows: int,
               cpu_ns: int = 0, plan_text: str = "",
               sched_wait_ns: int = 0, rus: float = 0.0,
               compile_ns: int = 0, sched_tasks: int = 0,
               fused: int = 0, retried: int = 0,
               trace_id: str = "") -> bool:
        """Returns True when the statement crossed the slow threshold
        (the caller flags its trace ``slow`` for the flight recorder)."""
        digest = normalize_sql(sql)
        now = time.time()
        with self._lock:
            st = self._stats.get(digest)
            if st is None:
                st = StmtStats(digest, sql, first_seen=now)
                self._stats[digest] = st
            st.exec_count += 1
            st.sum_latency_ns += latency_ns
            st.max_latency_ns = max(st.max_latency_ns, latency_ns)
            st.sum_rows += rows
            st.last_seen = now
            st.sum_cpu_ns += int(cpu_ns)
            st.sum_sched_wait_ns += int(sched_wait_ns)
            st.sum_rus += float(rus)
            st.sum_compile_ns += int(compile_ns)
            st.sum_sched_tasks += int(sched_tasks)
            st.sum_fused += int(fused)
            if plan_text:
                import hashlib
                st.plan_digest = hashlib.sha256(
                    plan_text.encode()).hexdigest()[:16]
                st.sample_plan = plan_text
            slow = latency_ns / 1e6 >= self.slow_threshold_ms
            if slow:
                self._slow.append(SlowQuery(
                    sql, latency_ns / 1e6, now, rows,
                    sched_wait_ms=sched_wait_ns / 1e6,
                    compile_ms=compile_ns / 1e6, ru=float(rus),
                    retried=int(retried), trace_id=trace_id))
                if len(self._slow) > self.max_slow:
                    self._slow.pop(0)
            return slow

    def summary_rows(self) -> list[tuple]:
        with self._lock:
            return [(s.digest, s.exec_count, round(s.avg_latency_ms, 3),
                     round(s.max_latency_ns / 1e6, 3), s.sum_rows,
                     s.sample_sql, round(s.avg_sched_wait_ms, 3),
                     round(s.avg_compile_ms, 3), s.sum_sched_tasks,
                     s.sum_fused, round(s.avg_ru, 2))
                    for s in sorted(self._stats.values(),
                                    key=lambda x: -x.sum_latency_ns)]

    def top_sql_rows(self, n: int = 30) -> list[tuple]:
        """Top statements by CPU time (util/topsql reporter analog):
        (sql_digest, plan_digest, cpu_ms, exec_count, avg_latency_ms,
        sample_sql, sample_plan)."""
        with self._lock:
            ranked = sorted(self._stats.values(),
                            key=lambda x: -(x.sum_cpu_ns
                                            or x.sum_latency_ns))[:n]
            return [(s.digest, s.plan_digest,
                     round((s.sum_cpu_ns or s.sum_latency_ns) / 1e6, 3),
                     s.exec_count, round(s.avg_latency_ms, 3),
                     s.sample_sql, s.sample_plan)
                    for s in ranked]

    def slow_rows(self) -> list[tuple]:
        with self._lock:
            return [(q.sql, round(q.latency_ms, 3), q.rows,
                     round(q.sched_wait_ms, 3), round(q.compile_ms, 3),
                     round(q.ru, 2), q.retried, q.trace_id)
                    for q in self._slow]
