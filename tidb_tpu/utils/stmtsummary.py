"""Statement summary + slow query log.

Reference analog: pkg/util/stmtsummary (per-digest aggregated workload
stats behind information_schema.statements_summary) and the slow-query
log (executor/adapter_slow_log.go, slow_query.go).  Digest = the SQL text
with literals normalized out, like pkg/parser/digester.go.
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, field

_NUM = re.compile(r"\b\d+(?:\.\d+)?\b")
_STR = re.compile(r"'(?:[^'\\]|\\.)*'")
_WS = re.compile(r"\s+")
_IN_LIST = re.compile(r"\(\s*\?(?:\s*,\s*\?)+\s*\)")


_COMMENT = re.compile(r"/\*.*?\*/", re.S)


def normalize_sql(sql: str) -> str:
    """Literal-free normalized form (digester.go analog).  Comments —
    including /*+ hint */ blocks — do not participate in the digest, so a
    hinted statement matches its unhinted original (bindinfo contract)."""
    s = _STR.sub("?", sql)       # strings first: comment markers inside
    s = _COMMENT.sub(" ", s)     # string literals must not swallow SQL
    s = _NUM.sub("?", s)
    s = _WS.sub(" ", s).strip().lower()
    s = _IN_LIST.sub("(...)", s)   # collapse IN/VALUES lists
    return s


@dataclass
class StmtStats:
    digest: str
    sample_sql: str
    exec_count: int = 0
    sum_latency_ns: int = 0
    max_latency_ns: int = 0
    sum_rows: int = 0
    first_seen: float = 0.0
    last_seen: float = 0.0

    @property
    def avg_latency_ms(self) -> float:
        return self.sum_latency_ns / max(self.exec_count, 1) / 1e6


@dataclass
class SlowQuery:
    sql: str
    latency_ms: float
    ts: float
    rows: int


class StmtSummary:
    """Per-Domain workload summary + slow log ring."""

    def __init__(self, slow_threshold_ms: float = 300.0, max_slow: int = 256):
        self._stats: dict[str, StmtStats] = {}
        self._slow: list[SlowQuery] = []
        self._lock = threading.Lock()
        self.slow_threshold_ms = slow_threshold_ms
        self.max_slow = max_slow

    def record(self, sql: str, latency_ns: int, rows: int):
        digest = normalize_sql(sql)
        now = time.time()
        with self._lock:
            st = self._stats.get(digest)
            if st is None:
                st = StmtStats(digest, sql, first_seen=now)
                self._stats[digest] = st
            st.exec_count += 1
            st.sum_latency_ns += latency_ns
            st.max_latency_ns = max(st.max_latency_ns, latency_ns)
            st.sum_rows += rows
            st.last_seen = now
            if latency_ns / 1e6 >= self.slow_threshold_ms:
                self._slow.append(SlowQuery(sql, latency_ns / 1e6, now, rows))
                if len(self._slow) > self.max_slow:
                    self._slow.pop(0)

    def summary_rows(self) -> list[tuple]:
        with self._lock:
            return [(s.digest, s.exec_count, round(s.avg_latency_ms, 3),
                     round(s.max_latency_ns / 1e6, 3), s.sum_rows,
                     s.sample_sql)
                    for s in sorted(self._stats.values(),
                                    key=lambda x: -x.sum_latency_ns)]

    def slow_rows(self) -> list[tuple]:
        with self._lock:
            return [(q.sql, round(q.latency_ms, 3), q.rows)
                    for q in self._slow]
