"""Advisory file locking for one-host multi-process coordination.

Reference analog: PD's etcd gives the reference cluster a linearizable
store; on one host the portable poor-man's equivalent is an fcntl
advisory lock around read-modify-write plus atomic temp-file rename
for the write itself.  Two subsystems share this seam:

- ``pd/store.py`` FileBackend — every transaction on the coordination
  store runs under the lock, so CAS semantics hold across processes.
- ``compilecache/manifest.py`` — concurrent manifest saves from two
  processes sharing one ``tidb_tpu_compile_cache_dir`` merge instead
  of clobbering.

Platforms without ``fcntl`` (a defensive gate only — tier-1 runs on
Linux) degrade to the atomic-rename-only discipline: last writer wins,
never a torn file.
"""

from __future__ import annotations

import os
from contextlib import contextmanager

try:
    import fcntl
except ImportError:          # pragma: no cover - non-POSIX fallback
    fcntl = None


@contextmanager
def locked_file(path: str):
    """Hold an exclusive advisory lock on ``path`` (created empty if
    missing) for the dynamic extent.  OSError propagates — callers own
    the unavailability semantics (pd maps it to PdUnavailable, the
    manifest swallows it: persistence is an optimization there)."""
    fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
    try:
        if fcntl is not None:
            fcntl.flock(fd, fcntl.LOCK_EX)
        yield fd
    finally:
        try:
            if fcntl is not None:
                fcntl.flock(fd, fcntl.LOCK_UN)
        finally:
            os.close(fd)


__all__ = ["locked_file"]
