"""mysql_native_password auth primitives (shared by the wire protocol
layer and the privilege manager; reference: pkg/util/hack + auth pkg)."""

from __future__ import annotations

import hashlib


def native_password_hash(password: str) -> bytes:
    """SHA1(SHA1(password)) — what mysql.user stores."""
    return hashlib.sha1(hashlib.sha1(password.encode()).digest()).digest()


def scramble_password(password: str, salt: bytes) -> bytes:
    """Client-side: SHA1(pwd) XOR SHA1(salt + SHA1(SHA1(pwd)))."""
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    mix = hashlib.sha1(salt + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, mix))


def check_scramble(scrambled: bytes, salt: bytes, stored_hash: bytes) -> bool:
    """Server-side verify: recover SHA1(pwd-hash) and compare."""
    if not scrambled:
        return stored_hash == native_password_hash("")
    mix = hashlib.sha1(salt + stored_hash).digest()
    h1 = bytes(a ^ b for a, b in zip(scrambled, mix))
    return hashlib.sha1(h1).digest() == stored_hash


__all__ = ["native_password_hash", "scramble_password", "check_scramble"]
