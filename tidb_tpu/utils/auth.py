"""mysql_native_password auth primitives (shared by the wire protocol
layer and the privilege manager; reference: pkg/util/hack + auth pkg)."""

from __future__ import annotations

import hashlib


def native_password_hash(password: str) -> bytes:
    """SHA1(SHA1(password)) — what mysql.user stores."""
    return hashlib.sha1(hashlib.sha1(password.encode()).digest()).digest()


def scramble_password(password: str, salt: bytes) -> bytes:
    """Client-side: SHA1(pwd) XOR SHA1(salt + SHA1(SHA1(pwd)))."""
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    mix = hashlib.sha1(salt + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, mix))


def check_scramble(scrambled: bytes, salt: bytes, stored_hash: bytes) -> bool:
    """Server-side verify: recover SHA1(pwd-hash) and compare."""
    if not scrambled:
        return stored_hash == native_password_hash("")
    mix = hashlib.sha1(salt + stored_hash).digest()
    h1 = bytes(a ^ b for a, b in zip(scrambled, mix))
    return hashlib.sha1(h1).digest() == stored_hash


def sha2_cache_digest(password: str) -> bytes:
    """SHA256(SHA256(password)) — the fast-auth cache entry the server
    keeps after one full authentication (reference: privilege/privileges
    globalPrivCache sha2 cache; MySQL's caching_sha2_password design)."""
    return hashlib.sha256(hashlib.sha256(password.encode()).digest()).digest()


def sha2_scramble(password: str, nonce: bytes) -> bytes:
    """Client-side caching_sha2_password fast-auth token:
    SHA256(pwd) XOR SHA256(SHA256(SHA256(pwd)) || nonce)."""
    if not password:
        return b""
    h1 = hashlib.sha256(password.encode()).digest()
    h2 = hashlib.sha256(h1).digest()
    mix = hashlib.sha256(h2 + nonce).digest()
    return bytes(a ^ b for a, b in zip(h1, mix))


def check_sha2_scramble(token: bytes, nonce: bytes,
                        cache_digest: bytes) -> bool:
    """Server-side fast-auth verify against the cached
    SHA256(SHA256(password)): recover SHA256(pwd) from the token and
    re-hash."""
    if not token:
        return cache_digest == sha2_cache_digest("")
    mix = hashlib.sha256(cache_digest + nonce).digest()
    h1 = bytes(a ^ b for a, b in zip(token, mix))
    return hashlib.sha256(h1).digest() == cache_digest


__all__ = ["native_password_hash", "scramble_password", "check_scramble",
           "sha2_cache_digest", "sha2_scramble", "check_sha2_scramble"]
