"""MySQL-compatible data type system, mapped onto TPU-friendly device dtypes.

Reference analog: pkg/types (types/field_type.go, Datum) — but re-designed
columnar-first: every SQL type has a dense fixed-width device representation
so entire columns are XLA arrays; variable-width data (strings) is
dictionary-encoded at columnarization time (SURVEY.md §7 "strings on device").

Device representations:

==============  =====================  =========================================
SQL type        device dtype           encoding
==============  =====================  =========================================
BIGINT          int64                  value
BIGINT UNSIGNED uint64                 value
DOUBLE          float64                value
FLOAT           float32                value
DECIMAL(p,s)    int64                  value * 10**s (scaled integer, p<=18)
CHAR/VARCHAR    int32                  code into per-column sorted dictionary
DATE            int32                  days since 1970-01-01
DATETIME        int64                  microseconds since 1970-01-01 00:00:00
TIME            int64                  signed microseconds (duration)
==============  =====================  =========================================

The sorted dictionary gives string columns the property that *code order ==
collation order* (binary / utf8mb4_bin), so range predicates and ORDER BY on
strings compile to integer compares on device.  NULLs ride in a separate
validity bitmap exactly like the reference's Arrow-layout chunk columns
(pkg/util/chunk/column.go:71-81).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace

import numpy as np


class TypeKind(enum.Enum):
    INT64 = "bigint"
    UINT64 = "bigint unsigned"
    FLOAT64 = "double"
    FLOAT32 = "float"
    DECIMAL = "decimal"
    STRING = "varchar"
    DATE = "date"
    DATETIME = "datetime"
    TIME = "time"
    ENUM = "enum"      # 1-based member index (pkg/types/enum.go)
    SET = "set"        # member bitmask (pkg/types/set.go)
    BIT = "bit"        # BIT(n): uint64 bit value (pkg/types/binary_literal.go)
    VECTOR = "vector"  # VECTOR(d): float32[d] embedding (types VectorFloat32)
    NULL = "null"  # type of the NULL literal before inference


# MySQL's default scale increment for division (divPrecisionIncrement),
# reference: pkg/expression/builtin_arithmetic.go / types/mydecimal.
DIV_FRAC_INCR = 4

# Max decimal digits representable in the scaled-int64 encoding.
DECIMAL64_MAX_PRECISION = 18

# Max digits of a "wide" decimal: declared columns/casts beyond 18 digits
# and aggregation results.  Matches MyDecimal's 65-digit ceiling
# (reference: pkg/types/mydecimal.go:47); wide values are python-int
# object arrays on the host (exact at any magnitude) and never ship to
# device.  The SUM widening rule mirrors the reference
# (DECIMAL(p,s) -> DECIMAL(min(p+22,65),s), expression/aggregation).
# Exactness of the device limb path: per-row |value| < 10^19 (decimal64/
# int64), so limb splits have |hi|,lo < 2^32; batches are fenced to
# < 2^31 rows (copr/exec.py), keeping int64 limb sums wrap-free, and
# cross-shard merges are exact (object ints host-side; the psum path is
# fenced to < 2^31 global rows in parallel/spmd.py).
DECIMAL_MAX_PRECISION = 65

# MySQL's maximum DECIMAL scale.
DECIMAL_MAX_SCALE = 30


@dataclass(frozen=True)
class DataType:
    kind: TypeKind
    nullable: bool = True
    # DECIMAL precision/scale (flen/decimal in the reference's FieldType).
    prec: int = -1
    scale: int = -1
    # string collation (util/collate analog); "binary" == utf8mb4_bin ==
    # raw dictionary-code order.  Case/accent-insensitive collations
    # compare through sortkey rank LUTs (utils/collate.py).
    collation: str = "binary"
    # ENUM/SET member list in DEFINITION order (ordinal semantics)
    members: tuple = ()

    # ------------------------------------------------------------------ #

    @property
    def is_numeric(self) -> bool:
        return self.kind in (
            TypeKind.INT64,
            TypeKind.UINT64,
            TypeKind.FLOAT64,
            TypeKind.FLOAT32,
            TypeKind.DECIMAL,
        )

    @property
    def is_integer(self) -> bool:
        return self.kind in (TypeKind.INT64, TypeKind.UINT64)

    @property
    def is_float(self) -> bool:
        return self.kind in (TypeKind.FLOAT64, TypeKind.FLOAT32)

    @property
    def is_string(self) -> bool:
        return self.kind == TypeKind.STRING

    @property
    def is_temporal(self) -> bool:
        return self.kind in (TypeKind.DATE, TypeKind.DATETIME, TypeKind.TIME)

    @property
    def is_wide_decimal(self) -> bool:
        """19-65 digit DECIMAL: python-int object representation,
        host-only (never device-fused)."""
        return (self.kind == TypeKind.DECIMAL
                and self.prec > DECIMAL64_MAX_PRECISION)

    @property
    def is_vector(self) -> bool:
        return self.kind == TypeKind.VECTOR

    @property
    def is_host_object(self) -> bool:
        """Object-array host representation: never stacked into device
        shards (wide decimals, float32 vectors)."""
        return self.is_wide_decimal or self.kind == TypeKind.VECTOR

    def np_dtype(self) -> np.dtype:
        """numpy dtype of the dense host/device representation."""
        if (self.kind == TypeKind.DECIMAL
                and self.prec > DECIMAL64_MAX_PRECISION):
            # wide decimal: host-only representation as python ints (exact);
            # never shipped to device — produced by aggregation finalize
            return np.dtype(object)
        if self.kind == TypeKind.VECTOR:
            # one float32[d] ndarray per row (object array on the host;
            # distance kernels stack to an (N, d) matrix)
            return np.dtype(object)
        return np.dtype(_NP_DTYPES[self.kind])

    def with_nullable(self, nullable: bool) -> "DataType":
        return replace(self, nullable=nullable)

    def __str__(self) -> str:
        if self.kind == TypeKind.DECIMAL:
            return f"decimal({self.prec},{self.scale})"
        if self.kind == TypeKind.VECTOR and self.prec > 0:
            return f"vector({self.prec})"
        return self.kind.value


_NP_DTYPES = {
    TypeKind.INT64: np.int64,
    TypeKind.UINT64: np.uint64,
    TypeKind.FLOAT64: np.float64,
    TypeKind.FLOAT32: np.float32,
    TypeKind.DECIMAL: np.int64,
    TypeKind.STRING: np.int32,
    TypeKind.DATE: np.int32,
    TypeKind.DATETIME: np.int64,
    TypeKind.TIME: np.int64,
    TypeKind.ENUM: np.int32,
    TypeKind.SET: np.int64,
    TypeKind.BIT: np.uint64,
    TypeKind.VECTOR: object,
    TypeKind.NULL: np.int64,
}


# Convenience constructors -------------------------------------------------- #

def bigint(nullable: bool = True) -> DataType:
    return DataType(TypeKind.INT64, nullable)


def ubigint(nullable: bool = True) -> DataType:
    return DataType(TypeKind.UINT64, nullable)


def double(nullable: bool = True) -> DataType:
    return DataType(TypeKind.FLOAT64, nullable)


def decimal(prec: int, scale: int, nullable: bool = True) -> DataType:
    """DECIMAL(p,s).  p <= 18 is the scaled-int64 fast representation;
    19..65 is the wide (python-int object array, host-only) one — no
    silent clamping: a declared DECIMAL(30,10) really holds 30 digits
    (reference: mydecimal.go:47).  p > 65 / s > 30 are MySQL errors."""
    if prec > DECIMAL_MAX_PRECISION:
        raise ValueError(
            f"DECIMAL precision {prec} exceeds the maximum "
            f"{DECIMAL_MAX_PRECISION} (ER_TOO_BIG_PRECISION)")
    if scale > DECIMAL_MAX_SCALE:
        raise ValueError(
            f"DECIMAL scale {scale} exceeds the maximum "
            f"{DECIMAL_MAX_SCALE} (ER_TOO_BIG_SCALE)")
    return DataType(TypeKind.DECIMAL, nullable, prec=prec, scale=scale)


def decimal_wide(prec: int, scale: int, nullable: bool = True) -> DataType:
    """Aggregation-result decimal: clamps to the 65-digit ceiling instead
    of raising (SUM widening may push past it)."""
    return DataType(TypeKind.DECIMAL, nullable,
                    prec=min(prec, DECIMAL_MAX_PRECISION),
                    scale=min(scale, DECIMAL_MAX_SCALE))


def varchar(nullable: bool = True, collation: str = "binary") -> DataType:
    return DataType(TypeKind.STRING, nullable, collation=collation)


def enum_type(members, nullable: bool = True) -> DataType:
    return DataType(TypeKind.ENUM, nullable, members=tuple(members))


def set_type(members, nullable: bool = True) -> DataType:
    # 63, not 64: masks ride the signed-int64 row/key codecs
    if len(members) > 63:
        raise ValueError("SET supports at most 63 members")
    return DataType(TypeKind.SET, nullable, members=tuple(members))


def bit(width: int = 1, nullable: bool = True) -> DataType:
    return DataType(TypeKind.BIT, nullable, prec=max(width, 1))


def vector(dim: int = -1, nullable: bool = True) -> DataType:
    """VECTOR(d) float32 embedding column (reference: types
    VectorFloat32, chunk/column.go:60 appender).  dim -1 = unconstrained
    (any dimension; per-value)."""
    return DataType(TypeKind.VECTOR, nullable, prec=dim)


def parse_vector_text(s: str, dim: int = -1) -> np.ndarray:
    """'[1,2,3]' -> float32 array, validating the declared dimension
    (types/vector.go ParseVectorFloat32 analog)."""
    txt = s.strip()
    if not (txt.startswith("[") and txt.endswith("]")):
        raise ValueError(f"invalid vector text: {s!r}")
    body = txt[1:-1].strip()
    vals = [float(x) for x in body.split(",")] if body else []
    arr = np.asarray(vals, dtype=np.float32)
    if not np.isfinite(arr).all():
        raise ValueError("vector values must be finite")
    if dim > 0 and len(arr) != dim:
        raise ValueError(f"vector has {len(arr)} dimensions, "
                         f"expected {dim}")
    return arr


def vector_to_text(v: np.ndarray) -> str:
    # shortest repr that round-trips float32 (vector.go String analog);
    # %g would truncate to 6 significant digits and corrupt embeddings
    return "[" + ",".join(
        np.format_float_positional(np.float32(x), unique=True, trim="-")
        for x in v) + "]"


def enum_index(t: DataType, s: str) -> int:
    """1-based member index of a string under MySQL's case-insensitive
    member match, or -1 when absent."""
    low = s.lower()
    for i, m in enumerate(t.members):
        if m.lower() == low:
            return i + 1
    return -1


def set_mask(t: DataType, s: str) -> int:
    """Bitmask of a comma-separated SET literal, or -1 when any element
    is not a member."""
    if s == "":
        return 0
    mask = 0
    for part in s.split(","):
        i = enum_index(t, part)
        if i < 0:
            return -1
        mask |= 1 << (i - 1)
    return mask


def date(nullable: bool = True) -> DataType:
    return DataType(TypeKind.DATE, nullable)


def datetime(nullable: bool = True) -> DataType:
    return DataType(TypeKind.DATETIME, nullable)


def time(nullable: bool = True) -> DataType:
    return DataType(TypeKind.TIME, nullable)


def null_type() -> DataType:
    return DataType(TypeKind.NULL, True)


# Type inference for arithmetic --------------------------------------------- #

_NUMERIC_RANK = {
    TypeKind.INT64: 0,
    TypeKind.UINT64: 1,
    TypeKind.DECIMAL: 2,
    TypeKind.FLOAT32: 3,
    TypeKind.FLOAT64: 4,
}


def common_numeric_type(a: DataType, b: DataType) -> DataType:
    """MySQL-style result type of a binary arithmetic over `a` op `b`.

    Mirrors the aggregate-type logic in pkg/expression/builtin_arithmetic.go:
    int op int -> int; anything with decimal -> decimal; anything with
    float -> double.
    """
    if a.kind == TypeKind.NULL:
        return b
    if b.kind == TypeKind.NULL:
        return a
    ra, rb = _NUMERIC_RANK.get(a.kind), _NUMERIC_RANK.get(b.kind)
    if ra is None or rb is None:
        # non-numeric operands coerce to double (MySQL string->number)
        return double()
    hi = a if ra >= rb else b
    if hi.kind == TypeKind.DECIMAL:
        scale = max(a.scale if a.kind == TypeKind.DECIMAL else 0,
                    b.scale if b.kind == TypeKind.DECIMAL else 0)
        return decimal(DECIMAL64_MAX_PRECISION, scale)
    return DataType(hi.kind)


__all__ = [
    "TypeKind", "DataType", "DIV_FRAC_INCR", "DECIMAL64_MAX_PRECISION",
    "DECIMAL_MAX_PRECISION", "bigint", "ubigint", "double", "decimal",
    "decimal_wide", "varchar", "date", "datetime", "time", "null_type",
    "common_numeric_type",
]
