from . import dtypes, decimal, temporal
from .dtypes import (
    TypeKind, DataType, bigint, ubigint, double, decimal as decimal_type,
    varchar, date, datetime, time, null_type, common_numeric_type,
)

__all__ = [
    "dtypes", "decimal", "temporal", "TypeKind", "DataType", "bigint",
    "ubigint", "double", "decimal_type", "varchar", "date", "datetime",
    "time", "null_type", "common_numeric_type",
]
