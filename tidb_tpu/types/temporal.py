"""Temporal type encoding/decoding.

Reference analog: pkg/types/time.go (core time types Time/Duration).  Device
encodings are epoch-relative integers (DATE = int32 days, DATETIME = int64
microseconds, TIME = int64 signed microseconds) so temporal predicates and
EXTRACT compile to integer arithmetic on the VPU.
"""

from __future__ import annotations

import datetime as _dt

import numpy as np

EPOCH = _dt.date(1970, 1, 1)
MICROS_PER_SEC = 1_000_000
MICROS_PER_DAY = 86_400 * MICROS_PER_SEC


def date_to_days(y: int, m: int, d: int) -> int:
    return (_dt.date(y, m, d) - EPOCH).days


def parse_date(s: str) -> int:
    s = s.strip()
    y, m, d = s.split("-")
    return date_to_days(int(y), int(m), int(d))


def days_to_date(days: int) -> _dt.date:
    return EPOCH + _dt.timedelta(days=int(days))


def date_to_string(days: int) -> str:
    return days_to_date(days).isoformat()


def parse_datetime(s: str) -> int:
    s = s.strip()
    if " " in s or "T" in s:
        sep = " " if " " in s else "T"
        dpart, tpart = s.split(sep, 1)
    else:
        dpart, tpart = s, "00:00:00"
    days = parse_date(dpart)
    parts = tpart.split(":")
    h = int(parts[0]); mi = int(parts[1]) if len(parts) > 1 else 0
    sec = parts[2] if len(parts) > 2 else "0"
    if "." in sec:
        sp, fp = sec.split(".")
        micros = int((fp + "000000")[:6])
        s_int = int(sp)
    else:
        micros, s_int = 0, int(sec)
    return (days * MICROS_PER_DAY
            + ((h * 60 + mi) * 60 + s_int) * MICROS_PER_SEC + micros)


def datetime_to_string(micros: int) -> str:
    micros = int(micros)
    days, rem = divmod(micros, MICROS_PER_DAY)
    d = days_to_date(days)
    sec, us = divmod(rem, MICROS_PER_SEC)
    h, rem2 = divmod(sec, 3600)
    mi, s = divmod(rem2, 60)
    base = f"{d.isoformat()} {h:02d}:{mi:02d}:{s:02d}"
    return f"{base}.{us:06d}" if us else base


# --- vectorized calendar decomposition (host precompute for device LUTs) --- #

def civil_from_days(xp, days):
    """Vectorized civil-from-days (Howard Hinnant's algorithm) in an array
    namespace `xp` (numpy or jax.numpy) — shared by host decoding and the
    device expression compiler (expr/compile.py year/month/dayofmonth)."""
    z = (days.astype(xp.int64) if hasattr(days, "astype") else days) + 719468
    era = xp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = xp.where(mp < 10, mp + 3, mp - 9)
    y = xp.where(m <= 2, y + 1, y)
    return y, m, d


def days_from_civil(xp, y, m, d):
    """Vectorized days-from-civil (inverse of civil_from_days), same
    algorithm family — used by device DATE_ADD month arithmetic."""
    y = y - (m <= 2)
    era = xp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = xp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def is_leap(xp, y):
    return ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)


_MONTH_DAYS = np.array([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                       dtype=np.int64)


def days_in_month(xp, y, m):
    """Vectorized month length (for DATE_ADD day clamping / LAST_DAY)."""
    base = xp.asarray(_MONTH_DAYS)[xp.clip(m - 1, 0, 11)]
    return base + (is_leap(xp, y) & (m == 2))


def year_month_day_np(days: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    y, m, d = civil_from_days(np, days)
    return y.astype(np.int64), m.astype(np.int64), d.astype(np.int64)


__all__ = [
    "EPOCH", "MICROS_PER_SEC", "MICROS_PER_DAY",
    "date_to_days", "parse_date", "days_to_date", "date_to_string",
    "parse_datetime", "datetime_to_string", "year_month_day_np",
]


def duration_to_string(micros: int) -> str:
    """TIME text: '[-]H:MM:SS[.ffffff]' (types/duration String analog)."""
    sign = "-" if micros < 0 else ""
    us = abs(int(micros))
    s, frac = divmod(us, 1_000_000)
    h, rem = divmod(s, 3600)
    m, sec = divmod(rem, 60)
    out = f"{sign}{h:02d}:{m:02d}:{sec:02d}"
    if frac:
        out += f".{frac:06d}".rstrip("0")
    return out


def days_from_civil(xp, y, m, d):
    """Vectorized days-since-epoch from (y, m, d) — the inverse of
    civil_from_days (Howard Hinnant's algorithm), shared by the device
    expression compiler's numeric->DATETIME cast."""
    y = y - (m <= 2)
    era = xp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    doy = (153 * (m + xp.where(m > 2, -3, 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def parse_time(s: str):
    """'[-][D ]HH:MM:SS[.frac]' / 'HH:MM' / bare digits ([H]H[MM][SS])
    -> signed micros, or None (MySQL abbreviated-TIME rules:
    '11:12' = 11:12:00; digits group from the right as [H]HMMSS)."""
    txt = s.strip()
    neg = txt.startswith("-")
    if neg:
        txt = txt[1:]
    try:
        if ":" in txt:
            parts = txt.split(":")
            if len(parts) == 3:
                h, m = int(parts[0]), int(parts[1])
                sec = float(parts[2])
            elif len(parts) == 2:
                # MySQL: 'HH:MM' means HH:MM:00, NOT MM:SS
                h, m, sec = int(parts[0]), int(parts[1]), 0.0
            else:
                return None
        else:
            v = float(txt)
            iv = int(v)
            frac = v - iv
            sec = iv % 100 + frac
            m = iv // 100 % 100
            h = iv // 10_000
        if m >= 60 or sec >= 60:
            return None
        us = int(round((h * 3600 + m * 60 + sec) * 1e6))
        return -us if neg else us
    except ValueError:
        return None
