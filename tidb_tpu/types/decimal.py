"""Scaled-integer DECIMAL support ("decimal64").

Reference analog: pkg/types/mydecimal.go (9-digit word representation with
up to 65 digits).  The TPU rebuild bounds DECIMAL to 18 significant digits and
represents values as ``int64`` scaled by ``10**scale`` — dense, fixed-width,
and exact, with MySQL half-up rounding implemented on integers.

Aggregation-overflow safety: SUM over billions of rows can exceed int64, so
device kernels accumulate decimals as *two int64 limbs* (hi = v >> 32,
lo = v & 0xffffffff); the exact 128-bit total is recombined host-side with
Python integers (see copr/aggregate.py).  This mirrors the reference's
partial-agg-state contract (SURVEY.md §A.4) where cop tasks return partial
states as plain columns.
"""

from __future__ import annotations

import decimal as pydec
from typing import Union

import numpy as np

_POW10 = [10 ** i for i in range(19)]


def pow10(n: int) -> int:
    # Negative exponents would silently produce floats and break the exact
    # scaled-int contract; callers must rescale the other operand instead.
    assert n >= 0, f"pow10({n})"
    return _POW10[n] if n < len(_POW10) else 10 ** n


def encode(value: Union[str, int, float, pydec.Decimal], scale: int) -> int:
    """Encode a python value into a scaled int with MySQL half-up rounding.
    A widened context covers 65-digit (wide) decimals — the default
    28-digit context raises InvalidOperation past ~28 digits."""
    d = pydec.Decimal(str(value)) if not isinstance(value, pydec.Decimal) else value
    with pydec.localcontext() as ctx:
        ctx.prec = 96
        q = d.scaleb(scale).quantize(pydec.Decimal(1),
                                     rounding=pydec.ROUND_HALF_UP)
    return int(q)


def decode(scaled: int, scale: int) -> pydec.Decimal:
    with pydec.localcontext() as ctx:
        ctx.prec = 96        # wide decimals exceed the default 28 digits
        return pydec.Decimal(scaled).scaleb(-scale)


def to_string(scaled: int, scale: int) -> str:
    """MySQL-style textual form with exactly `scale` fraction digits."""
    sign = "-" if scaled < 0 else ""
    mag = abs(int(scaled))
    if scale == 0:
        return f"{sign}{mag}"
    intpart, frac = divmod(mag, pow10(scale))
    return f"{sign}{intpart}.{frac:0{scale}d}"


def rescale_np(data: np.ndarray, from_scale: int, to_scale: int) -> np.ndarray:
    """Rescale a scaled-int array, half-up rounding on downscale."""
    if to_scale == from_scale:
        return data
    if to_scale > from_scale:
        return data * pow10(to_scale - from_scale)
    div = pow10(from_scale - to_scale)
    # round-half-away-from-zero on integers
    half = div // 2
    adj = np.where(data >= 0, data + half, data - half)
    return adj // div


def split_limbs(total: int) -> tuple[int, int]:
    """Split into (hi, lo) with lo in [0, 2^32) — the device accumulator form."""
    return total >> 32, total & 0xFFFFFFFF


def combine_limbs(hi: int, lo: int) -> int:
    """Recombine device partial sums; exact in Python ints."""
    return (int(hi) << 32) + int(lo)


__all__ = [
    "pow10", "encode", "decode", "to_string", "rescale_np",
    "split_limbs", "combine_limbs",
]
