"""Expression evaluator/compiler with MySQL NULL + decimal semantics.

Reference analog: pkg/expression's vectorized builtins
(builtin_*_vec.go, VectorizedExecute chunk_executor.go:99).  Instead of ~315
hand-written Go loop kernels, one recursive compiler lowers the IR to array
ops in a namespace `xp` that is either:

- ``jax.numpy`` — traced inside the fused coprocessor jit program; XLA fuses
  the whole predicate/projection tree into the scan kernel (the TPU analog of
  the closure executor, unistore/cophandler/closure_exec.go:468), or
- ``numpy`` — host-side evaluation for root-executor residue (expressions the
  capability registry refuses to push down, SURVEY.md §A.1).

Every node evaluates to a pair ``(value, valid)``:

- value: array in device representation (scaled ints for DECIMAL, dict codes
  for STRING, days/micros for temporal); comparisons/logic yield bool arrays.
- valid: bool array, or the literal ``True`` meaning "all valid" (so
  non-nullable columns never materialize a mask).

Three-valued logic, NULL propagation, decimal rescaling, and MySQL rounding
all live here, golden-tested against python Decimal in tests/test_expr.py.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from ..types import dtypes as dt
from ..types import decimal as dec
from .ir import ColumnRef, Const, Expr, Func

K = dt.TypeKind

Pair = tuple[Any, Any]  # (value, valid)


# extension scalar functions (tidb_tpu/extension): name -> (callable,
# arity); evaluated host-side row-at-a-time via Evaluator._ext_func
EXTENSION_FUNCS: dict = {}


def _jan1(xp, y):
    """Days-since-epoch of January 1st of year(s) y."""
    from ..types.temporal import days_from_civil
    return days_from_civil(xp, y, 1, 1)


def vand(a, b):
    if a is True:
        return b
    if b is True:
        return a
    return a & b


class Evaluator:
    """Evaluate IR over columns. `xp` = numpy or jax.numpy.

    `dicts` (host evaluation only) maps column index -> StringDict; with
    it, string functions that dictionary lowering could not rewrite fall
    back to per-row python evaluation — the residual row-wise builtin
    path of the reference (builtin_string.go evalString loops)."""

    def __init__(self, xp, dicts=None):
        self.xp = xp
        self.dicts = dicts

    # -- public entry ---------------------------------------------------- #

    def eval(self, e: Expr, cols: Sequence[Pair], memo: dict | None = None) -> Pair:
        if memo is None:
            memo = {}
        key = id(e)
        if key in memo:
            return memo[key]
        out = self._eval(e, cols, memo)
        memo[key] = out
        return out

    # -- dispatch -------------------------------------------------------- #

    def _eval(self, e: Expr, cols, memo) -> Pair:
        if isinstance(e, ColumnRef):
            return cols[e.index]
        if isinstance(e, Const):
            if e.value is None:
                return self.xp.int64(0), False
            if isinstance(e.value, np.ndarray):
                return self.xp.asarray(e.value), True
            return e.value, True
        assert isinstance(e, Func)
        if e.op.startswith("ext:"):
            return self._ext_func(e, cols, memo)
        fn = getattr(self, f"op_{e.op}", None)
        if fn is None:
            raise NotImplementedError(f"op {e.op}")
        return fn(e, cols, memo)

    def _ext_func(self, e: Func, cols, memo) -> Pair:
        """Extension scalar function (pkg/extension function point): a
        registered host python callable applied row-at-a-time — HOST
        evaluation only (never device-fused; _device_supported excludes
        ext: ops)."""
        ext = EXTENSION_FUNCS.get(e.op[4:])
        if ext is None:
            raise NotImplementedError(f"extension function {e.op[4:]}")
        fn, _arity = ext
        vals = [self.eval(a, cols, memo) for a in e.args]
        n = 1
        for v, _m in vals:
            if getattr(v, "ndim", 0):
                n = max(n, len(v))
        out = np.empty(n, np.float64)
        ok = np.ones(n, bool)
        for i in range(n):
            row = []
            null = False
            for v, m in vals:
                mv = m if m is True else (bool(m[i]) if getattr(
                    m, "ndim", 0) else bool(m))
                if not mv:
                    null = True
                    break
                row.append(v[i].item() if getattr(v, "ndim", 0) else v)
            if null:
                ok[i] = False
                out[i] = 0.0
                continue
            r = fn(*row)
            if r is None:
                ok[i] = False
                out[i] = 0.0
            else:
                out[i] = float(r)
        return self.xp.asarray(out), (True if ok.all()
                                      else self.xp.asarray(ok))

    # -- helpers --------------------------------------------------------- #

    def _num(self, a: Expr, cols, memo, as_kind: K | None = None):
        """Evaluate a numeric operand; cast bool compare-results to int."""
        v, m = self.eval(a, cols, memo)
        if getattr(v, "dtype", None) is not None and v.dtype == bool:
            v = v.astype(self.xp.int64)
        elif isinstance(v, bool):
            v = int(v)
        return v, m

    # -- narrow physical columns (chunk.Column.narrowed) ----------------- #
    #
    # Scans may hand the evaluator int8/int16/int32 arrays holding int64/
    # decimal/date logical values (the frame-of-reference column encoding:
    # 1-4 bytes/row of memory traffic instead of 8).  Integer arithmetic
    # must then compute at full width — numpy/jnp promotion would keep the
    # narrow width and overflow.  np: ufunc dtype= computes widened without
    # materializing upcast temporaries; jnp: astype converts fuse into the
    # surrounding XLA kernel.

    def _iwiden(self, op: str, va, vb, unsigned: bool):
        xp = self.xp
        tgt = xp.uint64 if unsigned else xp.int64
        if xp is np:
            # object arrays (exact python-int wide decimals) and pure
            # python scalars keep python arithmetic — exact at any
            # magnitude; the ufunc dtype= kwarg cannot cast the former
            # and would wrap/raise on >64-bit literals for the latter
            da = getattr(va, "dtype", None)
            db = getattr(vb, "dtype", None)
            if (da is not None and da.kind == "O") \
                    or (db is not None and db.kind == "O") \
                    or (da is None and db is None):
                return {"add": lambda: va + vb,
                        "subtract": lambda: va - vb,
                        "multiply": lambda: va * vb}[op]()
            return getattr(np, op)(va, vb, dtype=tgt)
        if getattr(va, "dtype", None) is not None and va.dtype != tgt:
            va = va.astype(tgt)
        if getattr(vb, "dtype", None) is not None and vb.dtype != tgt:
            vb = vb.astype(tgt)
        return {"add": xp.add, "subtract": xp.subtract,
                "multiply": xp.multiply}[op](va, vb)

    @staticmethod
    def _is_narrow(v) -> bool:
        d = getattr(v, "dtype", None)
        return d is not None and d.kind in "iu" and d.itemsize < 8

    def _cmp_fit(self, va, vb):
        """Make a (narrow array, int scalar) comparison width-safe AND
        narrow-fast: a literal that fits the array's physical dtype is cast
        down (the compare then runs at physical width); one that does not
        fit widens the array side (numpy NEP50 would raise OverflowError,
        jnp would silently wrap)."""
        for x, y, flip in ((va, vb, False), (vb, va, True)):
            if self._is_narrow(x) and isinstance(y, (int, np.integer)) \
                    and getattr(y, "ndim", 0) == 0:
                info = np.iinfo(x.dtype)
                if info.min <= int(y) <= info.max:
                    y = x.dtype.type(y)
                else:
                    x = x.astype(self.xp.int64)
                return (y, x) if flip else (x, y)
            # int64 array vs a beyond-64-bit python literal (wide decimal
            # rescales): numpy would raise; compare in exact object ints
            d = getattr(x, "dtype", None)
            if d is not None and d.kind in "iu" and isinstance(y, int) \
                    and not (-2 ** 63 <= y < 2 ** 64):
                x = x.astype(object)
                return (y, x) if flip else (x, y)
        return va, vb

    def _to_common(self, e: Func, cols, memo):
        """Evaluate both operands and unify numeric representation."""
        xp = self.xp
        a, b = e.args
        va, ma = self._num(a, cols, memo)
        vb, mb = self._num(b, cols, memo)
        ka, kb = a.dtype.kind, b.dtype.kind
        if ka in (K.FLOAT64, K.FLOAT32) or kb in (K.FLOAT64, K.FLOAT32):
            va = self._as_double(va, a.dtype)
            vb = self._as_double(vb, b.dtype)
            return va, ma, vb, mb, dt.double()
        if ka == K.DECIMAL or kb == K.DECIMAL:
            sa = a.dtype.scale if ka == K.DECIMAL else 0
            sb = b.dtype.scale if kb == K.DECIMAL else 0
            s = max(sa, sb)
            if sa < s:
                va = self._iwiden("multiply", va, dec.pow10(s - sa), False)
            if sb < s:
                vb = self._iwiden("multiply", vb, dec.pow10(s - sb), False)
            return va, ma, vb, mb, dt.decimal(18, s)
        # DATE (days) vs DATETIME (micros): coerce DATE up, MySQL-style
        if {ka, kb} == {K.DATE, K.DATETIME}:
            from ..types.temporal import MICROS_PER_DAY
            if ka == K.DATE:
                va = _as_i64(xp, va) * MICROS_PER_DAY
            else:
                vb = _as_i64(xp, vb) * MICROS_PER_DAY
            return va, ma, vb, mb, dt.datetime()
        # mixed signed/unsigned BIGINT: numpy would silently promote to
        # float64 (lossy past 2^53); compute in uint64 two's complement and
        # let _cmp fix up sign-aware comparisons
        if {ka, kb} == {K.INT64, K.UINT64}:
            va = va.astype(xp.uint64) if hasattr(va, "astype") else xp.uint64(va)
            vb = vb.astype(xp.uint64) if hasattr(vb, "astype") else xp.uint64(vb)
            return va, ma, vb, mb, dt.ubigint()
        return va, ma, vb, mb, (a.dtype if ka != K.NULL else b.dtype)

    def _as_double(self, v, t: dt.DataType):
        xp = self.xp
        if t.kind == K.DECIMAL:
            return v.astype(xp.float64) / float(dec.pow10(t.scale)) \
                if hasattr(v, "astype") else float(v) / dec.pow10(t.scale)
        if hasattr(v, "astype"):
            return v.astype(xp.float64)
        return float(v)

    def _truthy(self, e: Expr, cols, memo) -> Pair:
        """MySQL truthiness: nonzero numeric = true.  Scalar results are
        wrapped as xp.bool_ so ``~``/``&`` keep boolean semantics (a python
        bool would turn ``~True`` into -2 and poison validity masks)."""
        v, m = self.eval(e, cols, memo)
        if getattr(v, "dtype", None) is not None and v.dtype == bool:
            return v, m
        if isinstance(v, (bool, int, float)):
            return self.xp.bool_(v != 0), m
        return v != 0, m

    # -- arithmetic ------------------------------------------------------ #

    _INT_FAMILY = (K.INT64, K.UINT64, K.DECIMAL, K.DATE, K.DATETIME,
                   K.TIME)

    def _arith(self, op: str, va, vb, t):
        """Add/sub/mul honoring the logical (int64/uint64) width when a
        physical operand is narrow."""
        if t.kind in self._INT_FAMILY and (self._is_narrow(va)
                                           or self._is_narrow(vb)):
            return self._iwiden(op, va, vb, t.kind == K.UINT64)
        return {"add": lambda: va + vb, "subtract": lambda: va - vb,
                "multiply": lambda: va * vb}[op]()

    _I64_MIN = -2 ** 63

    def _guard_dec_overflow(self, op: str, va, vb, r, m) -> None:
        """int64 scalar-op overflow guard for DECIMAL arithmetic (the
        gap expr/builders._arith_result_type documents): a scaled-int64
        result that wrapped past 2^63 reads back as a wrong decimal with
        no error.  Host (numpy) evaluation detects the wrap on VALID
        lanes and raises — MySQL's "value is out of range" discipline —
        instead of returning wrapped digits.  Device (jnp) lanes cannot
        raise data-dependently inside a traced program and stay
        unguarded (the builders comment narrows to exactly that).

        The multiply check divides the wrapped product back: exact for
        two's-complement wrap (q != a whenever a*b left int64, plus the
        (INT64_MIN, -1) floor-division special case)."""
        if self.xp is not np or not isinstance(r, np.ndarray) \
                or r.dtype.kind != "i":
            return            # device lanes / object-int (exact) / scalar
        a, b = np.asarray(va), np.asarray(vb)
        if a.dtype.kind not in "iu" or b.dtype.kind not in "iu":
            return
        a = a.astype(np.int64, copy=False)
        b = b.astype(np.int64, copy=False)
        if op == "add":
            bad = ((b > 0) & (r < a)) | ((b < 0) & (r > a))
        elif op == "subtract":
            bad = ((b < 0) & (r < a)) | ((b > 0) & (r > a))
        else:
            nz = b != 0
            with np.errstate(over="ignore"):
                q = np.floor_divide(r, np.where(nz, b, 1))
            bad = (nz & (q != a)) \
                | ((a == self._I64_MIN) & (b == -1))
        if m is not True:
            bad = bad & m
        if np.any(bad):
            raise OverflowError(
                "DECIMAL value is out of range: scaled int64 "
                f"{'+' if op == 'add' else '-' if op == 'subtract' else '*'}"
                " overflowed 18 digits (narrow the operands or cast to "
                "DOUBLE)")

    def op_add(self, e, cols, memo):
        va, ma, vb, mb, t = self._to_common(e, cols, memo)
        r, m = self._arith("add", va, vb, t), vand(ma, mb)
        if t.kind == K.DECIMAL:
            self._guard_dec_overflow("add", va, vb, r, m)
        return r, m

    def op_sub(self, e, cols, memo):
        va, ma, vb, mb, t = self._to_common(e, cols, memo)
        r, m = self._arith("subtract", va, vb, t), vand(ma, mb)
        if t.kind == K.DECIMAL:
            self._guard_dec_overflow("subtract", va, vb, r, m)
        return r, m

    def op_mul(self, e, cols, memo):
        a, b = e.args
        if e.dtype.kind == K.DECIMAL:
            # scales add: no rescale needed before the integer multiply
            va, ma = self._num(a, cols, memo)
            vb, mb = self._num(b, cols, memo)
            r, m = self._arith("multiply", va, vb, e.dtype), vand(ma, mb)
            self._guard_dec_overflow("multiply", va, vb, r, m)
            return r, m
        va, ma, vb, mb, t = self._to_common(e, cols, memo)
        return self._arith("multiply", va, vb, t), vand(ma, mb)

    def op_div(self, e, cols, memo):
        xp = self.xp
        a, b = e.args
        if e.dtype.kind == K.DECIMAL:
            sa = a.dtype.scale if a.dtype.kind == K.DECIMAL else 0
            sb = b.dtype.scale if b.dtype.kind == K.DECIMAL else 0
            k = e.dtype.scale - sa + sb
            va, ma = self._num(a, cols, memo)
            vb, mb = self._num(b, cols, memo)
            # k < 0 (result scale capped below dividend scale): scale the
            # divisor instead — pow10 must stay integral to keep exactness.
            if k >= 0:
                num = self._iwiden("multiply", va, dec.pow10(k), False)
                # the pre-scaling multiply wraps exactly like any other
                # scaled-int64 multiply — exact divide-back guard on
                # host lanes (device lanes: valueflow NUM-DIV-PRESCALE
                # proves the interval pre-trace)
                self._guard_dec_overflow("multiply", va, dec.pow10(k),
                                         num, vand(ma, mb))
                den = _as_i64(xp, vb) if self._is_narrow(vb) else vb
            else:
                num = _as_i64(xp, va) if self._is_narrow(va) else va
                den = self._iwiden("multiply", vb, dec.pow10(-k), False)
                self._guard_dec_overflow("multiply", vb, dec.pow10(-k),
                                         den, vand(ma, mb))
            return (_round_div(xp, num, den), _div_valid(xp, ma, mb, vb))
        va, ma = self._num(a, cols, memo)
        vb, mb = self._num(b, cols, memo)
        va = self._as_double(va, a.dtype)
        vb = self._as_double(vb, b.dtype)
        safe = xp.where(vb == 0, 1.0, vb)
        return va / safe, _div_valid(xp, ma, mb, vb)

    def op_intdiv(self, e, cols, memo):
        xp = self.xp
        va, ma, vb, mb, t = self._to_common(e, cols, memo)
        if self._is_narrow(va):
            va = _as_i64(xp, va)
        if self._is_narrow(vb):
            vb = _as_i64(xp, vb)
        if t.kind == K.FLOAT64:
            safe = xp.where(vb == 0, 1.0, vb)
            q = xp.trunc(va / safe).astype(xp.int64)
        else:
            q = _trunc_div(xp, va, vb)
        return q, _div_valid(xp, ma, mb, vb)

    def op_mod(self, e, cols, memo):
        xp = self.xp
        va, ma, vb, mb, t = self._to_common(e, cols, memo)
        if self._is_narrow(va):
            va = _as_i64(xp, va)
        if self._is_narrow(vb):
            vb = _as_i64(xp, vb)
        if t.kind == K.FLOAT64:
            safe = xp.where(vb == 0, 1.0, vb)
            r = va - xp.trunc(va / safe) * vb
        else:
            r = va - _trunc_div(xp, va, vb) * vb
        return r, _div_valid(xp, ma, mb, vb)

    def op_neg(self, e, cols, memo):
        v, m = self._num(e.args[0], cols, memo)
        if self._is_narrow(v):
            v = _as_i64(self.xp, v)    # -(INT_MIN of the narrow width)
        return -v, m

    def op_abs(self, e, cols, memo):
        v, m = self._num(e.args[0], cols, memo)
        if self._is_narrow(v):
            v = _as_i64(self.xp, v)
        return self.xp.abs(v), m

    # -- vector functions (host-only; types VectorFloat32 analog) -------- #

    def _vec_mat(self, arg, cols, memo):
        """(matrix (n|1, maxd) zero-padded, dims (n|1,), valid (n|1,),
        is_column) for one vector arg.  Zero-padding to the column's max
        dimension keeps norms/dots/distances exact per row, so an
        unconstrained VECTOR column may hold mixed dimensions; binary
        functions enforce per-ROW dimension equality (vector.go
        CheckVectorDims semantics)."""
        v, m = self.eval(arg, cols, memo)
        if isinstance(v, np.ndarray) and v.dtype == object:
            n = len(v)
            valid = np.array(_mask_arr(np, m, v), bool).copy()
            dims = np.zeros(n, np.int64)
            for i in range(n):
                if not valid[i] or v[i] is None:
                    valid[i] = False
                else:
                    dims[i] = len(v[i])
            maxd = int(dims.max()) if n else 0
            mat = np.zeros((n, maxd), np.float32)
            for i in range(n):
                if valid[i]:
                    mat[i, :dims[i]] = v[i]
            return mat, dims, valid, True
        if v is None or (not isinstance(v, np.ndarray) and m is False):
            return (np.zeros((1, 0), np.float32), np.zeros(1, np.int64),
                    np.array([False]), False)
        arr = np.asarray(v, np.float32).reshape(1, -1)
        return (arr, np.full(1, arr.shape[1], np.int64),
                np.array([bool(m) if m in (True, False) else True]), False)

    def _vec_binary(self, e, cols, memo, fn):
        a, da, va, acol = self._vec_mat(e.args[0], cols, memo)
        b, db, vb, bcol = self._vec_mat(e.args[1], cols, memo)
        valid = va & vb
        # per-row dimension check over the rows that actually pair up
        nrows = max(len(da), len(db))
        pa = np.broadcast_to(da, (nrows,))
        pb = np.broadcast_to(db, (nrows,))
        pv = np.broadcast_to(valid, (nrows,))
        if bool(((pa != pb) & pv).any()):
            raise ValueError("vectors have different dimensions")
        d = max(a.shape[1], b.shape[1])
        if a.shape[1] != d:
            a = np.pad(a, ((0, 0), (0, d - a.shape[1])))
        if b.shape[1] != d:
            b = np.pad(b, ((0, 0), (0, d - b.shape[1])))
        out = fn(a.astype(np.float64), b.astype(np.float64))
        if not acol and not bcol:
            return float(out[0]), bool(valid[0])
        return out, valid

    def op_vec_l2_distance(self, e, cols, memo):
        return self._vec_binary(
            e, cols, memo,
            lambda a, b: np.sqrt(((a - b) ** 2).sum(axis=1)))

    def op_vec_l1_distance(self, e, cols, memo):
        return self._vec_binary(
            e, cols, memo, lambda a, b: np.abs(a - b).sum(axis=1))

    def op_vec_negative_inner_product(self, e, cols, memo):
        return self._vec_binary(
            e, cols, memo, lambda a, b: -(a * b).sum(axis=1))

    def op_vec_cosine_distance(self, e, cols, memo):
        def cos(a, b):
            na = np.sqrt((a * a).sum(axis=1))
            nb = np.sqrt((b * b).sum(axis=1))
            denom = na * nb
            with np.errstate(divide="ignore", invalid="ignore"):
                out = 1.0 - (a * b).sum(axis=1) / denom
            return np.where(denom == 0, np.nan, out)
        v, m = self._vec_binary(e, cols, memo, cos)
        # zero-norm input: NULL (undefined angle)
        if isinstance(v, np.ndarray):
            bad = np.isnan(v)
            return np.where(bad, 0.0, v), _mask_arr(np, m, v) & ~bad
        return (0.0, False) if v != v else (v, m)

    def op_vec_dims(self, e, cols, memo):
        v, m = self.eval(e.args[0], cols, memo)
        if isinstance(v, np.ndarray) and v.dtype == object:
            valid = np.array(_mask_arr(np, m, v), bool).copy()
            out = np.zeros(len(v), np.int64)
            for i, x in enumerate(v):
                if valid[i] and x is not None:
                    out[i] = len(x)
                else:
                    valid[i] = False
            return out, valid
        arr = np.asarray(v, np.float32).reshape(-1)
        return np.int64(len(arr)), m

    def op_vec_l2_norm(self, e, cols, memo):
        mat, _dims, valid, col = self._vec_mat(e.args[0], cols, memo)
        out = np.sqrt((mat.astype(np.float64) ** 2).sum(axis=1))
        if not col:
            return float(out[0]), bool(valid[0])
        return out, valid

    def op_vec_as_text(self, e, cols, memo):
        from ..types.dtypes import vector_to_text
        v, m = self.eval(e.args[0], cols, memo)
        if isinstance(v, np.ndarray) and v.dtype == object:
            valid = np.array(_mask_arr(np, m, v), bool).copy()
            out = np.empty(len(v), object)
            for i, x in enumerate(v):
                if valid[i] and x is not None:
                    out[i] = vector_to_text(x)
                else:
                    out[i] = ""
                    valid[i] = False
            return out, valid
        return vector_to_text(np.asarray(v, np.float32).reshape(-1)), m

    # -- comparisons ----------------------------------------------------- #

    def _cmp(self, e, cols, memo, fn):
        xp = self.xp
        a, b = e.args
        if a.dtype.is_string and b.dtype.is_string:
            # post-lowering both sides are dict codes / code thresholds
            va, ma = self.eval(a, cols, memo)
            vb, mb = self.eval(b, cols, memo)
            return fn(va, vb), vand(ma, mb)
        if {a.dtype.kind, b.dtype.kind} == {K.INT64, K.UINT64}:
            # sign-aware signed-vs-unsigned compare: a negative signed value
            # orders below every unsigned value; otherwise compare in uint64.
            va, ma = self._num(a, cols, memo)
            vb, mb = self._num(b, cols, memo)
            ua = _as_u64(xp, va)
            ub = _as_u64(xp, vb)
            res = fn(ua, ub)
            if a.dtype.kind == K.INT64:
                res = xp.where(va < 0, fn(xp.int64(-1), xp.int64(0)), res)
            else:
                res = xp.where(vb < 0, fn(xp.int64(0), xp.int64(-1)), res)
            return res, vand(ma, mb)
        va, ma, vb, mb, _ = self._to_common(e, cols, memo)
        va, vb = self._cmp_fit(va, vb)
        return fn(va, vb), vand(ma, mb)

    def op_eq(self, e, cols, memo):
        return self._cmp(e, cols, memo, lambda a, b: a == b)

    def op_ne(self, e, cols, memo):
        return self._cmp(e, cols, memo, lambda a, b: a != b)

    def op_lt(self, e, cols, memo):
        return self._cmp(e, cols, memo, lambda a, b: a < b)

    def op_le(self, e, cols, memo):
        return self._cmp(e, cols, memo, lambda a, b: a <= b)

    def op_gt(self, e, cols, memo):
        return self._cmp(e, cols, memo, lambda a, b: a > b)

    def op_ge(self, e, cols, memo):
        return self._cmp(e, cols, memo, lambda a, b: a >= b)

    # -- sequences (host-only, side-effecting; never folded/cached) ------ #

    def _seq_conn(self):
        from ..planner.build import SESSION_INFO
        info = SESSION_INFO.get()
        return int(info.get("conn_id", 0)) if info else 0

    def _rows_n(self, cols) -> int:
        for v, _m in cols:
            if getattr(v, "ndim", 0):
                return len(v)
        return 1

    def op_seq_next(self, e, cols, memo):
        """NEXTVAL(seq): advances once per evaluated row (MySQL/TiDB
        row-at-a-time semantics)."""
        seq = e.args[0].value
        conn = self._seq_conn()
        n = self._rows_n(cols)
        vals = np.fromiter((seq.next_value(conn) for _ in range(n)),
                           np.int64, count=n)
        return (self.xp.asarray(vals) if n > 1 or cols else
                int(vals[0])), True

    def op_seq_last(self, e, cols, memo):
        seq = e.args[0].value
        v = seq.last_value(self._seq_conn())
        if v is None:
            return self.xp.int64(0), False
        return int(v), True

    def op_seq_set(self, e, cols, memo):
        seq = e.args[0].value
        v, m = self._num(e.args[1], cols, memo)
        if getattr(v, "ndim", 0) and np.asarray(v).size != 1:
            raise ValueError("SETVAL takes a constant value, "
                             "not a per-row expression")
        if m is not True and not (np.asarray(m).reshape(-1)[:1].all()
                                  if getattr(m, "ndim", 0) else bool(m)):
            return self.xp.int64(0), False
        val = int(v if not getattr(v, "ndim", 0) else np.asarray(v).item())
        out = seq.set_value(val, self._seq_conn())
        if out is None:          # ignored backwards move -> NULL
            return self.xp.int64(0), False
        return int(out), True

    # -- three-valued logic ---------------------------------------------- #

    def op_and(self, e, cols, memo):
        va, ma = self._truthy(e.args[0], cols, memo)
        vb, mb = self._truthy(e.args[1], cols, memo)
        val = va & vb
        if ma is True and mb is True:   # all-valid fast path (hot scans)
            return val, True
        # NULL AND FALSE = FALSE:  valid if both valid, or either side is a valid FALSE
        valid = _or3(vand(ma, mb), vand(ma, ~va), vand(mb, ~vb))
        return val, valid

    def op_or(self, e, cols, memo):
        va, ma = self._truthy(e.args[0], cols, memo)
        vb, mb = self._truthy(e.args[1], cols, memo)
        val = va | vb
        if ma is True and mb is True:
            return val, True
        valid = _or3(vand(ma, mb), vand(ma, va), vand(mb, vb))
        return val, valid

    def op_xor(self, e, cols, memo):
        va, ma = self._truthy(e.args[0], cols, memo)
        vb, mb = self._truthy(e.args[1], cols, memo)
        return va ^ vb, vand(ma, mb)

    def op_not(self, e, cols, memo):
        v, m = self._truthy(e.args[0], cols, memo)
        return ~v, m

    # -- NULL handling ---------------------------------------------------- #

    def op_isnull(self, e, cols, memo):
        v, m = self.eval(e.args[0], cols, memo)
        if m is True:
            return _broadcast_false(self.xp, v), True
        if m is False:
            return True, True
        return ~m, True

    def op_if(self, e, cols, memo):
        xp = self.xp
        c, cm = self._truthy(e.args[0], cols, memo)
        tv, tm = self._branch_val(e, e.args[1], cols, memo)
        ev, em = self._branch_val(e, e.args[2], cols, memo)
        cond = c if cm is True else (c & cm)  # NULL condition -> else branch
        val = xp.where(cond, tv, ev)
        valid = xp.where(cond, _mask_arr(xp, tm, tv), _mask_arr(xp, em, ev))
        return val, valid

    def op_case(self, e, cols, memo):
        xp = self.xp
        args = e.args
        has_else = len(args) % 2 == 1
        pairs = [(args[i], args[i + 1]) for i in range(0, len(args) - (1 if has_else else 0), 2)]
        if has_else:
            acc_val, acc_valid = self._branch_val(e, args[-1], cols, memo)
        else:
            acc_val, acc_valid = xp.int64(0), False
        # fold from last WHEN to first
        for c, v in reversed(pairs):
            cv, cm = self._truthy(c, cols, memo)
            cond = cv if cm is True else (cv & cm)
            bv, bm = self._branch_val(e, v, cols, memo)
            acc_val = xp.where(cond, bv, acc_val)
            acc_valid = xp.where(cond, _mask_arr(xp, bm, bv), _mask_arr(xp, acc_valid, acc_val))
        return acc_val, acc_valid

    def op_coalesce(self, e, cols, memo):
        xp = self.xp
        val, valid = self._branch_val(e, e.args[-1], cols, memo)
        for a in reversed(e.args[:-1]):
            av, am = self._branch_val(e, a, cols, memo)
            use_a = _mask_arr(xp, am, av)
            val = xp.where(use_a, av, val)
            valid = use_a | _mask_arr(xp, valid, val)
        return val, valid

    def _branch_val(self, parent: Func, a: Expr, cols, memo) -> Pair:
        """Evaluate a CASE/IF branch, coercing to the parent's result type."""
        v, m = self.eval(a, cols, memo)
        pk = parent.dtype.kind
        if getattr(v, "dtype", None) is not None and v.dtype == bool:
            v = v.astype(self.xp.int64)
        elif isinstance(v, bool):
            v = int(v)
        if pk in (K.FLOAT64, K.FLOAT32) and a.dtype.kind not in (K.FLOAT64, K.FLOAT32):
            v = self._as_double(v, a.dtype)
        elif pk == K.DECIMAL:
            sa = a.dtype.scale if a.dtype.kind == K.DECIMAL else 0
            if sa < parent.dtype.scale:
                v = self._iwiden("multiply", v,
                                 dec.pow10(parent.dtype.scale - sa), False)
        if pk in self._INT_FAMILY and self._is_narrow(v):
            # branches of one CASE/IF must share a width: a narrow branch
            # next to a wide/const branch would overflow xp.where promotion
            v = _as_i64(self.xp, v)
        return v, m

    # -- IN -------------------------------------------------------------- #

    def op_in(self, e, cols, memo):
        xp = self.xp
        target, items = e.args[0], e.args[1:]
        tv, tm = self._num(target, cols, memo) if target.dtype.is_numeric \
            else self.eval(target, cols, memo)
        any_match = None
        all_valid = tm
        for it in items:
            iv, im = self._num(it, cols, memo) if it.dtype.is_numeric \
                else self.eval(it, cols, memo)
            # unify decimal scales between target and item
            if target.dtype.kind == K.DECIMAL or it.dtype.kind == K.DECIMAL:
                st = target.dtype.scale if target.dtype.kind == K.DECIMAL else 0
                si = it.dtype.scale if it.dtype.kind == K.DECIMAL else 0
                s = max(st, si)
                a = self._iwiden("multiply", tv, dec.pow10(s - st), False) \
                    if st < s else tv
                b = self._iwiden("multiply", iv, dec.pow10(s - si), False) \
                    if si < s else iv
                a, b = self._cmp_fit(a, b)
                match = a == b
            else:
                a, b = self._cmp_fit(tv, iv)
                match = a == b
            if im is not True:  # NULL/invalid item can never be a match
                match = match & im
            any_match = match if any_match is None else (any_match | match)
            all_valid = vand(all_valid, im)
        # true if any valid match; null if no match and some operand null
        valid = _or3(all_valid, vand(tm, any_match), False)
        return any_match, valid

    # -- strings (post-lowering) ----------------------------------------- #

    def _op_string_unlowered(self, e, cols, memo):
        out = self._rowwise_string(e, cols, memo)
        if out is not None:
            return out
        raise NotImplementedError(
            f"string function {e.op.upper()} could not be lowered onto "
            "dictionary codes (non-dictionary input, non-constant "
            "arguments, or dictionary product too large)")

    def _str_rows(self, a, cols, memo) -> Optional[tuple]:
        """(list[str], validity) of a string-producing argument for the
        row-wise fallback: dict columns decode through their dictionary,
        host string producers (cast_char/date_format) pass object arrays
        through, constants broadcast.  None when the values can't be
        recovered (no dictionary available)."""
        if isinstance(a, Const):
            if a.value is None:
                return ["", False]
            if isinstance(a.value, str):
                return [a.value, True]
            return [str(a.value), True]
        d = None
        if isinstance(a, ColumnRef) and a.dtype.is_string:
            d = (self.dicts or {}).get(a.index)
            if d is None:
                return None
        else:
            d = getattr(a, "_derived_dict", None)
        v, m = self.eval(a, cols, memo)
        v = np.atleast_1d(np.asarray(v))
        if v.dtype == object:
            return [list(v), m]
        if d is not None:
            return [[d.decode(int(c)) for c in v], m]
        if not a.dtype.is_string:
            # numeric operand in a string context (CONCAT(n, 'x'))
            k = a.dtype.kind
            if k in (K.FLOAT64, K.FLOAT32):
                vals = []
                for x in v:
                    s = repr(float(x))
                    vals.append(s[:-2] if s.endswith(".0") else s)
            else:
                vals = [str(int(x)) for x in v]
            return [vals, m]
        return None

    def _rowwise_string(self, e, cols, memo):
        """Per-row host evaluation of a string function over recoverable
        string inputs (numpy only) — composes dict columns with host
        string producers where no single dictionary space exists."""
        if self.xp is not np:
            return None
        from .lower_strings import _str_valued_impl
        from .builders import STRING_INT_FUNCS, STRING_VALUED_FUNCS
        arows = [self._str_rows(a, cols, memo) for a in e.args]
        n = 1
        for r in arows:
            if r is not None and isinstance(r[0], list):
                n = max(n, len(r[0]))

        def row(r, i):
            if r is None:
                return None, False
            vals, m = r
            v = vals if isinstance(vals, str) else vals[i]
            if m is True:
                ok = True
            elif m is False:
                ok = False
            else:
                mm = np.atleast_1d(np.asarray(m))
                ok = bool(mm[i]) if len(mm) > 1 else bool(mm[0])
            return v, ok

        if e.op == "concat":
            if any(r is None for r in arows):
                return None
            out = np.empty(n, object)
            valid = np.ones(n, bool)
            for i in range(n):
                parts = []
                for r in arows:
                    v, ok = row(r, i)
                    if not ok:
                        valid[i] = False
                        break
                    parts.append(v)
                out[i] = "".join(parts) if valid[i] else ""
            return out, valid
        if e.op in STRING_VALUED_FUNCS or e.op in (
                "length", "char_length", "ascii", "bit_length",
                "inet_aton", "regexp_like", "regexp_instr",
                "json_depth", "json_contains_path", "json_storage_size",
                "json_overlaps", "is_uuid", "ord"):
            col_rows = arows[0]
            if col_rows is None:
                return None
            if isinstance(col_rows[0], str):    # folded constant operand
                col_rows = [[col_rows[0]] * n, col_rows[1]]
            consts = []
            for a in e.args[1:]:
                if not isinstance(a, Const) or a.value is None:
                    return None
                consts.append(a.value)
            if e.op == "length":
                fn = lambda v: len(v.encode("utf-8"))
            elif e.op == "char_length":
                fn = lambda v: len(v)
            elif e.op == "ascii":
                fn = lambda v: ord(v[0]) if v else 0
            elif e.op in ("bit_length", "inet_aton", "regexp_like",
                          "regexp_instr", "json_depth",
                          "json_contains_path", "json_storage_size",
                          "json_overlaps", "is_uuid", "ord"):
                from .lower_strings import _str_int_impl
                fn = _str_int_impl(e.op, consts)
            else:
                fn = _str_valued_impl(e.op, consts)
            if fn is None:
                return None
            int_out = e.op in STRING_INT_FUNCS
            out = np.zeros(n, np.int64) if int_out else np.empty(n, object)
            valid = np.ones(n, bool)
            for i in range(n):
                v, ok = row(col_rows, i)
                if not ok:
                    valid[i] = False
                    if not int_out:
                        out[i] = ""
                    continue
                r = fn(v)
                if r is None:
                    valid[i] = False
                    if not int_out:
                        out[i] = ""
                else:
                    out[i] = r
            return out, valid
        return None

    op_upper = op_lower = op_trim = op_ltrim = op_rtrim = \
        op_reverse = op_substring = op_replace = op_concat = op_left = \
        op_right = op_lpad = op_rpad = op_length = op_char_length = \
        op_ascii = op_locate = op_instr = op_find_in_set = \
        op_json_extract = op_json_unquote = op_json_type = \
        op_json_valid = op_json_length = op_json_contains = \
        op_insert_str = op_quote = op_to_base64 = op_from_base64 = \
        op_unhex = op_regexp_substr = op_regexp_replace = op_conv = \
        op_bit_length = op_inet_aton = op_regexp_like = \
        op_regexp_instr = op_str_to_date = \
        op_json_set = op_json_insert = op_json_replace = \
        op_json_remove = op_json_keys = op_json_search = \
        op_json_merge_patch = op_json_merge_preserve = op_json_merge = \
        op_json_array_append = op_json_pretty = op_json_quote = \
        op_json_value = op_json_depth = op_json_contains_path = \
        op_json_storage_size = op_json_overlaps = op_is_uuid = \
        op_ord = op_uuid_to_bin = op_bin_to_uuid = op_inet6_aton = \
        op_inet6_ntoa = op_compress = op_uncompress = \
        op_weight_string = \
        _op_string_unlowered

    def op_dict_lut(self, e, cols, memo):
        xp = self.xp
        cv, cm = self.eval(e.args[0], cols, memo)
        lut, _ = self.eval(e.args[1], cols, memo)
        codes = xp.clip(cv, 0, lut.shape[0] - 1)
        return lut[codes], cm

    # same clip+gather body: code translation reuses the LUT machinery
    op_dict_map = op_dict_lut

    # -- temporal --------------------------------------------------------- #

    def _days_of(self, a: Expr, cols, memo):
        from ..types.temporal import MICROS_PER_DAY
        v, m = self.eval(a, cols, memo)
        if a.dtype.kind == K.DATETIME:
            v = self.xp.floor_divide(v, MICROS_PER_DAY)
        return v, m

    def _ymd(self, a: Expr, cols, memo):
        from ..types.temporal import civil_from_days
        days, m = self._days_of(a, cols, memo)
        y, mo, d = civil_from_days(self.xp, days)
        return y, mo, d, m

    def op_year(self, e, cols, memo):
        y, _, _, m = self._ymd(e.args[0], cols, memo)
        return y, m

    def op_month(self, e, cols, memo):
        _, mo, _, m = self._ymd(e.args[0], cols, memo)
        return mo, m

    def op_dayofmonth(self, e, cols, memo):
        _, _, d, m = self._ymd(e.args[0], cols, memo)
        return d, m

    # -- math builtins ---------------------------------------------------- #

    def op_ceil(self, e, cols, memo):
        return self._ceil_floor(e, cols, memo, self.xp.ceil)

    def op_floor(self, e, cols, memo):
        return self._ceil_floor(e, cols, memo, self.xp.floor)

    def _ceil_floor(self, e, cols, memo, fn):
        xp = self.xp
        a = e.args[0]
        v, m = self._num(a, cols, memo)
        if a.dtype.is_float:
            return fn(self._as_double(v, a.dtype)), m
        if a.dtype.kind == K.DECIMAL:
            p = dec.pow10(a.dtype.scale)
            q = xp.floor_divide(v, p)
            if fn is xp.ceil:
                q = q + ((v - q * p) != 0)
            return _as_i64(xp, q), m
        return _as_i64(xp, v), m

    def op_round(self, e, cols, memo):
        return self._round_trunc(e, cols, memo, False)

    def op_truncate(self, e, cols, memo):
        return self._round_trunc(e, cols, memo, True)

    def _round_trunc(self, e, cols, memo, trunc: bool):
        xp = self.xp
        a, d = e.args
        nd = int(d.value)
        v, m = self._num(a, cols, memo)
        if a.dtype.is_float:
            f = self._as_double(v, a.dtype)
            p = 10.0 ** nd
            scaled = f * p
            if trunc:
                out = xp.trunc(scaled) / p
            else:
                out = xp.where(scaled >= 0, xp.floor(scaled + 0.5),
                               xp.ceil(scaled - 0.5)) / p
            return out, m
        if a.dtype.kind == K.DECIMAL:
            drop = a.dtype.scale - e.dtype.scale
            if drop > 0:
                p = dec.pow10(drop)
                v = _trunc_div(xp, v, xp.int64(p)) if trunc \
                    else _round_div(xp, v, xp.int64(p))
            if nd < 0:   # ROUND(dec, -k): also round off integer digits
                p2 = dec.pow10(-nd)
                v = (_trunc_div(xp, v, xp.int64(p2)) if trunc
                     else _round_div(xp, v, xp.int64(p2))) * p2
            return v, m
        if nd < 0:       # integer rounding to powers of ten
            p = dec.pow10(-nd)
            out = _trunc_div(xp, v, xp.int64(p)) if trunc \
                else _round_div(xp, v, xp.int64(p))
            return out * p, m
        return v, m

    def op_sign(self, e, cols, memo):
        v, m = self._num(e.args[0], cols, memo)
        return _as_i64(self.xp, self.xp.sign(v)), m

    def _double1(self, e, cols, memo):
        a = e.args[0]
        v, m = self._num(a, cols, memo)
        return self._as_double(v, a.dtype), m

    def op_sqrt(self, e, cols, memo):
        xp = self.xp
        v, m = self._double1(e, cols, memo)
        return xp.sqrt(xp.where(v < 0, 0.0, v)), vand(m, v >= 0)

    def op_exp(self, e, cols, memo):
        v, m = self._double1(e, cols, memo)
        return self.xp.exp(v), m

    def op_ln(self, e, cols, memo):
        xp = self.xp
        v, m = self._double1(e, cols, memo)
        return xp.log(xp.where(v <= 0, 1.0, v)), vand(m, v > 0)

    def op_log(self, e, cols, memo):
        xp = self.xp
        if len(e.args) == 1:
            return self.op_ln(e, cols, memo)
        # LOG(base, x)
        bv, bm = self._num(e.args[0], cols, memo)
        b = self._as_double(bv, e.args[0].dtype)
        xv, xm = self._num(e.args[1], cols, memo)
        x = self._as_double(xv, e.args[1].dtype)
        ok = (x > 0) & (b > 0) & (b != 1.0)
        num = xp.log(xp.where(x <= 0, 1.0, x))
        den = xp.log(xp.where((b <= 0) | (b == 1.0), 2.0, b))
        return num / den, vand(vand(bm, xm), ok)

    def op_log2(self, e, cols, memo):
        xp = self.xp
        v, m = self._double1(e, cols, memo)
        return xp.log2(xp.where(v <= 0, 1.0, v)), vand(m, v > 0)

    def op_log10(self, e, cols, memo):
        xp = self.xp
        v, m = self._double1(e, cols, memo)
        return xp.log10(xp.where(v <= 0, 1.0, v)), vand(m, v > 0)

    def op_pow(self, e, cols, memo):
        xp = self.xp
        bv, bm = self._num(e.args[0], cols, memo)
        ev_, em = self._num(e.args[1], cols, memo)
        b = self._as_double(bv, e.args[0].dtype)
        x = self._as_double(ev_, e.args[1].dtype)
        # negative base with fractional exponent -> NULL (MySQL: error/NaN)
        ok = (b >= 0) | (x == xp.floor(x))
        out = xp.power(xp.where(ok, b, 1.0), x)
        return out, vand(vand(bm, em), ok)

    def op_sin(self, e, cols, memo):
        v, m = self._double1(e, cols, memo)
        return self.xp.sin(v), m

    def op_cos(self, e, cols, memo):
        v, m = self._double1(e, cols, memo)
        return self.xp.cos(v), m

    def op_tan(self, e, cols, memo):
        v, m = self._double1(e, cols, memo)
        return self.xp.tan(v), m

    def op_cot(self, e, cols, memo):
        xp = self.xp
        v, m = self._double1(e, cols, memo)
        t = xp.tan(v)
        return 1.0 / xp.where(t == 0, 1.0, t), vand(m, t != 0)

    def op_asin(self, e, cols, memo):
        xp = self.xp
        v, m = self._double1(e, cols, memo)
        ok = (v >= -1) & (v <= 1)
        return xp.arcsin(xp.clip(v, -1, 1)), vand(m, ok)

    def op_acos(self, e, cols, memo):
        xp = self.xp
        v, m = self._double1(e, cols, memo)
        ok = (v >= -1) & (v <= 1)
        return xp.arccos(xp.clip(v, -1, 1)), vand(m, ok)

    def op_atan(self, e, cols, memo):
        v, m = self._double1(e, cols, memo)
        return self.xp.arctan(v), m

    def op_atan2(self, e, cols, memo):
        xp = self.xp
        av, am = self._num(e.args[0], cols, memo)
        bv, bm = self._num(e.args[1], cols, memo)
        return xp.arctan2(self._as_double(av, e.args[0].dtype),
                          self._as_double(bv, e.args[1].dtype)), vand(am, bm)

    def op_radians(self, e, cols, memo):
        v, m = self._double1(e, cols, memo)
        return v * (np.pi / 180.0), m

    def op_degrees(self, e, cols, memo):
        v, m = self._double1(e, cols, memo)
        return v * (180.0 / np.pi), m

    def _minmax_chain(self, e, cols, memo, fn):
        xp = self.xp
        if e.dtype.is_string and getattr(e, "_derived_dict", None) is None:
            raise NotImplementedError(
                f"{e.op.upper()} over strings requires dictionary-encoded "
                "columns (merged-code lowering did not apply)")
        val = valid = None
        for a in e.args:
            v, m = self._branch_val(e, a, cols, memo)
            if val is None:
                val, valid = v, m
            else:
                val = fn(val, v)
                valid = vand(valid, m)   # MySQL: NULL if any arg NULL
        return val, valid

    def op_greatest(self, e, cols, memo):
        return self._minmax_chain(e, cols, memo, self.xp.maximum)

    def op_least(self, e, cols, memo):
        return self._minmax_chain(e, cols, memo, self.xp.minimum)

    # -- temporal builtins ------------------------------------------------- #

    def op_dayofweek(self, e, cols, memo):
        # 1 = Sunday (ODBC); epoch day 0 = Thursday
        days, m = self._days_of(e.args[0], cols, memo)
        return _pymod(self.xp, days + 4, 7) + 1, m

    def op_weekday(self, e, cols, memo):
        # 0 = Monday
        days, m = self._days_of(e.args[0], cols, memo)
        return _pymod(self.xp, days + 3, 7), m

    def op_dayofyear(self, e, cols, memo):
        from ..types.temporal import civil_from_days, days_from_civil
        xp = self.xp
        days, m = self._days_of(e.args[0], cols, memo)
        days = _as_i64(xp, days)
        y, _, _ = civil_from_days(xp, days)
        jan1 = days_from_civil(xp, y, xp.ones_like(y), xp.ones_like(y))
        return days - jan1 + 1, m

    def op_quarter(self, e, cols, memo):
        _, mo, _, m = self._ymd(e.args[0], cols, memo)
        return (mo + 2) // 3, m

    def _time_part(self, e, cols, memo, div, mod):
        from ..types.temporal import MICROS_PER_DAY
        xp = self.xp
        a = e.args[0]
        v, m = self.eval(a, cols, memo)
        if a.dtype.kind == K.DATE:
            return xp.zeros_like(_as_i64(xp, v)), m
        tod = _pymod(xp, _as_i64(xp, v), MICROS_PER_DAY)
        return _pymod(xp, tod // div, mod), m

    def op_hour(self, e, cols, memo):
        return self._time_part(e, cols, memo, 3_600_000_000, 24)

    def op_minute(self, e, cols, memo):
        return self._time_part(e, cols, memo, 60_000_000, 60)

    def op_second(self, e, cols, memo):
        return self._time_part(e, cols, memo, 1_000_000, 60)

    def op_microsecond(self, e, cols, memo):
        return self._time_part(e, cols, memo, 1, 1_000_000)

    def op_datediff(self, e, cols, memo):
        da, ma = self._days_of(e.args[0], cols, memo)
        db, mb = self._days_of(e.args[1], cols, memo)
        return _as_i64(self.xp, da) - _as_i64(self.xp, db), vand(ma, mb)

    def op_dateadd_days(self, e, cols, memo):
        from ..types.temporal import MICROS_PER_DAY
        a, n = e.args
        v, m = self.eval(a, cols, memo)
        nv, nm = self._num(n, cols, memo)
        step = MICROS_PER_DAY if a.dtype.kind == K.DATETIME else 1
        return _as_i64(self.xp, v) + _as_i64(self.xp, nv) * step, vand(m, nm)

    def op_dateadd_months(self, e, cols, memo):
        from ..types.temporal import (MICROS_PER_DAY, civil_from_days,
                                      days_from_civil, days_in_month)
        xp = self.xp
        a, n = e.args
        v, m = self.eval(a, cols, memo)
        nv, nm = self._num(n, cols, memo)
        v = _as_i64(xp, v)
        is_dt = a.dtype.kind == K.DATETIME
        days = xp.floor_divide(v, MICROS_PER_DAY) if is_dt else v
        tod = v - days * MICROS_PER_DAY if is_dt else 0
        y, mo, d = civil_from_days(xp, days)
        mi = y * 12 + (mo - 1) + _as_i64(xp, nv)
        y2 = xp.floor_divide(mi, 12)
        mo2 = mi - y2 * 12 + 1
        d2 = xp.minimum(d, days_in_month(xp, y2, mo2))
        out_days = days_from_civil(xp, y2, mo2, d2)
        out = out_days * MICROS_PER_DAY + tod if is_dt else out_days
        return out, vand(m, nm)

    def op_dateadd_micros(self, e, cols, memo):
        a, n = e.args
        v, m = self.eval(a, cols, memo)
        nv, nm = self._num(n, cols, memo)
        return _as_i64(self.xp, v) + _as_i64(self.xp, nv), vand(m, nm)

    def op_last_day(self, e, cols, memo):
        from ..types.temporal import days_from_civil, days_in_month
        xp = self.xp
        y, mo, _d, m = self._ymd(e.args[0], cols, memo)
        return days_from_civil(xp, y, mo, days_in_month(xp, y, mo)), m

    def op_to_days(self, e, cols, memo):
        # MySQL TO_DAYS: days since year 0 (epoch 1970-01-01 = 719528)
        days, m = self._days_of(e.args[0], cols, memo)
        return _as_i64(self.xp, days) + 719528, m

    def op_from_days(self, e, cols, memo):
        v, m = self._num(e.args[0], cols, memo)
        return _as_i64(self.xp, v) - 719528, m

    def op_week(self, e, cols, memo):
        """WEEK(d[, mode]): mode 0 (MySQL default, Sunday-start, week 1 =
        first week containing a Sunday) and mode 3 (ISO 8601, Monday-
        start) — builtin_time.go weekMode subset, vectorized over the
        civil-date math."""
        from ..types.temporal import civil_from_days, days_from_civil
        xp = self.xp
        days, m = self._days_of(e.args[0], cols, memo)
        days = _as_i64(xp, days)
        mode = int(e.args[1].value) if len(e.args) > 1 else 0
        if mode == 3:
            # the ISO week of d is the week of d's Thursday
            thursday = days - (days + 3) % 7 + 3
            y, _, _ = civil_from_days(xp, thursday)
            j = days_from_civil(xp, y, 1, 1)
            return (thursday - j) // 7 + 1, m
        y, _, _ = civil_from_days(xp, days)
        j = days_from_civil(xp, y, 1, 1)
        fs = j + (7 - (j + 4) % 7) % 7       # first Sunday of the year
        return xp.maximum(xp.floor_divide(days - fs, 7) + 1, 0), m

    def op_from_unixtime(self, e, cols, memo):
        from ..types.temporal import MICROS_PER_SEC
        v, m = self._num(e.args[0], cols, memo)
        a = e.args[0]
        if a.dtype.kind == K.DECIMAL:
            from ..types import decimal as dec
            micros = _as_i64(self.xp, v) * (
                MICROS_PER_SEC // dec.pow10(min(a.dtype.scale, 6)))
        else:
            micros = _as_i64(self.xp, v) * MICROS_PER_SEC
        return micros, m

    def op_makedate(self, e, cols, memo):
        """MAKEDATE(year, dayofyear) -> DATE; NULL when dayofyear < 1."""
        from ..types.temporal import days_from_civil
        xp = self.xp
        y, my = self._num(e.args[0], cols, memo)
        doy, md = self._num(e.args[1], cols, memo)
        y = _as_i64(xp, y)
        doy = _as_i64(xp, doy)
        j = days_from_civil(xp, y, 1, 1)
        out = j + doy - 1
        ok = doy >= 1
        return out, vand(vand(my, md), ok)

    # -- host string-producing builtins ------------------------------- #
    # These yield python-str object arrays; they are NOT in DEVICE_OPS,
    # so plans keep them in host root executors where _eval_to_column
    # dictionary-encodes the produced values (the residual-evaluation
    # half of the pushdown contract, SURVEY.md §A.1).

    def op_cast_char(self, e, cols, memo):
        """CAST(x AS CHAR[(n)]) for non-string x — per-row host string
        production, dictionary-encoded by the host projection
        (builtin_cast.go castAsStringSig).  String sources lower in
        lower_strings and never reach this op."""
        from ..types import temporal as tmp
        a = e.args[0]
        v, m = self.eval(a, cols, memo)
        v = np.atleast_1d(np.asarray(v))
        kind = a.dtype.kind
        out = np.empty(len(v), object)
        for i in range(len(v)):
            x = v[i]
            if kind == K.DECIMAL:
                s = dec.to_string(int(x), a.dtype.scale)
            elif kind == K.DATE:
                s = tmp.date_to_string(int(x))
            elif kind == K.DATETIME:
                s = tmp.datetime_to_string(int(x))
            elif kind in (K.FLOAT64, K.FLOAT32):
                s = repr(float(x))
                if s.endswith(".0"):
                    s = s[:-2]
                s = s.replace("e+", "e")
            elif kind == K.ENUM:
                ix = int(x)
                s = (a.dtype.members[ix - 1]
                     if 1 <= ix <= len(a.dtype.members) else "")
            elif kind == K.UINT64:
                s = str(int(np.uint64(np.int64(x))))
            else:
                s = str(int(x))
            out[i] = s
        n = getattr(e, "_char_len", None)
        if n is not None:
            out = np.array([s[:n] for s in out], object)
        return out, m

    def op_date_format(self, e, cols, memo):
        """DATE_FORMAT(d, fmt) — the common MySQL specifiers
        (builtin_time.go dateFormat subset)."""
        from ..types.temporal import MICROS_PER_DAY, civil_from_days
        xp = self.xp
        v, m = self.eval(e.args[0], cols, memo)
        fmt = str(e.args[1].value)
        v = np.asarray(v)
        if e.args[0].dtype.kind == K.DATETIME:
            days = v // MICROS_PER_DAY
            micros = v - days * MICROS_PER_DAY
        else:
            days = v
            micros = np.zeros_like(np.asarray(days))
        days = np.atleast_1d(np.asarray(days)).astype(np.int64)
        micros = np.atleast_1d(np.asarray(micros)).astype(np.int64)
        y, mo, d = civil_from_days(np, days)
        wd = (days + 3) % 7                      # 0 = Monday
        doy = days - _jan1(np, y) + 1
        hh = micros // 3_600_000_000
        mi = micros // 60_000_000 % 60
        ss = micros // 1_000_000 % 60
        day_names = ["Monday", "Tuesday", "Wednesday", "Thursday",
                     "Friday", "Saturday", "Sunday"]
        mon_names = ["January", "February", "March", "April", "May",
                     "June", "July", "August", "September", "October",
                     "November", "December"]
        out = np.empty(len(days), object)
        for i in range(len(days)):
            parts = []
            j = 0
            while j < len(fmt):
                c = fmt[j]
                if c != "%" or j + 1 >= len(fmt):
                    parts.append(c)
                    j += 1
                    continue
                sp = fmt[j + 1]
                j += 2
                yy, mm, dd = int(y[i]), int(mo[i]), int(d[i])
                rep = {
                    "Y": f"{yy:04d}", "y": f"{yy % 100:02d}",
                    "m": f"{mm:02d}", "c": str(mm),
                    "d": f"{dd:02d}", "e": str(dd),
                    "M": mon_names[mm - 1], "b": mon_names[mm - 1][:3],
                    "W": day_names[int(wd[i])],
                    "a": day_names[int(wd[i])][:3],
                    "j": f"{int(doy[i]):03d}",
                    "H": f"{int(hh[i]):02d}", "k": str(int(hh[i])),
                    "h": f"{(int(hh[i]) % 12) or 12:02d}",
                    "i": f"{int(mi[i]):02d}", "s": f"{int(ss[i]):02d}",
                    "S": f"{int(ss[i]):02d}",
                    "p": "AM" if int(hh[i]) < 12 else "PM",
                    "T": f"{int(hh[i]):02d}:{int(mi[i]):02d}"
                         f":{int(ss[i]):02d}",
                    "%": "%",
                }.get(sp)
                parts.append(rep if rep is not None else sp)
            out[i] = "".join(parts)
        return out, m

    def op_int_to_base(self, e, cols, memo):
        """BIN/OCT/HEX over integers: args = (value, base-const)."""
        v, m = self._num(e.args[0], cols, memo)
        base = int(e.args[1].value)
        arr = np.atleast_1d(_as_i64(self.xp, v))
        fmt = {2: "b", 8: "o", 16: "X"}[base]
        out = np.array([format(int(x) & 0xFFFFFFFFFFFFFFFF, fmt)
                        for x in arr], object)
        return out, m

    def op_uuid(self, e, cols, memo):
        """UUID(): fresh value PER ROW (host string producer; plans
        carrying it are tainted out of the plan cache)."""
        import uuid as _uuid
        n = len(cols[0][0]) if cols else 1
        out = np.array([str(_uuid.uuid4()) for _ in range(n)], object)
        return out, True

    def op_rand(self, e, cols, memo):
        """RAND([seed]): per-row uniform [0,1); seeded form is a
        deterministic sequence (builtin_math.go randSig)."""
        n = len(cols[0][0]) if cols else 1
        if e.args:
            rng = np.random.default_rng(int(e.args[0].value))
        else:
            rng = np.random.default_rng()
        return self.xp.asarray(rng.random(n)), True

    def op_inet_ntoa(self, e, cols, memo):
        """INET_NTOA(n) -> dotted-quad string (host string producer;
        builtin_miscellaneous.go inetNtoa)."""
        v, m = self._num(e.args[0], cols, memo)
        arr = np.atleast_1d(_as_i64(self.xp, v))
        out = np.empty(len(arr), object)
        ok = np.ones(len(arr), bool)
        for i, x in enumerate(arr):
            x = int(x)
            if 0 <= x <= 0xFFFFFFFF:
                out[i] = ".".join(str(x >> s & 255)
                                  for s in (24, 16, 8, 0))
            else:
                out[i] = ""
                ok[i] = False
        return out, vand(m, True if ok.all() else ok)

    def op_format_num(self, e, cols, memo):
        """FORMAT(n, d): thousands separators + d decimals."""
        v, m = self._num(e.args[0], cols, memo)
        d = max(int(e.args[1].value), 0)
        a0 = e.args[0]
        if a0.dtype.kind == K.DECIMAL:
            vals = [int(x) / dec.pow10(a0.dtype.scale)
                    for x in np.atleast_1d(np.asarray(v))]
        else:
            vals = [float(x) for x in np.atleast_1d(np.asarray(v))]
        out = np.array([f"{x:,.{d}f}" for x in vals], object)
        return out, m

    def op_unix_timestamp(self, e, cols, memo):
        from ..types.temporal import MICROS_PER_DAY, MICROS_PER_SEC
        xp = self.xp
        a = e.args[0]
        v, m = self.eval(a, cols, memo)
        v = _as_i64(xp, v)
        if a.dtype.kind == K.DATE:
            v = v * MICROS_PER_DAY
        return xp.floor_divide(v, MICROS_PER_SEC), m

    # -- casts ------------------------------------------------------------ #

    def op_cast(self, e, cols, memo):
        xp = self.xp
        a = e.args[0]
        src, dst = a.dtype, e.dtype
        if src.is_string or dst.is_string:
            # string casts must have been lowered onto dictionary codes
            # (lower_strings._lower_cast_strings) or routed to cast_char;
            # evaluating here would cast raw dict CODES
            raise NotImplementedError(f"unlowered string cast {src} -> {dst}")
        v, m = self._num(a, cols, memo)
        if dst.kind in (K.FLOAT64, K.FLOAT32):
            out = self._as_double(v, src)
            if dst.kind == K.FLOAT32 and hasattr(out, "astype"):
                out = out.astype(xp.float32)
            return out, m
        if dst.kind == K.DECIMAL:
            wide = dst.is_wide_decimal or src.is_wide_decimal
            if src.kind == K.DECIMAL:
                ds = dst.scale - src.scale
                if wide:
                    vo = _to_object(v)
                    out = (vo * dec.pow10(ds) if ds >= 0
                           else _round_div(np, vo, dec.pow10(-ds)))
                    return _dec_fit(out, m, dst), m
                if ds >= 0:
                    return self._iwiden("multiply", v,
                                        dec.pow10(ds), False), m
                return _round_div(xp, v, dec.pow10(-ds)), m
            if src.is_float:
                scaled = v * float(dec.pow10(dst.scale))
                out = xp.where(scaled >= 0, xp.floor(scaled + 0.5),
                               xp.ceil(scaled - 0.5))
                if dst.is_wide_decimal:
                    # python-int object lanes, exact for the float's value
                    vals = np.asarray(out, np.float64).reshape(-1)
                    obj = np.array([int(x) for x in vals], dtype=object)
                    return _dec_fit(obj, m, dst), m
                return out.astype(xp.int64), m
            if dst.is_wide_decimal:
                return _dec_fit(_to_object(v) * dec.pow10(dst.scale),
                                m, dst), m
            return self._iwiden("multiply", v,
                                dec.pow10(dst.scale), False), m
        if dst.kind in (K.INT64, K.UINT64):
            ity = xp.int64 if dst.kind == K.INT64 else xp.uint64
            if src.kind == K.DECIMAL:
                if src.is_wide_decimal:
                    out = _round_div(np, _to_object(v),
                                     dec.pow10(src.scale))
                    _int_fit(out, m, dst.kind == K.UINT64)
                    return out.astype(np.int64 if dst.kind == K.INT64
                                      else np.uint64), m
                out = _round_div(xp, v, dec.pow10(src.scale))
                return (out.astype(ity) if hasattr(out, "astype") else out), m
            if src.is_float:
                out = xp.where(v >= 0, xp.floor(v + 0.5), xp.ceil(v - 0.5))
                return out.astype(ity), m
            return (v.astype(ity) if hasattr(v, "astype") else int(v)), m
        if dst.kind == K.DATETIME and src.kind == K.DATE:
            from ..types.temporal import MICROS_PER_DAY
            return _as_i64(xp, v) * MICROS_PER_DAY, m
        if dst.kind == K.DATE and src.kind == K.DATETIME:
            from ..types.temporal import MICROS_PER_DAY
            return xp.floor_divide(_as_i64(xp, v), MICROS_PER_DAY), m
        if dst.kind == K.DATETIME and src.kind in (K.INT64, K.UINT64):
            # MySQL numeric->DATETIME: digits read as [YYYYMMDD]HHMMSS
            # (internal micros arithmetic uses the reinterp op instead)
            iv = _as_i64(xp, v)
            # date-only digits scale to [YYYYMMDD]000000; zero the other
            # lane BEFORE the multiply — 14-digit inputs times 10^6 wrap
            # int64 in the discarded lane otherwise (ADVICE r5)
            date_only = iv < 10 ** 8
            iv = xp.where(date_only, iv, 0) * 10 ** 6 \
                + xp.where(date_only, 0, iv)
            y = iv // 10 ** 10
            mo = iv // 10 ** 8 % 100
            d = iv // 10 ** 6 % 100
            h = iv // 10 ** 4 % 100
            mi = iv // 100 % 100
            sec = iv % 100
            ok = ((mo >= 1) & (mo <= 12) & (d >= 1) & (d <= 31)
                  & (h < 24) & (mi < 60) & (sec < 60))
            from ..types.temporal import civil_from_days, days_from_civil
            days = days_from_civil(xp, y, mo, d)
            # calendar validation: Feb 31 etc. must be NULL, not rolled
            y2, m2, d2 = civil_from_days(xp, days)
            ok = ok & (y2 == y) & (m2 == mo) & (d2 == d)
            micros = (days * 86_400 + h * 3600 + mi * 60 + sec) * 1_000_000
            mm = ok if m is True else _mask_arr(xp, m, micros) & ok
            return xp.where(ok, micros, 0), mm
        if dst.kind == K.TIME and src.kind in (K.INT64, K.UINT64):
            # MySQL numeric->TIME: digits read as [H]HMMSS
            iv = _as_i64(xp, v)
            neg = iv < 0
            av2 = xp.abs(iv)
            h = av2 // 10 ** 4
            mi = av2 // 100 % 100
            sec = av2 % 100
            ok = (mi < 60) & (sec < 60)
            us = (h * 3600 + mi * 60 + sec) * 1_000_000
            us = xp.where(neg, -us, us)
            mm = ok if m is True else _mask_arr(xp, m, us) & ok
            return xp.where(ok, us, 0), mm
        if dst.kind == K.TIME and src.kind == K.DATETIME:
            # time-of-day component (MySQL CAST(datetime AS TIME))
            from ..types.temporal import MICROS_PER_DAY
            return _as_i64(xp, v) % MICROS_PER_DAY, m
        if dst.kind == src.kind:
            return _as_i64(xp, v), m
        raise NotImplementedError(f"cast {src} -> {dst}")

    def op_reinterp(self, e, cols, memo):
        """Raw int64-micros reinterpret between numeric and temporal —
        the INTERNAL seam SEC_TO_TIME/MAKETIME/ADDTIME/TIMEDIFF compose
        through (user CASTs parse digits instead)."""
        v, m = self.eval(e.args[0], cols, memo)
        return _as_i64(self.xp, v), m


# ---------------------------------------------------------------------- #

def _to_object(v):
    """Numeric value(s) as python-int object array/scalar (exact wide-
    decimal representation; host only)."""
    if hasattr(v, "astype"):
        return v.astype(object)
    return int(v)


def _dec_fit(data, m, dst):
    """ER_DATA_OUT_OF_RANGE when a decimal result exceeds its declared
    precision (mydecimal.go overflow; strict-mode semantics)."""
    bound = dec.pow10(dst.prec if dst.prec > 0 else 65)
    vals = data if m is True else (data[np.asarray(m)]
                                   if hasattr(data, "__getitem__") else data)
    arr = np.asarray(vals, dtype=object).reshape(-1)
    if len(arr) and (max(arr.max(), -arr.min())) >= bound:
        raise ValueError(
            f"Out of range value for DECIMAL({dst.prec},{dst.scale})")
    return data


def _int_fit(data, m, unsigned: bool):
    lo, hi = (0, 2 ** 64 - 1) if unsigned else (-2 ** 63, 2 ** 63 - 1)
    vals = data if m is True else data[np.asarray(m)]
    arr = np.asarray(vals, dtype=object).reshape(-1)
    if len(arr) and (int(arr.min()) < lo or int(arr.max()) > hi):
        raise ValueError("Out of range value for BIGINT"
                         + (" UNSIGNED" if unsigned else ""))


def _or3(a, b, c):
    if a is True:
        return True
    out = a
    for x in (b, c):
        if x is True:
            return True
        if x is False:
            continue
        out = x if out is False else (out | x)
    return out


def _mask_arr(xp, m, like):
    """Validity as an array broadcastable with `like`."""
    if m is True:
        return _broadcast_true(xp, like)
    if m is False:
        return _broadcast_false(xp, like)
    return m


def _as_i64(xp, v):
    return v.astype(xp.int64) if hasattr(v, "astype") else xp.int64(v)


def _pymod(xp, a, b):
    """Floor (python-style, non-negative for positive divisor) modulo —
    keeps calendar arithmetic correct for pre-epoch dates."""
    return xp.mod(a, b)


def _as_u64(xp, v):
    return v.astype(xp.uint64) if hasattr(v, "astype") else xp.uint64(v)


def _broadcast_true(xp, like):
    if hasattr(like, "shape") and like.shape:
        return xp.ones(like.shape, dtype=bool)
    return True


def _broadcast_false(xp, like):
    if hasattr(like, "shape") and like.shape:
        return xp.zeros(like.shape, dtype=bool)
    return False


def _trunc_div(xp, a, b):
    """Integer division truncating toward zero (MySQL DIV), div-by-0-safe."""
    safe = xp.where(b == 0, 1, b)
    q = xp.floor_divide(xp.abs(a), xp.abs(safe))
    sign = xp.where((a < 0) != (safe < 0), -1, 1)
    return sign * q


def _round_div(xp, a, b):
    """Integer division rounding half away from zero (MySQL decimal div)."""
    safe = xp.where(b == 0, 1, b)
    absb = xp.abs(safe)
    q = xp.floor_divide(xp.abs(a) + absb // 2, absb)
    sign = xp.where((a < 0) != (safe < 0), -1, 1)
    return sign * q


def _div_valid(xp, ma, mb, vb):
    nz = vb != 0
    return vand(vand(ma, mb), nz)


def eval_expr(xp, e: Expr, cols: Sequence[Pair], dicts=None) -> Pair:
    return Evaluator(xp, dicts).eval(e, cols, {})


__all__ = ["Evaluator", "eval_expr", "vand"]
